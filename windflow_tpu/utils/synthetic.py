"""Synthetic stream generators for tests and benchmarks.

Mirrors the reference's shared test fixtures (mp_common.hpp:125-163):
a source whose timestamps progress with Pareto-distributed increments
and bounded out-of-order jitter -- the stress input for TB windows with
triggering delays and for the PROBABILISTIC (K-slack) mode.
"""
from __future__ import annotations

import random
from typing import Any, Callable

import numpy as np

from ..core.tuples import BasicRecord, TupleBatch


def ordered_keyed_stream(n_keys: int, per_key: int,
                         value_of: Callable[[int], float] = float):
    """Round-robin keys, per-key dense ids, ts == id (the in-order
    fixture used across the suites)."""
    state = {"i": 0}

    def fn(shipper, ctx):
        i = state["i"]
        if i >= n_keys * per_key:
            return False
        key = i % n_keys
        tid = i // n_keys
        shipper.push(BasicRecord(key, tid, tid, value_of(tid)))
        state["i"] = i + 1
        return True

    return fn


def pareto_ooo_stream(n_keys: int, per_key: int, seed: int = 0,
                      alpha: float = 1.5, jitter: int = 3,
                      key_type: str = "int"):
    """Out-of-order keyed stream: per-key timestamps advance by Pareto
    increments; emission order is per-key round-robin so the merged
    stream is out of order by up to ``jitter`` positions per key
    (mp_common.hpp Pareto timestamp source).

    ``key_type='str'`` exercises non-integral keys (the reference's
    ``_string`` test variants)."""
    rnd = random.Random(seed)
    ts = {k: 0 for k in range(n_keys)}
    # round-robin across keys (NOT key-segment concatenation: that
    # would reset the merged timeline to ~0 at every key boundary,
    # giving unbounded lateness instead of the documented
    # jitter-bounded disorder)
    buffer = []
    for i in range(per_key):
        for k in range(n_keys):
            ts[k] += max(1, int(rnd.paretovariate(alpha)))
            buffer.append((k, i, ts[k]))
    # bounded shuffle: permute within consecutive windows of `jitter`,
    # INCLUDING the final partial window -- the old loop stopped at
    # len(buffer) - jitter, so the stream tail was always in order and
    # tail-sensitive paths (EOS flush of open windows, K-slack late
    # handling at stream end) were never exercised out of order
    for i in range(0, len(buffer), jitter):
        window = buffer[i:i + jitter]
        rnd.shuffle(window)
        buffer[i:i + jitter] = window
    state = {"i": 0}

    def fn(shipper, ctx):
        i = state["i"]
        if i >= len(buffer):
            # exhausted state stays sticky: parallel replicas share this
            # closure, and an auto-rewind here would hand the whole
            # buffer to a replica still in its step loop.  reset() below
            # is the explicit restart.
            return False
        k, tid, t = buffer[i]
        key: Any = f"key_{k}" if key_type == "str" else k
        shipper.push(BasicRecord(key, tid, t, float(tid)))
        state["i"] = i + 1
        return True

    def reset():
        state["i"] = 0

    fn.events = list(buffer)
    fn.reset = reset
    return fn


def batch_stream(n_events: int, n_keys: int, batch_size: int = 65536,
                 seed: int = 0):
    """Columnar batch source body for the hot plane."""
    rng = np.random.default_rng(seed)
    state = {"sent": 0}

    def fn(ctx):
        i = state["sent"]
        if i >= n_events:
            return None
        n = min(batch_size, n_events - i)
        ts = i + np.arange(n, dtype=np.int64)
        state["sent"] = i + n
        return TupleBatch({
            "key": ts % n_keys,
            "id": ts // n_keys,
            "ts": ts // n_keys,
            "value": rng.random(n),
        })

    return fn
