"""Checkpoint / resume of operator state.

The reference has **no** checkpointing (SURVEY.md §5: "Absent. No
serialization of operator state exists"); windflow_tpu isolates it as a
policy layer, as the survey recommends.  Mechanism: every stateful
NodeLogic exposes ``state_dict() / load_state()`` (pickle-friendly
snapshots of per-key window state); this module walks a PipeGraph and
saves/restores every replica's state.

Scope and contract:
* checkpoint at quiescent points: before start, after wait_end, or
  mid-stream through the LIVE barrier (``PipeGraph.quiesce()`` /
  ``live_checkpoint()`` pause sources, drain channels and in-flight
  device batches, snapshot, resume);
* user record/result types must be picklable;
* restores pair with source replay from the captured offset
  (at-least-once without source acknowledgement).
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional

from ..graph.pipegraph import NodeFailureError

# snapshot-file header (the stats-JSON Schema_version contract applied
# to pickled snapshots): save_graph stamps every file; restore_graph
# tolerates header-less legacy files but rejects foreign, newer-schema
# or truncated ones with an actionable error instead of an unpickling
# crash mid-restore
SNAPSHOT_MAGIC = "windflow-graph-state"
SNAPSHOT_SCHEMA = 1


def _is_stateful(logic) -> bool:
    """Structural statefulness probe: True iff the logic's class
    overrides NodeLogic.state_dict (so the saved twin produced state).
    Avoids calling state_dict(), which serializes the full store just
    to test for None.  ChainedLogic defers to its halves (its own
    override returns None when both are stateless); FusedLogic to its
    segments."""
    from ..runtime.node import ChainedLogic, FusedLogic, NodeLogic
    if isinstance(logic, ChainedLogic):
        return _is_stateful(logic.a) or _is_stateful(logic.b)
    if isinstance(logic, FusedLogic):
        return any(_is_stateful(s.logic) for s in logic.segments)
    fn = getattr(type(logic), "state_dict", None)
    if fn is None:  # duck-typed logic: the instance hook decides
        return getattr(logic, "state_dict", None) is not None
    return fn is not NodeLogic.state_dict


def graph_state(graph) -> Dict[str, Any]:
    """Collect every replica's state_dict, keyed by (pre-fusion) node
    name.  Nodes the LEVEL2 compile pass fused (graph/fuse.py) are
    flattened back to their segments via ``iter_logics``, so snapshot
    keys are FUSION-INVARIANT: a LEVEL0 snapshot restores into a LEVEL2
    graph (started or not) and vice versa."""
    from ..graph.fuse import iter_logics
    out = {}
    for name, logic in iter_logics(graph):
        getter = getattr(logic, "state_dict", None)
        if getter is None:
            continue
        st = getter()
        if st is not None:
            out[name] = st
    return out


def write_snapshot(path: str, states: Dict[str, Any],
                   epoch: Optional[int] = None) -> None:
    """Persist a state map crash-safely: schema/epoch header, then
    write-temp + fsync + atomic rename (durability/store.py) -- a crash
    mid-write can no longer leave a truncated pickle at ``path`` that
    poisons every subsequent restart."""
    from ..durability.store import atomic_write_bytes
    payload = {"magic": SNAPSHOT_MAGIC, "schema": SNAPSHOT_SCHEMA,
               "epoch": epoch, "states": states}
    atomic_write_bytes(path, pickle.dumps(
        payload, protocol=pickle.HIGHEST_PROTOCOL))


def save_graph(graph, path: str) -> None:
    write_snapshot(path, graph_state(graph))


def read_snapshot(path: str) -> Dict[str, Any]:
    """Tolerant snapshot loader: stamped files validate their header
    (foreign magic / newer schema / truncation raise an actionable
    RuntimeError naming the file, via the validators shared with the
    epoch-manifest reader); header-less legacy files -- a plain
    pickled state map -- still load."""
    from ..durability.store import load_pickle, validate_header
    payload = load_pickle(path, "graph snapshot")
    if isinstance(payload, dict) and "magic" in payload:
        validate_header(payload, path, SNAPSHOT_MAGIC, SNAPSHOT_SCHEMA,
                        "graph snapshot")
        return payload["states"]
    if not isinstance(payload, dict):
        raise RuntimeError(
            f"{path!r} is not a windflow graph snapshot")
    return payload  # legacy header-less state map


def _replica_group(name: str):
    """Split a replica node name into (group_prefix, index): names end
    with ``.<int>`` per the wiring convention (multipipe._append_stage).
    Returns (None, None) for un-indexed names (sources, collectors)."""
    base, dot, idx = name.rpartition(".")
    if dot and idx.isdigit():
        return base, int(idx)
    return None, None


def _override_for(prefix: str, overrides) -> Optional[str]:
    """The override key authorizing repartition of replica group
    ``prefix`` (e.g. ``pipe0/acc``): exact prefix, its last path
    component (the operator name), or a substring -- the same loose
    matching PipeGraph.rescale applies to elastic registry keys."""
    if not overrides:
        return None
    tail = prefix.rsplit("/", 1)[-1]
    for key in overrides:
        if key == prefix or key == tail or key in prefix:
            return key
    return None


def _slice_keyed_entries(decoded: Any, scratch) -> Dict[Any, Any]:
    """One manifest slice -> {key: value}.  Delta manifests resolve to
    keyed marker payloads (durability/delta.py) that unpack directly;
    schema-1 slices are opaque ``state_dict`` pickles, so the slice is
    decoded THROUGH a scratch logic of the destination group
    (``load_state`` then ``keyed_state_dict``) -- the logic's own
    serialization round-trip is the only universal way back to per-key
    form.  The scratch logic's state is clobbered; callers overwrite
    it with its final partition afterwards."""
    from ..durability.delta import is_keyed_payload, unpack_keyed
    if is_keyed_payload(decoded):
        return unpack_keyed(decoded)
    scratch.load_state(decoded)
    return dict(scratch.keyed_state_dict())


def _repartition_group(prefix: str, describe: str, states, decode,
                       manifest_names, group_logics) -> None:
    """Repartition one replica group's manifest keyed state into a
    different replica count through the elastic ``hash % n`` contract
    (elastic/rescale.py owns the partitioner and the duplicate-key
    invariant)."""
    from ..durability.delta import keyed_capable
    from ..elastic.rescale import partition_keyed_state
    new_n = len(group_logics)
    for idx, logic in group_logics:
        if not keyed_capable(logic):
            raise RuntimeError(
                f"{describe}: parallelism override for {prefix!r} "
                f"needs the keyed-state contract, but replica "
                f"{prefix}.{idx}'s logic ({type(logic).__name__}) "
                "does not implement keyed_state_dict/load_keyed_state")
    scratch = group_logics[0][1]
    merged: Dict[Any, Any] = {}
    for name in manifest_names:
        st = states[name]
        decoded = decode(st) if decode is not None else st
        for k, v in _slice_keyed_entries(decoded, scratch).items():
            if k in merged:
                raise RuntimeError(
                    f"{describe}: key {k!r} appears in more than one "
                    f"manifest slice of {prefix!r} -- the snapshot "
                    "violates the single-owner contract; refusing to "
                    "merge")
            merged[k] = v
    parts = partition_keyed_state(merged, new_n)
    for i, (idx, logic) in enumerate(
            sorted(group_logics, key=lambda t: t[0])):
        logic.load_keyed_state(parts[i])


def restore_states(graph, states: Dict[str, Any], describe: str,
                   decode=None, overrides=None) -> int:
    """Load a state map into a graph, shared by ``restore_graph`` and
    the epoch-manifest restore (durability/recovery.py).  Returns the
    number of replicas restored.

    Without ``overrides`` the graph must be structurally identical:
    raises BEFORE loading anything if the map's stateful-node names
    differ from this graph's -- in either direction the resume would
    silently run with misdistributed window state (e.g. an N-replica
    farm snapshot into a coalesced single-engine lowering, or vice
    versa).  Which nodes are stateful is determined by the graph
    structure, not by stream data, so set equality is the structure
    check.  ``decode`` maps each stored entry to the load argument
    (the manifest path stores pickled blobs).

    ``overrides`` (operator-name keys, from
    ``run_with_epochs(parallelism_overrides=...)``) authorizes named
    replica GROUPS to restore into a DIFFERENT parallelism: the
    group's manifest slices are merged per key (duplicate keys abort)
    and repartitioned through the elastic ``hash % n`` owner contract,
    so every key lands on the replica the new topology's KEYBY emitter
    routes it to.  Groups not named by an override still require exact
    structure."""
    from ..durability.delta import load_into
    from ..graph.fuse import iter_logics
    loadable = {}
    for name, logic in iter_logics(graph):
        if _is_stateful(logic):
            loadable[name] = logic
    extra = set(states) - set(loadable)
    missing = set(loadable) - set(states)
    repartitioned = 0
    if (extra or missing) and overrides:
        # group mismatched names by replica prefix; an override that
        # names a group lifts it out of the exact-match contract
        groups = set()
        for name in list(extra) + list(missing):
            prefix, _idx = _replica_group(name)
            if prefix is not None and _override_for(prefix,
                                                    overrides):
                groups.add(prefix)
        for prefix in sorted(groups):
            manifest_names = sorted(
                n for n in states
                if _replica_group(n)[0] == prefix)
            group_logics = sorted(
                ((_replica_group(n)[1], lg)
                 for n, lg in loadable.items()
                 if _replica_group(n)[0] == prefix),
                key=lambda t: t[0])
            if not manifest_names or not group_logics:
                continue  # nothing to merge / nowhere to load
            _repartition_group(prefix, describe, states, decode,
                               manifest_names, group_logics)
            repartitioned += len(group_logics)
            extra -= set(manifest_names)
            for n in list(missing):
                if _replica_group(n)[0] == prefix:
                    missing.discard(n)
            # the group is fully restored: drop it from the exact-match
            # load below (states entries only load via loadable keys)
            loadable = {k: v for k, v in loadable.items()
                        if _replica_group(k)[0] != prefix}
    if extra or missing:
        raise RuntimeError(
            f"{describe}/graph structure mismatch (e.g. different "
            "parallelism or coalesce setting than at save time): "
            f"snapshot-only nodes {sorted(extra)}, "
            f"graph-only nodes {sorted(missing)}; nothing was restored"
            + ("" if overrides is None else
               " (parallelism_overrides matched no repartitionable "
               "group for these)"))
    for name, logic in loadable.items():
        st = states[name]
        load_into(logic, decode(st) if decode is not None else st)
    return len(loadable) + repartitioned


def restore_graph(graph, path: str) -> int:
    """Load a snapshot file into a structurally identical graph (same
    operator names/parallelisms); returns the replicas restored."""
    return restore_states(graph, read_snapshot(path),
                          f"snapshot {path!r}")


def run_with_recovery(graph_factory, checkpoint_path: str,
                      max_restarts: int = 3, on_failure=None) -> Any:
    """Failure-recovery policy runner (the recovery layer the reference
    lacks entirely, SURVEY.md §5 "failure detection / elastic
    recovery: Absent").

    ``graph_factory(attempt: int) -> PipeGraph`` builds a structurally
    identical graph each attempt (fresh sources may resume from their
    own offsets via the attempt number).  The graph runs to completion;
    on a node failure (``NodeFailureError`` from ``wait_end`` -- a
    replica thread died; deterministic validation errors raise plain
    RuntimeError and propagate immediately) the latest checkpoint -- taken after every successful
    run()-quiescent state, or seeded by the caller -- is restored into a
    freshly built graph and the run retries, up to ``max_restarts``.

    The failure-containment layer (resilience/; docs/RESILIENCE.md)
    makes this runner reach its retry path for *mid-stream* crashes
    too: graph cancellation guarantees ``wait_end`` returns (no
    full-channel deadlock) and a configured stall watchdog converts
    hangs into ``StallError`` (a ``NodeFailureError`` subclass, so
    stalled runs are retried as well).

    ``on_failure(attempt, error, graph)``, when given, observes every
    failed attempt before the retry -- e.g. to drain
    ``graph.dead_letters`` or emit alerts.  The failures of all
    attempts are attached to the finally raised error as
    ``error.attempt_history``.

    Checkpoints are only taken at quiescent points (this runner
    checkpoints AFTER a successful run; mid-stream snapshots require
    the caller to stage input so a replayed attempt re-feeds unacked
    data -- at-least-once semantics, like any checkpoint/replay system
    without source acknowledgement).

    Returns the graph whose run completed.
    """
    import os
    attempt = 0
    history: List[BaseException] = []
    while True:
        g = graph_factory(attempt)
        if attempt > 0 and os.path.exists(checkpoint_path):
            restore_graph(g, checkpoint_path)
        try:
            g.run()
            save_graph(g, checkpoint_path)
            return g
        except NodeFailureError as e:
            # only replica-thread deaths are retried; deterministic
            # graph-construction/validation errors (plain RuntimeError
            # from merge checks etc.) re-raise immediately instead of
            # silently re-running the full source stream
            history.append(e)
            if on_failure is not None:
                on_failure(attempt, e, g)
            attempt += 1
            if attempt > max_restarts:
                e.attempt_history = history
                raise
