"""Telemetry plane: end-to-end latency tracing, lock-free log-bucketed
histograms, flight recorder and OpenMetrics export
(docs/OBSERVABILITY.md).

The stats plane (monitoring/) reproduces the reference's counter
surface; this package adds the latency dimension a production runtime
is operated on: sampled source-to-sink trace contexts, per-operator
service/residency/e2e histograms with p50/p95/p99/max, a bounded
structured-event ring dumped on failure, and a Prometheus-scrapable
``/metrics`` endpoint on the dashboard HTTP server.
"""
from .histogram import LogHistogram, bucket_le_us
from .metrics import CONTENT_TYPE, render_openmetrics
from .profiler import launch_span
from .recorder import FlightRecorder
from .trace import (DEFAULT_TRACE_SAMPLE, TelemetryHub, TraceContext,
                    TraceSampler, attach_if_absent, get_trace)

__all__ = [
    "LogHistogram", "bucket_le_us",
    "TraceContext", "TraceSampler", "TelemetryHub",
    "get_trace", "attach_if_absent", "DEFAULT_TRACE_SAMPLE",
    "FlightRecorder",
    "render_openmetrics", "CONTENT_TYPE",
    "launch_span",
]
