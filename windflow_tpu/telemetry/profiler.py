"""jax.profiler capture hook around device launches
(docs/OBSERVABILITY.md).

``launch_span(label)`` wraps every window-engine program launch (the
dispatcher thread's ``engine.compute`` call).  By default it is a
no-op null context; setting ``WINDFLOW_JAX_PROFILE=1`` turns it into a
``jax.profiler.TraceAnnotation``, so a profiler capture started with
``jax.profiler.start_trace(logdir)`` (or the live
``start_server``/TensorBoard flow) shows each launch as a named span
that lines up with the per-launch ``Device_time_ms`` wall numbers in
the stats JSON.

Resolution happens once per process, on first use, never at import --
the telemetry plane must not pull jax into processes that only run the
host plane.
"""
from __future__ import annotations

import os
from contextlib import nullcontext

_impl = None  # resolved on first launch_span call


def _resolve():
    if os.environ.get("WINDFLOW_JAX_PROFILE", "0") == "0":
        return lambda label: nullcontext()
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation
    except ImportError:
        return lambda label: nullcontext()


def launch_span(label: str):
    """Context manager spanning one device launch."""
    global _impl
    if _impl is None:
        _impl = _resolve()
    return _impl(label)


def reset() -> None:
    """Re-read WINDFLOW_JAX_PROFILE (tests)."""
    global _impl
    _impl = None
