"""OpenMetrics / Prometheus text rendering of the stats-JSON surface
(docs/OBSERVABILITY.md).

The dashboard server keeps the latest report per registered app (the
framed TCP protocol, monitoring/dashboard.py); ``render_openmetrics``
turns that snapshot into the OpenMetrics text exposition served at
``GET /metrics`` on the existing web-UI HTTP server, so any Prometheus
scraper pointed at the dashboard sees every traced graph without a new
agent.  Latency histograms re-expose the log-bucket arrays the
replicas recorded (telemetry/histogram.py), converted to seconds and
cumulated into the `le` convention.
"""
from __future__ import annotations

from typing import List

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; " \
    "charset=utf-8"


def _esc(v) -> str:
    """Escape a label value per the OpenMetrics ABNF."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _labels(**kv) -> str:
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in kv.items())
    return "{" + inner + "}" if inner else ""


def _hist_lines(out: List[str], name: str, hist: dict, **labels) -> None:
    """Emit one histogram family instance from a LogHistogram dict
    (sparse non-cumulative [le_us, count] pairs; le -1 = +Inf)."""
    acc = 0
    saw_inf = False
    for le_us, count in hist.get("buckets", []):
        acc += count
        inf = le_us < 0
        saw_inf = saw_inf or inf
        le = "+Inf" if inf else repr(le_us / 1e6)
        out.append(f"{name}_bucket{_labels(**labels, le=le)} {acc}")
    n = hist.get("n", 0)
    if not saw_inf:
        # the +Inf bucket is mandatory (histogram_quantile returns NaN
        # without it), and the sparse source only materializes the
        # overflow bucket for >268 s observations
        out.append(f"{name}_bucket{_labels(**labels, le='+Inf')} {n}")
    out.append(f"{name}_count{_labels(**labels)} {n}")
    out.append(f"{name}_sum{_labels(**labels)} "
               f"{hist.get('sum_us', 0.0) / 1e6}")


_COUNTERS = (
    # (metric, per-replica stats-JSON field)
    ("windflow_inputs", "Inputs_received"),
    ("windflow_outputs", "Outputs_sent"),
    ("windflow_inputs_ignored", "Inputs_ignored"),
    ("windflow_svc_failures", "Svc_failures"),
    ("windflow_shed_tuples", "Shed_tuples"),
    ("windflow_device_launches", "Device_launches"),
    ("windflow_device_bytes_to", "Bytes_to_device"),
    ("windflow_device_bytes_from", "Bytes_from_device"),
)


def render_openmetrics(apps: dict) -> str:
    """OpenMetrics text for a dashboard snapshot
    (``DashboardServer.snapshot()``: app id -> {report, active, ...}).

    Emission is FAMILY-major: every sample of a MetricFamily sits
    contiguously under its ``# TYPE``/``# HELP`` header, across all
    apps and operators -- the spec requires it, and strict parsers
    (prometheus_client, promtool) reject interleaved families as a
    clashing name."""
    out: List[str] = []

    def family(name, mtype, help_):
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_}")

    reports = [(str(aid), app.get("report"))
               for aid, app in sorted(apps.items(), key=lambda kv: str(kv[0]))
               if isinstance(app, dict) and app.get("report")]

    def per_op():
        for aid, rep in reports:
            g = rep.get("PipeGraph_name", "")
            for op in rep.get("Operators", []):
                yield (op, op.get("Replicas", []),
                       dict(app=aid, graph=g,
                            operator=op.get("Operator_name", "")))

    def per_graph():
        for aid, rep in reports:
            yield rep, dict(app=aid, graph=rep.get("PipeGraph_name", ""))

    family("windflow_app_active", "gauge",
           "1 while the graph keeps reporting, 0 after deregistration")
    for aid, app in sorted(apps.items(), key=lambda kv: str(kv[0])):
        if not isinstance(app, dict):
            continue
        rep = app.get("report") or {}
        g = rep.get("PipeGraph_name", "")
        out.append(f"windflow_app_active"
                   f"{_labels(app=aid, graph=g)} "
                   f"{1 if app.get('active') else 0}")

    for metric, field in _COUNTERS:
        family(metric, "counter", f"sum of per-replica {field}")
        for _op, reps, lab in per_op():
            out.append(f"{metric}_total{_labels(**lab)} "
                       f"{sum(int(r.get(field, 0) or 0) for r in reps)}")
    # device-lane derivations (docs/PLANNER.md "Resident state"): NEW
    # bytes shipped per launch (state never re-ships on the resident
    # lane, so the >=10x claim is measurable here) + the resident
    # state footprint gauge
    family("windflow_device_bytes_per_launch", "gauge",
           "bytes shipped per device launch (events in + results out)")
    for _op, reps, lab in per_op():
        launches = sum(int(r.get("Device_launches", 0) or 0)
                       for r in reps)
        if launches:
            shipped = sum(int(r.get("Bytes_to_device", 0) or 0)
                          + int(r.get("Bytes_from_device", 0) or 0)
                          for r in reps)
            out.append(f"windflow_device_bytes_per_launch"
                       f"{_labels(**lab)} {shipped // launches}")
    family("windflow_device_state_bytes_resident", "gauge",
           "per-key window state resident in device memory")
    for _op, reps, lab in per_op():
        resident = sum(int(r.get("Device_state_bytes_resident", 0) or 0)
                       for r in reps)
        if resident:
            out.append(f"windflow_device_state_bytes_resident"
                       f"{_labels(**lab)} {resident}")
    family("windflow_queue_depth", "gauge",
           "tuples parked in the operator's inbound channels")
    for _op, reps, lab in per_op():
        out.append(f"windflow_queue_depth{_labels(**lab)} "
                   f"{sum(int(r.get('Queue_depth', 0) or 0) for r in reps)}")
    family("windflow_queue_high_watermark", "gauge",
           "peak depth of the operator's inbound channels")
    for _op, reps, lab in per_op():
        hwm = max((int(r.get("Queue_high_watermark", 0) or 0)
                   for r in reps), default=0)
        out.append(f"windflow_queue_high_watermark{_labels(**lab)} {hwm}")
    # audit plane (audit/; docs/OBSERVABILITY.md): frontier gauges per
    # operator (max over replicas = the most advanced replica; lag is
    # the max = the most held-back one)
    family("windflow_frontier", "gauge",
           "low-watermark progress frontier (per-source position units)")
    for _op, reps, lab in per_op():
        fr = max((float(r.get("Frontier", 0) or 0) for r in reps),
                 default=0.0)
        out.append(f"windflow_frontier{_labels(**lab)} {fr}")
    family("windflow_frontier_lag_seconds", "gauge",
           "how long the operator's frontier has been held while work "
           "was pending")
    for _op, reps, lab in per_op():
        lag = max((float(r.get("Frontier_lag_ms", 0) or 0)
                   for r in reps), default=0.0)
        out.append(f"windflow_frontier_lag_seconds{_labels(**lab)} "
                   f"{lag / 1e3}")
    # event-time plane (eventtime/; docs/EVENTTIME.md): lateness and
    # event-time state gauges -- absent on non-event-time operators
    # (the replica records emit them only when nonzero)
    family("windflow_late_tuples", "counter",
           "tuples behind the allowed-lateness horizon (quarantined "
           "into the dead-letter store)")
    for _op, reps, lab in per_op():
        late = sum(int(r.get("Late_tuples", 0) or 0) for r in reps)
        if late:
            out.append(f"windflow_late_tuples_total{_labels(**lab)} "
                       f"{late}")
    family("windflow_sessions_open", "gauge",
           "live gap sessions held by session-window replicas")
    for _op, reps, lab in per_op():
        if any("Sessions_open" in r for r in reps):
            out.append(f"windflow_sessions_open{_labels(**lab)} "
                       f"{sum(int(r.get('Sessions_open', 0) or 0) for r in reps)}")
    family("windflow_join_state_keys", "gauge",
           "keys holding buffered two-input join state")
    for _op, reps, lab in per_op():
        if any("Join_state_keys" in r for r in reps):
            out.append(f"windflow_join_state_keys{_labels(**lab)} "
                       f"{sum(int(r.get('Join_state_keys', 0) or 0) for r in reps)}")
    family("windflow_parallelism", "gauge", "live replica count")
    for op, reps, lab in per_op():
        out.append(f"windflow_parallelism{_labels(**lab)} "
                   f"{int(op.get('Parallelism', len(reps)) or 0)}")
    family("windflow_service_time_seconds", "histogram",
           "sampled per-tuple service time")
    for op, _reps, lab in per_op():
        lat = op.get("Latency") or {}
        if lat.get("service"):
            _hist_lines(out, "windflow_service_time_seconds",
                        lat["service"], **lab)
    family("windflow_channel_residency_seconds", "histogram",
           "traced channel residency before the operator")
    for op, _reps, lab in per_op():
        lat = op.get("Latency") or {}
        if lat.get("residency"):
            _hist_lines(out, "windflow_channel_residency_seconds",
                        lat["residency"], **lab)

    for metric, field, help_ in (
            ("windflow_dropped_tuples", "Dropped_tuples",
             "mode-plane drops"),
            ("windflow_dead_letter_tuples", "Dead_letter_tuples",
             "tuples quarantined in the dead-letter store"),
            ("windflow_rescales", "Rescales",
             "completed runtime rescales")):
        family(metric, "counter", help_)
        for rep, lab in per_graph():
            out.append(f"{metric}_total{_labels(**lab)} "
                       f"{int(rep.get(field, 0) or 0)}")
    family("windflow_memory_bytes", "gauge", "process resident memory")
    for rep, lab in per_graph():
        out.append(f"windflow_memory_bytes{_labels(**lab)} "
                   f"{int(rep.get('Memory_usage_KB', 0) or 0) * 1024}")
    # audit plane: flow-conservation ledger state per graph
    family("windflow_conservation_violations", "counter",
           "flow-conservation ledger violations detected by the auditor")
    for rep, lab in per_graph():
        cons = rep.get("Conservation") or {}
        out.append(f"windflow_conservation_violations_total"
                   f"{_labels(**lab)} "
                   f"{int(cons.get('Violations_total', 0) or 0)}")
    family("windflow_conservation_balanced", "gauge",
           "1 when every audited edge's delivery books balance")
    for rep, lab in per_graph():
        cons = rep.get("Conservation") or {}
        if cons:
            out.append(f"windflow_conservation_balanced{_labels(**lab)} "
                       f"{1 if cons.get('Edges_balanced') else 0}")
    family("windflow_keyed_state_keys", "gauge",
           "keys held by a replica's keyed state (audit census)")
    for rep, lab in per_graph():
        skew = rep.get("Skew") or {}
        for row in skew.get("Census", []):
            out.append(
                f"windflow_keyed_state_keys"
                f"{_labels(**lab, replica=row.get('replica', ''))} "
                f"{int(row.get('keys', 0) or 0)}")
    family("windflow_keyed_state_bytes", "gauge",
           "keyed-state bytes by storage tier (tiered store census)")
    for rep, lab in per_graph():
        skew = rep.get("Skew") or {}
        for row in skew.get("Census", []):
            for tier, kb in (row.get("tiers") or {}).items():
                out.append(
                    f"windflow_keyed_state_bytes"
                    f"{_labels(**lab, replica=row.get('replica', ''), tier=tier)} "
                    f"{int(kb[1] if isinstance(kb, (list, tuple)) else kb)}")
    family("windflow_state_spills", "counter",
           "keys spilled to disk by tiered keyed-state stores")
    for rep, lab in per_graph():
        skew = rep.get("Skew") or {}
        for row in skew.get("Census", []):
            if "spills" in row:
                out.append(
                    f"windflow_state_spills_total"
                    f"{_labels(**lab, replica=row.get('replica', ''))} "
                    f"{int(row.get('spills', 0) or 0)}")
    family("windflow_hot_key_share", "gauge",
           "estimated share of the hottest key on a KEYBY edge")
    for rep, lab in per_graph():
        skew = rep.get("Skew") or {}
        for row in skew.get("Hot_keys", []):
            out.append(
                f"windflow_hot_key_share"
                f"{_labels(**lab, operator=row.get('operator', ''))} "
                f"{float(row.get('share', 0) or 0)}")
    # diagnosis plane (diagnosis/; docs/OBSERVABILITY.md): regression
    # episodes currently outside their EWMA+MAD band, and the dominant
    # bottleneck's pressure score (labelled with the operator the
    # root-cause walk named)
    family("windflow_regressions_active", "gauge",
           "gauge series currently outside their EWMA+MAD band")
    for rep, lab in per_graph():
        diag = rep.get("Diagnosis") or {}
        if diag:
            out.append(f"windflow_regressions_active{_labels(**lab)} "
                       f"{len(diag.get('Anomalies') or [])}")
    family("windflow_regressions", "counter",
           "regression episodes opened since graph start")
    for rep, lab in per_graph():
        diag = rep.get("Diagnosis") or {}
        if diag:
            out.append(f"windflow_regressions_total{_labels(**lab)} "
                       f"{int(diag.get('Anomalies_total', 0) or 0)}")
    family("windflow_bottleneck_score", "gauge",
           "pressure score of the dominant bottleneck operator named "
           "by the diagnosis root-cause walk")
    for rep, lab in per_graph():
        bn = (rep.get("Diagnosis") or {}).get("Bottleneck") or {}
        if bn.get("Operator"):
            out.append(
                f"windflow_bottleneck_score"
                f"{_labels(**lab, operator=bn['Operator'], verdict=bn.get('Verdict', ''))} "
                f"{float(bn.get('Score', 0) or 0)}")
    # SLO plane (slo/; docs/OBSERVABILITY.md "SLO plane"): burn-rate
    # tracker gauges -- absent entirely with no declared objectives
    family("windflow_slo_breached", "gauge",
           "1 while an SLO breach episode is open")
    for rep, lab in per_graph():
        slo = rep.get("Slo")
        if slo:
            out.append(f"windflow_slo_breached{_labels(**lab)} "
                       f"{1 if slo.get('Breached') else 0}")
    family("windflow_slo_burn_rate", "gauge",
           "error-budget burn rate over the fast/slow window "
           "(1 = burning exactly at the target rate)")
    for rep, lab in per_graph():
        slo = rep.get("Slo")
        if slo:
            for win in ("fast", "slow"):
                out.append(
                    f"windflow_slo_burn_rate"
                    f"{_labels(**lab, window=win)} "
                    f"{float(slo.get(f'Burn_rate_{win}', 0) or 0)}")
    family("windflow_slo_budget_burned", "gauge",
           "fraction of the slow window's error budget consumed "
           "(> 1 = overdrawn)")
    for rep, lab in per_graph():
        slo = rep.get("Slo")
        if slo:
            out.append(f"windflow_slo_budget_burned{_labels(**lab)} "
                       f"{float(slo.get('Budget_burned', 0) or 0)}")
    family("windflow_slo_breaches", "counter",
           "SLO breach episodes opened since graph start")
    for rep, lab in per_graph():
        slo = rep.get("Slo")
        if slo:
            out.append(f"windflow_slo_breaches_total{_labels(**lab)} "
                       f"{int(slo.get('Breaches_total', 0) or 0)}")
    # serving plane (serving/; docs/SERVING.md): per-tenant identity +
    # live lease -- absent entirely outside a multi-tenant Server
    def per_tenant():
        for rep, lab in per_graph():
            t = rep.get("Tenant")
            if t:
                yield t, dict(lab, tenant=t.get("Name", ""))

    family("windflow_tenant_up", "gauge",
           "1 while the tenant's graph is RUNNING under its server")
    for t, lab in per_tenant():
        out.append(f"windflow_tenant_up{_labels(**lab)} "
                   f"{1 if t.get('State') == 'RUNNING' else 0}")
    family("windflow_tenant_credits", "gauge",
           "live ingest-credit lease under the server's global cap")
    for t, lab in per_tenant():
        out.append(f"windflow_tenant_credits{_labels(**lab)} "
                   f"{int(t.get('Credits', 0) or 0)}")
    family("windflow_tenant_priority", "gauge",
           "arbiter standing: higher = protected longer")
    for t, lab in per_tenant():
        out.append(f"windflow_tenant_priority{_labels(**lab)} "
                   f"{int(t.get('Priority', 0) or 0)}")
    family("windflow_tenant_weight", "gauge",
           "arbiter tie-break inside one priority class")
    for t, lab in per_tenant():
        out.append(f"windflow_tenant_weight{_labels(**lab)} "
                   f"{float(t.get('Weight', 0) or 0)}")
    family("windflow_tenant_arbitrations", "counter",
           "arbitration decisions this tenant was part of "
           "(victim or donor)")
    for t, lab in per_tenant():
        out.append(f"windflow_tenant_arbitrations_total{_labels(**lab)} "
                   f"{int(t.get('Arbitrations', 0) or 0)}")
    # scheduler plane (scheduler/; docs/SERVING.md "Global
    # scheduler"): fair-share gate waits, fleet placement identity and
    # device leases -- absent entirely when no worker runs the plane
    family("windflow_sched_wait_seconds", "counter",
           "time consume loops spent blocked in the fair-share gate")
    for _op, reps, lab in per_op():
        waited = sum(float(r.get("Sched_wait_s", 0) or 0) for r in reps)
        if any("Sched_wait_s" in r for r in reps):
            out.append(f"windflow_sched_wait_seconds_total"
                       f"{_labels(**lab)} {round(waited, 3)}")

    def sched_placements():
        for rep, lab in per_graph():
            sched = rep.get("Scheduler")
            if not sched:
                continue
            # worker-local block carries its own Placements; a merged
            # fleet view concatenates them under the same key
            for row in sched.get("Placements") or ():
                yield row, lab

    family("windflow_tenant_worker", "gauge",
           "1 for the worker currently hosting the tenant "
           "(fleet placement identity)")
    for row, lab in sched_placements():
        out.append(
            f"windflow_tenant_worker"
            f"{_labels(**lab, tenant=row.get('Tenant', ''), worker=row.get('Worker', ''))}"
            f" 1")
    family("windflow_device_lease", "gauge",
           "device-lane leases held by the tenant on the worker's chip")
    lease_counts: dict = {}
    for rep, lab in per_graph():
        sched = rep.get("Scheduler")
        if not sched:
            continue
        blocks = [sched.get("Devices")] if sched.get("Devices") \
            else [b.get("Devices") for b in sched.get("Workers") or ()
                  if isinstance(b, dict) and b.get("Devices")]
        for dev in blocks:
            for row in dev.get("Leases") or ():
                key = (tuple(sorted(lab.items())),
                       row.get("Tenant", ""))
                lease_counts[key] = lease_counts.get(key, 0) + 1
    for (lab_items, tenant), n in sorted(lease_counts.items(),
                                         key=lambda kv: kv[0]):
        out.append(f"windflow_device_lease"
                   f"{_labels(**dict(lab_items), tenant=tenant)} {n}")
    # ColumnPool arena occupancy (memory-pressure evidence next to
    # windflow_memory_bytes)
    family("windflow_pool_bytes", "gauge",
           "bytes held by the graph's ColumnPool arena")
    for rep, lab in per_graph():
        pool = rep.get("Pool")
        if pool:
            out.append(f"windflow_pool_bytes{_labels(**lab)} "
                       f"{int(pool.get('Bytes', 0) or 0)}")
    family("windflow_pool_buffers", "gauge",
           "buffers held by the graph's ColumnPool arena")
    for rep, lab in per_graph():
        pool = rep.get("Pool")
        if pool:
            out.append(f"windflow_pool_buffers{_labels(**lab)} "
                       f"{int(pool.get('Buffers', 0) or 0)}")
    # durability plane (durability/; docs/RESILIENCE.md): epoch
    # coordinator gauges -- absent entirely when the plane is off
    family("windflow_epoch", "gauge",
           "last durably committed epoch id")
    for rep, lab in per_graph():
        dur = rep.get("Durability") or {}
        if dur:
            out.append(f"windflow_epoch{_labels(**lab)} "
                       f"{int(dur.get('Committed_epoch', 0) or 0)}")
    family("windflow_epoch_lag_seconds", "gauge",
           "age of the oldest uncommitted epoch (0 when current)")
    for rep, lab in per_graph():
        dur = rep.get("Durability") or {}
        if dur:
            out.append(f"windflow_epoch_lag_seconds{_labels(**lab)} "
                       f"{float(dur.get('Epoch_lag_s', 0) or 0)}")
    family("windflow_epoch_commit_seconds", "gauge",
           "wall time of the last manifest commit + sink release")
    for rep, lab in per_graph():
        dur = rep.get("Durability") or {}
        if dur:
            out.append(f"windflow_epoch_commit_seconds{_labels(**lab)} "
                       f"{float(dur.get('Last_commit_s', 0) or 0)}")
    family("windflow_epoch_stalled", "gauge",
           "1 while the oldest uncommitted epoch exceeds the stall "
           "threshold")
    for rep, lab in per_graph():
        dur = rep.get("Durability") or {}
        if dur:
            out.append(f"windflow_epoch_stalled{_labels(**lab)} "
                       f"{1 if dur.get('Stalled') else 0}")
    family("windflow_epoch_commit_bytes", "gauge",
           "manifest + staged blob bytes written by the last epoch "
           "commit (delta snapshots shrink this under low churn)")
    for rep, lab in per_graph():
        dur = rep.get("Durability") or {}
        if dur:
            out.append(f"windflow_epoch_commit_bytes{_labels(**lab)} "
                       f"{int(dur.get('Last_commit_bytes', 0) or 0)}")
    family("windflow_replica_restarts", "counter",
           "supervised replica restarts healed in place "
           "(durability/supervision.py)")
    for rep, lab in per_graph():
        dur = rep.get("Durability") or {}
        if dur:
            out.append(f"windflow_replica_restarts{_labels(**lab)} "
                       f"{int(dur.get('Replica_restarts', 0) or 0)}")
    family("windflow_e2e_latency_seconds", "histogram",
           "traced source-to-sink latency")
    for rep, lab in per_graph():
        e2e = rep.get("Latency_e2e")
        if e2e:
            _hist_lines(out, "windflow_e2e_latency_seconds", e2e, **lab)

    out.append("# EOF")
    return "\n".join(out) + "\n"
