"""Sampled end-to-end tuple tracing (docs/OBSERVABILITY.md).

A :class:`TraceContext` is attached to a deterministic 1-in-N sample of
items at the source (``TraceSampler``), rides the item through
channels, KEYBY shuffles, fused segments and the device dispatcher
(the ``trace`` slot on TupleBatch / SynthChunk / BasicRecord, which
``take``/``concat``/``materialize`` propagate), collects one
(operator, arrive, done) hop stamp per operator it crosses, and is
closed at the sink into the per-replica latency histograms:

* **channel residency** per consuming operator:
  ``arrive - previous hop's done`` (time parked in the channel plus
  the emit->enqueue skew of the upstream batch flush);
* **end-to-end**: ``sink done - source stamp`` into the sink replica's
  e2e histogram (graph-wide after the report-time merge).

Per-operator *service* histograms are fed independently by the
runtime's existing sampled ``StatsRecord.observe`` path, so they cover
every replica even between trace samples.

A context forked by a KEYBY partition rides every sub-batch; each path
that reaches a sink closes once (one e2e sample per path).  Hop lists
and ``last`` stamps are then shared across threads -- list.append is
GIL-atomic and the stamps are gauge-grade, like every other telemetry
read in this plane.
"""
from __future__ import annotations

import time as _time
from typing import Optional

from .histogram import LogHistogram

# default 1-in-N source sampling period (RuntimeConfig.trace_sample)
DEFAULT_TRACE_SAMPLE = 128
# hop stamps kept per context (a pathological graph cannot grow it)
MAX_HOPS = 64


class TraceContext:
    """Per-sampled-item trace state: source stamp + per-hop stamps.

    ``trace_id`` names the trace across process boundaries: the
    sampler stamps ``<source>#<n>`` (deterministic per source
    replica), the wire codec ships it in the frame header, and the
    cross-worker merge (distributed/observe.stitch_traces) joins
    per-worker partial records back into one e2e record by it."""

    __slots__ = ("src", "t0", "last", "hops", "trace_id")

    def __init__(self, src: str, t0: float,
                 trace_id: Optional[str] = None):
        self.src = src
        self.t0 = t0
        self.last = t0          # most recent 'done' stamp (residency base)
        self.hops: list = []    # (operator, t_arrive, t_done)
        self.trace_id = trace_id

    def hop(self, name: str, t_in: float, t_done: float,
            meta: Optional[dict] = None) -> None:
        """Record one hop stamp.  ``meta`` (optional, gauge-grade)
        rides as a trailing dict on the serialized hop -- the device
        engines use it to carry launch count + transfer bytes on their
        ``@device`` hops so a whole-partition step (graph/device_step)
        stays attributable as ONE launch per chunk.  Readers index
        ``hop[0..2]`` and must ignore extra elements."""
        if len(self.hops) < MAX_HOPS:
            self.hops.append((name, t_in, t_done) if meta is None
                             else (name, t_in, t_done, meta))
        self.last = t_done

    def to_dict(self, t_end: float) -> dict:
        t0 = self.t0
        d = {
            "src": self.src,
            "e2e_ms": round((t_end - t0) * 1e3, 3),
            "hops": [[name, round((a - t0) * 1e3, 3),
                      round((d - t0) * 1e3, 3), *rest]
                     for name, a, d, *rest in self.hops],
        }
        if self.trace_id is not None:
            d["id"] = self.trace_id
        return d


def get_trace(item) -> Optional[TraceContext]:
    """The context riding ``item``, or None (unset slot / untraceable
    type both read as None)."""
    return getattr(item, "trace", None)


def attach(item, ctx: TraceContext) -> bool:
    """Attach ``ctx`` to ``item`` if its type carries a trace slot."""
    try:
        item.trace = ctx
        return True
    except AttributeError:
        return False


def attach_if_absent(item, ctx: TraceContext) -> None:
    if getattr(item, "trace", None) is None:
        try:
            item.trace = ctx
        except AttributeError:
            pass


class TraceSampler:
    """Deterministic 1-in-N sampling at a source replica: the N-th,
    2N-th, ... emitted item starts a trace (independent of wall time,
    so a rerun of the same stream samples the same items)."""

    __slots__ = ("period", "src", "_n", "started")

    def __init__(self, period: int, src: str):
        self.period = max(1, int(period))
        self.src = src
        self._n = 0
        self.started = 0

    def maybe_attach(self, item) -> None:
        self._n += 1
        if self._n >= self.period:
            # the slot is only consumed by an item that can carry a
            # context -- an untraceable item (dict, control marker)
            # landing on the N-th emission defers the sample to the
            # next attachable one instead of silently eating it
            ctx = TraceContext(self.src, _time.perf_counter(),
                               trace_id=f"{self.src}#{self.started + 1}")
            if attach(item, ctx):
                self._n = 0
                self.started += 1


class TelemetryHub:
    """Per-graph tracing coordinator: owns the sampling period, hands
    samplers to source nodes, and closes contexts at sinks into the
    histogram plane (monitoring/stats.py)."""

    def __init__(self, stats, sample_period: int = DEFAULT_TRACE_SAMPLE):
        self.stats = stats
        self.sample_period = max(1, int(sample_period))
        self.samplers: list = []
        self.closed = 0

    def sampler_for(self, node_name: str,
                    period: Optional[int] = None) -> TraceSampler:
        s = TraceSampler(period or self.sample_period, node_name)
        self.samplers.append(s)
        return s

    def close(self, ctx: TraceContext, rec, t_end: float) -> None:
        """Close one trace at a sink replica: e2e sample into the
        replica's histogram (or the graph-level fallback when the sink
        is untraced) plus a bounded recent-trace record.  The record
        keeps the LIVE context and serializes at report time: in a
        fused chain the upstream segments' hop stamps land moments
        AFTER the sink segment closes (their entries unwind outward),
        so an eager to_dict here would drop them."""
        self.closed += 1
        h = None
        if rec is not None:
            h = rec.e2e_hist
            if h is None:
                h = rec.e2e_hist = LogHistogram()
        if h is None:
            h = self.stats.e2e_extra
        if h is not None:
            h.observe((t_end - ctx.t0) * 1e6)
        self.stats.add_trace_record((ctx, t_end))
