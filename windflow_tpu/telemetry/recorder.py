"""Flight recorder: a bounded per-graph ring of structured runtime
events (docs/OBSERVABILITY.md).

Counters tell an operator *how much*; the flight recorder tells them
*what happened just before it went wrong*: rescales, placement
decisions, adaptive-batch resizes, credit stalls, admission sheds, svc
failures, checkpoint epochs, watchdog stalls -- and, since the audit
plane (audit/), ``conservation_violation`` (the flow ledger caught a
lost/duplicated delivery) and ``frontier_stall`` (an operator's
progress frontier froze while work was pending).  Events append into a
``deque(maxlen=N)`` (GIL-atomic, no lock on the hot path) and the ring
is dumped as JSONL by the stall watchdog, the ``NodeFailureError``
path in ``PipeGraph.wait_end``, and the auditor's final closure check
when it finds violations, so a post-mortem always has the last N
events of history even though the process is about to unwind.
"""
from __future__ import annotations

import json
import os
import time
from collections import deque
from itertools import count
from typing import List, Optional


class FlightRecorder:
    """Bounded structured-event ring.  ``record()`` is safe from any
    thread; ``capacity <= 0`` disables recording entirely.

    Every event carries a per-recorder monotone ``seq``: the live
    cluster view ships bounded flight *deltas* (events past the last
    acknowledged seq) and the cross-worker merge dedups overlapping
    tails by ``(worker, seq)`` (distributed/observe.py)."""

    __slots__ = ("_ring", "enabled", "dumped_path", "_seq")

    def __init__(self, capacity: int = 512):
        self.enabled = capacity > 0
        self._ring: deque = deque(maxlen=max(1, capacity))
        self.dumped_path: Optional[str] = None
        self._seq = count(1)  # itertools.count: GIL-atomic next()

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        ev = {"t": round(time.time(), 6), "seq": next(self._seq),
              "kind": kind}
        ev.update(fields)
        self._ring.append(ev)

    def snapshot(self) -> List[dict]:
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self, log_dir: str, graph_name: str,
             keep: Optional[int] = None) -> Optional[str]:
        """Write the ring as JSONL under ``log_dir``; returns the path
        (best-effort: an unwritable log dir must not mask the failure
        being post-mortemed).  ``keep`` > 0 additionally rotates the
        log dir's per-run artifact families down to the newest N
        (monitoring.rotate_snapshots), so repeated supervised dumps do
        not grow ``log/`` without bound."""
        if not self.enabled:
            return None
        try:
            # worker-id component (distributed/identity.py): a worker's
            # post-mortem must not clobber its box-mates'
            from ..distributed.identity import worker_suffix
            os.makedirs(log_dir, exist_ok=True)
            path = os.path.join(
                log_dir,
                f"{os.getpid()}_{graph_name}{worker_suffix()}"
                "_flight.jsonl")
            with open(path, "w") as f:
                for ev in self.snapshot():
                    f.write(json.dumps(ev, default=str) + "\n")
            self.dumped_path = path
            if keep:
                from ..monitoring.monitor import rotate_snapshots
                rotate_snapshots(log_dir, keep)
            return path
        except OSError:
            return None
