"""Lock-free log-bucketed latency histograms (docs/OBSERVABILITY.md).

HDR-style fixed bucket array: 4 sub-buckets per power of two over
[1 us, ~2^28 us ≈ 268 s] plus one overflow bucket, so any latency this
runtime can produce lands in a constant-time increment with <= 19%
relative quantile error (the 2^(1/4) bucket ratio).

Concurrency model (the reason there is no lock): every histogram has
exactly ONE writer -- the replica thread that owns its StatsRecord --
and `merged()` combines the per-replica instances at report time.
Readers (monitoring thread, /metrics renderer) see gauge-grade
snapshots: a read racing a write may lag by one observation, which is
the same contract as the channel depth gauges (runtime/queues.py).
"""
from __future__ import annotations

from math import log2
from typing import Iterable, List, Optional

# sub-buckets per octave; bucket i spans [2^(i/SUB), 2^((i+1)/SUB)) us
SUB = 4
# 28 octaves: 2^28 us ~ 268 s, far beyond any sane streaming latency
N_BUCKETS = 28 * SUB + 1  # +1 overflow


def bucket_le_us(i: int) -> float:
    """Inclusive upper bound (microseconds) of bucket ``i``."""
    if i >= N_BUCKETS - 1:
        return float("inf")
    return 2.0 ** ((i + 1) / SUB)


class LogHistogram:
    """Fixed-array log2 histogram over microsecond latencies."""

    __slots__ = ("counts", "count", "sum_us", "max_us")

    def __init__(self):
        self.counts: List[int] = [0] * N_BUCKETS
        self.count = 0
        self.sum_us = 0.0
        self.max_us = 0.0

    def observe(self, v_us: float) -> None:
        """Record one latency (microseconds).  Single-writer."""
        if v_us < 0.0:
            # gauge-grade stamps can race a few us backwards (a fused
            # producer stamps ctx.last after its emit); a negative
            # duration must not drive sum_us backwards -- Prometheus
            # reads any _sum decrease as a counter reset
            v_us = 0.0
        self.count += 1
        self.sum_us += v_us
        if v_us > self.max_us:
            self.max_us = v_us
        i = int(log2(v_us) * SUB) if v_us > 1.0 else 0
        if i >= N_BUCKETS:
            i = N_BUCKETS - 1
        self.counts[i] += 1

    # -- merge plane (report-time aggregation across replicas) ----------
    def merge_from(self, other: "LogHistogram") -> None:
        oc = other.counts
        c = self.counts
        for i in range(N_BUCKETS):
            c[i] += oc[i]
        self.count += other.count
        self.sum_us += other.sum_us
        if other.max_us > self.max_us:
            self.max_us = other.max_us

    @classmethod
    def merged(cls, hists: Iterable[Optional["LogHistogram"]]) \
            -> "LogHistogram":
        out = cls()
        for h in hists:
            if h is not None:
                out.merge_from(h)
        return out

    # -- queries ---------------------------------------------------------
    def percentile(self, q: float) -> float:
        """Upper bucket bound (us) of the q-quantile (q in [0, 1]).
        The overflow bucket reports the observed max instead of inf."""
        n = self.count
        if n == 0:
            return 0.0
        target = max(1, int(q * n + 0.9999999))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                le = bucket_le_us(i)
                return self.max_us if le == float("inf") else le
        return self.max_us

    def bucket_pairs(self) -> List[List[float]]:
        """Sparse non-cumulative [le_us, count] pairs (non-empty
        buckets only); the OpenMetrics renderer cumulates them."""
        out = []
        for i, c in enumerate(self.counts):
            if c:
                le = bucket_le_us(i)
                out.append([round(le, 3) if le != float("inf") else -1.0,
                            c])
        return out

    def to_dict(self, buckets: bool = False) -> dict:
        d = {
            "n": self.count,
            "mean_us": round(self.sum_us / self.count, 1) if self.count
            else 0.0,
            "p50_us": round(self.percentile(0.50), 1),
            "p95_us": round(self.percentile(0.95), 1),
            "p99_us": round(self.percentile(0.99), 1),
            "max_us": round(self.max_us, 1),
        }
        if buckets:
            d["sum_us"] = round(self.sum_us, 1)
            # le -1.0 encodes the overflow (+Inf) bucket in JSON
            d["buckets"] = self.bucket_pairs()
        return d
