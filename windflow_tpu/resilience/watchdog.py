"""Stall watchdog: liveness monitoring for a running PipeGraph.

A graph can hang without any replica raising: a dead-but-undetected
consumer, a livelocked user function, an exhausted external resource.
The watchdog samples a graph-wide progress counter (channel ``gets``
plus per-node completed items); when it does not advance for
``deadline_s`` while replica threads are still alive, it dumps a
diagnostic report (per-node channel depth / high-watermark / put-get
counters plus every Python thread's stack) under ``log_dir`` and --
when ``cancel`` is set -- cancels the graph through its CancelToken
with a :class:`StallError`, so ``wait_end`` returns instead of joining
forever.

Enable per graph via ``RuntimeConfig.watchdog_timeout_s`` (None =
disabled; ``watchdog_cancel`` picks dump-only vs dump-and-cancel).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Optional

from .errors import StallError


def _thread_stacks() -> str:
    """Formatted stacks of every live Python thread (the py-spy-style
    dump that makes a deadlock diagnosable post mortem)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} (ident {ident}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


def stall_report(graph) -> dict:
    """Channel-depth snapshot of every consumer node plus thread
    stacks.  When the audit plane is on (audit/), each row also
    carries the node's frontier watermark and lag -- the stalled node
    is usually the one whose frontier froze first."""
    channels = []
    auditor = getattr(graph, "auditor", None)
    frontiers = auditor.tracker.frontiers if auditor is not None else {}
    for n in graph._all_nodes():
        ch = n.channel
        row = {
            "node": n.name,
            "alive": n.is_alive(),
            "taken": n.taken,
            "done": n.done,
        }
        fr = frontiers.get(n.name)
        if fr is not None:
            row["frontier"] = round(fr["frontier"], 1)
            row["frontier_lag_ms"] = round(fr["lag_ms"], 1)
            row["frontier_stalled"] = fr["stalled"]
        if ch is not None:
            row.update({
                "channel_impl": type(ch).__name__,
                "depth": ch.qsize(),
                "capacity": getattr(ch, "capacity", None),
                "puts": getattr(ch, "puts", 0),
                "gets": getattr(ch, "gets", 0),
                "high_watermark": getattr(ch, "high_watermark", 0),
            })
        channels.append(row)
    return {
        "graph": graph.name,
        "time": time.time(),
        "nodes": channels,
        "thread_stacks": _thread_stacks(),
    }


def dump_stall_report(graph, log_dir: str) -> str:
    """Write the stall report JSON; returns the file path."""
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir,
                        f"{os.getpid()}_{graph.name}_stall.json")
    with open(path, "w") as f:
        json.dump(stall_report(graph), f, indent=1)
    return path


class StallWatchdog(threading.Thread):
    """Monitor thread owned by a PipeGraph (started/stopped with it)."""

    def __init__(self, graph, deadline_s: float, poll_s: float = None,
                 cancel: bool = True):
        super().__init__(name=f"windflow-watchdog-{graph.name}",
                         daemon=True)
        self.graph = graph
        self.deadline_s = deadline_s
        self.poll_s = poll_s if poll_s is not None \
            else max(0.05, min(1.0, deadline_s / 4))
        self.cancel = cancel
        self._stop_evt = threading.Event()
        self.fired = False
        self.report_path: Optional[str] = None

    def _progress(self) -> int:
        from ..runtime.node import FusedLogic
        total = 0
        for n in self.graph._all_nodes():
            total += n.done
            if isinstance(n.logic, FusedLogic):
                # fused stages process inline (no channel hop): their
                # per-segment take counters are the progress signal --
                # without them a fully fused source-headed pipeline
                # would look stalled forever
                for seg in n.logic.segments:
                    total += seg.taken
            ch = n.channel
            if ch is not None:
                total += getattr(ch, "gets", 0)
        return total

    def run(self) -> None:
        last = self._progress()
        last_change = time.monotonic()
        while not self._stop_evt.wait(self.poll_s):
            nodes = self.graph._all_nodes()
            if not any(n.is_alive() for n in nodes):
                return  # graph finished between polls
            pause = self.graph._pause_ctl
            if pause is not None and pause.pausing:
                last_change = time.monotonic()  # checkpoint barrier
                continue
            cur = self._progress()
            if cur != last:
                last, last_change = cur, time.monotonic()
                continue
            if time.monotonic() - last_change < self.deadline_s:
                continue
            self.fired = True
            try:
                self.report_path = dump_stall_report(
                    self.graph, self.graph.config.log_dir)
            except OSError:
                self.report_path = None
            # flight recorder (telemetry/recorder.py): the stall event
            # plus the last-N-events history next to the channel dump,
            # so the post-mortem sees what led up to the stall
            flight = getattr(self.graph, "flight", None)
            if flight is not None:
                flight.record("stall", deadline_s=self.deadline_s,
                              report=self.report_path,
                              cancelling=self.cancel)
                flight.dump(self.graph.config.log_dir, self.graph.name)
            if self.cancel:
                err = StallError(
                    f"graph {self.graph.name!r} made no progress for "
                    f"{self.deadline_s:.1f}s; channel/thread dump at "
                    f"{self.report_path}")
                self.graph._cancel.cancel(err, origin="watchdog")
                return
            last_change = time.monotonic()  # dump-only: re-arm

    def stop(self) -> None:
        self._stop_evt.set()
        self.join(timeout=5.0)
