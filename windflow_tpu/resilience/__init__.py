"""Failure containment for PipeGraphs (a layer the reference lacks,
SURVEY.md §5: "failure detection / elastic recovery: Absent").

Four cooperating pieces:

* :mod:`~windflow_tpu.resilience.cancel` -- graph-wide CancelToken +
  poisoned channels, so a dead replica can never deadlock the graph;
* :mod:`~windflow_tpu.resilience.policies` -- per-operator error
  policies (``fail`` / ``skip`` / ``dead_letter``) and the graph
  dead-letter store;
* :mod:`~windflow_tpu.resilience.watchdog` -- the stall watchdog
  (progress monitoring, channel/thread dumps, optional cancellation);
* :mod:`~windflow_tpu.resilience.faults` -- the deterministic seeded
  fault-injection harness the recovery tests drive.

See docs/RESILIENCE.md for the user-facing guide.
"""
from .cancel import CancelToken, GraphCancelled
from .errors import NodeFailureError, StallError
from .faults import FaultPlan, InjectedFailure, NodeFaults
from .policies import (DeadLetterEntry, DeadLetterStore, ERROR_POLICIES,
                       POLICY_DEAD_LETTER, POLICY_FAIL, POLICY_SKIP,
                       validate_policy)
from .watchdog import StallWatchdog, dump_stall_report, stall_report

__all__ = [
    "CancelToken", "GraphCancelled", "NodeFailureError", "StallError",
    "FaultPlan", "InjectedFailure", "NodeFaults", "DeadLetterEntry",
    "DeadLetterStore", "ERROR_POLICIES", "POLICY_DEAD_LETTER",
    "POLICY_FAIL", "POLICY_SKIP", "validate_policy", "StallWatchdog",
    "dump_stall_report", "stall_report",
]
