"""Per-operator error policies and the graph dead-letter store.

Policy semantics (selected per operator from the builders via
``.with_error_policy(...)``; the default matches the reference, where
any svc exception kills the replica):

* ``'fail'``        -- the exception propagates, the replica dies and
                       the graph is cancelled (CancelToken).
* ``'skip'``        -- the offending tuple is dropped, a per-replica
                       failure counter increments, the replica lives.
* ``'dead_letter'`` -- like skip, but the tuple is quarantined (with
                       node name, error and traceback) into the
                       graph-level :class:`DeadLetterStore`, readable
                       after ``wait_end``.

Policies apply to per-tuple ``svc`` processing only; source generation
loops and EOS flushes always fail hard (there is no offending tuple to
quarantine).
"""
from __future__ import annotations

import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List

POLICY_FAIL = "fail"
POLICY_SKIP = "skip"
POLICY_DEAD_LETTER = "dead_letter"
ERROR_POLICIES = (POLICY_FAIL, POLICY_SKIP, POLICY_DEAD_LETTER)


def validate_policy(policy: str) -> str:
    if policy not in ERROR_POLICIES:
        raise ValueError(
            f"unknown error policy {policy!r}; expected one of "
            f"{ERROR_POLICIES}")
    return policy


@dataclass
class DeadLetterEntry:
    """One quarantined tuple."""

    node: str                       # replica (RtNode) name
    item: Any                       # the offending tuple itself
    error: BaseException
    traceback: str                  # formatted traceback text
    time: float = field(default_factory=time.time)

    def __repr__(self) -> str:
        return (f"DeadLetterEntry(node={self.node!r}, "
                f"error={self.error!r}, item={self.item!r})")


class DeadLetterStore:
    """Graph-level quarantine of poisoned tuples (bounded, thread-safe).

    ``max_entries`` bounds memory: beyond it only the counters advance
    (the count is exact, the retained sample is the earliest entries).
    """

    def __init__(self, max_entries: int = 10_000):
        self._lock = threading.Lock()
        self._entries: List[DeadLetterEntry] = []
        self._count = 0
        self._by_node: Dict[str, int] = {}
        self.max_entries = max_entries

    def add(self, node: str, item: Any, error: BaseException,
            count: int = 1) -> None:
        """Quarantine one entry advancing the counters by ``count``
        tuples.  Bulk callers (ingest admission shedding) pass the shed
        total with a sample batch as ``item`` -- a columnar overload
        must not cost one store entry per tuple."""
        # format the traceback OF THE GIVEN ERROR, not whatever
        # exception happens to be ambient (format_exc would record
        # "NoneType: None" when called outside an except block)
        tb = "".join(traceback.format_exception(
            type(error), error, error.__traceback__))
        entry = DeadLetterEntry(node, item, error, tb)
        with self._lock:
            self._count += count
            self._by_node[node] = self._by_node.get(node, 0) + count
            if len(self._entries) < self.max_entries:
                self._entries.append(entry)

    @property
    def entries(self) -> List[DeadLetterEntry]:
        with self._lock:
            return list(self._entries)

    def count(self) -> int:
        with self._lock:
            return self._count

    def counts_by_node(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._by_node)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_node.clear()
            self._count = 0

    def __len__(self) -> int:
        return self.count()

    def __bool__(self) -> bool:
        return self.count() > 0
