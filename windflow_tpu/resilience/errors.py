"""Failure-containment exception types.

Defined here (below both the graph and runtime layers) so the
watchdog, the checkpoint/recovery runner and PipeGraph can all share
them without import cycles.  ``graph.pipegraph`` re-exports
``NodeFailureError`` at its historical location.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple


class NodeFailureError(RuntimeError):
    """A replica thread died at runtime (vs. graph-validation errors,
    which raise plain RuntimeError/ValueError and are not recoverable
    by restarting -- utils/checkpoint.run_with_recovery retries only
    this type).

    ``errors`` carries every failed replica as ``(node_name, error)``
    pairs -- cancellation guarantees ``wait_end`` observes all of them,
    not just the first.
    """

    def __init__(self, message: str,
                 errors: Optional[Sequence[Tuple[str, BaseException]]] = None):
        super().__init__(message)
        self.errors: List[Tuple[str, BaseException]] = list(errors or [])

    @classmethod
    def from_pairs(cls, errors: Sequence[Tuple[str, BaseException]],
                   stuck: Sequence[str] = ()) -> "NodeFailureError":
        detail = "; ".join(f"{name}: {err!r}" for name, err in errors)
        msg = f"{len(errors)} node(s) failed: {detail}"
        if stuck:
            msg += ("; nodes still running after cancellation grace: "
                    + ", ".join(stuck))
        return cls(msg, errors)


class StallError(NodeFailureError):
    """The stall watchdog cancelled the graph: no channel made progress
    for the configured deadline.  Subclasses NodeFailureError so
    ``run_with_recovery`` treats a stalled run as retryable."""
