"""Graph-wide cancellation: the failure-containment primitive.

The reference has no failure layer at all (SURVEY.md §5): an ff_node
that throws takes its thread down and leaves every upstream producer
blocked on a full bounded queue.  windflow_tpu's containment design is
a single **CancelToken** per PipeGraph holding every channel of the
wired graph.  When any replica dies (or a watchdog fires), the token
poisons every channel in both directions: blocked ``put()``s and
``get()``s wake immediately and raise :class:`GraphCancelled`, which
the runtime node treats as a clean shutdown signal rather than a
failure -- so ``wait_end`` always returns, carrying only the *real*
errors.
"""
from __future__ import annotations

import threading
from typing import Any, List, Optional


class GraphCancelled(BaseException):
    """Raised by channel put/get once the owning graph is cancelled.

    Deliberately a ``BaseException`` (like ``asyncio.CancelledError``):
    operator error policies and user ``except Exception`` blocks must
    not swallow the shutdown signal.
    """


class CancelToken:
    """One per PipeGraph: fans a cancellation out to every channel.

    Channels (anything with a ``poison()`` method) register at graph
    start.  ``cancel(reason)`` is idempotent -- the first reason wins,
    later calls are no-ops -- and poisons every registered channel so
    all blocked channel operations raise :class:`GraphCancelled`.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._channels: List[Any] = []
        self._event = threading.Event()
        self.reason: Optional[BaseException] = None
        self.origin: Optional[str] = None  # node name that triggered it

    def register(self, channel: Any) -> None:
        with self._lock:
            self._channels.append(channel)
            poisoned = self._event.is_set()
        if poisoned:  # late registration after a cancel: poison now
            channel.poison()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def cancel(self, reason: Optional[BaseException] = None,
               origin: Optional[str] = None) -> bool:
        """Poison every channel; returns False if already cancelled."""
        with self._lock:
            if self._event.is_set():
                return False
            self.reason = reason
            self.origin = origin
            self._event.set()
            channels = list(self._channels)
        for ch in channels:
            ch.poison()
        return True
