"""Deterministic seeded fault-injection harness.

Recovery paths that only fire under failure are untestable without a
way to *cause* failure on demand.  A :class:`FaultPlan` describes, up
front and reproducibly, which faults fire where:

* ``crash_replica(node_substr, at_tuple)`` -- the matching replica
  raises :class:`InjectedFailure` when it takes its Nth tuple (1-based),
  simulating a mid-stream replica death;
* ``delay_puts(node_substr, delay_s, every_n)`` -- the matching
  replica sleeps before every Nth downstream put (seeded jitter),
  simulating a slow consumer / full-channel backpressure window;
* ``fail_native_build()`` -- the native toolchain probe is forced to
  fail, exercising the pure-Python fallback (and its warning);
* ``drop_put(node_substr, at_put)`` / ``dup_put(node_substr, at_put)``
  -- the matching replica's Nth channel delivery (1-based, counted at
  the Outlet layer across all destinations) is silently lost "on the
  wire" / delivered twice.  These simulate transport-plane conservation
  bugs: the emitted item is counted as intent but never (or doubly)
  reaches the channel, which the audit plane's flow ledger
  (audit/ledger.py) must flag as a conservation violation.

Attach a plan via ``RuntimeConfig.fault_plan``; ``PipeGraph.start``
binds per-node fault state (each node's counters are independent, so a
plan is deterministic regardless of thread interleaving).  Use as a
context manager to guarantee global faults (native build) are undone::

    with FaultPlan(seed=7).crash_replica("map", at_tuple=50) as plan:
        cfg = RuntimeConfig(fault_plan=plan)
        ...
"""
from __future__ import annotations

import random
import threading
import time
from typing import List, Optional


class InjectedFailure(RuntimeError):
    """Raised by a FaultPlan crash rule inside the replica loop."""


# -- forced native-build failure (module-global: the native module probes
# this from _build(), which can run before any graph exists) --------------
_native_fail_lock = threading.Lock()
_native_fail_count = 0


def native_build_forced_to_fail() -> bool:
    return _native_fail_count > 0


def _reset_native_cache() -> None:
    """Drop the cached native lib so the next probe re-runs _build()."""
    from ..runtime import native as _native
    with _native._lib_lock:
        _native._lib = None


def _arm_native_failure() -> None:
    global _native_fail_count
    with _native_fail_lock:
        _native_fail_count += 1
    _reset_native_cache()


def _disarm_native_failure() -> None:
    global _native_fail_count
    with _native_fail_lock:
        _native_fail_count = max(0, _native_fail_count - 1)
    _reset_native_cache()


class _CrashRule:
    __slots__ = ("node_substr", "at_tuple", "message")

    def __init__(self, node_substr: str, at_tuple: int, message: str):
        self.node_substr = node_substr
        self.at_tuple = at_tuple
        self.message = message


class _DelayRule:
    __slots__ = ("node_substr", "delay_s", "every_n", "jitter_s")

    def __init__(self, node_substr: str, delay_s: float, every_n: int,
                 jitter_s: float):
        self.node_substr = node_substr
        self.delay_s = delay_s
        self.every_n = every_n
        self.jitter_s = jitter_s


class _PutRule:
    """Nth-channel-delivery fault: action in {'drop', 'dup'}."""

    __slots__ = ("node_substr", "at_put", "action")

    def __init__(self, node_substr: str, at_put: int, action: str):
        self.node_substr = node_substr
        self.at_put = at_put
        self.action = action


class _LinkDropRule:
    """Nth-frame wire loss on a shuffle edge (distributed/transport.py):
    the frame is counted as sent intent but never hits the socket --
    the cross-process conservation surfaces must flag it."""

    __slots__ = ("edge_substr", "at_frame")

    def __init__(self, edge_substr: str, at_frame: int):
        self.edge_substr = edge_substr
        self.at_frame = at_frame


class _LinkDelayRule:
    """Per-frame send delay on a shuffle edge (a slow / congested
    link), seeded jitter like delay_puts."""

    __slots__ = ("edge_substr", "delay_s", "every_n")

    def __init__(self, edge_substr: str, delay_s: float, every_n: int):
        self.edge_substr = edge_substr
        self.delay_s = delay_s
        self.every_n = every_n


class LinkFaults:
    """Per-sender link fault state (bound by the distributed wiring;
    own counters, so injection is deterministic per edge)."""

    __slots__ = ("edge", "drops", "delays")

    def __init__(self, edge: str, drops: List[_LinkDropRule],
                 delays: List[_LinkDelayRule]):
        self.edge = edge
        self.drops = drops
        self.delays = delays

    def drop_frame(self, frame_no: int) -> bool:
        """True when the sender's ``frame_no``-th frame (1-based, per
        edge) must be lost on the wire."""
        return any(frame_no == r.at_frame for r in self.drops)

    def maybe_delay(self, frame_no: int) -> None:
        for r in self.delays:
            if frame_no % r.every_n == 0:
                time.sleep(r.delay_s)


class _EpochCrashRule:
    """Barrier-window crash (durability/): the replica dies while
    taking its epoch cut for ``epoch`` -- deterministic on the epoch
    id, so it cannot drift with stream timing like a tuple clock."""

    __slots__ = ("node_substr", "epoch", "message")

    def __init__(self, node_substr: str, epoch: int, message: str):
        self.node_substr = node_substr
        self.epoch = epoch
        self.message = message


class NodeFaults:
    """Per-replica fault state bound at graph start (own counters +
    own seeded RNG, so injection is deterministic per node)."""

    __slots__ = ("node_name", "crash", "delays", "put_rules",
                 "epoch_crashes", "_rng", "_emits", "_puts")

    def __init__(self, node_name: str, crash: Optional[_CrashRule],
                 delays: List[_DelayRule], seed: int,
                 put_rules: Optional[List[_PutRule]] = None,
                 epoch_crashes: Optional[List[_EpochCrashRule]] = None):
        self.node_name = node_name
        self.crash = crash
        self.delays = delays
        self.put_rules = put_rules or []
        self.epoch_crashes = epoch_crashes or []
        self._rng = random.Random((seed, node_name).__repr__())
        self._emits = 0
        self._puts = 0

    def on_tuple(self, taken: int) -> None:
        """Called by the replica loop with its 1-based take counter."""
        c = self.crash
        if c is not None and taken == c.at_tuple:
            raise InjectedFailure(
                f"{c.message} (node {self.node_name}, tuple {taken})")

    def on_epoch(self, epoch: int) -> None:
        """Called by the durability plane's epoch cut (barrier aligned,
        before the snapshot) with the epoch id."""
        for r in self.epoch_crashes:
            if epoch == r.epoch:
                raise InjectedFailure(
                    f"{r.message} (node {self.node_name}, "
                    f"epoch {epoch})")

    def before_put(self) -> None:
        """Called before each downstream emission."""
        self._emits += 1
        for d in self.delays:
            if self._emits % d.every_n == 0:
                time.sleep(d.delay_s
                           + (self._rng.random() * d.jitter_s
                              if d.jitter_s else 0.0))

    def put_action(self) -> Optional[str]:
        """Called by the Outlet layer per channel delivery (after the
        ledger counted the intent, before the actual ``put``): 'drop'
        loses the delivery on the wire, 'dup' delivers it twice, None
        delivers normally.  The counter is per node across all
        destinations, 1-based like the crash clock."""
        if not self.put_rules:
            return None
        self._puts += 1
        for r in self.put_rules:
            if self._puts == r.at_put:
                return r.action
        return None


class FaultPlan:
    """Seeded, declarative fault schedule for one (test) run."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._crashes: List[_CrashRule] = []
        self._delays: List[_DelayRule] = []
        self._put_rules: List[_PutRule] = []
        self._epoch_crashes: List[_EpochCrashRule] = []
        # network actions (distributed/; docs/DISTRIBUTED.md), consumed
        # at the shuffle-transport layer
        self._link_drops: List[_LinkDropRule] = []
        self._link_delays: List[_LinkDelayRule] = []
        self._kills: dict = {}          # worker id -> at_tuple
        # epochs whose manifest commit is torn (read by the
        # EpochCoordinator; graph-global, no node binding)
        self.torn_commit_epochs: set = set()
        # injected full-filesystem windows per durable-write kind
        # ("manifest" | "blob" | "spill"): kind -> list of (first,
        # last) 1-based write indices that raise ENOSPC.  Graph-global
        # with its own clock per kind, like torn_commit_epochs.
        self._fail_writes: dict = {}
        self._write_clock: dict = {}
        self._write_lock = threading.Lock()
        self._native_armed = False

    # -- declaration (chainable) --------------------------------------
    def crash_replica(self, node_substr: str, at_tuple: int,
                      message: str = "injected replica crash") -> "FaultPlan":
        if at_tuple < 1:
            raise ValueError("at_tuple is 1-based")
        self._crashes.append(_CrashRule(node_substr, at_tuple, message))
        return self

    def delay_puts(self, node_substr: str, delay_s: float,
                   every_n: int = 1, jitter_s: float = 0.0) -> "FaultPlan":
        if every_n < 1:
            raise ValueError("every_n must be >= 1")
        self._delays.append(_DelayRule(node_substr, delay_s, every_n,
                                       jitter_s))
        return self

    def drop_put(self, node_substr: str, at_put: int) -> "FaultPlan":
        """The matching replica's Nth channel delivery is silently lost
        between the ledger's intent book and the channel (a simulated
        transport drop the conservation auditor must flag)."""
        if at_put < 1:
            raise ValueError("at_put is 1-based")
        self._put_rules.append(_PutRule(node_substr, at_put, "drop"))
        return self

    def dup_put(self, node_substr: str, at_put: int) -> "FaultPlan":
        """The matching replica's Nth channel delivery is delivered
        twice (a simulated transport duplication the conservation
        auditor must flag)."""
        if at_put < 1:
            raise ValueError("at_put is 1-based")
        self._put_rules.append(_PutRule(node_substr, at_put, "dup"))
        return self

    def crash_at_epoch(self, node_substr: str, epoch: int,
                       message: str = "injected barrier-window crash"
                       ) -> "FaultPlan":
        """The matching replica dies INSIDE the barrier window of
        ``epoch`` (durability/: after alignment, before the snapshot)
        -- deterministic and seeded like ``crash_replica``, but keyed
        to the epoch id so barrier-window crashes cannot drift with
        stream timing.  Fires on fused-away operators too (the cut
        walks every segment's fault state)."""
        if epoch < 1:
            raise ValueError("epoch ids are 1-based")
        self._epoch_crashes.append(
            _EpochCrashRule(node_substr, epoch, message))
        return self

    def torn_commit(self, epoch: int) -> "FaultPlan":
        """The manifest commit of ``epoch`` is torn: a truncated
        payload lands at the FINAL manifest path (simulating a
        non-atomic writer dying mid-commit) and the graph dies with an
        injected failure -- the restarted run's tolerant manifest
        reader must skip the damage and fall back to the previous
        committed epoch."""
        if epoch < 1:
            raise ValueError("epoch ids are 1-based")
        self.torn_commit_epochs.add(int(epoch))
        return self

    # -- network actions (distributed/; docs/DISTRIBUTED.md) ----------
    def drop_link(self, edge_substr: str, at_frame: int) -> "FaultPlan":
        """The matching shuffle edge's Nth frame (1-based, counted at
        the sender across reconnects) is silently lost on the wire:
        sent intent counted, never delivered.  The receiver must flag
        the sequence gap and the STATS-trailer shortfall with the
        exact edge and tuple count, and the cross-process merge must
        fail the conservation identity by exactly that much."""
        if at_frame < 1:
            raise ValueError("at_frame is 1-based")
        self._link_drops.append(_LinkDropRule(edge_substr, at_frame))
        return self

    def delay_link(self, edge_substr: str, delay_ms: float,
                   every_n: int = 1) -> "FaultPlan":
        """Sleep ``delay_ms`` before every ``every_n``-th frame send on
        matching shuffle edges -- a slow link whose backpressure must
        throttle the remote producer through the credit window."""
        if every_n < 1:
            raise ValueError("every_n must be >= 1")
        self._link_delays.append(
            _LinkDelayRule(edge_substr, delay_ms / 1e3, every_n))
        return self

    def kill_worker(self, worker: int, at_tuple: int) -> "FaultPlan":
        """Hard-kill worker ``worker`` (``os._exit``, no teardown) when
        its transport tuple clock -- tuples sent plus received over its
        shuffle edges -- reaches ``at_tuple``.  Deterministic per
        worker; the run_distributed restart loop must recover from the
        newest globally-committed epoch."""
        if at_tuple < 1:
            raise ValueError("at_tuple is 1-based")
        self._kills[int(worker)] = int(at_tuple)
        return self

    def for_link(self, edge_name: str):
        """Link fault state for one shuffle edge (bound per sender by
        the distributed wiring); None when no rule matches."""
        drops = [r for r in self._link_drops
                 if r.edge_substr in edge_name]
        delays = [r for r in self._link_delays
                  if r.edge_substr in edge_name]
        if not drops and not delays:
            return None
        return LinkFaults(edge_name, drops, delays)

    def kill_tuple_for(self, worker: int):
        """The kill threshold of ``worker``'s transport clock, or None."""
        return self._kills.get(int(worker))

    def fail_write(self, path_kind: str, at_write: int = 1,
                   count: int = 1) -> "FaultPlan":
        """The filesystem "fills up" for durable writes of
        ``path_kind`` -- ``"manifest"`` (epoch manifests),
        ``"blob"`` (delta blobs) or ``"spill"`` (cold-tier segments):
        writes ``at_write .. at_write+count-1`` (1-based, counted per
        kind across the graph) raise ``OSError(ENOSPC)`` at the write
        layer.  The durability/state planes must degrade -- abort the
        epoch / keep the batch warm with a flight event -- never die.
        A large ``count`` models a disk that stays full."""
        if path_kind not in ("manifest", "blob", "spill"):
            raise ValueError(
                "path_kind must be 'manifest', 'blob' or 'spill', "
                f"not {path_kind!r}")
        if at_write < 1:
            raise ValueError("at_write is 1-based")
        if count < 1:
            raise ValueError("count must be >= 1")
        self._fail_writes.setdefault(path_kind, []).append(
            (at_write, at_write + count - 1))
        return self

    def write_should_fail(self, path_kind: str) -> bool:
        """Called by the write layer (EpochStore manifests, BlobStore
        delta blobs, SpillStore segments) before each durable write of
        ``path_kind``; advances that kind's clock and reports whether
        this write lands in an injected full-filesystem window."""
        rules = self._fail_writes.get(path_kind)
        if not rules:
            return False
        with self._write_lock:
            self._write_clock[path_kind] = n = \
                self._write_clock.get(path_kind, 0) + 1
        return any(first <= n <= last for first, last in rules)

    def fail_native_build(self) -> "FaultPlan":
        """Force the native toolchain probe to fail from now until
        ``deactivate()`` (or context-manager exit)."""
        if not self._native_armed:
            self._native_armed = True
            _arm_native_failure()
        return self

    def deactivate(self) -> None:
        if self._native_armed:
            self._native_armed = False
            _disarm_native_failure()

    # -- binding (called by PipeGraph.start per node) ------------------
    def for_node(self, node_name: str) -> Optional[NodeFaults]:
        # collector nodes ("<stage>.coll<i>" / ".collector" / ".coll.g<g>",
        # multipipe wiring) share their stage's name but are runtime
        # plumbing, not operator replicas: rules never bind to them
        if ".coll" in node_name.rsplit("/", 1)[-1]:
            return None
        crash = next((c for c in self._crashes
                      if c.node_substr in node_name), None)
        delays = [d for d in self._delays if d.node_substr in node_name]
        puts = [p for p in self._put_rules if p.node_substr in node_name]
        epochs = [e for e in self._epoch_crashes
                  if e.node_substr in node_name]
        if crash is None and not delays and not puts and not epochs:
            return None
        return NodeFaults(node_name, crash, delays, self.seed,
                          put_rules=puts, epoch_crashes=epochs)

    # -- context manager ----------------------------------------------
    def __enter__(self) -> "FaultPlan":
        return self

    def __exit__(self, *exc) -> None:
        self.deactivate()
