"""Self-contained HTML front-end for the dashboard.

The reference's dashboard is a Java Spring + React app (README "Web
Dashboard"; its directory is empty in the snapshot).  This module is
the renderer-free equivalent: one dependency-free HTML page, served by
``dashboard.serve_http`` at ``/``, that polls the ``/apps`` JSON
snapshot once a second and renders

* per-app stat tiles (throughput, memory, dropped tuples, replicas),
* the PipeGraph topology (parsed client-side from the DOT diagram the
  MonitoringThread registers -- multipipe.hpp:522-591 equivalent),
* a throughput sparkline built from successive report deltas,
* the per-operator replica table (stats_record.hpp:45-165 counters).

No external assets: the page must work on an air-gapped TPU VM.
"""

HTML_PAGE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>WindFlow-TPU dashboard</title>
<style>
  .viz-root {
    color-scheme: light;
    --surface-1: #fcfcfb; --surface-2: #f1f0ee;
    --text-primary: #0b0b0b; --text-secondary: #52514e;
    --series-1: #2a78d6; --grid: #e3e2df;
    --status-good: #008300; --status-serious: #e34948;
  }
  @media (prefers-color-scheme: dark) {
    :root:where(:not([data-theme="light"])) .viz-root {
      color-scheme: dark;
      --surface-1: #1a1a19; --surface-2: #242423;
      --text-primary: #ffffff; --text-secondary: #c3c2b7;
      --series-1: #3987e5; --grid: #33332f;
      --status-good: #35b559; --status-serious: #e66767;
    }
  }
  body { margin: 0; }
  .viz-root {
    font: 14px/1.45 system-ui, sans-serif; background: var(--surface-1);
    color: var(--text-primary); min-height: 100vh; padding: 20px 24px;
    box-sizing: border-box;
  }
  h1 { font-size: 17px; font-weight: 600; margin: 0 0 4px; }
  .sub { color: var(--text-secondary); font-size: 12px; margin: 0 0 18px; }
  .app { border: 1px solid var(--grid); border-radius: 8px;
         padding: 14px 16px; margin-bottom: 16px; }
  .app h2 { font-size: 14px; font-weight: 600; margin: 0 8px 0 0;
            display: inline-block; }
  .badge { font-size: 11px; border-radius: 9px; padding: 1px 8px;
           vertical-align: 1px; }
  .badge.live  { color: var(--status-good);
                 border: 1px solid var(--status-good); }
  .badge.ended { color: var(--status-serious);
                 border: 1px solid var(--status-serious); }
  .tiles { display: flex; flex-wrap: wrap; gap: 10px; margin: 12px 0; }
  .tile { background: var(--surface-2); border-radius: 6px;
          padding: 8px 14px; min-width: 110px; }
  .tile .v { font-size: 20px; font-weight: 600; font-variant-numeric:
             tabular-nums; }
  .tile .v.bad { color: var(--status-serious); }
  .tile .k { color: var(--text-secondary); font-size: 11px; }
  svg text { fill: var(--text-secondary); font: 11px system-ui, sans-serif; }
  .topo rect { fill: var(--surface-2); stroke: var(--grid); rx: 4; }
  .topo text.op { fill: var(--text-primary); }
  .topo path { stroke: var(--text-secondary); fill: none;
               stroke-width: 1.2; }
  table { border-collapse: collapse; width: 100%; margin-top: 10px;
          font-variant-numeric: tabular-nums; }
  th { text-align: right; color: var(--text-secondary); font-weight: 500;
       font-size: 11px; padding: 4px 10px; border-bottom: 1px solid
       var(--grid); }
  th:first-child, td:first-child { text-align: left; }
  td { text-align: right; padding: 4px 10px; border-bottom: 1px solid
       var(--grid); }
  .spark-wrap { position: relative; margin-top: 6px; }
  .hist-row { display: flex; flex-wrap: wrap; gap: 14px; margin-top: 6px; }
  .hist-row .k { color: var(--text-secondary); font-size: 11px; }
  #tip { position: fixed; pointer-events: none; display: none;
         background: var(--surface-2); border: 1px solid var(--grid);
         border-radius: 4px; padding: 2px 8px; font-size: 11px;
         color: var(--text-primary); z-index: 9; }
</style>
</head>
<body>
<div class="viz-root">
  <h1>WindFlow-TPU dashboard</h1>
  <p class="sub">polling <code>/apps</code> every second &mdash; framed-TCP
  ingest from traced PipeGraphs (RuntimeConfig.tracing)</p>
  <div id="apps"><p class="sub">no applications registered yet</p></div>
  <div id="tip"></div>
</div>
<script>
"use strict";
const hist = {};           // app id -> [{t, outputs}] report-delta history
// counters come off the wire: coerce before arithmetic so a malformed
// report cannot smuggle strings through the sums into the markup
const num = v => { const n = Number(v); return isFinite(n) ? n : 0; };
const fmt = v => { const n = num(v);
  return n >= 1e9 ? (n / 1e9).toFixed(2) + "B"
       : n >= 1e6 ? (n / 1e6).toFixed(2) + "M"
       : n >= 1e3 ? (n / 1e3).toFixed(1) + "k" : String(n); };
// names come off the wire (any local process can register an app) --
// escape everything interpolated into innerHTML
const esc = s => String(s).replace(/[&<>"']/g, c => ({"&": "&amp;",
  "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));

function svgImg(svg) {
  // foreign SVG payloads render as an <img> data URI: an image context
  // never executes scripts or event handlers, unlike raw injection
  const b64 = btoa(unescape(encodeURIComponent(svg)));
  return `<img class="topo" alt="topology" ` +
         `src="data:image/svg+xml;base64,${b64}">`;
}

function parseDot(src) {
  const nodes = [], labels = {}, edges = [];
  for (const line of (src || "").split("\\n")) {
    // labels use DOT double-quoted-string escaping (graph_to_dot):
    // match escaped sequences so a quote in an operator name does not
    // truncate the label, then unescape for display
    let m = line.match(/^\\s*(\\w+)\\s*\\[label="((?:[^"\\\\]|\\\\.)*)"/);
    if (m) {
      nodes.push(m[1]);
      labels[m[1]] = m[2].replace(/\\\\(.)/g, "$1");
      continue;
    }
    m = line.match(/^\\s*(\\w+)\\s*->\\s*(\\w+)/);
    if (m) edges.push([m[1], m[2]]);
  }
  return { nodes, labels, edges };
}

function topoSvg(g) {
  if (!g.nodes.length) return "";
  const depth = {};                       // longest path from a root
  for (let pass = 0; pass <= g.nodes.length; pass++)
    for (const [a, b] of g.edges)
      depth[b] = Math.max(depth[b] || 0, (depth[a] || 0) + 1);
  const cols = {};
  for (const n of g.nodes) (cols[depth[n] || 0] ||= []).push(n);
  const CW = 148, RH = 40, pos = {};
  let H = 0;
  for (const [c, ns] of Object.entries(cols)) {
    ns.forEach((n, i) => pos[n] = [8 + c * CW, 8 + i * RH]);
    H = Math.max(H, ns.length * RH);
  }
  const W = 8 + (Object.keys(cols).length) * CW;
  let s = `<svg class="topo" width="${W}" height="${H + 10}"
    role="img" aria-label="pipeline topology">`;
  for (const [a, b] of g.edges) {
    if (!pos[a] || !pos[b]) continue;   // edge to an undeclared node
    const [x1, y1] = pos[a], [x2, y2] = pos[b];
    s += `<path d="M ${x1 + 128} ${y1 + 13} C ${x1 + 140} ${y1 + 13},
      ${x2 - 12} ${y2 + 13}, ${x2} ${y2 + 13}" />`;
  }
  for (const n of g.nodes) {
    const [x, y] = pos[n], lab = g.labels[n] || n;
    s += `<rect x="${x}" y="${y}" width="128" height="26" rx="4"></rect>
      <text class="op" x="${x + 64}" y="${y + 17}" text-anchor="middle">
      ${esc(lab.length > 18 ? lab.slice(0, 17) + "\\u2026" : lab)}</text>`;
  }
  return s + "</svg>";
}

function sparkline(id, h) {
  if (h.length < 2) return "";
  const W = 320, H = 48, rates = [];
  for (let i = 1; i < h.length; i++) {
    const dt = (h[i].t - h[i - 1].t) / 1000 || 1;
    rates.push(Math.max(0, (h[i].outputs - h[i - 1].outputs) / dt));
  }
  const mx = Math.max(...rates, 1);
  const pts = rates.map((r, i) =>
    [8 + i * (W - 16) / Math.max(1, rates.length - 1),
     H - 6 - r / mx * (H - 16), r]);
  let s = `<svg width="${W}" height="${H}" data-app="${esc(id)}"
    class="spark" role="img" aria-label="output rate">`;
  s += `<line x1="8" y1="${H - 6}" x2="${W - 8}" y2="${H - 6}"
    stroke="var(--grid)" />`;
  s += `<polyline fill="none" stroke="var(--series-1)" stroke-width="2"
    points="${pts.map(p => p[0].toFixed(1) + "," + p[1].toFixed(1)).join(" ")}" />`;
  const last = pts[pts.length - 1];
  s += `<circle cx="${last[0]}" cy="${last[1]}" r="3"
    fill="var(--series-1)" />`;
  s += `<text x="${W - 8}" y="10" text-anchor="end">${fmt(last[2])}/s</text>`;
  return s + "</svg>";
}

function hookHover() {
  const tip = document.getElementById("tip");
  document.querySelectorAll("svg.spark").forEach(sv => {
    sv.onmousemove = e => {
      const h = hist[sv.dataset.app] || [];
      if (h.length < 2) return;
      const r = sv.getBoundingClientRect();
      const i = Math.min(h.length - 2, Math.max(0, Math.round(
        (e.clientX - r.left - 8) / (r.width - 16) * (h.length - 2))));
      const dt = (h[i + 1].t - h[i].t) / 1000 || 1;
      tip.textContent = fmt((h[i + 1].outputs - h[i].outputs) / dt)
        + " results/s";
      tip.style.left = (e.clientX + 12) + "px";
      tip.style.top = (e.clientY - 10) + "px";
      tip.style.display = "block";
    };
    sv.onmouseleave = () => tip.style.display = "none";
  });
}

// latency pretty-printer: log-bucketed histogram values in microseconds
const lus = v => { const n = num(v);
  return n >= 1e6 ? (n / 1e6).toFixed(2) + "s"
       : n >= 1e3 ? (n / 1e3).toFixed(1) + "ms" : n.toFixed(0) + "us"; };

// diagnosis plane: server-side gauge-history sparklines (the History
// stats block -- trends survive a page reload, unlike the client-side
// report-delta history above)
function histSpark(label, vals, fmtfn) {
  if (!vals || vals.length < 2) return "";
  const W = 150, H = 36;
  const mx = Math.max(...vals), mn = Math.min(...vals, 0);
  const pts = vals.map((v, i) =>
    [4 + i * (W - 8) / (vals.length - 1),
     H - 8 - (num(v) - mn) / ((mx - mn) || 1) * (H - 18)]);
  return `<div><svg width="${W}" height="${H}" role="img"
      aria-label="${esc(label)}">
    <line x1="4" y1="${H - 8}" x2="${W - 4}" y2="${H - 8}"
      stroke="var(--grid)" />
    <polyline fill="none" stroke="var(--series-1)" stroke-width="1.5"
      points="${pts.map(p => p[0].toFixed(1) + "," + p[1].toFixed(1)).join(" ")}" />
    <text x="${W - 4}" y="10" text-anchor="end">
      ${fmtfn(vals[vals.length - 1])}</text>
  </svg><div class="k">${esc(label)}</div></div>`;
}

function historyRow(hist) {
  const s = (hist || {}).Series || {};
  if (!(hist || {}).Len) return "";
  return `<div class="hist-row">
    ${histSpark("results/s (history)", s.throughput_rps, fmt)}
    ${histSpark("e2e p99", s.e2e_p99_us, lus)}
    ${histSpark("frontier lag", s.frontier_lag_ms,
                v => num(v).toFixed(0) + "ms")}
    ${histSpark("queue depth", s.queue_depth, fmt)}
  </div>`;
}

// audit plane: keyed-state census + hot-key skew (Skew block)
function skewTable(skew) {
  if (!skew) return "";
  const hot = (skew.Hot_keys || []).filter(h => num(h.observed) > 0);
  const census = (skew.Census || []).filter(c => num(c.keys) > 0);
  if (!hot.length && !census.length) return "";
  let s = "";
  if (hot.length) {
    s += `<table><thead><tr><th>keyby edge</th><th>hot key</th>
      <th>share</th><th>est count</th><th>observed</th></tr></thead><tbody>`;
    for (const h of hot) {
      const top = (h.top || [])[0] || [];
      s += `<tr><td>${esc(h.operator)}</td><td>${esc(top[0])}</td>
        <td>${(num(h.share) * 100).toFixed(1)}%</td>
        <td>${fmt(top[1])}</td><td>${fmt(h.observed)}</td></tr>`;
    }
    s += "</tbody></table>";
  }
  if (census.length) {
    s += `<table><thead><tr><th>keyed state (replica)</th>
      <th>keys</th><th>est bytes</th><th>tiers</th></tr></thead><tbody>`;
    for (const c of census) {
      // tiered stores (state/tiers.py): per-tier key/byte splits
      const tiers = c.tiers ?
        Object.entries(c.tiers).filter(([, v]) => num(v[0]) > 0)
          .map(([t, v]) => `${esc(t)}:${fmt(v[0])}k/${fmt(v[1])}B`)
          .join(" ") : "–";
      s += `<tr><td>${esc(c.replica)}</td><td>${fmt(c.keys)}</td>
        <td>${fmt(c.bytes_est)}B</td><td>${tiers || "–"}</td></tr>`;
    }
    s += "</tbody></table>";
  }
  return s;
}

function opRow(op) {
  const rs = op.Replicas || [];
  const sum = k => rs.reduce((a, r) => a + num(r[k]), 0);
  const svc = rs.length ?
    rs.reduce((a, r) => a + num(r.Service_time_usec), 0) / rs.length : 0;
  // telemetry plane: merged per-operator latency histograms
  const lat = op.Latency || {};
  const svcH = lat.service || {}, resH = lat.residency || {};
  const svcP = svcH.n ? `${lus(svcH.p50_us)}/${lus(svcH.p99_us)}` : "–";
  const resP = resH.n ? lus(resH.p99_us) : "–";
  // ingest replicas report credits / queue depth / controller batch
  // size; other operators render a dash
  const ing = rs.some(r => "Ingest_batch_size" in r) ?
    `${fmt(sum("Ingest_credits"))}cr q${fmt(sum("Ingest_queue_depth"))} ` +
    `b${fmt(sum("Ingest_batch_size"))}` : "–";
  // standalone load gauges (refresh_gauges): inbound channel depth and
  // credit-wait seconds -- the elastic signal plane's raw inputs
  const cwait = sum("Credit_wait_s");
  // audit plane: peak inbound depth + the most held-back replica's
  // frontier lag (0 everywhere = every operator caught up)
  const hwm = rs.reduce((a, r) =>
    Math.max(a, num(r.Queue_high_watermark)), 0);
  const flag = rs.reduce((a, r) =>
    Math.max(a, num(r.Frontier_lag_ms)), 0);
  return `<tr><td>${esc(op.Operator_name)}</td><td>${num(op.Parallelism)}</td>
    <td>${fmt(sum("Inputs_received"))}</td>
    <td>${fmt(sum("Outputs_sent"))}</td>
    <td>${fmt(sum("Inputs_ignored"))}</td>
    <td>${fmt(sum("Svc_failures"))}</td>
    <td>${fmt(sum("Shed_tuples"))}</td>
    <td>${fmt(sum("Queue_depth"))}</td>
    <td>${fmt(hwm)}</td>
    <td>${flag ? lus(flag * 1e3) : "–"}</td>
    <td>${cwait ? cwait.toFixed(1) + "s" : "–"}</td>
    <td>${ing}</td>
    <td>${svc.toFixed(1)}</td>
    <td>${svcP}</td>
    <td>${resP}</td>
    <td>${fmt(sum("Device_launches"))}</td>
    <td>${sum("Device_time_ms") ? sum("Device_time_ms").toFixed(0) : "–"}</td>
    <td>${fmt(sum("Bytes_to_device"))}</td>
    <td>${fmt(sum("Bytes_from_device"))}</td>
    <td>${sum("Device_launches")
      ? fmt(Math.round((sum("Bytes_to_device") + sum("Bytes_from_device"))
                       / sum("Device_launches"))) : "–"}</td>
    <td>${sum("Device_state_bytes_resident")
      ? fmt(sum("Device_state_bytes_resident")) : "–"}</td></tr>`;
}

// serving plane: tenants index (one row per tenant-carrying app, the
// multi-tenant operator's discovery view; /tenants serves the JSON)
function tenantsIndex(apps) {
  const rows = Object.keys(apps).filter(id =>
    ((apps[id] || {}).report || {}).Tenant);
  if (!rows.length) return "";
  let s = `<div class="app"><h2>tenants</h2>
    <span class="badge live">${rows.length} registered</span>
    <table><thead><tr><th>tenant</th><th>graph</th><th>state</th>
    <th>priority</th><th>weight</th><th>credits</th>
    <th>arbitrations</th><th>slo</th><th>links</th></tr></thead><tbody>`;
  for (const id of rows) {
    const a = apps[id], rep = a.report || {}, t = rep.Tenant || {};
    const slo = rep.Slo;
    const sloTxt = !slo ? "\\u2013"
      : slo.Breached ? "\\u2715 breached" : "\\u2713 in SLO";
    s += `<tr><td>${esc(t.Name)}</td>
      <td>${esc(rep.PipeGraph_name || "")}</td>
      <td>${esc(t.State || (a.active ? "RUNNING" : "ended"))}</td>
      <td>${num(t.Priority)}</td><td>${num(t.Weight)}</td>
      <td>${fmt(t.Credits)}</td><td>${num(t.Arbitrations)}</td>
      <td>${sloTxt}</td>
      <td><a href="/explain?app=${esc(id)}">explain</a>
        <a href="/flight?app=${esc(id)}">flight</a>
        <a href="/apps?app=${esc(id)}">stats</a></td></tr>`;
  }
  return s + "</tbody></table></div>";
}

function render(apps) {
  const root = document.getElementById("apps");
  const ids = Object.keys(apps);
  if (!ids.length) return;
  root.innerHTML = tenantsIndex(apps) + ids.map(id => {
    const a = apps[id], rep = a.report || {};
    const ops = rep.Operators || [];
    const outputs = ops.length ?          // sink row: results RECEIVED
      (ops[ops.length - 1].Replicas || []).reduce(
        (s, r) => s + num(r.Inputs_received), 0) : 0;
    (hist[id] ||= []).push({ t: Date.now(), outputs });
    if (hist[id].length > 120) hist[id].shift();
    const replicas = ops.reduce((s, o) => s + num(o.Parallelism), 0);
    const h = hist[id], rate = h.length > 1 ?
      Math.max(0, (h[h.length - 1].outputs - h[h.length - 2].outputs) /
        ((h[h.length - 1].t - h[h.length - 2].t) / 1000 || 1)) : 0;
    return `<div class="app">
      <h2>#${esc(id)} ${esc(rep.PipeGraph_name || "(no report yet)")}</h2>
      <span class="badge ${a.active ? "live" : "ended"}">
        ${a.active ? "\\u25cf live" : "\\u25a0 ended"}</span>
      ${rep.Tenant ? `<span class="badge live">tenant
        ${esc(rep.Tenant.Name)} p${num(rep.Tenant.Priority)}
        ${fmt(rep.Tenant.Credits)}cr</span>` : ""}
      <div class="tiles">
        <div class="tile"><div class="v">${fmt(rate)}/s</div>
          <div class="k">result rate at sink</div></div>
        <div class="tile"><div class="v">${fmt(outputs)}</div>
          <div class="k">results received</div></div>
        <div class="tile"><div class="v">${fmt(rep.Dropped_tuples || 0)}
          </div><div class="k">dropped tuples</div></div>
        <div class="tile"><div class="v${num(rep.Svc_failures) ? " bad" : ""}">
          ${fmt(rep.Svc_failures || 0)}</div>
          <div class="k">svc failures
          (${fmt(rep.Dead_letter_tuples || 0)} dead-lettered)</div></div>
        <div class="tile"><div class="v${num(rep.Shed_tuples) ? " bad" : ""}">
          ${fmt(rep.Shed_tuples || 0)}</div>
          <div class="k">shed tuples (admission)</div></div>
        <div class="tile"><div class="v">${replicas}</div>
          <div class="k">replicas (${num(rep.Operator_number)} ops)</div></div>
        ${rep.Conservation ? `<div class="tile">
          <div class="v${num(rep.Conservation.Violations_total)
            ? " bad" : ""}">
            ${num(rep.Conservation.Violations_total)
              ? fmt(rep.Conservation.Violations_total) + " viol."
              : (rep.Conservation.Edges_balanced
                 ? "\\u2713 balanced" : "\\u2026 settling")}</div>
          <div class="k">conservation ledger
            (${fmt((rep.Conservation.Edges || []).length)} edges,
            ${fmt(rep.Conservation.Audit_passes || 0)} audits)</div>
          </div>` : ""}
        <div class="tile"><div class="v">${fmt(rep.Rescales || 0)}</div>
          <div class="k">rescale events${(rep.Rescale_events || []).length
            ? " (last " + esc((e => e.old_parallelism + "\\u2192" +
              e.new_parallelism)(rep.Rescale_events[
                rep.Rescale_events.length - 1])) + ")" : ""}</div></div>
        <div class="tile"><div class="v">
          ${fmt(num(rep.Memory_usage_KB) * 1024)}B</div>
          <div class="k">resident memory</div></div>
        ${(rep.Latency_e2e && rep.Latency_e2e.n) ? `<div class="tile">
          <div class="v">${lus(rep.Latency_e2e.p50_us)} /
            ${lus(rep.Latency_e2e.p99_us)}</div>
          <div class="k">e2e latency p50/p99
            (${fmt(rep.Latency_e2e.n)} traces)</div></div>` : ""}
        ${(() => {  // diagnosis plane: doctor verdict tile
          const d = rep.Diagnosis || {}, bn = d.Bottleneck || {};
          const anoms = (d.Anomalies || []).length;
          if (!bn.Operator && !anoms) return "";
          const bad = anoms || bn.Verdict === "backpressure";
          const name = String(bn.Operator || "\\u2013");
          return `<div class="tile"><div class="v${bad ? " bad" : ""}">
            ${esc(name.length > 16 ? "\\u2026" + name.slice(-15) : name)}
            </div><div class="k">bottleneck (${esc(bn.Verdict || "?")},
            score ${num(bn.Score).toFixed(2)},
            ${anoms} regression${anoms === 1 ? "" : "s"})</div></div>`;
        })()}
        ${(() => {  // SLO plane: burn-rate tile (Slo stats block)
          const s = rep.Slo;
          if (!s) return "";
          const bad = !!s.Breached;
          return `<div class="tile"><div class="v${bad ? " bad" : ""}">
            ${bad ? "\\u2715 SLO breached" : "\\u2713 in SLO"}</div>
            <div class="k">burn ${num(s.Burn_rate_fast).toFixed(1)}x /
            ${num(s.Burn_rate_slow).toFixed(1)}x, budget
            ${(num(s.Budget_burned) * 100).toFixed(0)}% burned
            (${num(s.Breaches_total)} episode${
              num(s.Breaches_total) === 1 ? "" : "s"})</div></div>`;
        })()}
      </div>
      ${a.diagram.trim().startsWith("<svg") ? svgImg(a.diagram) : topoSvg(parseDot(a.diagram))}
      <div class="spark-wrap">${sparkline(id, hist[id])}</div>
      ${historyRow(rep.History)}
      <table><thead><tr><th>operator</th><th>par</th><th>in</th>
        <th>out</th><th>ignored</th><th>fails</th><th>shed</th>
        <th>q-depth</th><th>q-hwm</th><th>fr-lag</th><th>cr-wait</th>
        <th>ingest</th><th>svc &micro;s</th>
        <th>svc p50/p99</th><th>res p99</th>
        <th>launches</th><th>dev ms</th>
        <th>B&rarr;dev</th><th>B&larr;dev</th>
        <th>dev B/launch</th><th>dev B resident</th></tr>
      </thead><tbody>${ops.map(opRow).join("")}</tbody></table>
      ${skewTable(rep.Skew)}
    </div>`;
  }).join("");
  hookHover();
}

async function tick() {
  let apps;
  try {
    const r = await fetch("/apps");
    apps = await r.json();
  } catch (e) { return; /* server restarting */ }
  try {
    render(apps);
  } catch (e) { console.error("dashboard render:", e); }
}
setInterval(tick, 1000); tick();
</script>
</body>
</html>
"""
