"""MonitoringThread: the dashboard TCP reporter.

Re-design of reference ``wf/monitoring.hpp`` (:162-314): connects to a
dashboard at (machine, port) -- default localhost:20207 -- and speaks
the same framed protocol:

* type 0: registerApp    [int32 type][int32 len][payload: SVG diagram]
          -> ack [int32 app_id]                        (:232-257)
* type 1: sendReport     [int32 type][int32 app_id][int32 len][JSON]
          every second                                 (:260-285)
* type 2: deregisterApp  [int32 type][int32 app_id][int32 0]  (:288-313)

Integers are little-endian int32 (the reference sends raw host-order
ints from x86).  The registerApp payload is an SVG diagram, as the
reference renders via libgvc (:243) -- here produced by the pure-python
``graph_to_svg`` (no graphviz binary); ``graph_to_dot`` still provides
the DOT text for the log-dir artifact dump (multipipe.hpp:522-591).
"""
from __future__ import annotations

import socket
import struct
import threading


def graph_to_dot(graph) -> str:
    """Graphviz description of the PipeGraph topology
    (multipipe.hpp:522-591: vertices per operator, edges labelled by
    routing mode)."""
    lines = [f'digraph "{graph.name}" {{', "  rankdir=LR;"]
    for pipe in graph.pipes:
        prev = None
        for name in pipe._op_names:
            node_id = f"{pipe.name}_{name}".replace("/", "_").replace(
                "(", "_").replace(")", "_").replace("+", "_")
            lines.append(f'  {node_id} [label="{name}"];')
            if prev is not None:
                lines.append(f"  {prev} -> {node_id};")
            prev = node_id
    lines.append("}")
    return "\n".join(lines)


def graph_to_svg(graph) -> str:
    """Pure-python SVG render of the PipeGraph topology -- the diagram
    artifact twin of the reference's graphviz PDF/SVG dump
    (pipegraph.hpp:683-709) without an external graphviz binary.
    Layout: one row per MultiPipe, operators left to right."""
    BOX_W, BOX_H, GAP_X, GAP_Y, PAD = 148, 40, 42, 26, 16
    rows = [list(pipe._op_names) for pipe in graph.pipes]
    if not rows:
        rows = [[]]
    width = PAD * 2 + max((len(r) for r in rows), default=0) * \
        (BOX_W + GAP_X) - (GAP_X if any(rows) else 0)
    height = PAD * 2 + len(rows) * (BOX_H + GAP_Y) - GAP_Y
    out = [f'<svg xmlns="http://www.w3.org/2000/svg" '
           f'width="{max(width, 60)}" height="{max(height, 60)}" '
           f'font-family="monospace" font-size="11">',
           f'<title>{_xml(graph.name)}</title>']
    for ri, names in enumerate(rows):
        y = PAD + ri * (BOX_H + GAP_Y)
        for ci, name in enumerate(names):
            x = PAD + ci * (BOX_W + GAP_X)
            out.append(
                f'<rect x="{x}" y="{y}" width="{BOX_W}" height="{BOX_H}"'
                f' rx="6" fill="#eef3fa" stroke="#47618a"/>')
            label = name if len(name) <= 20 else name[:19] + "…"
            out.append(f'<text x="{x + BOX_W / 2}" y="{y + BOX_H / 2 + 4}"'
                       f' text-anchor="middle">{_xml(label)}</text>')
            if ci:
                ax = x - GAP_X
                out.append(
                    f'<line x1="{ax}" y1="{y + BOX_H / 2}" x2="{x - 6}"'
                    f' y2="{y + BOX_H / 2}" stroke="#47618a"/>'
                    f'<polygon points="{x - 6},{y + BOX_H / 2 - 4} '
                    f'{x},{y + BOX_H / 2} {x - 6},{y + BOX_H / 2 + 4}"'
                    f' fill="#47618a"/>')
    out.append("</svg>")
    return "\n".join(out)


def _xml(s: str) -> str:
    import html
    return html.escape(s, quote=True)


class MonitoringThread(threading.Thread):
    """1 Hz stats reporter (monitoring.hpp:162-314)."""

    def __init__(self, graph, machine: str = None, port: int = None,
                 interval_s: float = 1.0):
        super().__init__(name="windflow-monitor", daemon=True)
        self.graph = graph
        cfg = graph.config
        self.machine = machine or cfg.dashboard_machine
        self.port = port or cfg.dashboard_port
        self.interval_s = interval_s
        self._stop_evt = threading.Event()
        self.app_id = -1
        self.sock = None

    # -- framed protocol ---------------------------------------------------
    def _send_frame(self, *parts: bytes) -> None:
        self.sock.sendall(b"".join(parts))

    def _register(self) -> bool:
        try:
            self.sock = socket.create_connection(
                (self.machine, self.port), timeout=2.0)
            diagram = graph_to_svg(self.graph).encode()
            self._send_frame(struct.pack("<ii", 0, len(diagram)), diagram)
            ack = self.sock.recv(4)
            if len(ack) == 4:
                self.app_id = struct.unpack("<i", ack)[0]
                return True
        except OSError:
            pass
        return False

    def _report(self) -> None:
        payload = self._stats_json().encode()
        self._send_frame(struct.pack("<iii", 1, self.app_id, len(payload)),
                         payload)

    def _deregister(self) -> None:
        try:
            self._send_frame(struct.pack("<iii", 2, self.app_id, 0))
        except OSError:
            pass

    def _stats_json(self) -> str:
        stats = getattr(self.graph, "stats", None)
        refresh = getattr(self.graph, "refresh_gauges", None)
        if refresh is not None:
            refresh()  # channel-depth / credit-wait gauges per replica
        if stats is not None:
            dls = getattr(self.graph, "dead_letters", None)
            return stats.to_json(self.graph.get_num_dropped_tuples(),
                                 dls.count() if dls is not None else 0)
        return "{}"

    # -- thread body -------------------------------------------------------
    def run(self) -> None:
        if not self._register():
            return  # dashboard unreachable: tracing silently disabled
        try:
            while not self._stop_evt.is_set():
                self._report()
                self._stop_evt.wait(self.interval_s)
            self._report()
            self._deregister()
        except OSError:
            pass
        finally:
            if self.sock is not None:
                self.sock.close()

    def stop(self) -> None:
        self._stop_evt.set()
        self.join(timeout=5.0)
