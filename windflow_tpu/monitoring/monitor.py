"""MonitoringThread: the dashboard TCP reporter.

Re-design of reference ``wf/monitoring.hpp`` (:162-314): connects to a
dashboard at (machine, port) -- default localhost:20207 -- and speaks
the same framed protocol:

* type 0: registerApp    [int32 type][int32 len][payload: SVG diagram]
          -> ack [int32 app_id]                        (:232-257)
* type 1: sendReport     [int32 type][int32 app_id][int32 len][JSON]
          every second                                 (:260-285)
* type 2: deregisterApp  [int32 type][int32 app_id][int32 0]  (:288-313)

Integers are little-endian int32 (the reference sends raw host-order
ints from x86).  The registerApp payload is an SVG diagram, as the
reference renders via libgvc (:243) -- here produced by the pure-python
``graph_to_svg`` (no graphviz binary); ``graph_to_dot`` still provides
the DOT text for the log-dir artifact dump (multipipe.hpp:522-591).
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import warnings

# flight-recorder events shipped inside each monitor report (the
# dashboard's /flight endpoint and the doctor's offline path read
# them; the full ring still dumps as JSONL on failure)
FLIGHT_IN_REPORT = 256


def _dot_quote(s: str) -> str:
    """DOT double-quoted-string escaping: a backslash or quote in an
    operator name must not break the generated graph (graph_to_svg
    already escapes its XML; this is the DOT twin)."""
    return s.replace("\\", "\\\\").replace('"', '\\"')


def graph_to_dot(graph) -> str:
    """Graphviz description of the PipeGraph topology
    (multipipe.hpp:522-591: vertices per operator, edges labelled by
    routing mode)."""
    lines = [f'digraph "{_dot_quote(graph.name)}" {{', "  rankdir=LR;"]
    # bare-word node ids (the web UI's parseDot expects \w+), made
    # collision-free: sanitizing 'op.1' and 'op-1' both to 'op_1'
    # would otherwise silently merge two operators into one vertex
    assigned: dict = {}
    used: set = set()

    def node_id(raw: str) -> str:
        nid = assigned.get(raw)
        if nid is None:
            base = "".join(c if c.isalnum() or c == "_" else "_"
                           for c in raw)
            nid, k = base, 2
            while nid in used:
                nid = f"{base}_{k}"
                k += 1
            used.add(nid)
            assigned[raw] = nid
        return nid

    for pipe in graph.pipes:
        prev = None
        for name in pipe._op_names:
            nid = node_id(f"{pipe.name}_{name}")
            lines.append(f'  {nid} [label="{_dot_quote(name)}"];')
            if prev is not None:
                lines.append(f"  {prev} -> {nid};")
            prev = nid
    lines.append("}")
    return "\n".join(lines)


def graph_to_svg(graph) -> str:
    """Pure-python SVG render of the PipeGraph topology -- the diagram
    artifact twin of the reference's graphviz PDF/SVG dump
    (pipegraph.hpp:683-709) without an external graphviz binary.
    Layout: one row per MultiPipe, operators left to right."""
    BOX_W, BOX_H, GAP_X, GAP_Y, PAD = 148, 40, 42, 26, 16
    rows = [list(pipe._op_names) for pipe in graph.pipes]
    if not rows:
        rows = [[]]
    width = PAD * 2 + max((len(r) for r in rows), default=0) * \
        (BOX_W + GAP_X) - (GAP_X if any(rows) else 0)
    height = PAD * 2 + len(rows) * (BOX_H + GAP_Y) - GAP_Y
    out = [f'<svg xmlns="http://www.w3.org/2000/svg" '
           f'width="{max(width, 60)}" height="{max(height, 60)}" '
           f'font-family="monospace" font-size="11">',
           f'<title>{_xml(graph.name)}</title>']
    for ri, names in enumerate(rows):
        y = PAD + ri * (BOX_H + GAP_Y)
        for ci, name in enumerate(names):
            x = PAD + ci * (BOX_W + GAP_X)
            out.append(
                f'<rect x="{x}" y="{y}" width="{BOX_W}" height="{BOX_H}"'
                f' rx="6" fill="#eef3fa" stroke="#47618a"/>')
            label = name if len(name) <= 20 else name[:19] + "…"
            out.append(f'<text x="{x + BOX_W / 2}" y="{y + BOX_H / 2 + 4}"'
                       f' text-anchor="middle">{_xml(label)}</text>')
            if ci:
                ax = x - GAP_X
                out.append(
                    f'<line x1="{ax}" y1="{y + BOX_H / 2}" x2="{x - 6}"'
                    f' y2="{y + BOX_H / 2}" stroke="#47618a"/>'
                    f'<polygon points="{x - 6},{y + BOX_H / 2 - 4} '
                    f'{x},{y + BOX_H / 2} {x - 6},{y + BOX_H / 2 + 4}"'
                    f' fill="#47618a"/>')
    out.append("</svg>")
    return "\n".join(out)


def _xml(s: str) -> str:
    import html
    return html.escape(s, quote=True)


class MonitoringThread(threading.Thread):
    """1 Hz stats reporter (monitoring.hpp:162-314)."""

    def __init__(self, graph, machine: str = None, port: int = None,
                 interval_s: float = 1.0):
        super().__init__(name="windflow-monitor", daemon=True)
        self.graph = graph
        cfg = graph.config
        self.machine = machine or cfg.dashboard_machine
        self.port = port or cfg.dashboard_port
        self.interval_s = interval_s
        self._stop_evt = threading.Event()
        self.app_id = -1
        self.sock = None
        self.snapshot_path = None  # set by the dashboard-less fallback

    # -- framed protocol ---------------------------------------------------
    def _send_frame(self, *parts: bytes) -> None:
        self.sock.sendall(b"".join(parts))

    def _register(self) -> bool:
        try:
            self.sock = socket.create_connection(
                (self.machine, self.port), timeout=2.0)
            diagram = graph_to_svg(self.graph).encode()
            self._send_frame(struct.pack("<ii", 0, len(diagram)), diagram)
            ack = b""
            while len(ack) < 4:  # the 4-byte app-id ack may fragment
                chunk = self.sock.recv(4 - len(ack))
                if not chunk:
                    break
                ack += chunk
            if len(ack) == 4:
                self.app_id = struct.unpack("<i", ack)[0]
                return True
        except OSError:
            pass
        # failure: don't carry a half-registered connection into the
        # long-lived snapshot fallback (leaked fd + a ghost app on the
        # dashboard side if the register frame landed)
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        return False

    def _report(self) -> None:
        payload = self._stats_json().encode()
        self._send_frame(struct.pack("<iii", 1, self.app_id, len(payload)),
                         payload)

    def _deregister(self) -> None:
        try:
            self._send_frame(struct.pack("<iii", 2, self.app_id, 0))
        except OSError:
            pass

    def _stats_json(self) -> str:
        stats = getattr(self.graph, "stats", None)
        refresh = getattr(self.graph, "refresh_gauges", None)
        if refresh is not None:
            refresh()  # channel-depth / credit-wait gauges per replica
        # diagnosis plane (diagnosis/): the monitor tick doubles as the
        # history/anomaly/attribution cadence (rate-limited internally)
        diag = getattr(self.graph, "diagnosis", None)
        if diag is not None:
            diag.maybe_tick()
        if stats is not None:
            dls = getattr(self.graph, "dead_letters", None)
            flight = getattr(self.graph, "flight", None)
            events = None
            if flight is not None and flight.enabled:
                events = flight.snapshot()[-FLIGHT_IN_REPORT:]
            return stats.to_json(self.graph.get_num_dropped_tuples(),
                                 dls.count() if dls is not None else 0,
                                 flight_events=events)
        return "{}"

    # -- thread body -------------------------------------------------------
    def _fallback(self) -> None:
        """Dashboard unreachable (at registration or mid-run): never
        silently stop reporting -- drop the socket, warn once per
        process and switch to periodic log-dir stats-JSON snapshots,
        so the run is not silently untraced."""
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        _warn_dashboard_unreachable(self.machine, self.port,
                                    self.graph.config.log_dir)
        self._snapshot_loop()

    def run(self) -> None:
        if not self._register():
            self._fallback()
            return
        while not self._stop_evt.is_set():
            try:
                self._report()
            except OSError:
                self._fallback()  # dashboard died mid-run
                return
            self._stop_evt.wait(self.interval_s)
        try:
            self._report()
            self._deregister()
        except OSError:
            pass  # shutdown path: the graph is ending anyway
        finally:
            if self.sock is not None:
                self.sock.close()

    def _snapshot_loop(self) -> None:
        """Dashboard-less fallback: refresh + write the stats JSON to
        ``log_dir/<pid>_<graph>_stats.json`` every reporting interval
        (atomic rename so a reader never sees a torn file).  Each run
        writes ONE file keyed by pid+graph, but successive runs used to
        accumulate in ``log_dir`` without bound; rotation keeps the
        newest ``RuntimeConfig.snapshot_keep`` snapshot files (default
        16; <= 0 disables rotation)."""
        from ..distributed.identity import worker_suffix
        d = self.graph.config.log_dir
        # worker-id component (distributed/identity.py): two workers of
        # one graph on one box never clobber each other's snapshots
        path = os.path.join(
            d,
            f"{os.getpid()}_{self.graph.name}{worker_suffix()}_stats.json")
        self.snapshot_path = path

        def write():
            try:
                os.makedirs(d, exist_ok=True)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(self._stats_json())
                os.replace(tmp, path)
            except OSError:
                pass  # log dir gone read-only: keep trying, stay alive

        write()
        rotate_snapshots(d, self.graph.config.snapshot_keep)
        while True:
            if self._stop_evt.wait(self.interval_s):
                write()  # final state at wait_end
                return
            write()

    def stop(self) -> None:
        self._stop_evt.set()
        self.join(timeout=5.0)


# the per-run artifact families rotation prunes INDEPENDENTLY (keep
# the newest N of each): periodic stats snapshots, flight-recorder
# JSONL dumps, raw runtime-channel stats, and the tracing log dump's
# json/dot/svg triple.  Families are suffix-disjoint by construction
# (the log dump's plain ``.json`` carries no ``_stats``/``_runtime``
# marker), so one family's churn never evicts another's history.
_ROTATED_FAMILIES = ("_stats.json", "_flight.jsonl", "_runtime.json",
                     ".dot", ".svg", ".json")


def _family_of(name: str) -> Optional[str]:
    for suffix in _ROTATED_FAMILIES:
        if name.endswith(suffix):
            return suffix
    return None


def rotate_snapshots(log_dir: str, keep: int) -> None:
    """Keep-last-N rotation of ``log_dir``'s per-run artifact
    families: stats snapshots (``*_stats.json``), flight-recorder
    dumps (``*_flight.jsonl``), runtime channel stats
    (``*_runtime.json``) and tracing log dumps (``*.json/.dot/.svg``)
    -- each family pruned independently, oldest (by mtime) first, so a
    long supervised soak no longer grows ``log/`` without bound.
    Stall reports and anything unrecognized stay.  ``keep <= 0``
    disables rotation.  Called when a snapshot fallback loop starts
    and after every flight/log dump."""
    if keep is None or keep <= 0:
        return
    try:
        by_family: dict = {}
        for n in os.listdir(log_dir):
            fam = _family_of(n)
            if fam is None:
                continue
            p = os.path.join(log_dir, n)
            try:
                by_family.setdefault(fam, []).append(
                    (os.path.getmtime(p), p))
            except OSError:
                continue  # raced with another process's rotation
        for paths in by_family.values():
            if len(paths) <= keep:
                continue
            paths.sort()
            for _mt, p in paths[:len(paths) - keep]:
                try:
                    os.remove(p)
                except OSError:
                    pass
    except OSError:
        pass  # unreadable log dir: rotation is best-effort


_dash_warned = False


def _warn_dashboard_unreachable(machine: str, port: int,
                                log_dir: str) -> None:
    global _dash_warned
    if _dash_warned:
        return
    _dash_warned = True
    warnings.warn(
        f"windflow_tpu monitoring: dashboard at {machine}:{port} is "
        f"unreachable; falling back to periodic stats-JSON snapshots "
        f"under {log_dir!r}", RuntimeWarning, stacklevel=2)
