"""Minimal dashboard server: receives the framed TCP protocol.

The reference's dashboard directory is empty in its snapshot (a Java
Spring + React app upstream, README "Web Dashboard"); the wire protocol
is fully specified by monitoring.hpp (SURVEY.md §3.5).  This module
provides a self-contained receiver speaking that protocol so traced
graphs have somewhere to report: it stores the latest stats per app and
can serve them as JSON over HTTP for any front-end.

Run standalone:  python -m windflow_tpu.monitoring.dashboard
(ingest on :20207, HTTP snapshot on :20208/apps)
"""
from __future__ import annotations

import json
import socket
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict


class DashboardServer(threading.Thread):
    """Accepts many apps; keeps per-app diagram + latest report."""

    def __init__(self, host: str = "127.0.0.1", port: int = 20207):
        super().__init__(name="windflow-dashboard", daemon=True)
        self.server = socket.create_server((host, port))
        self.port = self.server.getsockname()[1]
        self.lock = threading.Lock()
        self.apps: Dict[int, dict] = {}
        self._next_id = 1
        self._stop_evt = threading.Event()

    # -- framed protocol (mirror of monitoring.hpp:232-313) ---------------
    @staticmethod
    def _recv_exact(conn, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def _serve_conn(self, conn) -> None:
        app_id = None
        try:
            with conn:
                mtype, length = struct.unpack(
                    "<ii", self._recv_exact(conn, 8))
                if mtype != 0:
                    return
                diagram = self._recv_exact(conn, length).decode(
                    errors="replace")
                with self.lock:
                    app_id = self._next_id
                    self._next_id += 1
                    self.apps[app_id] = {"diagram": diagram, "report": None,
                                         "reports_received": 0,
                                         "active": True}
                conn.sendall(struct.pack("<i", app_id))
                while True:
                    mtype, aid, length = struct.unpack(
                        "<iii", self._recv_exact(conn, 12))
                    if mtype == 2:
                        with self.lock:
                            if aid in self.apps:
                                self.apps[aid]["active"] = False
                        return
                    payload = self._recv_exact(conn, length)
                    with self.lock:
                        if aid in self.apps:
                            try:
                                self.apps[aid]["report"] = json.loads(payload)
                            except json.JSONDecodeError:
                                pass
                            self.apps[aid]["reports_received"] += 1
        except (ConnectionError, OSError, struct.error):
            if app_id is not None:
                with self.lock:
                    if app_id in self.apps:
                        self.apps[app_id]["active"] = False

    def run(self) -> None:
        self.server.settimeout(0.5)
        while not self._stop_evt.is_set():
            try:
                conn, _ = self.server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def stop(self) -> None:
        self._stop_evt.set()
        self.server.close()
        self.join(timeout=2)

    def snapshot(self) -> dict:
        with self.lock:
            return json.loads(json.dumps(self.apps))


def serve_http(dash: DashboardServer, port: int = 20208, server=None):
    """Expose the dashboard over HTTP: the self-contained HTML
    front-end at ``/`` (webui.py -- the React-dashboard equivalent),
    the registered-apps index at ``/index`` (one row per app with its
    per-app links, so a multi-tenant operator discovers tenants
    without knowing names a priori), the OpenMetrics text exposition
    at ``/metrics`` (telemetry/metrics.py -- point a Prometheus
    scraper here and every traced graph's counters and latency
    histograms come along), the diagnosis surfaces at ``/flight``
    (per-app FlightRecorder ring, as shipped inside the monitor
    reports -- reachable without a stall or crash triggering a JSONL
    dump) and ``/explain`` (per-app doctor report, the same pure fold
    as ``PipeGraph.explain()`` and the doctor CLI), the serving
    plane's ``/tenants`` view (per-app ``Tenant`` blocks, plus the
    hosting Server's Tenants block when ``server`` is given), and the
    JSON state at ``/apps`` (and any other path, kept permissive for
    curl users).  ``/apps``, ``/explain`` and ``/flight`` accept an
    ``?app=<id>`` filter.  ``port=0`` binds an ephemeral port (read it
    back from ``httpd.server_address``)."""

    class Handler(BaseHTTPRequestHandler):
        def _filtered(self):
            """Dashboard snapshot, narrowed by ?app=<id> when given."""
            from urllib.parse import parse_qs, urlsplit
            snap = dash.snapshot()
            qs = parse_qs(urlsplit(self.path).query)
            wanted = qs.get("app")
            if wanted:
                snap = {aid: app for aid, app in snap.items()
                        if str(aid) in wanted}
            return snap

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path in ("/", "/index.html"):
                from .webui import HTML_PAGE
                body = HTML_PAGE.encode()
                ctype = "text/html; charset=utf-8"
            elif path == "/index":
                # registered-apps index: discovery endpoint for
                # multi-tenant operators -- every app with its name,
                # tenant identity (when served) and per-app links
                snap = dash.snapshot()
                out = {}
                for aid, app in sorted(snap.items(),
                                       key=lambda kv: str(kv[0])):
                    if not isinstance(app, dict):
                        continue
                    rep = app.get("report") or {}
                    out[str(aid)] = {
                        "graph": rep.get("PipeGraph_name"),
                        "active": bool(app.get("active")),
                        "tenant": rep.get("Tenant"),
                        "links": {
                            "apps": f"/apps?app={aid}",
                            "explain": f"/explain?app={aid}",
                            "flight": f"/flight?app={aid}",
                            "metrics": "/metrics",
                        },
                    }
                body = json.dumps(out).encode()
                ctype = "application/json"
            elif path == "/tenants":
                # serving plane: per-app Tenant blocks (+ the hosting
                # Server's own Tenants view when one is attached)
                snap = dash.snapshot()
                tenants = {}
                for aid, app in sorted(snap.items(),
                                       key=lambda kv: str(kv[0])):
                    if not isinstance(app, dict):
                        continue
                    rep = app.get("report") or {}
                    if rep.get("Tenant"):
                        tenants[str(aid)] = dict(
                            rep["Tenant"],
                            graph=rep.get("PipeGraph_name"),
                            active=bool(app.get("active")))
                out = {"apps": tenants}
                if server is not None:
                    out["server"] = server.stats()
                body = json.dumps(out).encode()
                ctype = "application/json"
            elif path == "/metrics":
                from ..telemetry.metrics import (CONTENT_TYPE,
                                                 render_openmetrics)
                body = render_openmetrics(dash.snapshot()).encode()
                ctype = CONTENT_TYPE
            elif path == "/flight":
                snap = self._filtered()
                body = json.dumps({
                    str(aid): (app.get("report") or {}).get("Flight") or []
                    for aid, app in snap.items()
                    if isinstance(app, dict)}).encode()
                ctype = "application/json"
            elif path == "/cluster":
                # live cluster view (docs/OBSERVABILITY.md): fold every
                # registered app's latest report with merge_stats --
                # the workers of one distributed run each register as
                # an app carrying a Worker id, so the fold is the same
                # one-graph view the coordinator's ClusterObserver
                # serves (and `doctor --watch` polls either endpoint)
                from ..diagnosis.report import build_report
                from ..distributed.observe import merge_stats
                snap = dash.snapshot()
                reports = []
                for aid, app in sorted(snap.items(),
                                       key=lambda kv: str(kv[0])):
                    if not isinstance(app, dict) or not app.get("report"):
                        continue
                    rep = dict(app["report"])
                    if rep.get("Worker") is None:
                        # single-process apps carry no worker id; give
                        # each a distinct pseudo-id so the merge's
                        # (worker, seq) flight dedup cannot collide
                        # two unrelated graphs' per-process seqs
                        rep["Worker"] = f"app{aid}"
                    reports.append(rep)
                # live=True: these are mid-run snapshots captured at
                # different instants -- merge-time wire imbalances are
                # skew, not loss (online detectors own live loss)
                merged = merge_stats(reports, live=True)
                rep = build_report(merged, merged.get("Flight")) \
                    if merged else None
                body = json.dumps({"merged": merged,
                                   "report": rep}).encode()
                ctype = "application/json"
            elif path == "/explain":
                from ..diagnosis.report import build_report
                snap = self._filtered()
                out = {}
                for aid, app in snap.items():
                    if isinstance(app, dict) and app.get("report"):
                        out[str(aid)] = build_report(app["report"])
                body = json.dumps(out).encode()
                ctype = "application/json"
            else:
                body = json.dumps(self._filtered()).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


if __name__ == "__main__":
    dash = DashboardServer()
    dash.start()
    serve_http(dash)
    print(f"windflow dashboard: ingest :{dash.port}, http :20208/apps")
    dash.join()
