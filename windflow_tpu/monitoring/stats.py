"""Per-replica statistics records and JSON aggregation.

Re-design of reference ``wf/stats_record.hpp`` (:45-165) and the
JSON aggregation spread across operators (source.hpp:399-427) and
PipeGraph (pipegraph.hpp:791-851).  Counters kept per replica, updated
inline by the runtime node loop, aggregated into the same JSON shape
the reference ships to its dashboard; device-era metrics replace the
CUDA ones (kernels launched / bytes H2D/D2H -> program launches /
bytes staged to device, stats_record.hpp:77-79).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..telemetry.histogram import LogHistogram

# Stats-JSON schema version (the top-level ``Schema_version`` field).
# 3 = the diagnosis-plane layout (adds Topology / Diagnosis / History /
# optional Flight on top of the PR 7 telemetry and PR 9 audit blocks).
# 4 = adds the optional Durability block (epoch coordinator gauges).
# 5 = adds the optional Worker id + Wire block (distributed runtime's
# per-edge wire delivery books; distributed/observe.py merges them).
# 6 = adds the optional Slo block (burn-rate tracker gauges,
# slo/plane.py) and the Pool block (ColumnPool arena occupancy).
# 7 = adds the optional Tenant block (serving plane identity: name,
# state, priority/weight, live credit lease, arbitration count --
# serving/server.py publishes it per tenant graph).
# 8 = the Durability block gains Delta / Last_commit_bytes (delta
# snapshot sizing) and the optional Replica_restarts counter
# (supervised self-healing, durability/supervision.py).
# 9 = Skew.Census rows may carry tiered keyed-state extras (per-tier
# "tiers" key/byte splits plus spills / spill_bytes / promotions /
# demotions / sheds counters -- state/tiers.py census()) and
# Skew.Hot_keys entries may name each hot key's tier ("tiers").
# 10 = replica records may carry event-time plane gauges
# (eventtime/; docs/EVENTTIME.md): Late_tuples (allowed-lateness
# misses quarantined to dead letters), Sessions_open (live gap
# sessions) and Join_state_keys (keys with buffered join state) --
# emitted only when nonzero.
# 11 = adds the optional Scheduler block (global-scheduler plane,
# scheduler/: tenant->worker placement, fair-share leases, device
# leases -- serving/server.py publishes it per tenant graph when the
# plane is on) and replica records may carry Sched_wait_s (seconds a
# consume loop spent gated by the fair-share lease; emitted only when
# nonzero).
# Readers (doctor CLI, dashboard /explain, tests) must tolerate MISSING
# blocks rather than dispatch on this number: older dumps carry no
# version field at all, and every block is optional by contract.
SCHEMA_VERSION = 11


@dataclass
class StatsRecord:
    """Per-replica counters (stats_record.hpp:45-165)."""

    operator_name: str = ""
    replica_id: str = "0"
    start_time: float = field(default_factory=time.time)
    terminated: bool = False
    inputs_received: int = 0
    bytes_received: int = 0
    outputs_sent: int = 0
    bytes_sent: int = 0
    inputs_ignored: int = 0
    # tuples whose svc raised under a skip/dead_letter error policy
    # (resilience/policies.py); the replica stayed alive
    svc_failures: int = 0
    # EWMA service times (microseconds), updated inline like
    # win_seq.hpp:499-509.  Since the batched-stats amortization
    # (graph compile pass PR) observations are SAMPLED -- stride 1 for
    # the first 64, then 1/16 (or once per get_many batch) -- so the
    # mean runs over ``samples``, not ``inputs_received``; tracing no
    # longer costs a perf_counter pair per tuple
    service_time_us: float = 0.0
    eff_service_time_us: float = 0.0
    samples: int = 0
    # device metrics (TPU analogues of stats_record.hpp:77-79)
    num_launches: int = 0
    bytes_to_device: int = 0
    bytes_from_device: int = 0
    # per-launch device timing (docs/PLANNER.md): cumulative wall time
    # from program submit to result-on-host, summed over launches by
    # the engine's dispatcher.  With the transport RTT floor this
    # finally separates transport from compute behind the tunnel:
    # est. transport = launches x floor, est. compute = the rest.
    device_time_ms: float = 0.0
    # resident-lane gauge (docs/PLANNER.md "Resident state"): bytes of
    # per-key window state living in device memory ACROSS launches
    # (FFAT forest / pane-partial rings).  Separate from the shipped
    # byte counters above, which on the resident lane count only NEW
    # bytes per launch (events in + results out) -- the >=10x
    # bytes/launch claim is the ratio between the two lanes' shipped
    # counters, measurable because state never re-ships.
    device_state_bytes: int = 0
    # ingest-plane metrics (ingest/; zero outside ingest sources):
    # admission-shed tuples, live credit level, tuples parked in outlet
    # channels, the controller's current coalesced batch size and its
    # recent (time, batch_size) decision trace
    tuples_shed: int = 0
    credits_available: int = 0
    ingest_queue_depth: int = 0
    ingest_batch_size: int = 0
    # DEFENSIVE bound only: the ingest reporter REBINDS this attribute
    # with the controller's <=32-entry trace tail each report
    # (ingest/sources.py), and the real rolling bound on long-running
    # sources lives in MicrobatchController.trace; the deque caps any
    # direct appender so the record can never become a slow leak
    controller_trace: deque = field(
        default_factory=lambda: deque(maxlen=64))
    # standalone gauges refreshed by PipeGraph.refresh_gauges before
    # every report: tuples parked in this replica's inbound channel and
    # cumulative seconds its source gate spent blocked on credits.
    # Useful to operators on their own and the raw inputs of the
    # elastic signal plane (elastic/signals.py)
    queue_depth: int = 0
    credit_wait_s: float = 0.0
    # cumulative seconds this replica's consume loop spent blocked in
    # the worker's fair-share gate (scheduler/leases.py) -- lets the
    # diagnosis plane name SCHEDULING, not queueing or credits, as the
    # bottleneck.  Zero (and not emitted) when the plane is off.
    sched_wait_s: float = 0.0
    # peak inbound-channel depth, measured by both channel planes since
    # PR 1 (runtime/queues.py:73 / native.py:209) and exported here
    queue_high_watermark: int = 0
    # audit plane (audit/progress.py): the replica's low-watermark
    # frontier (per-source position units) and how long it has been
    # held back while work was pending
    frontier: float = 0.0
    frontier_lag_ms: float = 0.0
    # event-time plane gauges (eventtime/; docs/EVENTTIME.md), written
    # inline by the event-time logics: tuples behind the allowed-
    # lateness horizon (quarantined, never silently dropped), live gap
    # sessions, and keys holding buffered join state
    late_tuples: int = 0
    sessions_open: int = 0
    join_state_keys: int = 0
    # telemetry plane (telemetry/; docs/OBSERVABILITY.md): per-replica
    # single-writer log-bucketed latency histograms, merged across
    # replicas at report time.  ``service`` is fed by the sampled
    # observe() path below; ``residency`` and ``e2e`` by the trace
    # stamping in the runtime node loop (e2e on sink replicas only,
    # created lazily at the first trace closure)
    service_hist: Optional[LogHistogram] = None
    residency_hist: Optional[LogHistogram] = None
    e2e_hist: Optional[LogHistogram] = None

    def ensure_hists(self) -> None:
        """Create the service/residency histograms (idempotent);
        called when the graph's telemetry plane is enabled."""
        if self.service_hist is None:
            self.service_hist = LogHistogram()
        if self.residency_hist is None:
            self.residency_hist = LogHistogram()

    def observe(self, elapsed_us: float) -> None:
        self.samples += 1
        self.service_time_us += \
            (elapsed_us - self.service_time_us) / self.samples
        h = self.service_hist
        if h is not None:
            h.observe(elapsed_us)

    def set_terminated(self) -> None:
        self.terminated = True

    def to_dict(self) -> dict:
        d = {
            "Replica_id": self.replica_id,
            "Starting_time": self.start_time,
            "Terminated": self.terminated,
            "Inputs_received": self.inputs_received,
            "Bytes_received": self.bytes_received,
            "Outputs_sent": self.outputs_sent,
            "Bytes_sent": self.bytes_sent,
            "Inputs_ignored": self.inputs_ignored,
            "Svc_failures": self.svc_failures,
            "Shed_tuples": self.tuples_shed,
            "Service_time_usec": round(self.service_time_us, 3),
            "Eff_Service_time_usec": round(self.eff_service_time_us, 3),
            "Device_launches": self.num_launches,
            "Bytes_to_device": self.bytes_to_device,
            "Bytes_from_device": self.bytes_from_device,
            "Device_time_ms": round(self.device_time_ms, 3),
            "Queue_depth": self.queue_depth,
            "Queue_high_watermark": self.queue_high_watermark,
            "Credit_wait_s": round(self.credit_wait_s, 3),
            "Frontier": round(self.frontier, 1),
            "Frontier_lag_ms": round(self.frontier_lag_ms, 1),
        }
        if self.sched_wait_s:
            # fair-share gate wait (scheduler/leases.py): nonzero only
            # when co-resident tenants actually contended
            d["Sched_wait_s"] = round(self.sched_wait_s, 3)
        if self.device_state_bytes:
            d["Device_state_bytes_resident"] = self.device_state_bytes
        # event-time plane gauges: nonzero only on eventtime/ replicas
        if self.late_tuples:
            d["Late_tuples"] = self.late_tuples
        if self.sessions_open:
            d["Sessions_open"] = self.sessions_open
        if self.join_state_keys:
            d["Join_state_keys"] = self.join_state_keys
        if self.num_launches:
            # per-launch derivations + the roofline estimate: achieved
            # bytes/s over the launch wall time as a fraction of the
            # configured peak (WINDFLOW_ROOFLINE_GBPS; an estimate --
            # wall time includes transport, so this UNDERSTATES the
            # on-chip HBM fraction and is honest as a lower bound)
            d["Device_ms_per_launch"] = round(
                self.device_time_ms / self.num_launches, 3)
            d["Device_bytes_per_launch"] = int(
                (self.bytes_to_device + self.bytes_from_device)
                / self.num_launches)
            try:
                peak = float(os.environ.get("WINDFLOW_ROOFLINE_GBPS", "32"))
            except ValueError:
                peak = 0.0  # malformed override: omit the estimate
            if self.device_time_ms > 0 and peak > 0:
                achieved = (self.bytes_to_device + self.bytes_from_device) \
                    / (self.device_time_ms / 1e3) / 1e9
                d["Device_roofline_frac"] = round(achieved / peak, 4)
        if self.ingest_batch_size:     # ingest source replicas only
            d["Ingest_credits"] = self.credits_available
            d["Ingest_queue_depth"] = self.ingest_queue_depth
            d["Ingest_batch_size"] = self.ingest_batch_size
            d["Controller_batch_trace"] = [
                [round(t, 3), b]
                for t, b in list(self.controller_trace)[-32:]]
        if self.service_hist is not None:
            lat = {"service": self.service_hist.to_dict(),
                   "residency": self.residency_hist.to_dict()}
            if self.e2e_hist is not None:
                lat["e2e"] = self.e2e_hist.to_dict()
            d["Latency"] = lat
        return d


def get_mem_usage_kb() -> int:
    """Process RSS in KiB (monitoring.hpp:49-68 reads /proc/self/status)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return 0


class GraphStats:
    """Aggregates per-operator replica records into the dashboard JSON
    (pipegraph.hpp:791-851 generate_JSONStats)."""

    def __init__(self, graph_name: str):
        self.graph_name = graph_name
        self.lock = threading.Lock()
        self.records: Dict[str, List[StatsRecord]] = {}
        # elastic scaling plane (elastic/): records of retired replicas
        # stay (terminated, history), so the LIVE parallelism of a
        # rescaled operator is an explicit override; plus the rescale
        # event log surfaced in the JSON
        self.current_parallelism: Dict[str, int] = {}
        self.rescale_events: List[dict] = []
        # placement planner decisions (graph/planner.py): one entry per
        # window engine replica, recorded at PipeGraph.start
        self.placements: List[dict] = []
        # telemetry plane (telemetry/; docs/OBSERVABILITY.md): once
        # enabled, every record (existing and future -- rescale-created
        # replicas register through register()) carries latency
        # histograms; closed traces land in the bounded recent-record
        # ring and, when a sink replica has no record, in the graph-
        # level e2e fallback histogram
        self.histograms = False
        self.e2e_extra: Optional[LogHistogram] = None
        self.trace_records: deque = deque(maxlen=16)
        # distributed plane: producer-side PARTIAL records of traces
        # that left this worker over a wire edge (the consumer closes
        # them; the merge stitches by id).  A separate ring so a busy
        # outbound edge can never evict this worker's own closed
        # records from the bounded ring above.
        self.trace_partials: deque = deque(maxlen=16)
        # audit plane (audit/; docs/OBSERVABILITY.md): the latest
        # Conservation and Skew blocks, published by the GraphAuditor
        # after every pass (and after the wait_end final check)
        self.audit_conservation: Optional[dict] = None
        self.audit_skew: Optional[dict] = None
        # diagnosis plane (diagnosis/; docs/OBSERVABILITY.md): the
        # operator-level topology (set once at start), and the latest
        # Diagnosis / History blocks published per tick
        self.topology: Optional[List[List[str]]] = None
        self.diagnosis: Optional[dict] = None
        self.history: Optional[dict] = None
        # durability plane (durability/; docs/RESILIENCE.md): the
        # latest epoch-coordinator gauges (committed epoch, lag,
        # commit wall time, stall flag), published per commit/tick
        self.durability: Optional[dict] = None
        # distributed runtime plane (distributed/; docs/DISTRIBUTED.md):
        # this process's worker id (None = single-process graph) and
        # the latest per-edge wire delivery books, refreshed per report
        self.worker: Optional[int] = None
        self.wire: Optional[dict] = None
        # SLO plane (slo/; docs/OBSERVABILITY.md "SLO plane"): the
        # burn-rate tracker's latest gauges, published per diagnosis
        # tick; and the ColumnPool arena occupancy gauges (memory-
        # pressure evidence for the SLO/doctor surfaces)
        self.slo: Optional[dict] = None
        self.pool: Optional[dict] = None
        # serving plane (serving/; docs/SERVING.md): this graph's
        # tenant identity under a multi-tenant Server -- name, state,
        # priority/weight standing, live credit lease, arbitration
        # count; None outside a served run
        self.tenant: Optional[dict] = None
        # global-scheduler plane (scheduler/; docs/SERVING.md "Global
        # scheduler"): which worker hosts this tenant, its fair-share
        # weight, its device leases; None when the plane is off
        self.scheduler: Optional[dict] = None

    def register(self, operator_name: str, replica_id: str) -> StatsRecord:
        rec = StatsRecord(operator_name, replica_id)
        with self.lock:
            if self.histograms:
                rec.ensure_hists()
            self.records.setdefault(operator_name, []).append(rec)
        return rec

    def enable_histograms(self) -> None:
        """Turn on the latency-histogram surface: backfills every
        already-registered record and marks future registrations."""
        with self.lock:
            self.histograms = True
            if self.e2e_extra is None:
                self.e2e_extra = LogHistogram()
            for replicas in self.records.values():
                for r in replicas:
                    r.ensure_hists()

    def add_trace_record(self, rec) -> None:
        """Append one closed end-to-end trace as a live ``(TraceContext,
        t_end)`` pair (deque append: no lock).  Serialization happens at
        report time so hop stamps that land just after closure -- fused
        upstream segments unwind outward through the closing sink --
        still make the record."""
        self.trace_records.append(rec)

    def add_trace_partial(self, rec) -> None:
        """Append one producer-side partial trace view (same live
        ``(view, t)`` contract as :meth:`add_trace_record`, separate
        bounded ring)."""
        self.trace_partials.append(rec)

    def set_parallelism(self, operator_name: str, n: int) -> None:
        with self.lock:
            self.current_parallelism[operator_name] = n

    def record_rescale(self, event) -> None:
        """Append a completed RescaleEvent (elastic/rescale.py)."""
        with self.lock:
            self.rescale_events.append(event.to_dict())

    def set_placements(self, decisions: List[dict]) -> None:
        """Record the planner's per-engine placement decisions
        (graph/planner.plan_graph)."""
        with self.lock:
            self.placements = list(decisions)

    def set_audit(self, conservation: dict, skew: dict) -> None:
        """Publish the auditor's latest Conservation/Skew blocks
        (audit/auditor.py)."""
        with self.lock:
            self.audit_conservation = conservation
            self.audit_skew = skew

    def set_topology(self, edges: List[List[str]]) -> None:
        """Record the operator-level edge list (diagnosis/topology.py)
        so the bottleneck walk works on serialized reports too."""
        with self.lock:
            self.topology = list(edges)

    def set_diagnosis(self, block: dict, history: Optional[dict]) -> None:
        """Publish the diagnosis plane's latest Diagnosis/History
        blocks (diagnosis/plane.py, once per tick)."""
        with self.lock:
            self.diagnosis = block
            self.history = history

    def set_durability(self, block: dict) -> None:
        """Publish the epoch coordinator's latest gauges
        (durability/coordinator.py, per commit/tick)."""
        with self.lock:
            self.durability = block

    def set_wire(self, block: dict) -> None:
        """Publish the distributed plane's per-edge wire books
        (distributed/wiring.DistRuntime.wire_block, per gauge
        refresh)."""
        with self.lock:
            self.wire = block

    def set_slo(self, block: dict) -> None:
        """Publish the SLO tracker's latest burn-rate gauges
        (slo/plane.py, once per diagnosis tick)."""
        with self.lock:
            self.slo = block

    def set_pool(self, block: Optional[dict]) -> None:
        """Publish the ColumnPool arena occupancy gauges
        (diagnosis/plane.py, once per tick)."""
        with self.lock:
            self.pool = block

    def set_tenant(self, block: Optional[dict]) -> None:
        """Publish the serving plane's tenant identity block
        (serving/server.py, at submit and on every state/lease
        change)."""
        with self.lock:
            self.tenant = block

    def set_scheduler(self, block: Optional[dict]) -> None:
        """Publish the global-scheduler plane's placement/lease block
        (serving/server.py, after start and on every lease change)."""
        with self.lock:
            self.scheduler = block

    def to_json(self, dropped_tuples: int = 0,
                dead_letter_tuples: int = 0,
                flight_events: Optional[List[dict]] = None) -> str:
        with self.lock:
            ops = []
            for name, replicas in self.records.items():
                op = {
                    "Operator_name": name,
                    "Operator_type": name.rsplit("/", 1)[-1],
                    "Parallelism": self.current_parallelism.get(
                        name, len(replicas)),
                    "Replicas": [r.to_dict() for r in replicas],
                }
                if self.histograms:
                    # report-time merge of the per-replica single-writer
                    # histograms (telemetry/histogram.py)
                    op["Latency"] = {
                        "service": LogHistogram.merged(
                            r.service_hist for r in replicas
                        ).to_dict(buckets=True),
                        "residency": LogHistogram.merged(
                            r.residency_hist for r in replicas
                        ).to_dict(buckets=True),
                    }
                ops.append(op)
            svc_failures = sum(r.svc_failures
                               for rs in self.records.values() for r in rs)
            shed_tuples = sum(r.tuples_shed
                              for rs in self.records.values() for r in rs)
            rescales = list(self.rescale_events)
            placements = list(self.placements)
            conservation = self.audit_conservation
            skew = self.audit_skew
            topology = self.topology
            diagnosis = self.diagnosis
            history = self.history
            durability = self.durability
            worker = self.worker
            wire = self.wire
            slo = self.slo
            pool = self.pool
            tenant = self.tenant
            scheduler = self.scheduler
            latency_e2e = None
            trace_records: List[dict] = []
            if self.histograms:
                e2e = LogHistogram.merged(
                    r.e2e_hist for rs in self.records.values() for r in rs)
                if self.e2e_extra is not None:
                    e2e.merge_from(self.e2e_extra)
                latency_e2e = e2e.to_dict(buckets=True)
                # snapshot FIRST: list(deque) is one C call (atomic
                # under the GIL), while comprehending over the live
                # deque would raise 'deque mutated during iteration'
                # when a sink thread closes a trace mid-report
                trace_records = [ctx.to_dict(t_end)
                                 for ctx, t_end in list(self.trace_records)]
                # wire-crossing partials ride the same JSON list (the
                # serialized dicts carry "partial": true; attribution
                # skips them, the cross-worker merge stitches by id)
                trace_records += [v.to_dict(t_end) for v, t_end
                                  in list(self.trace_partials)]
        payload = {
            "PipeGraph_name": self.graph_name,
            # report-shape version (see SCHEMA_VERSION above); loaders
            # must treat every block below as optional regardless
            "Schema_version": SCHEMA_VERSION,
            "Mode": "DEFAULT",
            "Backpressure": "ON",
            "Dropped_tuples": dropped_tuples,
            # failure-containment counters (resilience/): tuples whose
            # svc raised under a skip/dead_letter policy, and how many
            # of those were quarantined in the dead-letter store
            "Svc_failures": svc_failures,
            "Dead_letter_tuples": dead_letter_tuples,
            # ingest admission control (ingest/admission.py): tuples
            # shed under overload (also quarantined above)
            "Shed_tuples": shed_tuples,
            # elastic scaling plane (elastic/; docs/ELASTIC.md):
            # completed runtime rescales (timestamp, operator,
            # old -> new parallelism, trigger signal)
            "Rescales": len(rescales),
            "Rescale_events": rescales,
            # cost-based placement planner (graph/planner.py;
            # docs/PLANNER.md): resolved lane + the measured inputs
            # behind every 'auto' decision
            "Placements": placements,
            # audit plane (audit/; docs/OBSERVABILITY.md): the online
            # flow-conservation ledger (per-edge books + graph-wide
            # identity inputs + violations) and the keyed-state /
            # hot-key skew census; None when RuntimeConfig.audit is off
            "Conservation": conservation,
            "Skew": skew,
            # telemetry plane (telemetry/; docs/OBSERVABILITY.md):
            # graph-wide end-to-end latency histogram (merged across
            # sink replicas) and the most recent closed traces with
            # per-hop stamps; None / absent histograms when tracing
            # sampling is off
            "Latency_e2e": latency_e2e,
            "Trace_records": trace_records,
            # diagnosis plane (diagnosis/; docs/OBSERVABILITY.md):
            # operator-level topology edges, the latest critical-path /
            # bottleneck / anomaly diagnosis, and the rolling gauge
            # history ring; None until the first tick (or with the
            # plane disabled)
            "Topology": {"Edges": topology} if topology else None,
            "Diagnosis": diagnosis,
            "History": history,
            # durability plane (durability/; docs/RESILIENCE.md):
            # epoch-coordinator gauges -- committed/begun epoch ids,
            # lag of the oldest uncommitted epoch, last commit wall
            # time, stall flag; None with the plane disabled
            "Durability": durability,
            # distributed runtime plane (distributed/;
            # docs/DISTRIBUTED.md): this process's worker id and the
            # per-edge wire delivery books; None/absent outside
            # distributed runs.  distributed/observe.merge_stats folds
            # N such dumps into one graph view.
            "Worker": worker,
            "Wire": wire,
            # SLO plane (slo/; docs/OBSERVABILITY.md "SLO plane"):
            # burn-rate tracker gauges -- windows, fast/slow burn
            # rates, budget burned, open-breach flag; None with no
            # declared objectives.  The ColumnPool arena occupancy
            # rides next to it as memory-pressure evidence.
            "Slo": slo,
            "Pool": pool,
            # serving plane (serving/; docs/SERVING.md): tenant
            # identity + live lease under a multi-tenant Server; None
            # outside a served run
            "Tenant": tenant,
            # global-scheduler plane (scheduler/; docs/SERVING.md
            # "Global scheduler"): hosting worker, fair-share weight,
            # device leases; None when the plane is off
            "Scheduler": scheduler,
            "Memory_usage_KB": get_mem_usage_kb(),
            "Operator_number": len(ops),
            "Operators": ops,
        }
        if flight_events is not None:
            # bounded FlightRecorder ring snapshot: ships with the
            # monitor reports so the dashboard's /flight endpoint (and
            # the doctor's offline path) can read recent events without
            # a stall/crash triggering a JSONL dump
            payload["Flight"] = flight_events
        return json.dumps(payload)
