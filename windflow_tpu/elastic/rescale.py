"""Pause-drain-migrate rescale mechanics (docs/ELASTIC.md).

The protocol that turns a frozen-parallelism graph into a rescalable
one, composed entirely from machinery earlier planes already proved:

1. **Pause + drain (the rescale barrier).**  ``PipeGraph.quiesce``
   parks every source at a generation-step boundary (the live
   checkpoint barrier, SourcePauseControl) and drains channels and
   in-flight device batches to a globally quiescent state.  Because
   the target operator's inbound channels are empty and its replicas
   are parked between items, *no tuple is in flight across the
   operator*: conservation is structural, not probabilistic.
2. **Snapshot keyed state.**  Every replica's ``keyed_state_dict()``
   (the per-key flattening ``utils/checkpoint.py`` established) is
   merged; keys must be disjoint across replicas -- the KEYBY routing
   invariant -- and a duplicate aborts the rescale loudly.
3. **Repartition + rewire.**  Keys re-hash over the new replica count
   with the exact routing contract the emitters use
   (``default_hash(key) % parallelism``, runtime/win_routing.py /
   StandardEmitter), so ownership after the rescale equals where the
   emitter will route.  Scale-up builds fresh replica threads,
   channels and downstream outlets (mirroring PipeGraph.start's
   bindings: cancel token, pause gate, dead letters, buffer pool,
   fault clocks, stats records) and extends every upstream emitter's
   destination set; CreditedChannel proxies are mirrored onto the new
   channels so ingest credit accounting stays exact.  Scale-down trims
   the upstream fan-out and closes the retiring replicas' channels so
   they unwind through their normal EOS path (their logics emit
   nothing at EOS -- enforced by the elastic validation in
   MultiPipe.add).
4. **Restore + resume.**  Each surviving/new replica loads exactly the
   keys it now owns, the sources resume, and the event is recorded in
   ``GraphStats`` (``Rescale_events`` in the stats JSON + dashboard).

Elastic replicas are a fusion barrier (graph/fuse.py skips them, like
the ingest credit boundary): the compile pass must not fold a node
whose thread set changes at runtime into a neighbour.
"""
from __future__ import annotations

import time as _time
from dataclasses import asdict, dataclass
from typing import Callable, Dict, List, Optional

from ..core.meta import default_hash
from ..ingest.credits import CreditedChannel
from ..runtime.node import NodeLogic, Outlet, RtNode
from ..runtime.queues import make_channel


class RescaleError(RuntimeError):
    """A rescale attempt failed; the graph was resumed and keeps its
    previous parallelism unless stated otherwise in the message."""


@dataclass
class RescaleEvent:
    """One completed rescale, recorded in GraphStats (stats JSON)."""

    at: float            # epoch seconds
    operator: str
    old_parallelism: int
    new_parallelism: int
    trigger: str         # controller signal string or "manual"
    duration_s: float    # pause-to-resume wall time

    def to_dict(self) -> dict:
        d = asdict(self)
        d["duration_s"] = round(d["duration_s"], 6)
        return d


class ElasticHandle:
    """Runtime registry entry for one elastic operator: everything the
    rescale mechanics need, captured at wiring time (MultiPipe).

    ``outlets`` are the upstream Outlet OBJECTS feeding the stage --
    stable across the LEVEL2 compile pass (fusion moves outlet lists by
    reference) and across ingest wiring (credit proxies are swapped
    into ``outlet.dests`` in place)."""

    def __init__(self, name: str, spec, pipe, factory: Callable,
                 replicas: List[RtNode], outlets: List[Outlet],
                 error_policy: str = "fail"):
        self.name = name          # graph-wide key, also the stats key
        self.spec = spec
        self.pipe = pipe
        self.make_logic = factory  # (replica_index, parallelism) -> logic
        self.replicas = list(replicas)
        self.outlets = list(outlets)
        self.error_policy = error_policy

    @property
    def parallelism(self) -> int:
        return len(self.replicas)


def owner_of(key, parallelism: int) -> int:
    """The replica owning ``key`` at ``parallelism`` -- the SAME
    contract as the KEYBY routing plane (StandardEmitter record path
    ``default_hash(key) % n``; its batch path ``abs(int64) % n`` agrees
    because ``default_hash`` is identity-abs on ints)."""
    return default_hash(key) % parallelism


def partition_keyed_state(merged: Dict, parallelism: int) -> List[Dict]:
    """Deterministic, total partition of a merged per-key state mapping
    over ``parallelism`` replicas: every key lands in exactly one part,
    parts are disjoint, and their union is ``merged``."""
    parts: List[Dict] = [{} for _ in range(parallelism)]
    for k, v in merged.items():
        parts[owner_of(k, parallelism)][k] = v
    return parts


def merge_keyed_states(nodes: List[RtNode]):
    """(merged, stateful): snapshot + merge every replica's keyed
    state.  A key owned by two replicas would mean the routing
    invariant was already broken -- abort rather than silently pick
    one."""
    states = []
    for node in nodes:
        getter = getattr(node.logic, "keyed_state_dict", None)
        states.append(getter() if getter is not None else None)
    stateful = any(s is not None for s in states)
    merged: Dict = {}
    if stateful:
        for node, st in zip(nodes, states):
            for k, v in (st or {}).items():
                if k in merged:
                    raise RescaleError(
                        f"key {k!r} held by two replicas of "
                        f"{node.name!r}: keyed routing invariant broken")
                merged[k] = v
    return merged, stateful


def _reset_round_robin(emitter, n: int) -> None:
    # FORWARD StandardEmitter keeps a round-robin cursor; after a
    # shrink it could point past the new destination count
    rr = getattr(emitter, "_rr", None)
    if rr is not None and n > 0:
        emitter._rr = rr % n


def _clone_emitter(emitter):
    """Emitter.clone() with the graph ColumnPool detached first: the
    pool holds locks (not deep-copyable) and must be SHARED by the
    clone, not duplicated.  Any audit hot-key sketch is detached the
    same way -- deep-copying it would duplicate the observed counts;
    the auditor attaches a fresh sketch to the clone instead."""
    pool = getattr(emitter, "pool", None)
    sketch = getattr(emitter, "key_sketch", None)
    if pool is not None:
        emitter.pool = None
    if sketch is not None:
        emitter.key_sketch = None
    try:
        clone = emitter.clone()
    finally:
        if pool is not None:
            emitter.pool = pool
        if sketch is not None:
            emitter.key_sketch = sketch
    clone.pool = pool
    return clone


def _can_load_keyed(logic: NodeLogic) -> bool:
    fn = getattr(type(logic), "load_keyed_state", None)
    return fn is not None and fn is not NodeLogic.load_keyed_state


def rescale_operator(graph, handle: ElasticHandle, new_n: int,
                     trigger: str = "manual",
                     timeout: float = 60.0) -> Optional[RescaleEvent]:
    """Rescale ``handle`` to ``new_n`` replicas; returns the recorded
    event, or None when ``new_n`` equals the current parallelism.
    Caller (PipeGraph.rescale) holds the graph's rescale lock."""
    spec = handle.spec
    new_n = int(new_n)
    if not spec.min_replicas <= new_n <= spec.max_replicas:
        raise ValueError(
            f"rescale({handle.name!r}, {new_n}) outside the declared "
            f"elastic interval [{spec.min_replicas}, "
            f"{spec.max_replicas}]")
    if new_n == len(handle.replicas):
        return None
    t0 = _time.monotonic()
    graph.quiesce(timeout)
    try:
        old_nodes = list(handle.replicas)
        old_n = len(old_nodes)
        if any(not n.is_alive() for n in old_nodes):
            # EOS (or a failure unwind) already reached the operator:
            # there is no live replica set to migrate -- refuse instead
            # of wiring new replicas whose producers will never close
            raise RescaleError(
                f"cannot rescale {handle.name!r}: stream already "
                "ended at the operator")
        merged, stateful = merge_keyed_states(old_nodes)
        if stateful and not all(_can_load_keyed(n.logic)
                                for n in old_nodes):
            # validate BEFORE any rewiring: a failure past this point
            # would leave the graph half-rewired
            raise RescaleError(
                f"{handle.name!r} snapshots keyed state but cannot "
                "load it (load_keyed_state missing)")
        kept = old_nodes[:min(old_n, new_n)]
        added: List[RtNode] = []
        closing = []  # (channel, producer_id) of retiring replicas
        if new_n > old_n:
            added = _grow(graph, handle, old_nodes, new_n)
        else:
            for outlet in handle.outlets:
                closing.extend(outlet.dests[new_n:])
                del outlet.dests[new_n:]
                if outlet.audit_cells is not None:
                    # audit plane: the trimmed destinations are the
                    # retiring replicas' (drained) channels -- their
                    # edges leave the topology with them, but a
                    # source's deliveries into them stay part of the
                    # graph-wide Sources_emitted roll-up
                    if graph.auditor is not None:
                        graph.auditor.ledger.fold_trimmed(
                            outlet, outlet.audit_cells[new_n:])
                    del outlet.audit_cells[new_n:]
                outlet.emitter.set_n_destinations(new_n)
                _reset_round_robin(outlet.emitter, new_n)
        retired = old_nodes[new_n:]
        new_replicas = kept + added
        for node in kept:
            # added replicas were built with the new parallelism; kept
            # ones still hold the old count in their RuntimeContext,
            # which a rich fn(t, ctx) may read for per-replica sharding
            ctx = getattr(node.logic, "context", None)
            if ctx is not None:
                ctx.parallelism = new_n
        if stateful:
            parts = partition_keyed_state(merged, new_n)
            for i, node in enumerate(new_replicas):
                if not _can_load_keyed(node.logic):
                    raise RescaleError(
                        f"{type(node.logic).__name__} cannot load "
                        "keyed state")
                node.logic.load_keyed_state(parts[i])
            for node in old_nodes[new_n:]:
                # the snapshot above is shallow: the survivors' loaded
                # partitions alias the retiring replicas' inner state
                # objects.  Clear the retiring copies before their EOS
                # unwind -- a keyed logic with a destructive eos_flush
                # (event-time windows/joins fire-and-pop) would
                # otherwise re-fire the migrated windows AND mutate
                # state now owned by a survivor
                if _can_load_keyed(node.logic):
                    node.logic.load_keyed_state({})
        handle.replicas = new_replicas
        graph.stats.set_parallelism(handle.name, new_n)
        for node in added:
            node.start()
        # wake the retiring replicas through their EOS path: every
        # producer slot of their (drained) channels closes, get()
        # returns None, eos_flush emits nothing (validated at wiring)
        # and flush_eos closes their downstream producer slots exactly
        # as a natural end of stream would
        for ch, pid in closing:
            ch.close(pid)
        deadline = _time.monotonic() + 10.0
        for node in retired:
            node.join(timeout=max(0.0, deadline - _time.monotonic()))
            if node.is_alive():
                raise RescaleError(
                    f"retired replica {node.name!r} failed to unwind")
            if graph.auditor is not None:
                # migration accounting: fold the retiring replica's
                # delivery books into the per-channel retired ledger --
                # its downstream channels keep cumulative put counts,
                # so dropping the cells without folding would read as
                # a permanent duplication on every scale-down
                graph.auditor.fold_retired(node)
            if getattr(graph, "tiered_state", None) is not None:
                # the retired replica's keys migrated with the merge;
                # its spill segments are dead weight on disk
                graph.tiered_state.release(node.name)
            if node in handle.pipe.nodes:
                handle.pipe.nodes.remove(node)
            if node.stats is not None:
                # the retired record stays as history, but its gauges
                # must not freeze at their last pre-rescale value: the
                # channel is drained and closed, so zero is the truth
                # (dashboard columns sum over ALL replica records)
                node.stats.queue_depth = 0
                node.stats.credit_wait_s = 0.0
    finally:
        graph.resume()
    event = RescaleEvent(_time.time(), handle.name, old_n, new_n,
                         trigger, _time.monotonic() - t0)
    graph.stats.record_rescale(event)
    return event


def _grow(graph, handle: ElasticHandle, old_nodes: List[RtNode],
          new_n: int) -> List[RtNode]:
    """Build, wire and bind replicas old_n..new_n-1 (not yet started)."""
    cfg = graph.config
    old_n = len(old_nodes)
    template = old_nodes[0]
    prefix = template.name.rsplit(".", 1)[0]
    added: List[RtNode] = []
    for i in range(old_n, new_n):
        logic = handle.make_logic(i, new_n)
        node = RtNode(f"{prefix}.{i}", logic, make_channel(cfg), [])
        node.elastic_group = handle.name
        node.error_policy = handle.error_policy
        added.append(node)
    # upstream fan-out: one new destination per outlet, mirroring any
    # credit proxy of the existing destinations (each outlet belongs to
    # one upstream replica, so its gate -- if any -- is uniform across
    # its dests)
    for outlet in handle.outlets:
        gate = None
        proxied = False
        if outlet.dests:
            ch0, pid0 = outlet.dests[0]
            if isinstance(ch0, CreditedChannel):
                proxied = True
                gate = ch0.gates.get(pid0)
        for node in added:
            ch = node.channel
            if proxied and not isinstance(ch, CreditedChannel):
                ch = CreditedChannel(ch)
                node.channel = ch
            pid = ch.register_producer()
            if proxied and gate is not None:
                ch.bind_gate(pid, gate)
            outlet.dests.append((ch, pid))
            if outlet.audit_cells is not None:
                # audit plane: a fresh delivery book per new edge
                from ..audit import EdgeCell
                outlet.audit_cells.append(EdgeCell())
        outlet.emitter.set_n_destinations(new_n)
    # downstream wiring: clone replica 0's outlet shape, registering a
    # fresh producer slot per destination channel (EOS accounting on
    # the consumer side counts slots, so mid-run registration before
    # our stage's own EOS is exact)
    for node in added:
        for o in template.outlets:
            dests = [(dch, dch.register_producer()) for dch, _pid in o.dests]
            node.outlets.append(Outlet(_clone_emitter(o.emitter), dests))
    # runtime plumbing: the same bindings PipeGraph.start applies
    fault_plan = getattr(cfg, "fault_plan", None)
    for idx, node in enumerate(added, start=old_n):
        node.pause_ctl = graph._pause_ctl
        node.cancel_token = graph._cancel
        node.dead_letters = graph.dead_letters
        node.pool = graph.buffer_pool
        # telemetry plane: rescale-created replicas trace and record
        # exactly like start()-wired ones (their stats records pick up
        # histograms via GraphStats.register's enabled flag)
        node.flight = graph.flight
        node.logic.flight = graph.flight
        if graph.telemetry is not None:
            node.telemetry = graph.telemetry
            node.logic.telemetry = graph.telemetry
        if node.pool is not None:
            for o in node.outlets:
                o.emitter.pool = node.pool
        if fault_plan is not None:
            node.faults = fault_plan.for_node(node.name)
            node.bind_outlet_faults()
        if getattr(graph, "tiered_state", None) is not None:
            # tiered keyed state (state/): the grown replica's store
            # must exist BEFORE the auditor binds its hot-key sketch
            # and before load_keyed_state repartitions into it
            graph.tiered_state.enable(node.logic, node.name)
        if graph.auditor is not None:
            # audit plane: delivery books + put faults + sketches on
            # the new replica's own outlets, exactly as at start()
            graph.auditor.attach_node(node)
        if graph.durability is not None:
            # durability plane: the aligner must exist BEFORE the
            # replica thread starts, exactly as the auditor's books
            graph.durability.attach_node(node)
        node.stats = graph.stats.register(handle.name, str(idx))
        graph._cancel.register(node.channel)
    handle.pipe.nodes.extend(added)
    return added
