"""Elastic scaling plane: load-driven runtime rescaling with
keyed-state migration (docs/ELASTIC.md).

The capability the reference lacks outright (SURVEY.md: "no
rescaling" -- replica counts frozen at build time) and the survey's
production gap: DS2 (Kalavri et al., OSDI '18) for the scaling policy,
Flink's key-group state reassignment (Carbone et al., VLDB '17) for
the migration mechanics.  Three parts:

* :mod:`signals` -- per-operator LoadReports from service-time EWMAs,
  channel depth gauges and ingest credit-wait time;
* :mod:`controller` -- hysteresis controller emitting scale decisions
  inside each operator's declared ``[min, max]`` interval;
* :mod:`rescale` -- the epoch-based pause-drain-migrate protocol
  (quiesce barrier, keyed-state repartition by the emitter's
  ``hash % parallelism`` contract, replica/channel rewiring).

Declare with ``.with_elasticity(min, max, target_util)`` on a builder;
tune with ``RuntimeConfig.elasticity = ElasticityConfig(...)``; drive
manually with ``PipeGraph.rescale(name, n)``.
"""
from ..core.basic import ElasticSpec
from .controller import ElasticController, ElasticityConfig, decide, \
    start_controller
from .rescale import (ElasticHandle, RescaleError, RescaleEvent,
                      merge_keyed_states, owner_of, partition_keyed_state,
                      rescale_operator)
from .signals import LoadReport, OperatorSignals, SignalSampler

__all__ = [
    "ElasticSpec", "ElasticityConfig", "ElasticController", "decide",
    "start_controller", "ElasticHandle", "RescaleError", "RescaleEvent",
    "merge_keyed_states", "owner_of", "partition_keyed_state",
    "rescale_operator", "LoadReport", "OperatorSignals", "SignalSampler",
]
