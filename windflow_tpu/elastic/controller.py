"""Hysteresis scaling policy + the controller thread (docs/ELASTIC.md).

Per elastic operator the controller keeps utilization inside a band
around the operator's declared ``target_util``: persistent load above
the band (or a backlog / credit-starvation trigger) scales up toward
``ceil(n * util / target)`` (the DS2 proportional rule, Kalavri et al.
OSDI '18); load below the band with empty queues scales down.  A
per-operator cooldown after every rescale prevents oscillation while
the pipeline re-equilibrates, and every decision is clamped into the
operator's ``[min_replicas, max_replicas]`` interval.

The controller never touches replica threads itself: it calls
``PipeGraph.rescale``, whose pause-drain-migrate mechanics live in
elastic/rescale.py.
"""
from __future__ import annotations

import math
import threading
import time as _time
import warnings
from dataclasses import dataclass
from typing import Optional, Tuple

from .signals import LoadReport, SignalSampler


@dataclass
class ElasticityConfig:
    """Graph-level controller tuning (``RuntimeConfig.elasticity``)."""

    enabled: bool = True
    sample_period_s: float = 0.2
    ewma_alpha: float = 0.5
    # no further rescale of the same operator for this long after one
    cooldown_s: float = 2.0
    # band half-width around each operator's target_util
    hysteresis: float = 0.15
    # backlog trigger: scale up once inbound depth exceeds this fraction
    # of the bounded capacity, regardless of the utilization estimate
    depth_high_frac: float = 0.5
    # credit-starvation trigger: fraction of source wall time spent
    # blocked on credits that counts as upstream pressure
    credit_wait_high: float = 0.5
    # attribution trigger: diagnosis-plane bottleneck score from which
    # being named the root cause behind a sink counts as pressure
    # (fires only for the culprit operator, not the cascade behind it)
    bottleneck_high: float = 0.6
    # max replicas added/removed per decision (0 = jump straight to the
    # proportional estimate)
    max_step: int = 0
    # drain budget handed to PipeGraph.rescale per decision
    quiesce_timeout_s: float = 60.0


def decide(report: LoadReport, spec, cfg: ElasticityConfig) \
        -> Optional[Tuple[int, str]]:
    """(new_parallelism, trigger) or None to hold."""
    n = report.replicas
    hi = spec.target_util + cfg.hysteresis
    lo = spec.target_util - cfg.hysteresis
    pressured = (report.depth_frac >= cfg.depth_high_frac
                 or report.credit_wait_frac >= cfg.credit_wait_high
                 or report.bottleneck >= cfg.bottleneck_high)
    desired = n
    if report.util > hi or pressured:
        base = max(report.util, spec.target_util)  # backlog with a noisy
        #                       low util estimate still adds a replica
        desired = max(n + 1, math.ceil(n * base / spec.target_util))
    elif report.util < lo and report.depth_frac < 0.05 \
            and report.credit_wait_frac < 0.05:
        if report.util > 0.0:
            desired = min(n - 1, max(1, math.ceil(
                n * report.util / spec.target_util)))
        else:
            desired = spec.min_replicas
    if cfg.max_step > 0:
        desired = max(n - cfg.max_step, min(n + cfg.max_step, desired))
    desired = max(spec.min_replicas, min(spec.max_replicas, desired))
    if desired == n:
        return None
    trigger = (f"util={report.util:.2f} depth={report.depth} "
               f"depth_frac={report.depth_frac:.2f} "
               f"credit_wait={report.credit_wait_frac:.2f} "
               f"rate={report.rate:.0f}/s")
    if report.skew > 0.0:
        # audit-plane skew signal: recorded with the decision so an
        # operator diagnosing a scale-up that did not help can see the
        # hot key was the bottleneck, not replica count
        trigger += f" skew={report.skew:.2f}"
    if report.bottleneck > 0.0:
        # diagnosis-plane attribution: the root-cause walk named this
        # operator the bottleneck behind a sink with this score
        trigger += f" bottleneck={report.bottleneck:.2f}"
    return desired, trigger


class ElasticController(threading.Thread):
    """Owns the sampler and applies scaling decisions to the graph."""

    def __init__(self, graph, cfg: Optional[ElasticityConfig] = None):
        super().__init__(name="windflow-elastic-controller", daemon=True)
        self.graph = graph
        self.cfg = cfg or ElasticityConfig()
        self.sampler = SignalSampler(graph.elastic,
                                     self.cfg.sample_period_s,
                                     self.cfg.ewma_alpha)
        self._stop_evt = threading.Event()
        self._cooldown_until: dict = {}
        # (operator, target_n, exc) per failed decision, for operators
        # diagnosing why the controller is holding
        self.failed_rescales: list = []

    def run(self) -> None:
        self.sampler.start()
        try:
            while not self._stop_evt.wait(self.cfg.sample_period_s):
                g = self.graph
                if g._ended or g._cancel.cancelled:
                    return
                now = _time.monotonic()
                for name, report in self.sampler.latest().items():
                    if now < self._cooldown_until.get(name, 0.0):
                        continue
                    handle = g.elastic.get(name)
                    if handle is None:
                        continue
                    d = decide(report, handle.spec, self.cfg)
                    if d is None:
                        continue
                    new_n, trigger = d
                    try:
                        g.rescale(name, new_n, trigger=trigger,
                                  timeout=self.cfg.quiesce_timeout_s)
                    except RuntimeError as exc:
                        # the graph ended/cancelled under us, or the
                        # drain timed out (sources were resumed by the
                        # rescale path); hold and retry after cooldown.
                        # A RescaleError can also mean a PARTIALLY
                        # applied rescale (e.g. a retired replica that
                        # failed to unwind) -- never drop that silently
                        self.failed_rescales.append((name, new_n, exc))
                        warnings.warn(
                            f"elastic rescale of {name!r} to {new_n} "
                            f"failed: {exc!r}; holding for cooldown",
                            RuntimeWarning, stacklevel=1)
                    self.sampler.reset(name)
                    self._cooldown_until[name] = \
                        _time.monotonic() + self.cfg.cooldown_s
        finally:
            self.sampler.stop()

    def stop(self) -> None:
        self._stop_evt.set()
        self.sampler.stop()
        if self.is_alive():
            self.join(timeout=10.0)


def start_controller(graph) -> Optional[ElasticController]:
    """PipeGraph.start hook: spin up the controller when the graph has
    elastic operators and the config does not disable it."""
    cfg = getattr(graph.config, "elasticity", None)
    if cfg is not None and not getattr(cfg, "enabled", True):
        return None
    ctl = ElasticController(graph, cfg)
    ctl.start()
    return ctl
