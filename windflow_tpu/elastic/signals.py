"""Load signals for the elastic scaling plane (docs/ELASTIC.md).

The controller's decisions are only as good as its load estimate, so
this module concentrates the measurement side: per elastic operator a
:class:`LoadReport` is derived from three existing instrumentation
sources, none of which was added for elasticity --

* **service-time EWMAs** from the replicas' :class:`StatsRecord`
  (monitoring/stats.py): ``inputs_received`` deltas times the sampled
  mean service time give the DS2-style "useful time" utilization
  estimate (Kalavri et al., OSDI '18);
* **channel depth gauges** (``Channel.depth``, runtime/queues.py): a
  lock-free read of each replica's inbound queue -- sustained backlog
  means the operator is the bottleneck even when the utilization
  estimate is noisy;
* **credit-wait time** from the ingest plane's :class:`CreditGate`
  (ingest/credits.py): a source blocked on credits is upstream evidence
  that some consumer cannot keep up.

A :class:`SignalSampler` thread owns the sampling cadence and publishes
the latest report per operator; the controller (elastic/controller.py)
reads them and decides.
"""
from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class LoadReport:
    """One sampling window's aggregated view of an elastic operator."""

    operator: str
    replicas: int
    util: float              # EWMA busy fraction per replica (can be > 1)
    depth: int               # tuples parked in the replicas' inbound channels
    depth_frac: float        # depth / total bounded capacity
    credit_wait_frac: float  # fraction of wall time feeding sources spent
    #                          blocked on credits during the window
    rate: float              # channel items/s entering the operator
    at: float                # monotonic sample time
    # audit plane (audit/census.py): estimated share of the hottest
    # key in the operator's KEYBY input stream.  A share near 1.0
    # means one replica owns the hot key regardless of parallelism --
    # scaling out cannot relieve it -- so the controller records the
    # signal with every decision it makes on this operator
    skew: float = 0.0
    # diagnosis plane (diagnosis/bottleneck.py): the root-cause walk's
    # pressure score when IT named this operator the bottleneck behind
    # a sink (0.0 otherwise).  Attribution-aware scaling: unlike the
    # raw depth_frac above, this only fires for the operator where the
    # backpressure actually originates, so a cascade of full queues
    # scales the culprit instead of every operator on the path
    bottleneck: float = 0.0


class OperatorSignals:
    """Per-operator EWMA state over successive samples of its replicas.

    Replica sets change at rescale: totals are tracked as sums over the
    CURRENT replicas, deltas clamped at zero, and ``reset()`` re-primes
    the baselines right after a rescale so the first post-rescale window
    never mixes the two configurations."""

    def __init__(self, handle, alpha: float = 0.5):
        self.handle = handle
        self.alpha = alpha
        self.util = 0.0
        self._last_t: Optional[float] = None
        self._last_inputs = 0
        self._last_wait = 0.0

    def reset(self) -> None:
        self._last_t = None
        self.util = 0.0

    def _gates(self):
        """Credit gates feeding this operator, discovered through the
        CreditedChannel proxies wrapped around the replicas' inbound
        channels (ingest/wiring.py; rescale mirrors the wrap)."""
        gates = {}
        for node in self.handle.replicas:
            ch_gates = getattr(node.channel, "gates", None)
            if ch_gates:
                for gate in ch_gates.values():
                    gates[id(gate)] = gate
        return list(gates.values())

    def sample(self, now: Optional[float] = None) -> Optional[LoadReport]:
        """One sampling window; returns None on the priming call (no
        previous baseline to difference against)."""
        if now is None:
            now = _time.monotonic()
        nodes = list(self.handle.replicas)
        inputs = 0
        svc_sum, svc_n = 0.0, 0
        depth = 0
        cap = 0
        for n in nodes:
            rec = n.stats
            if rec is not None:
                inputs += rec.inputs_received
                if rec.samples:
                    svc_sum += rec.service_time_us
                    svc_n += 1
            ch = n.channel
            if ch is not None:
                depth += ch.depth
                cap += getattr(ch, "capacity", None) or 1 << 20
        gates = self._gates()
        wait = sum(g.wait_time_s for g in gates)
        if self._last_t is None:
            self._last_t = now
            self._last_inputs = inputs
            self._last_wait = wait
            return None
        dt = max(now - self._last_t, 1e-6)
        d_in = max(0, inputs - self._last_inputs)
        d_wait = max(0.0, wait - self._last_wait)
        self._last_t = now
        self._last_inputs = inputs
        self._last_wait = wait
        mean_svc = (svc_sum / svc_n) if svc_n else 0.0
        raw = d_in * mean_svc / (dt * 1e6 * max(1, len(nodes)))
        # clamp the raw sample: a burst consumed from backlog can claim
        # >1 busy fraction, which is signal (scale up), but unbounded
        # spikes would dominate the EWMA for many windows
        raw = min(raw, 4.0)
        self.util = self.alpha * raw + (1.0 - self.alpha) * self.util
        # hot-key skew from the audit plane's KEYBY sketches (0.0 when
        # the auditor is off or the operator is not KEYBY-fed)
        skew = 0.0
        graph = self.handle.pipe.graph
        auditor = getattr(graph, "auditor", None)
        if auditor is not None:
            skew = auditor.skew_of(self.handle.name)
        # root-cause score from the diagnosis plane's bottleneck walk
        # (0.0 when the plane is off or another operator is the cause)
        bottleneck = 0.0
        diag = getattr(graph, "diagnosis", None)
        if diag is not None:
            bottleneck = diag.bottleneck_score(self.handle.name)
        return LoadReport(
            operator=self.handle.name,
            replicas=len(nodes),
            util=self.util,
            depth=depth,
            depth_frac=depth / cap if cap else 0.0,
            credit_wait_frac=min(d_wait / (dt * max(1, len(gates))), 1.0),
            rate=d_in / dt,
            at=now,
            skew=skew,
            bottleneck=bottleneck,
        )


class SignalSampler(threading.Thread):
    """Samples every elastic operator at a fixed cadence and publishes
    the latest LoadReport per operator (thread-safe snapshot via
    ``latest()``)."""

    def __init__(self, elastic: Dict[str, object], period_s: float,
                 alpha: float):
        super().__init__(name="windflow-elastic-sampler", daemon=True)
        self._signals = {name: OperatorSignals(h, alpha)
                         for name, h in elastic.items()}
        self.period_s = period_s
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self._reports: Dict[str, LoadReport] = {}

    def latest(self) -> Dict[str, LoadReport]:
        with self._lock:
            return dict(self._reports)

    def reset(self, name: str) -> None:
        """Drop an operator's baselines and last report (called by the
        controller right after rescaling it)."""
        sig = self._signals.get(name)
        if sig is not None:
            sig.reset()
        with self._lock:
            self._reports.pop(name, None)

    def sample_once(self, now: Optional[float] = None) -> None:
        for name, sig in self._signals.items():
            report = sig.sample(now)
            if report is not None:
                with self._lock:
                    self._reports[name] = report

    def run(self) -> None:
        while not self._stop_evt.wait(self.period_s):
            self.sample_once()

    def stop(self) -> None:
        self._stop_evt.set()
        # join so repeated start/teardown cycles in one process leave
        # no sampler thread behind (the serving plane's census test)
        if self.is_alive() and threading.current_thread() is not self:
            self.join(timeout=5.0)
