"""Tenant-aware device placement: the per-worker DeviceLeaseRegistry.

The placement planner (graph/planner.py) decides *host vs device* per
operator but is per-graph: two tenants in one worker can both resolve
``device`` and silently share the chip through XLA's stream queue.
The registry makes the chip a *scheduled* resource:

* the planner ``acquire()``s a lease for every lane it resolves to
  the device (including resident FFAT engines, which are recorded as
  non-demotable);
* leases are GRANTED even past capacity -- oversubscription is legal,
  it is just *visible*: ``contended()`` flips once holders exceed the
  worker's lanes, and every lease row carries the contention bit;
* the arbiter consults the rows to find, on a contended chip, the
  lowest-priority demotable neighbour of a breaching tenant and flips
  that lane device->host through the replace_lane quiesce path.

Grant-and-record (rather than block-or-refuse) is deliberate: a lease
denial at plan time would fail a graph that might run fine off-peak,
while recorded oversubscription lets the SLO plane decide *at run
time* whether contention actually hurts anyone.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class DeviceLeaseRegistry:
    """Per-worker ledger of device-lane leases."""

    def __init__(self, lanes: int = 1, chip: str = "tpu:0") -> None:
        self.lanes = max(1, int(lanes))
        self.chip = chip
        self._lock = threading.Lock()
        # (tenant, operator) -> {"Priority":…, "Resident":…}
        self._leases: Dict[Tuple[str, str], dict] = {}

    # -- planner side -----------------------------------------------------
    def acquire(self, tenant: str, operator: str, *,
                priority: int = 0, resident: bool = False) -> dict:
        """Grant (and record) a device lease for one lane.

        Returns the grant the planner annotates into its placement
        entry: the chip, whether the chip is now contended, and the
        holder count at grant time.
        """
        with self._lock:
            self._leases[(str(tenant), str(operator))] = {
                "Priority": int(priority),
                "Resident": bool(resident),
            }
            n = len(self._leases)
        return {"chip": self.chip, "holders": n,
                "contended": n > self.lanes}

    def release(self, tenant: str, operator: Optional[str] = None) -> int:
        """Drop one lease, or every lease of a tenant; returns count."""
        with self._lock:
            if operator is not None:
                return 1 if self._leases.pop(
                    (str(tenant), str(operator)), None) else 0
            gone = [k for k in self._leases if k[0] == str(tenant)]
            for k in gone:
                del self._leases[k]
            return len(gone)

    # -- arbiter / observability side ------------------------------------
    def contended(self) -> bool:
        with self._lock:
            return len(self._leases) > self.lanes

    def holders(self) -> int:
        with self._lock:
            return len(self._leases)

    def rows(self) -> List[dict]:
        with self._lock:
            n = len(self._leases)
            contended = n > self.lanes
            return [{
                "Tenant": t, "Operator": op, "Chip": self.chip,
                "Priority": meta["Priority"],
                "Resident": meta["Resident"],
                "Contended": contended,
            } for (t, op), meta in sorted(self._leases.items())]

    def tenant_rows(self, tenant: str) -> List[dict]:
        return [r for r in self.rows() if r["Tenant"] == str(tenant)]

    def block(self) -> dict:
        rows = self.rows()
        return {"Chip": self.chip, "Lanes": self.lanes,
                "Holders": len(rows),
                "Contended": len(rows) > self.lanes,
                "Leases": rows}
