"""Fair segment scheduling: weighted executor leases per worker.

Co-resident tenants in one worker process share cores by whatever the
OS thread scheduler does -- which is to say, not by tenant weight at
all.  The ``FairShareRegistry`` turns the consume loops into a
weighted fair queue: every tenant's loops hold a ``FairShareLease``
and must ``acquire(k)`` before processing a batch of ``k`` items.

The gate is a **weighted deficit bound**, not an absolute token rate:
a tenant may run ahead of the slowest *active* tenant's normalized
consumption (items/weight) by at most ``burst`` items.  Consequences:

* a tenant running ALONE never waits (the floor is undefined) -- the
  plane is pay-for-what-you-use and scheduler-on/off results are
  identical for a single-tenant graph;
* when two tenants contend, their throughputs converge to the ratio
  of their weights regardless of per-item cost;
* a tenant that stops consuming (finished, stalled upstream) ages out
  of the floor after ``active_window_s`` so it cannot park the
  survivors at its last position.

Waits are timed and surfaced as the per-replica ``Sched_wait_s``
gauge so the diagnosis plane can name *scheduling* -- not queueing,
not credits -- as the bottleneck.  Leases register with the graph's
CancelToken (they expose ``poison()``) so cancellation never leaves a
consume loop blocked in the gate.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

# Re-check cadence while gated: long enough to stay off the lock under
# contention, short enough that activity expiry is observed promptly.
_WAIT_SLICE_S = 0.02


class FairShareLease:
    """One tenant's handle on the worker's fair-share gate."""

    def __init__(self, registry: "FairShareRegistry", tenant: str,
                 weight: float) -> None:
        self._reg = registry
        self.tenant = tenant
        self.weight = max(1e-6, float(weight))
        self.consumed = 0
        self.wait_s = 0.0
        self._last_active = 0.0   # monotonic; 0 = never ran
        self._poisoned = False

    # -- consume-loop side ------------------------------------------------
    def acquire(self, k: int) -> float:
        """Charge ``k`` items; block while this tenant is over its
        fair share.  Returns seconds spent waiting (0.0 normally)."""
        reg = self._reg
        waited = 0.0
        with reg._cond:
            now = time.monotonic()
            self._last_active = now
            while not self._poisoned:
                floor = reg._floor(exclude=self, now=now)
                if floor is None:
                    break       # running alone: no gate at all
                ahead = (self.consumed + k) / self.weight - floor
                if ahead <= reg.burst / self.weight:
                    break
                t0 = now
                reg._cond.wait(_WAIT_SLICE_S)
                now = time.monotonic()
                waited += now - t0
                self._last_active = now
            self.consumed += k
            self.wait_s += waited
            if waited or reg._gated:
                reg._cond.notify_all()
        return waited

    def poison(self) -> None:
        """CancelToken hook: unblock any consume loop in acquire()."""
        with self._reg._cond:
            self._poisoned = True
            self._reg._cond.notify_all()

    def block(self) -> dict:
        return {
            "Tenant": self.tenant,
            "Weight": round(self.weight, 3),
            "Consumed": self.consumed,
            "Sched_wait_s": round(self.wait_s, 3),
        }


class FairShareRegistry:
    """Per-worker registry of tenant leases (the shared gate state)."""

    def __init__(self, *, burst: int = 4096,
                 active_window_s: float = 1.0) -> None:
        self.burst = int(burst)
        self.active_window_s = float(active_window_s)
        self._cond = threading.Condition()
        self._leases: Dict[str, FairShareLease] = {}
        self._gated = False     # any lease ever waited (notify hint)

    def lease(self, tenant: str, weight: float = 1.0) -> FairShareLease:
        with self._cond:
            ls = self._leases.get(tenant)
            if ls is None:
                ls = FairShareLease(self, tenant, weight)
                # Join at the current floor, not at zero: a late tenant
                # must not park established tenants until it catches up.
                floor = self._floor(exclude=ls, now=time.monotonic())
                if floor is not None:
                    ls.consumed = int(floor * ls.weight)
                self._leases[tenant] = ls
            self._cond.notify_all()
            return ls

    def release(self, tenant: str) -> None:
        with self._cond:
            ls = self._leases.pop(tenant, None)
            if ls is not None:
                ls._poisoned = True
            self._cond.notify_all()

    def _floor(self, exclude: FairShareLease,
               now: float) -> Optional[float]:
        """Minimum normalized consumption among OTHER active leases.

        None when no other lease is active -- the caller is alone and
        must not be gated.  Called with the condition held.
        """
        floor = None
        horizon = now - self.active_window_s
        for ls in self._leases.values():
            if ls is exclude or ls._poisoned:
                continue
            if ls._last_active < horizon:
                continue        # idle: aged out of the floor
            norm = ls.consumed / ls.weight
            if floor is None or norm < floor:
                floor = norm
        if floor is not None:
            self._gated = True
        return floor

    def block(self) -> dict:
        with self._cond:
            rows = [ls.block() for ls in self._leases.values()]
        return {
            "Burst": self.burst,
            "Leases": rows,
            "Sched_wait_s": round(sum(r["Sched_wait_s"] for r in rows), 3),
        }
