"""Structured scheduler failures.

A placement or actuation failure is an *operational* event, not a
programming error: the caller needs to know which worker owns the
resource, which operators are involved, and what the fleet-level
remedy is.  ``SchedulerError`` carries those fields so the serving
plane can record a ``sched_rejected`` flight event and the doctor can
explain the rejection instead of printing a bare traceback.
"""
from __future__ import annotations

from typing import Optional, Sequence


class SchedulerError(RuntimeError):
    """A scheduling decision could not be made or actuated.

    Attributes
    ----------
    worker:     the worker that owns the contended/rejecting resource
                (``None`` when no single worker is responsible, e.g.
                "no worker has capacity").
    tenant:     the tenant whose request failed, when known.
    operators:  operator names involved in the rejection.
    hint:       the fleet-level path that WOULD handle the request.
    """

    def __init__(self, message: str, *,
                 worker: Optional[int] = None,
                 tenant: Optional[str] = None,
                 operators: Sequence[str] = (),
                 hint: str = "") -> None:
        super().__init__(message)
        self.worker = worker
        self.tenant = tenant
        self.operators = list(operators)
        self.hint = hint

    def block(self) -> dict:
        """Structured form for flight events and doctor output."""
        d = {"Error": str(self)}
        if self.worker is not None:
            d["Worker"] = self.worker
        if self.tenant is not None:
            d["Tenant"] = self.tenant
        if self.operators:
            d["Operators"] = list(self.operators)
        if self.hint:
            d["Hint"] = self.hint
        return d
