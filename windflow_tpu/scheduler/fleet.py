"""FleetServer: tenant->worker placement over spawned worker processes.

The serving plane's ``Server`` hosts many tenants in ONE process; the
distributed plane runs one graph across MANY processes.  The
``FleetServer`` is the production shape of both at once: it spawns a
bounded pool of worker processes (each hosting a fair-share,
device-scheduling ``Server`` -- scheduler/worker.py), places every
submitted tenant onto one worker via the pure policy
(scheduler/policy.py, re-reading the live cluster view pushed by the
workers into a PR 13 ``ClusterObserver``), and supervises the pool:
one worker's death fails only its own tenants (per-tenant crash
isolation is per-PROCESS here), and the victims are re-placed onto the
survivors under their original specs.

Control protocol: one persistent framed-JSON connection per worker
(``[u32 len][json]``, the same framing as the observer push channel).
Build/config functions travel as importable ``(file, qualname)``
references (distributed/runtime.py ``_callable_ref``), never pickled.

Every decision is a flight event in the fleet's own ring:
``sched_place`` / ``sched_replace`` / ``sched_rejected`` /
``worker_death`` -- the doctor explains each (diagnosis/report.py).
"""
from __future__ import annotations

import json
import os
import struct
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from .errors import SchedulerError
from .policy import Placement, WorkerCaps, plan_placement, request_for

# framed-JSON control channel (same shape as the observer push frames)
FRAME_HEADER = struct.Struct("<I")
FRAME_MAX_BYTES = 1 << 26

# terminal tenant states (mirrors serving.tenant.TenantState.TERMINAL,
# but the fleet must not import the serving plane just for strings)
_TERMINAL = ("COMPLETED", "STOPPED", "FAILED")


def send_frame(sock, doc: dict) -> None:
    payload = json.dumps(doc).encode()
    sock.sendall(FRAME_HEADER.pack(len(payload)) + payload)


def recv_frame(sock, timeout: Optional[float] = None) -> dict:
    """Read one length-prefixed JSON frame; raises OSError on EOF or a
    desynced stream (the caller treats the peer as dead)."""
    sock.settimeout(timeout)
    buf = b""
    while len(buf) < FRAME_HEADER.size:
        chunk = sock.recv(FRAME_HEADER.size - len(buf))
        if not chunk:
            raise OSError("control connection closed")
        buf += chunk
    (ln,) = FRAME_HEADER.unpack(buf)
    if ln > FRAME_MAX_BYTES:
        raise OSError(f"oversized control frame ({ln} bytes)")
    payload = b""
    while len(payload) < ln:
        chunk = sock.recv(ln - len(payload))
        if not chunk:
            raise OSError("control connection closed mid-frame")
        payload += chunk
    return json.loads(payload)


class _Worker:
    """One spawned worker process + its control connection."""

    def __init__(self, wid: int, port: int, proc) -> None:
        self.wid = wid
        self.port = port
        self.proc = proc
        self.sock = None
        self.lock = threading.Lock()
        self.alive = True
        # separate from ``alive``: an _rpc that hits the broken
        # channel first flips alive, but the death must still be
        # handled (exactly once) when the process exit is observed
        self.death_handled = False
        self.exit_code: Optional[int] = None


class _FleetPlacement:
    """The fleet's memory of one submitted tenant (original spec +
    refs kept so a crash victim can be re-placed as submitted)."""

    def __init__(self, name: str, spec, build_ref: dict,
                 config_ref: Optional[dict], worker: int) -> None:
        self.name = name
        self.spec = spec
        self.build_ref = build_ref
        self.config_ref = config_ref
        self.worker = worker
        self.state = "PLACED"
        self.attempts = 1
        self.error: Optional[str] = None

    def row(self) -> dict:
        return {"Tenant": self.name, "Worker": self.worker,
                "State": self.state, "Attempts": self.attempts,
                "Credits": self.spec.credits,
                "Devices": getattr(self.spec, "devices", 0),
                "Priority": self.spec.priority,
                "Weight": self.spec.weight,
                "Error": self.error}


class FleetServer:
    """Fleet-level control plane: spawn workers, place tenants, watch
    the pool, re-place crash victims.  Context-manager friendly."""

    def __init__(self, workers: int = 2, capacity: int = 1 << 20, *,
                 device_lanes: int = 1,
                 name: str = "windflow-fleet",
                 push_interval_s: float = 0.25,
                 spawn_timeout_s: float = 30.0,
                 python: Optional[str] = None) -> None:
        if workers < 1:
            raise ValueError("FleetServer needs at least one worker")
        from ..distributed.observe import ClusterObserver
        from ..distributed.runtime import free_ports
        from ..telemetry import FlightRecorder
        self.name = name
        self.capacity = capacity
        self.device_lanes = device_lanes
        self.flight = FlightRecorder(512)
        self._lock = threading.RLock()
        self._placements: Dict[str, _FleetPlacement] = {}
        self._closed = False
        self.observer = ClusterObserver()
        self.observer.start()
        py = python or sys.executable
        ports = free_ports(workers)
        self._workers: Dict[int, _Worker] = {}
        for wid in range(workers):
            argv = [py, "-m", "windflow_tpu.scheduler.worker",
                    "--worker-id", str(wid),
                    "--port", str(ports[wid]),
                    "--capacity", str(capacity),
                    "--lanes", str(device_lanes),
                    "--observer",
                    f"{self.observer.host}:{self.observer.port}",
                    "--push-interval", str(push_interval_s)]
            proc = subprocess.Popen(argv, cwd=os.getcwd())
            self._workers[wid] = _Worker(wid, ports[wid], proc)
        try:
            self._connect_all(spawn_timeout_s)
        except BaseException:
            self.close()
            raise
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True,
            name=f"windflow-fleet-supervisor-{name}")
        self._supervisor.start()

    # -- spawn / connect ------------------------------------------------
    def _connect_all(self, timeout: float) -> None:
        import socket
        deadline = time.monotonic() + timeout
        for wk in self._workers.values():
            last_err: Optional[BaseException] = None
            while time.monotonic() < deadline:
                if wk.proc.poll() is not None:
                    raise SchedulerError(
                        f"worker {wk.wid} exited rc={wk.proc.returncode}"
                        " before accepting control connections",
                        worker=wk.wid)
                try:
                    wk.sock = socket.create_connection(
                        ("127.0.0.1", wk.port), timeout=1.0)
                    wk.sock.settimeout(None)
                    break
                except OSError as e:
                    last_err = e
                    time.sleep(0.05)
            if wk.sock is None:
                raise SchedulerError(
                    f"worker {wk.wid} did not come up within "
                    f"{timeout}s ({last_err!r})", worker=wk.wid)

    # -- placement ------------------------------------------------------
    def _live_view(self) -> Dict[int, bool]:
        """Worker liveness for the policy: the process must be up AND,
        once the observer has heard from anyone, only workers it still
        tracks count (a wedged worker that stopped pushing is as dead
        to placement as an exited one after its process goes)."""
        return {wid: wk.alive and wk.proc.poll() is None
                for wid, wk in self._workers.items()}

    def _placed_view(self) -> List[Placement]:
        """Current load for the policy: the union of the observer's
        live per-worker placements (a COMPLETED tenant frees its
        reservation automatically on the next push) and the fleet's
        own records (a just-placed tenant counts immediately, before
        any push carries it)."""
        rows: Dict[str, Placement] = {}
        for stats in self.observer.worker_stats():
            sched = stats.get("Scheduler")
            if not isinstance(sched, dict):
                continue
            for p in sched.get("Placements") or ():
                if p.get("State") == "RUNNING":
                    rows[p["Tenant"]] = Placement(
                        name=p["Tenant"], worker=int(p["Worker"]),
                        credits=int(p["Credits"]),
                        devices=int(p.get("Devices") or 0))
        with self._lock:
            for rec in self._placements.values():
                if rec.state == "PLACED" and rec.name not in rows:
                    rows[rec.name] = Placement(
                        name=rec.name, worker=rec.worker,
                        credits=rec.spec.credits,
                        devices=getattr(rec.spec, "devices", 0))
        return list(rows.values())

    def _choose_worker(self, name: str, spec) -> int:
        caps = [WorkerCaps(wid, self.capacity, self.device_lanes)
                for wid in self._workers]
        return plan_placement(
            [request_for(name, spec)], caps,
            placed=self._placed_view(),
            live=self._live_view())[name]

    def submit(self, name: str, build_fn: Callable, tenant=None,
               config_fn: Optional[Callable] = None) -> dict:
        """Place one tenant onto a worker and start it there.

        ``build_fn`` (and the optional ``config_fn`` returning a
        RuntimeConfig) must be importable top-level functions -- they
        run in the worker process.  Returns the placement row."""
        from ..distributed.runtime import _callable_ref
        from ..serving.tenant import TenantSpec
        spec = tenant or TenantSpec()
        build_ref = _callable_ref(build_fn)
        config_ref = _callable_ref(config_fn) \
            if config_fn is not None else None
        with self._lock:
            if self._closed:
                raise SchedulerError("FleetServer is closed")
            if name in self._placements \
                    and self._placements[name].state != "FAILED":
                raise ValueError(f"tenant {name!r} already placed "
                                 "(evict it first)")
            try:
                wid = self._choose_worker(name, spec)
            except SchedulerError as e:
                self.flight.record("sched_rejected", tenant=name,
                                   error=str(e), hint=e.hint,
                                   path="scheduler.FleetServer")
                raise
            rec = _FleetPlacement(name, spec, build_ref, config_ref,
                                  wid)
            self._placements[name] = rec
        try:
            self._submit_to(wid, rec)
        except BaseException:
            with self._lock:
                self._placements.pop(name, None)
            raise
        self.flight.record("sched_place", tenant=name, worker=wid,
                           credits=spec.credits,
                           devices=getattr(spec, "devices", 0),
                           priority=spec.priority, weight=spec.weight)
        return rec.row()

    def _submit_to(self, wid: int, rec: _FleetPlacement) -> None:
        import dataclasses
        spec_doc = dataclasses.asdict(rec.spec)
        reply = self._rpc(wid, {
            "cmd": "submit", "name": rec.name,
            "build": rec.build_ref, "config": rec.config_ref,
            "spec": spec_doc})
        if not reply.get("ok"):
            raise SchedulerError(
                f"worker {wid} refused tenant {rec.name!r}: "
                f"{reply.get('error')}",
                worker=wid, tenant=rec.name,
                hint=reply.get("kind", ""))

    # -- control RPC ----------------------------------------------------
    def _rpc(self, wid: int, doc: dict, timeout: float = 60.0) -> dict:
        wk = self._workers[wid]
        with wk.lock:
            if not wk.alive or wk.sock is None:
                raise SchedulerError(f"worker {wid} is dead",
                                     worker=wid)
            try:
                send_frame(wk.sock, doc)
                return recv_frame(wk.sock, timeout)
            except OSError as e:
                wk.alive = False
                raise SchedulerError(
                    f"worker {wid} control channel failed: {e!r}",
                    worker=wid)

    # -- tenant surface -------------------------------------------------
    def tenant_state(self, name: str) -> dict:
        """The owning worker's live row for one tenant (state, lease,
        conservation books once terminal)."""
        with self._lock:
            rec = self._placements.get(name)
            if rec is None:
                raise KeyError(f"no tenant {name!r}")
            wid, state = rec.worker, rec.state
        if state != "PLACED":
            return rec.row()
        reply = self._rpc(wid, {"cmd": "tenant", "name": name})
        if not reply.get("ok"):
            raise SchedulerError(
                f"worker {wid} has no tenant {name!r}: "
                f"{reply.get('error')}", worker=wid, tenant=name)
        row = reply["row"]
        row["Worker"] = wid
        return row

    def wait(self, name: str, timeout: float = 120.0) -> dict:
        """Poll the owning worker until the tenant is terminal (the
        owner may CHANGE mid-wait when a crash re-places it)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                row = self.tenant_state(name)
            except SchedulerError:
                # owning worker just died: give the supervisor a beat
                # to re-place or fail the tenant, then re-read
                time.sleep(0.1)
                continue
            if row.get("State") in _TERMINAL:
                return row
            time.sleep(0.05)
        raise TimeoutError(
            f"tenant {name!r} not terminal within {timeout}s")

    def evict(self, name: str) -> dict:
        with self._lock:
            rec = self._placements.get(name)
            if rec is None:
                raise KeyError(f"no tenant {name!r}")
            wid = rec.worker
        reply = self._rpc(wid, {"cmd": "evict", "name": name})
        with self._lock:
            self._placements.pop(name, None)
        if not reply.get("ok"):
            raise SchedulerError(
                f"worker {wid} failed to evict {name!r}: "
                f"{reply.get('error')}", worker=wid, tenant=name)
        return reply.get("row") or {}

    # -- supervision ----------------------------------------------------
    def _supervise(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            for wid, wk in list(self._workers.items()):
                rc = wk.proc.poll()
                if rc is not None and not wk.death_handled:
                    self._on_worker_death(wid, rc)
            time.sleep(0.1)

    def _on_worker_death(self, wid: int, rc: int) -> None:
        wk = self._workers[wid]
        wk.death_handled = True
        wk.alive = False
        wk.exit_code = rc
        try:
            if wk.sock is not None:
                wk.sock.close()
        except OSError:
            pass
        with self._lock:
            if self._closed:
                return
            victims = [rec for rec in self._placements.values()
                       if rec.worker == wid and rec.state == "PLACED"]
            for rec in victims:
                # not FAILED yet: a wait() polling mid-recovery must
                # keep waiting while the re-placement is in flight
                rec.state = "REPLACING"
                rec.error = f"worker {wid} died rc={rc}"
        self.flight.record("worker_death", worker=wid, exit=rc,
                           tenants=[r.name for r in victims])
        # re-place every victim under its ORIGINAL spec on a survivor
        # -- the same pure policy path as first placement, against the
        # re-read live view (the dead worker is gone from it)
        for rec in victims:
            try:
                with self._lock:
                    new_wid = self._choose_worker(rec.name, rec.spec)
                    rec.worker = new_wid
                    rec.state = "PLACED"
                    rec.attempts += 1
                    rec.error = None
                self._submit_to(new_wid, rec)
                self.flight.record("sched_replace", tenant=rec.name,
                                   worker=new_wid, from_worker=wid,
                                   attempts=rec.attempts)
            except (SchedulerError, ValueError) as e:
                with self._lock:
                    rec.state = "FAILED"
                    rec.error = str(e)
                self.flight.record("sched_rejected", tenant=rec.name,
                                   worker=wid, error=str(e),
                                   path="scheduler.FleetServer")

    def kill_worker(self, wid: int) -> None:
        """Chaos hook: SIGKILL one worker; the supervisor observes the
        death and re-places its tenants."""
        self._workers[wid].proc.kill()

    # -- observability --------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            placements = [r.row() for r in self._placements.values()]
        return {
            "Fleet": self.name,
            "Capacity": self.capacity,
            "Device_lanes": self.device_lanes,
            "Workers": [{"Worker": wid, "Alive": wk.alive,
                         "Pid": wk.proc.pid, "Exit": wk.exit_code}
                        for wid, wk in sorted(self._workers.items())],
            "Placements": placements,
            "Flight": self.flight.snapshot(),
        }

    def cluster(self) -> dict:
        """The merged live cluster view (distributed/observe.py):
        worker Scheduler blocks folded fleet-wide."""
        return self.observer.merged()

    # -- shutdown -------------------------------------------------------
    def close(self, timeout: float = 30.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for wid, wk in self._workers.items():
            if wk.alive and wk.sock is not None:
                try:
                    self._rpc(wid, {"cmd": "shutdown"}, timeout=10.0)
                except SchedulerError:
                    pass
        deadline = time.monotonic() + timeout
        for wk in self._workers.values():
            if wk.proc.poll() is None:
                try:
                    wk.proc.wait(max(0.1,
                                     deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    wk.proc.kill()
                    wk.proc.wait(5.0)
            if wk.exit_code is None:
                wk.exit_code = wk.proc.returncode
            wk.alive = False
            if wk.sock is not None:
                try:
                    wk.sock.close()
                except OSError:
                    pass
        self.observer.stop()

    def __enter__(self) -> "FleetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
