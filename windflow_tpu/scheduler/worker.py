"""Fleet worker entry point: ``python -m windflow_tpu.scheduler.worker``.

One worker process = one fair-share, device-scheduling
:class:`~windflow_tpu.serving.server.Server` plus two side channels:

* a framed-JSON **control** listener the FleetServer drives
  (submit / tenant / stats / evict / shutdown);
* a **push** loop feeding the fleet's ClusterObserver the worker's
  ``Scheduler`` block (capacity, placements, fair-share leases with
  their waits, device leases) and its flight ring, every interval and
  once more -- marked final -- at shutdown.

Build/config functions arrive as importable references and are loaded
with the distributed plane's ``_load_ref`` (module path first, source
file fallback), never pickled.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
from typing import Optional

from .fleet import FRAME_HEADER, recv_frame, send_frame


def _tenant_row(server, name: str) -> Optional[dict]:
    handle = server.get(name)
    if handle is None:
        return None
    g = handle.graph
    row = {
        "Tenant": name,
        "State": handle.state,
        "Credits": handle.credits,
        "Arbitrations": handle.arbitrations,
        "Error": repr(handle.error)
        if handle.error is not None else None,
    }
    with g.stats.lock:
        row["Conservation"] = g.stats.audit_conservation
        row["Slo"] = g.stats.slo
        row["Scheduler"] = g.stats.scheduler
    try:
        # per-tenant latency books (bench 20 gates fleet p99 on this)
        doc = json.loads(g.stats.to_json(0, 0))
        row["Latency_e2e"] = doc.get("Latency_e2e")
    except Exception:
        row["Latency_e2e"] = None
    try:
        row["Dead_letters"] = g.dead_letters.count()
    except Exception:
        row["Dead_letters"] = None
    return row


class _Pusher(threading.Thread):
    """Best-effort observer feed (the StatsPusher discipline: a dead
    observer must never take the worker down)."""

    def __init__(self, server, wid: int, endpoint, interval: float):
        super().__init__(name="windflow-fleet-pusher", daemon=True)
        self.server = server
        self.wid = wid
        self.endpoint = endpoint
        self.interval = interval
        self._sock = None
        self._stop = threading.Event()

    def _push(self, final: bool = False) -> None:
        doc = {"pid": os.getpid(), "final": final,
               "stats": {"Worker": self.wid,
                         "Scheduler": self.server.scheduler_block(),
                         "Flight": self.server.flight.snapshot()}}
        payload = json.dumps(doc, default=str).encode()
        if self._sock is None:
            self._sock = socket.create_connection(self.endpoint,
                                                  timeout=2.0)
        self._sock.sendall(FRAME_HEADER.pack(len(payload)) + payload)

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._push()
            except OSError:
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None

    def stop(self) -> None:
        self._stop.set()
        try:
            self._push(final=True)
        except OSError:
            pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


def _handle(server, doc: dict) -> dict:
    cmd = doc.get("cmd")
    if cmd == "ping":
        return {"ok": True}
    if cmd == "submit":
        from ..distributed.runtime import _load_ref
        from ..serving.tenant import TenantSpec
        try:
            build_fn = _load_ref(doc["build"])
            config = None
            if doc.get("config") is not None:
                config = _load_ref(doc["config"])()
            spec = TenantSpec(**(doc.get("spec") or {}))
            server.submit(doc["name"], build_fn, tenant=spec,
                          config=config)
            return {"ok": True, "tenant": doc["name"]}
        except BaseException as e:
            return {"ok": False, "error": str(e),
                    "kind": type(e).__name__}
    if cmd == "tenant":
        row = _tenant_row(server, doc["name"])
        if row is None:
            return {"ok": False,
                    "error": f"no tenant {doc['name']!r}"}
        return {"ok": True, "row": row}
    if cmd == "stats":
        return {"ok": True, "stats": server.stats()}
    if cmd == "evict":
        try:
            handle = server.evict(doc["name"])
            return {"ok": True,
                    "row": {"Tenant": doc["name"],
                            "State": handle.state}}
        except BaseException as e:
            return {"ok": False, "error": str(e),
                    "kind": type(e).__name__}
    return {"ok": False, "error": f"unknown command {cmd!r}"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="windflow_tpu.scheduler.worker",
        description="fleet worker: a fair-share tenant host under a "
                    "FleetServer control connection")
    ap.add_argument("--worker-id", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--capacity", type=int, default=1 << 20)
    ap.add_argument("--lanes", type=int, default=1)
    ap.add_argument("--observer", default=None,
                    help="host:port of the fleet's ClusterObserver")
    ap.add_argument("--push-interval", type=float, default=0.25)
    args = ap.parse_args(argv)

    from ..serving.server import Server
    server = Server(args.capacity,
                    name=f"fleet-worker{args.worker_id}",
                    fair_share=True, devices=args.lanes,
                    worker_id=args.worker_id)
    pusher = None
    if args.observer:
        host, port = args.observer.rsplit(":", 1)
        pusher = _Pusher(server, args.worker_id, (host, int(port)),
                         args.push_interval)
        pusher.start()

    lsock = socket.create_server(("127.0.0.1", args.port))
    lsock.settimeout(0.2)
    stop = False
    try:
        while not stop:
            try:
                conn, _addr = lsock.accept()
            except socket.timeout:
                continue
            with conn:
                while True:
                    try:
                        doc = recv_frame(conn, timeout=None)
                    except (OSError, ValueError):
                        break  # fleet went away; await a reconnect
                    if doc.get("cmd") == "shutdown":
                        send_frame(conn, {"ok": True})
                        stop = True
                        break
                    try:
                        send_frame(conn, _handle(server, doc))
                    except OSError:
                        break
    finally:
        lsock.close()
        server.close()
        if pusher is not None:
            pusher.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
