"""Pure tenant->worker placement policy.

The policy is a function, not a service: given the fleet's worker
capacities, the tenants already placed, and the incoming requests, it
returns ``{tenant: worker}`` -- no sockets, no clocks, no globals --
so every placement decision is unit-testable and replayable from a
flight event.

Shape of the decision (mirrors the credit discipline everywhere else
in windflow_tpu):

* **credits are a hard reservation** -- a worker's ``Server`` refuses
  admission past its capacity, so the policy never plans a placement
  that would be refused;
* **device lanes are a soft reservation** -- lanes can be
  oversubscribed (the arbiter resolves contention at run time by
  demoting a low-priority lane device->host), but the policy avoids
  creating contention when an uncontended worker exists;
* **priority-weighted bin-packing** -- requests are placed highest
  priority first (then largest reservation first), and among feasible
  workers the one with the lowest normalized load after placement
  wins, which spreads tenants instead of piling them onto worker 0.

The live cluster view (PR 13's ``ClusterObserver``) enters as the
``live`` map: workers missing from it or marked dead are excluded, so
re-placement after a crash is the SAME code path as first placement.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Optional, Sequence

from .errors import SchedulerError


@dataclass(frozen=True)
class WorkerCaps:
    """Static capacity envelope of one worker process."""
    worker: int
    credits: int
    device_lanes: int = 1


@dataclass(frozen=True)
class PlacementRequest:
    """One tenant's declared demand (from its TenantSpec)."""
    name: str
    credits: int
    devices: int = 0        # declared device-lane demand
    priority: int = 0
    weight: float = 1.0


@dataclass(frozen=True)
class Placement:
    """An existing tenant->worker assignment (the policy's memory)."""
    name: str
    worker: int
    credits: int
    devices: int = 0


@dataclass
class _Load:
    credits: int = 0
    devices: int = 0
    tenants: int = 0


def _loads(workers: Sequence[WorkerCaps],
           placed: Iterable[Placement]) -> Dict[int, _Load]:
    loads = {w.worker: _Load() for w in workers}
    for p in placed:
        ld = loads.get(p.worker)
        if ld is None:      # placement on a dead/unknown worker: ignore
            continue
        ld.credits += p.credits
        ld.devices += p.devices
        ld.tenants += 1
    return loads


def plan_placement(requests: Sequence[PlacementRequest],
                   workers: Sequence[WorkerCaps],
                   *,
                   placed: Iterable[Placement] = (),
                   live: Optional[Mapping[int, bool]] = None,
                   ) -> Dict[str, int]:
    """Choose a worker for every request; raise SchedulerError if any
    request cannot be placed.

    ``live`` maps worker id -> alive; workers absent from a non-None
    map are treated as dead (the observer has never heard from them or
    their process exited).
    """
    if live is not None:
        workers = [w for w in workers if live.get(w.worker, False)]
    if not workers:
        raise SchedulerError(
            "no live workers in the fleet",
            hint="spawn workers before submitting tenants")

    loads = _loads(workers, placed)
    caps = {w.worker: w for w in workers}
    out: Dict[str, int] = {}

    order = sorted(requests,
                   key=lambda r: (-r.priority, -r.credits, r.name))
    for req in order:
        best = None
        best_key = None
        for w in workers:
            ld = loads[w.worker]
            if ld.credits + req.credits > w.credits:
                continue    # hard: the worker Server would refuse this
            lanes = max(1, w.device_lanes)
            dev_over = max(0, ld.devices + req.devices - lanes) \
                if req.devices else 0
            norm = (ld.credits + req.credits) / max(1, w.credits)
            key = (dev_over, norm, ld.tenants, w.worker)
            if best_key is None or key < best_key:
                best, best_key = w, key
        if best is None:
            free = {w.worker: w.credits - loads[w.worker].credits
                    for w in workers}
            raise SchedulerError(
                f"no worker can host tenant {req.name!r} "
                f"(needs {req.credits} credits; free: {free})",
                tenant=req.name,
                hint="raise worker capacity or evict a tenant")
        ld = loads[best.worker]
        ld.credits += req.credits
        ld.devices += req.devices
        ld.tenants += 1
        out[req.name] = best.worker
    return out


def request_for(name: str, spec) -> PlacementRequest:
    """Build a PlacementRequest from a serving TenantSpec."""
    return PlacementRequest(
        name=name,
        credits=int(spec.credits),
        devices=int(getattr(spec, "devices", 0)),
        priority=int(spec.priority),
        weight=float(spec.weight),
    )
