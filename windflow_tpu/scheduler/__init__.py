"""Global scheduler: the fleet-level control plane.

Promotes the serving plane's single-process ``Server`` to a cluster
scheduler (ROADMAP item 5):

* :mod:`policy`  -- pure tenant->worker placement (bin-pack by credit
  reservation + declared device demand, priority-weighted);
* :mod:`leases`  -- weighted fair-share executor leases gating the
  consume loops of co-resident tenants (``Sched_wait_s``);
* :mod:`devices` -- per-worker device-lane leases the planner
  consults before resolving ``device``, and the arbiter reads to
  demote a low-priority neighbour on a contended chip;
* :mod:`fleet`   -- ``FleetServer``: spawns worker processes, places
  tenants via the policy against the live ``ClusterObserver`` view,
  re-places victims when a worker dies;
* :mod:`worker`  -- the worker-process entry point hosting a
  fair-share ``Server`` (``python -m windflow_tpu.scheduler.worker``).

See docs/SERVING.md "Global scheduler".
"""
from .errors import SchedulerError
from .policy import (Placement, PlacementRequest, WorkerCaps,
                     plan_placement, request_for)
from .leases import FairShareLease, FairShareRegistry
from .devices import DeviceLeaseRegistry

__all__ = [
    "SchedulerError",
    "Placement", "PlacementRequest", "WorkerCaps",
    "plan_placement", "request_for",
    "FairShareLease", "FairShareRegistry",
    "DeviceLeaseRegistry",
    "FleetServer",
]


def __getattr__(name):
    # FleetServer pulls in serving + distributed; keep the import lazy
    # so `from windflow_tpu.scheduler import plan_placement` stays cheap.
    if name == "FleetServer":
        from .fleet import FleetServer
        return FleetServer
    raise AttributeError(name)
