"""Crash-safe cold-tier spill segments (docs/RESILIENCE.md "Tiered
state & memory pressure").

A :class:`SpillStore` owns one directory of immutable segment files,
each holding a batch of cold keys as pre-pickled bytes::

    seg-00000042-<sha256-of-payload>.spill

Writes follow the same write-temp + fsync + atomic-rename protocol as
the epoch manifests (``durability/store.py``), and the payload digest
rides in the file NAME, so a segment either lands complete or not at
all -- a crash mid-spill leaves at most a ``.tmp`` orphan the next
incarnation wipes.  Reads re-hash the payload against the name; a torn
or bit-flipped segment surfaces as a RuntimeError at the replica's
next access to one of its keys, which under supervision is a healable
crash (fresh replica, rewind to the last committed epoch) rather than
silently-wrong state.

The spill directory is a RUNTIME WORKING SET, not a durability
surface: epoch manifests/blob chains remain the single source of
truth, every restore path funnels through ``load_keyed_state`` →
``TieredKeyedStore.replace_all`` which starts from an empty spill dir.
That is the crash-safety argument in one line -- kill-restart
mid-spill bitwise-matches an uninterrupted run because nothing under
this directory is ever read across a restart.

The in-memory index (key → segment seq) is the only record of where a
key lives; per-segment live counts drive space reclamation: a segment
whose keys were all deleted/re-promoted is unlinked, and ``compact()``
rewrites the survivors of mostly-dead segments into a fresh one.
"""
from __future__ import annotations

import errno
import hashlib
import os
import pickle
from collections import OrderedDict
from typing import Any, Dict, List, Optional

SPILL_MAGIC = "windflow-spill-segment"
# segments with a live fraction below this are rewritten by compact()
COMPACT_LIVE_FRAC = 0.5
# bounded cache of decoded segments (reads cluster by segment)
_READ_CACHE_SEGS = 4


def _digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


class SpillStore:
    """One replica's cold tier: immutable digest-named segment files
    plus the in-memory key index.  Single-writer (the replica thread);
    gauge reads (census) only touch plain counters."""

    def __init__(self, root: str):
        self.root = root
        self.fault_plan = None      # FaultPlan.fail_write("spill") hook
        self._seq = 0
        self._index: Dict[Any, int] = {}        # key -> segment seq
        self._seg_path: Dict[int, str] = {}     # seq -> file path
        self._seg_total: Dict[int, int] = {}    # seq -> keys at write
        self._seg_live: Dict[int, int] = {}     # seq -> live keys now
        self._cache: "OrderedDict[int, Dict[Any, bytes]]" = OrderedDict()
        self.bytes_written = 0                  # lifetime spill volume
        self.segments_written = 0
        os.makedirs(self.root, exist_ok=True)
        self._wipe()                            # working set: start clean

    # -- lifecycle -----------------------------------------------------
    def _wipe(self) -> None:
        for n in os.listdir(self.root):
            if n.endswith(".spill") or n.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.root, n))
                except OSError:
                    pass

    def clear(self) -> None:
        """Drop every key and segment (restore paths start here)."""
        self._index.clear()
        self._seg_path.clear()
        self._seg_total.clear()
        self._seg_live.clear()
        self._cache.clear()
        self._wipe()

    # -- writes --------------------------------------------------------
    def put_batch(self, entries: Dict[Any, bytes]) -> int:
        """Spill a batch of keys (pre-pickled values) as ONE immutable
        segment; returns bytes written.  Raises OSError (e.g. ENOSPC)
        without mutating the index -- the caller keeps the keys warm
        and degrades (``spill_abort``)."""
        if not entries:
            return 0
        fp = self.fault_plan
        if fp is not None and fp.write_should_fail("spill"):
            raise OSError(errno.ENOSPC,
                          "injected disk full (spill segment)")
        seq = self._seq
        payload = pickle.dumps(
            {"magic": SPILL_MAGIC, "seq": seq, "entries": dict(entries)},
            protocol=pickle.HIGHEST_PROTOCOL)
        path = os.path.join(
            self.root, f"seg-{seq:08d}-{_digest(payload)}.spill")
        from ..durability.store import atomic_write_bytes
        atomic_write_bytes(path, payload)
        # index mutations only after the segment is durable
        self._seq = seq + 1
        self._seg_path[seq] = path
        self._seg_total[seq] = len(entries)
        self._seg_live[seq] = 0
        for k in entries:
            self._drop_ref(k)           # key may move cold -> cold
            self._index[k] = seq
            self._seg_live[seq] += 1
        self.bytes_written += len(payload)
        self.segments_written += 1
        return len(payload)

    # -- reads ---------------------------------------------------------
    def _load_segment(self, seq: int) -> Dict[Any, bytes]:
        got = self._cache.get(seq)
        if got is not None:
            self._cache.move_to_end(seq)
            return got
        path = self._seg_path[seq]
        try:
            with open(path, "rb") as f:
                payload = f.read()
        except OSError as e:
            raise RuntimeError(
                f"spill segment {path!r} missing or unreadable: "
                f"{e}") from e
        name_digest = os.path.basename(path).rsplit("-", 1)[-1][:-6]
        if _digest(payload) != name_digest:
            raise RuntimeError(
                f"spill segment {path!r} fails its content digest "
                "(torn or corrupt write)")
        doc = pickle.loads(payload)
        if not isinstance(doc, dict) or doc.get("magic") != SPILL_MAGIC:
            raise RuntimeError(
                f"file at {path!r} is not a windflow spill segment")
        entries = doc["entries"]
        self._cache[seq] = entries
        while len(self._cache) > _READ_CACHE_SEGS:
            self._cache.popitem(last=False)
        return entries

    def get(self, key) -> Optional[bytes]:
        """Pickled bytes of ``key``, or None when not spilled.  Raises
        RuntimeError on a torn segment."""
        seq = self._index.get(key)
        if seq is None:
            return None
        return self._load_segment(seq)[key]

    def __contains__(self, key) -> bool:
        return key in self._index

    def __len__(self) -> int:
        return len(self._index)

    def keys(self):
        return self._index.keys()

    def items_pickled(self):
        """Every (key, pickled bytes) -- restore/capture reads."""
        for k, seq in list(self._index.items()):
            yield k, self._load_segment(seq)[k]

    # -- deletes + space reclamation -----------------------------------
    def _drop_ref(self, key) -> None:
        seq = self._index.pop(key, None)
        if seq is None:
            return
        live = self._seg_live.get(seq, 0) - 1
        self._seg_live[seq] = live
        if live <= 0:
            self._unlink_seg(seq)

    def _unlink_seg(self, seq: int) -> None:
        path = self._seg_path.pop(seq, None)
        self._seg_total.pop(seq, None)
        self._seg_live.pop(seq, None)
        self._cache.pop(seq, None)
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass

    def discard(self, key) -> None:
        """Remove ``key``; a segment with no live keys left is
        unlinked."""
        self._drop_ref(key)

    def compact(self) -> int:
        """Rewrite the live keys of mostly-dead segments into a fresh
        segment; returns bytes written (0 when nothing qualified).
        Write failures propagate like ``put_batch``."""
        victims = [s for s, total in self._seg_total.items()
                   if total and self._seg_live.get(s, 0) / total
                   < COMPACT_LIVE_FRAC]
        if not victims:
            return 0
        vic = set(victims)
        move: Dict[Any, bytes] = {}
        for k, seq in list(self._index.items()):
            if seq in vic:
                move[k] = self._load_segment(seq)[k]
        if not move:
            for s in victims:
                self._unlink_seg(s)
            return 0
        return self.put_batch(move)   # re-index drops the old refs

    # -- gauges --------------------------------------------------------
    def disk_bytes(self) -> int:
        total = 0
        try:
            paths = list(self._seg_path.values())
        except RuntimeError:      # gauge read racing a writer resize
            return total
        for path in paths:
            try:
                total += os.path.getsize(path)
            except OSError:
                pass
        return total
