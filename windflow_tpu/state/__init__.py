"""Tiered keyed-state backend (docs/RESILIENCE.md "Tiered state &
memory pressure").

Keyed stores that do not fit in memory: hot keys stay live Python
objects (or device-resident, PR 15), warm keys are pickled host bytes,
cold keys spill to disk in crash-safe segments.  The whole tier ladder
lives UNDER the existing ``keyed_state_dict`` contract, so every plane
built on that contract -- delta epoch snapshots, rescale repartition,
supervision rewind, census -- composes without knowing tiers exist.

* :class:`~windflow_tpu.state.tiers.TieredKeyedStore` -- the dict-like
  store a keyed logic adopts via ``enable_tiered_state``;
* :class:`~windflow_tpu.state.spill.SpillStore` -- append-friendly
  immutable on-disk segments (atomic-rename protocol, digest-named so
  a torn segment is detected on read);
* :class:`~windflow_tpu.state.budget.StateBudget` -- the per-store
  watermark governor under ``RuntimeConfig.state_budget_bytes``;
* :class:`TieredStateManager` -- graph-level wiring: splits the graph
  budget across capable replicas and re-enables tiering on replicas
  born later (elastic ``_grow``, supervised heals).
"""
from __future__ import annotations

import os
import re
from typing import Dict, Optional

from .budget import StateBudget
from .spill import SpillStore
from .tiers import TieredKeyedStore

__all__ = ["SpillStore", "StateBudget", "TieredKeyedStore",
           "TieredStateManager", "attach_tiered_state"]

_SAFE = re.compile(r"[^A-Za-z0-9_.-]+")


def _safe(name: str) -> str:
    return _SAFE.sub("_", name)


class TieredStateManager:
    """Per-graph tiered-state wiring, attached by ``PipeGraph.start``
    as ``graph.tiered_state``.

    Splits ``RuntimeConfig.state_budget_bytes`` evenly across the
    replicas that expose ``enable_tiered_state`` and owns the spill
    root (``<log_dir>/state_spill/<graph>/<replica>/``).  Kept on the
    graph so replicas created AFTER start -- elastic ``_grow`` growth,
    supervised heals -- get the same enablement as their build-time
    siblings (``enable(logic, replica_name)`` is idempotent per
    name: re-enabling wipes the previous incarnation's spill
    segments, which are a runtime working set, not a durability
    surface)."""

    def __init__(self, graph, capable: int):
        cfg = graph.config
        self.graph = graph
        self.budget_bytes = int(cfg.state_budget_bytes)
        self.share = max(1, self.budget_bytes // max(1, capable))
        self.tier_cfg = cfg.state_tiers
        self.spill_root = os.path.join(
            cfg.log_dir or "log", "state_spill", _safe(graph.name))
        self.stores: Dict[str, TieredKeyedStore] = {}

    def enable(self, logic, replica_name: str) -> Optional[TieredKeyedStore]:
        hook = getattr(logic, "enable_tiered_state", None)
        if hook is None:
            return None
        g = self.graph
        spill = SpillStore(os.path.join(self.spill_root,
                                        _safe(replica_name)))
        spill.fault_plan = g.config.fault_plan
        tc = self.tier_cfg
        store = TieredKeyedStore(
            budget=StateBudget(
                self.share,
                demote_frac=getattr(tc, "demote_frac", 0.7),
                spill_frac=getattr(tc, "spill_frac", 0.85)),
            spill=spill,
            node=replica_name,
            flight=g.flight,
            dead_letters=g.dead_letters,
            hot_max_keys=getattr(tc, "hot_max_keys", None),
            maintain_every=getattr(tc, "maintain_every", 64),
            spill_batch=getattr(tc, "spill_batch", 256))
        hook(store)
        self.stores[replica_name] = store
        return store

    def release(self, replica_name: str) -> None:
        """Drop a retired replica's store (rescale shrink): its keys
        migrated with the keyed-state merge, so the spill segments on
        disk are dead weight."""
        store = self.stores.pop(replica_name, None)
        if store is not None:
            store.spill.clear()


def attach_tiered_state(graph) -> Optional[TieredStateManager]:
    """Wire tiered keyed state across ``graph`` (called by
    ``PipeGraph.start`` once fault/flight/dead-letter binding is done,
    BEFORE the audit plane attaches -- the auditor hands its hot-key
    sketches to the stores it finds).  Returns the manager, or None
    when no ``state_budget_bytes`` is configured or no logic is
    capable."""
    if not getattr(graph.config, "state_budget_bytes", None):
        return None
    from ..runtime.node import FusedLogic

    def capable_logics(node):
        if isinstance(node.logic, FusedLogic):
            for seg in node.logic.segments:
                if getattr(seg.logic, "enable_tiered_state", None):
                    yield seg.logic, seg.name
        elif getattr(node.logic, "enable_tiered_state", None):
            yield node.logic, node.name

    targets = [(lg, name) for n in graph._all_nodes()
               for lg, name in capable_logics(n)]
    if not targets:
        return None
    mgr = TieredStateManager(graph, len(targets))
    for lg, name in targets:
        mgr.enable(lg, name)
    return mgr
