"""Per-store memory-budget governor (docs/RESILIENCE.md "Tiered state
& memory pressure").

One :class:`StateBudget` per :class:`~windflow_tpu.state.tiers.
TieredKeyedStore`: a hard byte ceiling (the replica's share of
``RuntimeConfig.state_budget_bytes``) with two watermarks below it::

    0 ........ demote ........ spill ........ budget
                 (0.7B)         (0.85B)        (B)

* above **demote**: hot keys (live objects) are demoted to warm
  (pickled host bytes) -- cheap, reversible, frees the object graph;
* above **spill**: warm keys move to cold disk segments in batches;
* above the **budget** itself: admission-style shed -- the coldest
  keys are dropped into ``dead_letters`` with a ``state_pressure``
  flight event.  Degraded, loud, and alive beats an allocator crash.

Process RSS (``monitoring/stats.get_mem_usage_kb``) is deliberately
NOT the enforcement signal: it is process-global (shared by pools,
JAX, every other replica) and lags the allocator.  The governor
enforces the store's own byte accounting; RSS stays what the History
gauges assert in the soak test -- the independent evidence that the
accounting tracks reality.
"""
from __future__ import annotations


class StateBudget:
    __slots__ = ("limit", "demote_at", "spill_at")

    def __init__(self, limit: int, demote_frac: float = 0.7,
                 spill_frac: float = 0.85):
        self.limit = max(1, int(limit))
        demote_frac = min(max(float(demote_frac), 0.05), 1.0)
        spill_frac = min(max(float(spill_frac), demote_frac), 1.0)
        self.demote_at = int(self.limit * demote_frac)
        self.spill_at = int(self.limit * spill_frac)

    def pressure(self, mem_bytes: int) -> str:
        """Band of ``mem_bytes`` (hot + warm accounting) on the
        ladder: 'ok' | 'demote' | 'spill' | 'shed'."""
        if mem_bytes > self.limit:
            return "shed"
        if mem_bytes > self.spill_at:
            return "spill"
        if mem_bytes > self.demote_at:
            return "demote"
        return "ok"
