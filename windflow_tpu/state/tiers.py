"""The tiered keyed store (docs/RESILIENCE.md "Tiered state & memory
pressure").

Drop-in replacement for the plain dict a keyed logic keeps per-key
state in (``AccumulatorLogic.state``), adopted at graph start through
the logic's ``enable_tiered_state`` hook.  Three tiers under one
dict-like surface::

    hot   live Python objects, LRU-ordered   (device forests keep
          their own residency -- they report tier "device")
    warm  pickled bytes in host RAM, demotion-ordered
    cold  pickled bytes in disk segments (state/spill.py)

Reads promote (cold/warm → hot); ``maintain()`` -- called every
``maintain_every`` store operations on the replica's own thread --
walks the :class:`~windflow_tpu.state.budget.StateBudget` ladder:
demote LRU hot keys, spill the oldest warm keys in batches, and past
the hard budget SHED the coldest keys into ``dead_letters`` with a
``state_pressure`` flight event (a shed key restarts from the
operator's init value on its next appearance -- degraded and loud,
never an allocator crash).  Keys the audit plane's hot-key sketch
currently names (bound via ``bind_hot_sketch``) are pinned hot.

Composition with the other planes:

* delta snapshots: ``keyed_state_pickled()`` serves warm/cold keys
  from their STORED pickled bytes, so an unchanged cold key digests
  identically every epoch and the chain references it with zero new
  blob bytes (the "cold tier by reference" property);
* restore/rescale/supervision: every restore funnels through
  ``replace_all``, which wipes all tiers (spill dir included) before
  loading -- the disk working set never survives a restore;
* census: ``census()`` returns per-tier key/byte counts and the
  spill/promotion/shed counters as a third gauge element.

Spill-write failures (ENOSPC) degrade: a ``spill_abort`` flight event,
the batch stays warm, and spilling backs off for a few maintenance
rounds while demotion/shed keep enforcing the ceiling.
"""
from __future__ import annotations

import pickle
import sys
from collections import OrderedDict
from typing import Any, Dict, Iterable, Optional

# per-key bookkeeping overhead added to getsizeof (dict slot, control
# fields, fragmentation) -- gauge-grade, same spirit as the census
_KEY_OVERHEAD = 96
_MISSING = object()
# maintenance rounds to skip spilling after a failed segment write
_SPILL_COOLDOWN = 8


def _size_of(value) -> int:
    try:
        return sys.getsizeof(value) + _KEY_OVERHEAD
    except TypeError:
        return 2 * _KEY_OVERHEAD


class TieredKeyedStore:
    """Single-writer (the owning replica thread); the auditor reads
    ``census()``/``tier_of()`` as lock-free gauges."""

    def __init__(self, budget, spill, node: str = "?", flight=None,
                 dead_letters=None, hot_max_keys: Optional[int] = None,
                 maintain_every: int = 64, spill_batch: int = 256):
        self.budget = budget
        self.spill = spill
        self.node = node
        self.flight = flight
        self.dead_letters = dead_letters
        self.hot_max_keys = hot_max_keys
        self.maintain_every = max(1, int(maintain_every))
        self.spill_batch = max(1, int(spill_batch))
        self.hot_keys_fn = None          # audit sketch (bind_hot_sketch)
        # the most recently accessed key is pinned until the next
        # access: the caller (AccumulatorLogic.svc) mutates the
        # returned value IN PLACE after get()/[]= returns, so demoting
        # (pickling) it inside the same call would strand the mutation
        # on a dead object
        self._mru: Any = _MISSING
        self._hot: Dict[Any, Any] = {}   # insertion order == LRU order
        self._warm: "OrderedDict[Any, bytes]" = OrderedDict()
        self._hot_sizes: Dict[Any, int] = {}
        self._hot_bytes = 0
        self._warm_bytes = 0
        self._ops = 0
        self._cooldown = 0
        self.promotions = 0
        self.demotions = 0
        self.spilled_keys = 0
        self.sheds = 0

    # -- dict surface (what AccumulatorLogic.svc touches) --------------
    def get(self, key, default=None):
        hot = self._hot
        v = hot.get(key, _MISSING)
        if v is not _MISSING:
            hot[key] = hot.pop(key)          # LRU touch
            self._mru = key
            self._tick()
            return v
        vb = self._warm.pop(key, None)
        if vb is not None:
            self._warm_bytes -= len(vb)
            return self._admit(key, pickle.loads(vb), promoted=True)
        if key in self.spill:
            vb = self.spill.get(key)
            self.spill.discard(key)
            return self._admit(key, pickle.loads(vb), promoted=True)
        self._tick()
        return default

    def __getitem__(self, key):
        v = self.get(key, _MISSING)
        if v is _MISSING:
            raise KeyError(key)
        return v

    def __setitem__(self, key, value) -> None:
        vb = self._warm.pop(key, None)
        if vb is not None:
            self._warm_bytes -= len(vb)
        elif key in self.spill:
            self.spill.discard(key)
        self._admit(key, value)

    def __delitem__(self, key) -> None:
        if self._drop(key) is _MISSING:
            raise KeyError(key)

    def pop(self, key, default=_MISSING):
        got = self._drop(key)
        if got is _MISSING:
            if default is _MISSING:
                raise KeyError(key)
            return default
        return got

    def __contains__(self, key) -> bool:
        return (key in self._hot or key in self._warm
                or key in self.spill)

    def __len__(self) -> int:
        return len(self._hot) + len(self._warm) + len(self.spill)

    def __bool__(self) -> bool:
        return len(self) > 0

    def keys(self):
        yield from self._hot
        yield from self._warm
        yield from self.spill.keys()

    __iter__ = keys

    def items(self):
        yield from self._hot.items()
        for k, vb in list(self._warm.items()):
            yield k, pickle.loads(vb)
        for k, vb in self.spill.items_pickled():
            yield k, pickle.loads(vb)

    def values(self):
        for _k, v in self.items():
            yield v

    # -- internal admission/removal ------------------------------------
    def _admit(self, key, value, promoted: bool = False):
        hot, sizes = self._hot, self._hot_sizes
        old = sizes.get(key)
        if old is not None:
            self._hot_bytes -= old
            hot.pop(key, None)
        sz = _size_of(value)
        hot[key] = value
        sizes[key] = sz
        self._hot_bytes += sz
        self._mru = key
        if promoted:
            self.promotions += 1
        self._tick()
        return value

    def _drop(self, key):
        if key == self._mru:
            self._mru = _MISSING
        v = self._hot.pop(key, _MISSING)
        if v is not _MISSING:
            self._hot_bytes -= self._hot_sizes.pop(key, 0)
            return v
        vb = self._warm.pop(key, None)
        if vb is not None:
            self._warm_bytes -= len(vb)
            return pickle.loads(vb)
        if key in self.spill:
            vb = self.spill.get(key)
            self.spill.discard(key)
            return pickle.loads(vb)
        return _MISSING

    def _tick(self) -> None:
        self._ops += 1
        if self._ops % self.maintain_every == 0:
            self.maintain()

    # -- budget enforcement --------------------------------------------
    def mem_bytes(self) -> int:
        return self._hot_bytes + self._warm_bytes

    def _pinned(self) -> frozenset:
        fn = self.hot_keys_fn
        if fn is None:
            return frozenset()
        try:
            got = fn()
        except Exception:
            return frozenset()
        return frozenset(got or ())

    def maintain(self) -> None:
        """Enforce the budget ladder; replica-thread only."""
        budget = self.budget
        band = budget.pressure(self.mem_bytes())
        over_keys = (self.hot_max_keys is not None
                     and len(self._hot) > self.hot_max_keys)
        if band == "ok" and not over_keys:
            return
        pinned = self._pinned()
        if self._mru is not _MISSING:
            pinned = pinned | {self._mru}
        self._demote(budget.demote_at, pinned)
        if self.budget.pressure(self.mem_bytes()) in ("spill", "shed") \
                or self._warm_bytes > budget.spill_at:
            self._spill_warm(budget)
        if self.mem_bytes() > budget.limit:
            # the pinned floor lost to the hard ceiling: demoting even
            # sketch-hot keys is LOSSLESS (they promote back on their
            # next access), so it always beats shedding.  Only the
            # in-flight MRU object must stay live.
            mru_only = (frozenset() if self._mru is _MISSING
                        else frozenset((self._mru,)))
            self._demote(budget.demote_at, mru_only)
            if self._cooldown == 0:
                self._spill_warm(budget)
        if self.mem_bytes() > budget.limit:
            self._shed(budget, pinned)

    def _demote(self, target: int, pinned: frozenset) -> None:
        """Pickle LRU hot keys into warm until hot+warm fits under the
        demote watermark (or only pinned/most-recent keys remain)."""
        hot = self._hot
        floor = max(1, len(pinned))
        for key in list(hot.keys()):
            under_bytes = self.mem_bytes() <= target
            under_keys = (self.hot_max_keys is None
                          or len(hot) <= self.hot_max_keys)
            if under_bytes and under_keys:
                return
            if len(hot) <= floor:
                return
            if key in pinned:
                continue
            value = hot.pop(key)
            self._hot_bytes -= self._hot_sizes.pop(key, 0)
            vb = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
            self._warm[key] = vb
            self._warm_bytes += len(vb)
            self.demotions += 1

    def _spill_warm(self, budget) -> None:
        """Move the oldest warm keys to disk, one immutable segment per
        batch, until warm pressure clears.  A write failure aborts the
        spill loudly and backs off -- the keys stay warm."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        warm = self._warm
        while warm and self.mem_bytes() > budget.demote_at:
            batch: Dict[Any, bytes] = {}
            nb = 0
            while warm and len(batch) < self.spill_batch:
                k, vb = warm.popitem(last=False)   # oldest first
                batch[k] = vb
                nb += len(vb)
            try:
                self.spill.put_batch(batch)
            except OSError as e:
                # disk full: re-warm the batch, degrade loudly
                for k, vb in batch.items():
                    warm[k] = vb
                    warm.move_to_end(k, last=False)
                self._cooldown = _SPILL_COOLDOWN
                if self.flight is not None:
                    self.flight.record(
                        "spill_abort", node=self.node,
                        keys=len(batch), bytes=nb, error=str(e))
                return
            self._warm_bytes -= nb
            self.spilled_keys += len(batch)

    def _shed(self, budget, pinned: frozenset) -> None:
        """Past the hard ceiling with nowhere to spill: drop the
        coldest keys into dead_letters (admission-style degradation)."""
        shed = 0
        sample = None
        warm, hot = self._warm, self._hot
        while self.mem_bytes() > budget.limit:
            if warm:
                key, vb = warm.popitem(last=False)
                self._warm_bytes -= len(vb)
            elif len(hot) > 1:
                # prefer unpinned victims; under a hard ceiling even
                # sketch-hot keys shed -- but never the in-flight MRU
                # key (its caller still mutates the live object)
                key = next((k for k in hot if k not in pinned), None)
                if key is None:
                    key = next((k for k in hot if k != self._mru),
                               None)
                if key is None:
                    break
                hot.pop(key)
                self._hot_bytes -= self._hot_sizes.pop(key, 0)
            else:
                break   # a single live key never sheds
            shed += 1
            if sample is None:
                sample = key
        if not shed:
            return
        self.sheds += shed
        if self.dead_letters is not None:
            self.dead_letters.add(
                self.node, {"key": sample},
                MemoryError("state_pressure: keyed state shed under "
                            "memory budget"),
                count=shed)
        if self.flight is not None:
            self.flight.record(
                "state_pressure", node=self.node, shed=shed,
                sample_key=repr(sample), budget=budget.limit,
                mem_bytes=self.mem_bytes())

    # -- audit / sketch binding ----------------------------------------
    def bind_hot_sketch(self, hot_keys_fn) -> None:
        self.hot_keys_fn = hot_keys_fn

    def tier_of(self, key) -> Optional[str]:
        if key in self._hot:
            return "hot"
        if key in self._warm:
            return "warm"
        if key in self.spill:
            return "cold"
        return None

    def census(self):
        """(total keys, in-memory bytes estimate, per-tier extras) --
        gauge-grade, read from the auditor thread."""
        hn, wn, cn = len(self._hot), len(self._warm), len(self.spill)
        hb, wb = self._hot_bytes, self._warm_bytes
        extras = {
            "tiers": {"hot": [hn, hb], "warm": [wn, wb],
                      "cold": [cn, self.spill.disk_bytes()]},
            "spills": self.spilled_keys,
            "spill_bytes": self.spill.bytes_written,
            "promotions": self.promotions,
            "demotions": self.demotions,
            "sheds": self.sheds,
        }
        return (hn + wn + cn, hb + wb, extras)

    # -- restore / capture funnel --------------------------------------
    def materialize(self) -> Dict[Any, Any]:
        """Every key as a live value (rescale merge, schema-1
        snapshots).  Promotes nothing."""
        return dict(self.items())

    def keyed_state_pickled(self) -> Dict[Any, bytes]:
        """Per-key pickled values for the delta capture: hot keys are
        pickled fresh, warm/cold keys reuse their STORED bytes so
        unchanged keys digest identically across epochs."""
        out: Dict[Any, bytes] = {
            k: pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)
            for k, v in self._hot.items()}
        out.update(self._warm)
        for k, vb in self.spill.items_pickled():
            out[k] = vb
        return out

    def replace_all(self, kv: Dict[Any, Any]) -> None:
        """The single restore funnel: wipe every tier (spill segments
        included -- the disk working set never survives a restore),
        load ``kv`` hot, then re-tier under the budget."""
        self._hot = {}
        self._warm = OrderedDict()
        self._hot_sizes = {}
        self._hot_bytes = self._warm_bytes = 0
        self._mru = _MISSING
        self.spill.clear()
        for k, v in kv.items():
            sz = _size_of(v)
            self._hot[k] = v
            self._hot_sizes[k] = sz
            self._hot_bytes += sz
        self.maintain()

    def clear(self) -> None:
        self.replace_all({})
