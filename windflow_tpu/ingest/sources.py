"""Ingest source operators: socket, async-generator and replay feeds.

Each source replica runs a non-blocking transport poll loop on its
node thread and ships through a :class:`~.coalesce.ChunkCoalescer`
(credit-gated, admission-controlled, controller-batched -- see the
package docstring).  All transports poll with short timeouts and check
the graph CancelToken between polls, so cancellation unblocks a source
mid-recv (the PR-1 containment contract extended to the network edge).
"""
from __future__ import annotations

import os
import socket
import time as _time
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

from ..core.basic import Pattern, RoutingMode
from ..core.context import RuntimeContext
from ..core.tuples import TupleBatch
from ..distributed.wire import StreamDecoder
from ..operators.base import Operator, StageSpec
from ..resilience.cancel import GraphCancelled
from ..runtime.emitters import StandardEmitter
from ..runtime.node import SourceLoopLogic
from .admission import AdmissionConfig, ShedTuples
from .coalesce import ChunkCoalescer
from .controller import MicrobatchController
from .credits import CreditGate

DEFAULT_CREDITS = 1 << 16
_POLL_S = 0.05

# transport poll outcomes
_EOS = object()


class IngestSourceLogic(SourceLoopLogic):
    """One ingest source replica: transport poll loop + coalescer.

    ``transport`` must provide ``open(cancelled_fn)``,
    ``poll(n_hint) -> list[TupleBatch] | _EOS`` (an empty list means
    "nothing yet") and ``close()``.
    """

    def __init__(self, name: str, transport, *,
                 credits: Optional[int] = None,
                 admission: Optional[AdmissionConfig] = None,
                 latency_target_ms: Optional[float] = None,
                 initial_batch: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 coalesce: bool = True,
                 pre_reduce: Union[str, bool] = "auto",
                 closing_func: Optional[Callable] = None,
                 parallelism: int = 1, replica_index: int = 0):
        self.context = RuntimeContext(parallelism, replica_index)
        self.transport = transport
        self.closing_func = closing_func
        self.credits_explicit = credits is not None
        credits = credits or DEFAULT_CREDITS
        self.gate = CreditGate(credits)
        self.controller = MicrobatchController(
            latency_target_ms=latency_target_ms,
            initial_batch=initial_batch,
            max_batch=max_batch or max(credits, 1 << 10))
        self.gate.bind_observer(self.controller.observe)
        self.coalescer = ChunkCoalescer(
            self.gate, self.controller, admission=admission,
            shed_cb=self._on_shed, on_emit=self._on_emit,
            coalesce=coalesce)
        self.pre_reduce_mode = pre_reduce
        # wired by ingest.wiring at PipeGraph.start
        self.node_name = name
        self.cancel_token = None
        self.dead_letters = None
        self.tuples_shed = 0
        self.emit_stamps: List = []   # (raw tuples emitted, perf_counter)
        self._opened = False
        super().__init__(self._step)

    # -- coalescer callbacks (flusher / transport threads) --------------
    def _on_shed(self, batch, n: int, policy: str) -> None:
        self.tuples_shed += n
        if self.dead_letters is not None:
            self.dead_letters.add(self.node_name, batch,
                                  ShedTuples(policy, n), count=n)
        if self.flight is not None:  # telemetry flight recorder
            self.flight.record("shed", node=self.node_name, n=n,
                               policy=policy,
                               total=self.tuples_shed)
        if self.stats is not None:
            self.stats.tuples_shed = self.tuples_shed

    def _on_emit(self, raw_cum: int, batch_len: int, t: float) -> None:
        if len(self.emit_stamps) < 1_000_000:
            self.emit_stamps.append((raw_cum, t))
        stats = self.stats
        if stats is not None:
            stats.ingest_batch_size = self.controller.batch_size
            stats.ingest_queue_depth = self.gate.outstanding()
            stats.credits_available = self.gate.available
            stats.controller_trace = self.controller.trace_tail()

    def _cancelled(self) -> bool:
        tok = self.cancel_token
        return tok is not None and tok.cancelled

    # -- generation loop -------------------------------------------------
    def _step(self, emit) -> bool:
        self.coalescer.ensure_started(emit)
        self.coalescer.check_error()
        if self._cancelled():
            raise GraphCancelled(f"ingest source {self.node_name} cancelled")
        if not self._opened:
            self.transport.open(self._cancelled)
            self._opened = True
        got = self.transport.poll(self.controller.target_batch())
        if got is _EOS:
            self.coalescer.close()
            return False
        for batch in got:
            self.coalescer.put(batch)
        return True

    def svc_end(self) -> None:
        # error-path teardown (close() already stopped the flusher on
        # the normal path): drop the staged backlog, free the transport
        self.coalescer.abort()
        try:
            self.transport.close()
        except OSError:
            pass
        if self.closing_func is not None:
            self.closing_func(self.context)

    def quiesce(self, emit) -> bool:
        """Live-checkpoint barrier hook: wait for the flusher to drain
        the stage (the barrier pauses the poll loop, not the flusher)."""
        return self.coalescer.wait_idle()

    # -- checkpoint: transports with a position resume from it ----------
    def state_dict(self):
        # always a real dict: _is_stateful() sees the override, so a
        # None here would omit the node from the snapshot while
        # restore_graph still requires it (structure-mismatch error).
        # Position-less transports (socket/async) snapshot as None and
        # restore as a no-op (the stream resumes wherever the peer is).
        sd = getattr(self.transport, "state_dict", None)
        return {"transport": sd() if sd is not None else None}

    def load_state(self, state) -> None:
        ts = state.get("transport")
        if ts is not None:
            self.transport.load_state(ts)

    # -- audit plane (audit/progress.py): monotone source position ------
    def progress_frontier(self):
        """Transport position when the transport keeps one (replay
        offset, socket bytes decoded into tuples), else the coalescer's
        cumulative raw-emitted counter -- both monotone, both updated
        by the replica's own threads (gauge-grade read)."""
        tp = getattr(self.transport, "position", None)
        if tp is not None:
            try:
                v = tp()
            except (RuntimeError, TypeError):
                v = None
            if v is not None:
                return v
        return self.coalescer.raw_emitted

    # -- observability ---------------------------------------------------
    def metrics(self) -> dict:
        return {
            "credits_budget": self.gate.budget,
            "credits_available": self.gate.available,
            "credits_peak_outstanding": self.gate.peak_outstanding,
            "credit_waits": self.gate.credit_waits,
            "credit_wait_time_s": round(self.gate.wait_time_s, 4),
            "tuples_shed": self.tuples_shed,
            "tuples_staged": self.coalescer.tuples_staged,
            "tuples_emitted": self.coalescer.tuples_emitted,
            "raw_emitted": self.coalescer.raw_emitted,
            "batches_emitted": self.coalescer.batches_emitted,
            "peak_staged": self.coalescer.peak_staged,
            "batch_size": self.controller.batch_size,
            "flush_interval_ms": round(self.controller.flush_interval_ms, 3),
            "controller_trace": self.controller.trace_tail(),
            "pre_reduce": self.coalescer.pre_reduce is not None,
        }


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------

class _SocketTransport:
    """Non-blocking TCP client speaking the `codec` frame protocol."""

    def __init__(self, host: str, port: int, connect_timeout_s: float = 10.0,
                 recv_bytes: int = 1 << 20):
        self.host = host
        self.port = port
        self.connect_timeout_s = connect_timeout_s
        self.recv_bytes = recv_bytes
        self.sock: Optional[socket.socket] = None
        self.decoder = StreamDecoder()
        self.bytes_received = 0

    def open(self, cancelled_fn: Callable[[], bool]) -> None:
        deadline = _time.monotonic() + self.connect_timeout_s
        last_err: Optional[Exception] = None
        while True:
            if cancelled_fn():
                raise GraphCancelled("socket source cancelled while "
                                     "connecting")
            try:
                s = socket.create_connection((self.host, self.port),
                                             timeout=0.25)
                s.settimeout(_POLL_S)
                self.sock = s
                return
            except OSError as e:
                last_err = e
                if _time.monotonic() > deadline:
                    raise ConnectionError(
                        f"socket source: cannot connect to "
                        f"{self.host}:{self.port}") from last_err
                _time.sleep(0.05)

    def poll(self, n_hint: int):
        try:
            data = self.sock.recv(self.recv_bytes)
        except socket.timeout:
            return []
        except OSError as e:
            # a reset/abort mid-stream is a transport FAILURE, not end
            # of stream: fail the replica (graph cancels, the error is
            # reported) instead of completing on a truncated prefix.
            # Clean EOS is recv() returning b"" below.
            raise ConnectionError(
                f"socket source: connection to {self.host}:{self.port} "
                f"failed mid-stream after {self.bytes_received} bytes: "
                f"{e}") from e
        if not data:
            return _EOS
        self.bytes_received += len(data)
        return self.decoder.feed(data)

    def close(self) -> None:
        if self.sock is not None:
            self.sock.close()
            self.sock = None

    def position(self):
        """Audit frontier: the socket chunk sequence -- frames decoded
        so far (monotone; decoder counters are single-writer)."""
        return self.decoder.frames_decoded \
            if hasattr(self.decoder, "frames_decoded") \
            else self.bytes_received


class _ReplayTransport:
    """Timestamp-faithful trace replay with rate control.

    ``trace`` is a TupleBatch, a dict of columns, or a path to an
    ``.npz`` with key/id/ts/value arrays.  ``speedup`` scales the
    recorded inter-arrival gaps (None = as fast as possible);
    ``ts_unit_s`` converts the ts column to seconds.  With ``chunk``
    set, chunk sizes are drawn (in [chunk//2, chunk]) from a
    seed-keyed RNG: boundaries are a pure function of (trace, chunk,
    seed, shard), never of wall clock, so a seeded replay is
    deterministic and composes with the resilience FaultPlan harness
    while different seeds exercise different batching.  ``chunk=None``
    instead lets the adaptive controller size chunks (max-throughput
    mode).
    """

    def __init__(self, trace, *, speedup: Optional[float] = 1.0,
                 ts_unit_s: float = 1e-6, chunk: Optional[int] = 65536,
                 seed: int = 0, shard: tuple = (0, 1)):
        self.trace_spec = trace
        self.speedup = speedup
        self.ts_unit_s = ts_unit_s
        self.chunk = chunk
        self.seed = seed
        self.shard = shard
        self.cols = None
        self.off = 0
        self.hi = 0
        self._t0 = 0.0
        self._ts0 = 0
        self._rng = np.random.default_rng(seed)

    def open(self, cancelled_fn) -> None:
        spec = self.trace_spec
        if isinstance(spec, (str, os.PathLike)):
            with np.load(spec) as z:
                cols = {k: z[k] for k in z.files}
        elif isinstance(spec, TupleBatch):
            cols = spec.cols
        else:
            cols = dict(spec)
        n = len(cols["ts"])
        ridx, nrep = self.shard
        lo = n * ridx // nrep
        self.hi = n * (ridx + 1) // nrep
        self.off = lo
        self.cols = cols
        self._t0 = _time.monotonic()
        self._ts0 = int(cols["ts"][lo]) if self.hi > lo else 0

    def poll(self, n_hint: int):
        if self.off >= self.hi:
            return _EOS
        if self.chunk is not None:
            # seeded chunk-size jitter: boundaries are a pure function
            # of (trace, chunk, seed, shard) -- reproducible for the
            # FaultPlan harness, varied across seeds
            n = int(self._rng.integers(max(1, self.chunk // 2),
                                       self.chunk + 1))
        else:
            n = max(1, n_hint)
        end = min(self.off + n, self.hi)
        if self.speedup:
            # pace on the chunk's first timestamp; sleep in short,
            # cancel-checkable slices (the caller re-polls)
            due = (self._t0 + (int(self.cols["ts"][self.off]) - self._ts0)
                   * self.ts_unit_s / self.speedup)
            delay = due - _time.monotonic()
            if delay > 0:
                _time.sleep(min(delay, _POLL_S))
                if delay > _POLL_S:
                    return []
        batch = TupleBatch({k: v[self.off:end]
                            for k, v in self.cols.items()})
        self.off = end
        return [batch]

    def close(self) -> None:
        self.cols = None

    # -- checkpoint: replay resumes from its offset ---------------------
    def state_dict(self):
        return {"off": self.off}

    def load_state(self, state) -> None:
        self.off = state["off"]

    def position(self):
        """Audit frontier: the replay offset (same monotone position
        the checkpoint plane snapshots)."""
        return self.off


class _AsyncGenTransport:
    """Drives an async generator on a private event loop.

    The generator may yield ``TupleBatch`` items (passed through) or
    record objects / ``(key, id, ts, value)`` tuples (accumulated and
    converted columnar per poll).
    """

    def __init__(self, factory: Callable[[], Any], record_batch: int = 4096):
        self.factory = factory
        self.record_batch = record_batch
        self.loop = None
        self.agen = None
        self._pending = None
        self._records: List = []
        self._done = False

    def open(self, cancelled_fn) -> None:
        import asyncio
        self.loop = asyncio.new_event_loop()
        self.agen = self.factory()
        if not hasattr(self.agen, "__anext__"):
            raise TypeError("AsyncGeneratorSource needs a factory "
                            "returning an async generator")

    def _flush_records(self) -> List[TupleBatch]:
        if not self._records:
            return []
        recs, self._records = self._records, []
        if isinstance(recs[0], tuple):
            arr = np.asarray(recs)
            out = TupleBatch({
                "key": arr[:, 0].astype(np.int64),
                "id": arr[:, 1].astype(np.int64),
                "ts": arr[:, 2].astype(np.int64),
                "value": arr[:, 3].astype(np.float64)})
        else:
            out = TupleBatch.from_records(recs)
        return [out]

    def poll(self, n_hint: int):
        import asyncio
        if self._done:
            return self._flush_records() or _EOS
        out: List[TupleBatch] = []
        deadline = _time.monotonic() + _POLL_S
        budget = max(n_hint, self.record_batch)
        while True:
            if self._pending is None:
                self._pending = self.loop.create_task(
                    self.agen.__anext__())
            timeout = deadline - _time.monotonic()
            done, _ = self.loop.run_until_complete(asyncio.wait(
                {self._pending}, timeout=max(0.0, timeout)))
            if not done:
                break
            task, self._pending = self._pending, None
            try:
                item = task.result()
            except StopAsyncIteration:
                self._done = True
                break
            if isinstance(item, TupleBatch):
                out.extend(self._flush_records())
                out.append(item)
            else:
                self._records.append(item)
            got = sum(len(b) for b in out) + len(self._records)
            if got >= budget or _time.monotonic() >= deadline:
                break
        if self._done or sum(len(b) for b in out) + len(self._records) \
                >= self.record_batch:
            out.extend(self._flush_records())
        if self._done and not out:
            return self._flush_records() or _EOS
        return out

    def close(self) -> None:
        if self.loop is not None:
            if self._pending is not None:
                self._pending.cancel()
                try:
                    self.loop.run_until_complete(self._pending)
                except BaseException:
                    pass
                self._pending = None
            if self.agen is not None:
                try:
                    self.loop.run_until_complete(self.agen.aclose())
                except BaseException:
                    pass
            self.loop.close()
            self.loop = None


# ---------------------------------------------------------------------------
# Operator descriptors
# ---------------------------------------------------------------------------

class _IngestOperator(Operator):
    """Shared descriptor: N replica logics, standard emitter."""

    def __init__(self, name: str, parallelism: int = 1, *,
                 credits: Optional[int] = None,
                 admission: Optional[AdmissionConfig] = None,
                 latency_target_ms: Optional[float] = None,
                 initial_batch: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 coalesce: bool = True,
                 pre_reduce: Union[str, bool] = "auto",
                 closing_func: Optional[Callable] = None):
        super().__init__(name, parallelism, RoutingMode.NONE, Pattern.SOURCE)
        self.credits = credits
        self.admission = admission
        self.latency_target_ms = latency_target_ms
        self.initial_batch = initial_batch
        self.max_batch = max_batch
        self.coalesce = coalesce
        self.pre_reduce = pre_reduce
        self.closing_func = closing_func
        self.logics: List[IngestSourceLogic] = []  # filled by stages()

    def _transport(self, replica_index: int):
        raise NotImplementedError

    def _logic_kwargs(self) -> dict:
        return dict(credits=self.credits, admission=self.admission,
                    latency_target_ms=self.latency_target_ms,
                    initial_batch=self.initial_batch,
                    max_batch=self.max_batch, coalesce=self.coalesce,
                    pre_reduce=self.pre_reduce,
                    closing_func=self.closing_func)

    def stages(self) -> List[StageSpec]:
        self.logics = [
            IngestSourceLogic(self.name, self._transport(i),
                              parallelism=self.parallelism, replica_index=i,
                              **self._logic_kwargs())
            for i in range(self.parallelism)]
        return [StageSpec(self.name, self.logics, StandardEmitter(),
                          self.routing)]

    def metrics(self) -> List[dict]:
        return [lg.metrics() for lg in self.logics]

    def shed_count(self) -> int:
        return sum(lg.tuples_shed for lg in self.logics)


class SocketSource(_IngestOperator):
    """Framed-TCP ingest: each replica opens one client connection to
    ``host:port`` and decodes `codec` frames into the batch plane."""

    def __init__(self, host: str, port: int, parallelism: int = 1,
                 name: str = "socket_source",
                 connect_timeout_s: float = 10.0, **kw):
        super().__init__(name, parallelism, **kw)
        self.host = host
        self.port = port
        self.connect_timeout_s = connect_timeout_s

    def _transport(self, replica_index: int):
        return _SocketTransport(self.host, self.port,
                                self.connect_timeout_s)


class ReplaySource(_IngestOperator):
    """Timestamp-faithful trace replay (see :class:`_ReplayTransport`).
    Replicas replay contiguous shards of the trace."""

    def __init__(self, trace, parallelism: int = 1, name: str = "replay",
                 speedup: Optional[float] = 1.0, ts_unit_s: float = 1e-6,
                 chunk: Optional[int] = 65536, seed: int = 0, **kw):
        super().__init__(name, parallelism, **kw)
        self.trace = trace
        self.speedup = speedup
        self.ts_unit_s = ts_unit_s
        self.chunk = chunk
        self.seed = seed

    def _transport(self, replica_index: int):
        return _ReplayTransport(
            self.trace, speedup=self.speedup, ts_unit_s=self.ts_unit_s,
            chunk=self.chunk, seed=self.seed,
            shard=(replica_index, self.parallelism))


class AsyncGeneratorSource(_IngestOperator):
    """Async-generator ingest: ``factory()`` is called once per replica
    and must return an async generator yielding batches or records."""

    def __init__(self, factory: Callable[[], Any], parallelism: int = 1,
                 name: str = "async_source", **kw):
        super().__init__(name, parallelism, **kw)
        self.factory = factory

    def _transport(self, replica_index: int):
        return _AsyncGenTransport(self.factory)


def serve_batches(sock: socket.socket,
                  batches: Sequence[TupleBatch]) -> int:
    """Test/bench helper: send ``batches`` as codec frames over an
    accepted connection; returns bytes sent."""
    from ..distributed.wire import encode_batch
    total = 0
    for b in batches:
        data = encode_batch(b)
        sock.sendall(data)
        total += len(data)
    return total
