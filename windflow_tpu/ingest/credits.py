"""Credit-based backpressure for ingest sources.

A source replica may have ``budget`` tuples outstanding in its outlet
channels; every emitted item spends ``len(item)`` credits and every
item the downstream consumer dequeues returns them.  Exhausted credits
block (or, with an admission policy, shed) at the *ingest* boundary --
the transport stops reading, so for TCP the kernel's flow control
propagates backpressure to the peer instead of the process buffering
without bound.

The mechanism is two halves:

* :class:`CreditGate` -- the per-source-replica budget.  ``acquire``
  blocks until credits are available (cancel-aware: the graph
  CancelToken poisons gates so a cancelled graph unblocks a source
  stuck waiting for credits).  Spend times are kept FIFO so each
  ``release`` yields a queue-residency latency sample -- the feedback
  signal of the microbatch controller.
* :class:`CreditedChannel` -- a transparent proxy wrapped around the
  source's outlet channel at graph start (`wiring.py`).  Consumer
  ``get``s pass through and return the item's credits to the gate of
  the producer that sent it.
"""
from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Any, Dict, Optional

from ..resilience.cancel import GraphCancelled


def credits_of(item: Any) -> int:
    """Credit cost of one channel item, in tuples."""
    try:
        return max(1, len(item))
    except TypeError:
        return 1


class CreditGate:
    """Per-source-replica credit budget (tuples outstanding downstream)."""

    def __init__(self, budget: int):
        if budget < 1:
            raise ValueError("credit budget must be >= 1")
        self.budget = budget
        self._lock = threading.Lock()
        self._avail = threading.Condition(self._lock)
        self.available = budget
        self.poisoned = False
        # FIFO of (spend_time, n): channel delivery is FIFO per
        # producer, so releases pop in spend order and the head's age is
        # the dequeued item's queue residency
        self._inflight: deque = deque()
        # -- observability (monitoring JSON / tests) -------------------
        self.peak_outstanding = 0
        self.credit_waits = 0          # acquires that had to block/shed
        self.wait_time_s = 0.0
        self._observer = None          # MicrobatchController.observe

    def bind_observer(self, observer) -> None:
        self._observer = observer

    def resize(self, budget: int) -> None:
        """Rebudget -- pre-start (wiring applies RuntimeConfig
        defaults) or LIVE (the serving plane's arbiter moves credits
        between running tenants, docs/SERVING.md).  Waiters are woken
        so an upward resize unblocks promptly, and ``acquire``
        re-reads the budget inside its wait loop so a downward resize
        can never wedge a blocked source against a need the new
        budget can no longer satisfy."""
        if budget < 1:
            raise ValueError("credit budget must be >= 1")
        with self._avail:
            self.available += budget - self.budget
            self.budget = budget
            self._avail.notify_all()

    def outstanding(self) -> int:
        with self._lock:
            return self.budget - self.available

    def try_acquire(self, n: int) -> bool:
        """Non-blocking acquire; full-budget grants are always allowed
        so a single over-budget batch cannot wedge the source."""
        with self._lock:
            if self.poisoned:
                raise GraphCancelled("credit gate poisoned")
            if self.available < min(n, self.budget):
                return False
            self._spend_locked(n)
            return True

    def acquire(self, n: int, timeout: Optional[float] = None) -> bool:
        """Block until ``n`` credits are available (or the full budget,
        when ``n`` exceeds it).  Returns False on timeout -- the
        admission layer's shed trigger.  Raises GraphCancelled once the
        owning graph is cancelled."""
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._avail:
            if self.available < min(n, self.budget):
                self.credit_waits += 1
                t0 = _time.monotonic()
                # re-read the budget each pass: a live resize may have
                # shrunk it below a captured `need`, which release()'s
                # budget clamp could then never satisfy (permanent
                # wedge of the blocked source)
                while self.available < min(n, self.budget):
                    if self.poisoned:
                        raise GraphCancelled("credit gate poisoned")
                    if deadline is None:
                        self._avail.wait(0.1)
                    else:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            self.wait_time_s += _time.monotonic() - t0
                            return False
                        self._avail.wait(min(remaining, 0.1))
                self.wait_time_s += _time.monotonic() - t0
            if self.poisoned:
                raise GraphCancelled("credit gate poisoned")
            self._spend_locked(n)
            return True

    def _spend_locked(self, n: int) -> None:
        self.available -= n
        out = self.budget - self.available
        if out > self.peak_outstanding:
            self.peak_outstanding = out
        self._inflight.append((_time.monotonic(), n))

    def release(self, n: int) -> None:
        """Return credits (consumer dequeued an item of ``n`` tuples)
        and feed the controller one queue-residency latency sample."""
        now = _time.monotonic()
        sample = None
        with self._avail:
            self.available = min(self.budget, self.available + n)
            left = n
            while left > 0 and self._inflight:
                t0, m = self._inflight[0]
                sample = now - t0
                if m <= left:
                    self._inflight.popleft()
                    left -= m
                else:
                    self._inflight[0] = (t0, m - left)
                    left = 0
            self._avail.notify_all()
        if sample is not None and self._observer is not None:
            self._observer(sample)

    def poison(self) -> None:
        """CancelToken hook: wake every blocked acquire."""
        with self._avail:
            self.poisoned = True
            self._avail.notify_all()


class CreditedChannel:
    """Transparent channel proxy returning credits on consumer gets.

    Wraps either the pure-Python ``Channel`` or the native C++ channel
    (same duck type: put/get/close/poison/qsize + counter attrs).  The
    producer-id -> gate map routes each dequeued item's credits back to
    the source replica that emitted it; producers without a gate (a
    non-ingest operator feeding the same consumer) pass through
    untouched.
    """

    __slots__ = ("inner", "gates")

    def __init__(self, inner, gates: Optional[Dict[int, CreditGate]] = None):
        self.inner = inner
        self.gates = gates or {}

    def bind_gate(self, producer_id: int, gate: CreditGate) -> None:
        self.gates[producer_id] = gate

    # -- forwarded surface (runtime/queues.Channel duck type) ----------
    def register_producer(self) -> int:
        return self.inner.register_producer()

    def put(self, producer_id: int, item: Any) -> None:
        # credits are spent HERE, per actual delivery, so the books
        # balance for every emitter shape: round-robin puts into one of
        # N channels (one spend, one release), multicast puts into all
        # N (N spends, N releases).  Spending at emit time instead
        # would over- or under-charge depending on the emitter.
        gate = self.gates.get(producer_id)
        if gate is not None:
            gate.acquire(credits_of(item))
        self.inner.put(producer_id, item)

    def put_many(self, producer_id: int, items) -> None:
        """Bulk put with EXACT credit accounting: each item's credits
        are acquired immediately before its own delivery (never summed
        up front -- a bulk acquire larger than the budget could wait on
        releases only the not-yet-delivered prefix can produce)."""
        gate = self.gates.get(producer_id)
        if gate is None:
            pm = getattr(self.inner, "put_many", None)
            if pm is not None:
                pm(producer_id, items)
            else:
                for item in items:
                    self.inner.put(producer_id, item)
            return
        for item in items:
            gate.acquire(credits_of(item))
            self.inner.put(producer_id, item)

    def close(self, producer_id: int) -> None:
        self.inner.close(producer_id)

    def get(self, timeout: Optional[float] = None):
        got = self.inner.get(timeout)
        if isinstance(got, tuple):
            pid, item = got
            gate = self.gates.get(pid)
            if gate is not None:
                gate.release(credits_of(item))
        return got

    def get_many(self, max_n: int = 128, timeout: Optional[float] = None):
        """Bulk get; every dequeued item returns its credits to its
        producer's gate, exactly as the per-item path does."""
        gm = getattr(self.inner, "get_many", None)
        if gm is None:
            got = self.get(timeout)
            return [got] if isinstance(got, tuple) else got
        got = gm(max_n, timeout)
        if isinstance(got, list):
            gates = self.gates
            for pid, item in got:
                gate = gates.get(pid)
                if gate is not None:
                    gate.release(credits_of(item))
        return got

    def poison(self) -> None:
        self.inner.poison()

    def qsize(self) -> int:
        return self.inner.qsize()

    @property
    def depth(self) -> int:
        return self.inner.depth

    @property
    def n_producers(self) -> int:
        return self.inner.n_producers

    @property
    def capacity(self):
        return getattr(self.inner, "capacity", None)

    @property
    def puts(self) -> int:
        return getattr(self.inner, "puts", 0)

    @property
    def gets(self) -> int:
        return getattr(self.inner, "gets", 0)

    @property
    def high_watermark(self) -> int:
        return getattr(self.inner, "high_watermark", 0)

    @property
    def poisoned(self) -> bool:
        return getattr(self.inner, "poisoned", False)
