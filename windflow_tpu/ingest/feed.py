"""Parallel zero-copy columnar feed: N feeder threads, one arena.

The externally-fed gap (VERDICT r5, ROADMAP item 1): the synthetic
fusion lane hits hundreds of M tuples/s because the C++ engine
generates and folds chunks in place, while an external feed used to
pay a single Python source thread materializing fresh numpy columns
per batch.  This module closes the gap from the feed side:

* :class:`FeedSource` -- a graph source whose ``feeders`` replicas
  pull chunk indices from a shared cursor and materialize columns
  **through a shared ColumnPool arena** (`core/tuples.ColumnPool`):
  buffers recycle by refcount, so steady state allocates nothing, and
  the emitted TupleBatches enter the consuming window engine's
  columnar ingest (`WinSeqTPULogic._svc_batch_native` -> one C++ call
  per chunk) with no per-tuple Python anywhere on the path.
* :class:`ParallelColumnFeeder` -- the channel-free variant: feeder
  threads hand pooled columns **straight into a columnar sink** under
  one lock -- `WinSeqTPULogic.feed_columns` (device staging) or
  `NativeRecordPipeline.feed` (the native record plane; its feed ring
  is SPSC, hence the serialization).  The lock is held for one
  GIL-released C call per chunk, so N feeders overlap their column
  materialization with each other's ingest.

Chunk protocol (both classes): ``chunk_fn(i, take) -> TupleBatch |
(keys, ids, ts, vals) | None`` where ``i`` is the dense chunk index
claimed by a feeder and ``take(n, dtype)`` is the arena allocator.
``None`` ends the stream; every index below the first None must
produce a chunk (feeders claim indices atomically, so the stream is a
partition of the chunk sequence, not an interleaving race).
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from ..core.basic import Pattern, RoutingMode
from ..core.tuples import ColumnPool, TupleBatch
from ..runtime.emitters import StandardEmitter
from ..runtime.node import SourceLoopLogic
from ..operators.base import Operator, StageSpec


class _ChunkCursor:
    """Atomic claim of dense chunk indices plus an emission
    **turnstile**: feeders materialize their chunks concurrently but
    deliver them in index order.  A window engine drops tuples behind
    its fired frontier (the acceptance rule, win_seq.hpp:417-428), so
    out-of-order chunk delivery from racing feeders would silently
    lose windows -- materialization is the expensive part, delivery is
    one GIL-released C call, so ordering delivery costs ~nothing."""

    __slots__ = ("_cond", "_next_claim", "_next_emit", "ended")

    def __init__(self):
        self._cond = threading.Condition()
        self._next_claim = 0
        self._next_emit = 0
        self.ended = False

    def claim(self) -> int:
        with self._cond:
            i = self._next_claim
            self._next_claim += 1
            return i

    def wait_turn(self, i: int) -> bool:
        """Block until chunk ``i`` may be delivered; False when the
        stream ended first (an earlier chunk was None / a feeder
        failed)."""
        with self._cond:
            while self._next_emit != i and not self.ended:
                self._cond.wait(0.25)
            return not self.ended

    def release_turn(self, i: int) -> None:
        with self._cond:
            if self._next_emit == i:
                self._next_emit = i + 1
            self._cond.notify_all()

    def end(self) -> None:
        with self._cond:
            self.ended = True
            self._cond.notify_all()


def _as_batch(chunk) -> TupleBatch:
    if isinstance(chunk, TupleBatch):
        return chunk
    keys, ids, ts, vals = chunk
    return TupleBatch({"key": keys, "id": ids, "ts": ts, "value": vals})


class _FeedSourceLogic(SourceLoopLogic):
    """One feeder replica: claim index, materialize through the shared
    arena, emit.  Ends when chunk_fn returns None (the cursor's ended
    flag stops the other feeders at their next claim)."""

    def __init__(self, chunk_fn: Callable, cursor: _ChunkCursor,
                 pool: ColumnPool):
        self.chunk_fn = chunk_fn
        self.cursor = cursor
        self.pool = pool

        def step(emit):
            if cursor.ended:
                return False
            i = cursor.claim()
            try:
                chunk = self.chunk_fn(i, pool.take)  # parallel with peers
            except BaseException:
                # a chunk_fn failure must end the turnstile, or peer
                # feeders blocked in wait_turn would never unwind (the
                # cursor is not a channel: poisoning can't reach it)
                cursor.end()
                raise
            if not cursor.wait_turn(i):
                return False
            try:
                if chunk is None:
                    cursor.end()
                    return False
                emit(_as_batch(chunk))  # in chunk order, by the turnstile
            finally:
                cursor.release_turn(i)
            return True

        super().__init__(step)


class FeedSource(Operator):
    """Graph source with N parallel zero-copy feeder replicas.

    The pooled arena is shared across replicas (and sized by the
    deepest in-flight window the downstream engine keeps, via the
    refcount recycling -- no tuning knob needed).  Compared to
    ``BatchSource(fn, parallelism=N)``, the differences are exactly
    the hot-path ones: chunk indices are claimed atomically (a
    partition, not per-replica modular striping), and columns come
    from the arena instead of fresh numpy allocations."""

    def __init__(self, chunk_fn: Callable, feeders: int = 1,
                 name: str = "feed_source",
                 pool: Optional[ColumnPool] = None):
        super().__init__(name, feeders, RoutingMode.NONE, Pattern.SOURCE)
        self.chunk_fn = chunk_fn
        self.pool = pool or ColumnPool(max_per_bucket=max(64, 8 * feeders))
        self._cursor = _ChunkCursor()

    def stages(self):
        reps = [_FeedSourceLogic(self.chunk_fn, self._cursor, self.pool)
                for _ in range(self.parallelism)]
        return [StageSpec(self.name, reps, StandardEmitter(),
                          self.routing)]


class ParallelColumnFeeder:
    """Channel-free parallel feed into a columnar sink.

    ``sink`` is anything accepting ``(keys, ids, ts, vals)`` columns --
    `NativeRecordPipeline.feed` bound, or a wrapper over
    `WinSeqTPULogic.feed_columns`.  Feeders claim chunk indices from
    the shared cursor, materialize through the pooled arena in
    parallel, and serialize only the sink call itself (one
    GIL-released C crossing per chunk)."""

    def __init__(self, chunk_fn: Callable, sink: Callable,
                 feeders: int = 2, pool: Optional[ColumnPool] = None):
        self.chunk_fn = chunk_fn
        self.sink = sink
        self.feeders = max(1, feeders)
        self.pool = pool or ColumnPool(max_per_bucket=max(64, 8 * feeders))
        self._sink_lock = threading.Lock()
        self.chunks_fed = 0
        self.tuples_fed = 0
        self._error: Optional[BaseException] = None

    def _run_one(self, cursor: _ChunkCursor) -> None:
        try:
            while not cursor.ended and self._error is None:
                i = cursor.claim()
                chunk = self.chunk_fn(i, self.pool.take)
                if not cursor.wait_turn(i):
                    return
                try:
                    if chunk is None:
                        cursor.end()
                        return
                    batch = _as_batch(chunk)
                    with self._sink_lock:
                        self.sink(batch.key, batch.id, batch.ts,
                                  batch["value"])
                        self.chunks_fed += 1
                        self.tuples_fed += len(batch)
                finally:
                    cursor.release_turn(i)
        except BaseException as e:  # re-raised by run()
            self._error = e
            cursor.end()

    def run(self) -> int:
        """Feed until a feeder sees None; returns tuples fed."""
        cursor = _ChunkCursor()
        threads = [threading.Thread(target=self._run_one, args=(cursor,),
                                    daemon=True, name=f"col-feeder-{i}")
                   for i in range(self.feeders)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if self._error is not None:
            raise self._error
        return self.tuples_fed
