"""Graph-start wiring of the ingest plane (called by PipeGraph.start).

Four jobs, all cross-layer and therefore done here rather than inside
any single module:

1. every ingest source replica learns its runtime identity (node name,
   CancelToken, DeadLetterStore) and inherits the graph's
   ``latency_target_ms`` unless the builder set its own;
2. the source's outlet channels are wrapped in
   :class:`~.credits.CreditedChannel` proxies (consumer side too), so
   downstream ``get``s return credits to the emitting replica's gate;
3. gates and stages register with the CancelToken -- cancellation must
   unblock a source stuck in ``acquire`` or a full stage, not just in
   channel ops;
4. directly-fed device window engines are bound to the microbatch
   controller (launch-delay steering) and, when the combine is
   provably pane-decomposable, the coalescer gets a
   :class:`~.coalesce.PanePreReducer` ("ship partials, not tuples" at
   the ingest boundary).
"""
from __future__ import annotations

import math
from typing import Dict, List

from ..core.basic import Mode, Role, WinType
from .coalesce import PanePreReducer
from .credits import CreditedChannel
from .sources import IngestSourceLogic

# pane pre-reduction only pays once a pane spans this many tuples
MIN_PREREDUCE_PANE = 16


def wire_ingest(graph) -> None:
    nodes = graph._all_nodes()
    ingest_nodes = [n for n in nodes
                    if isinstance(n.logic, IngestSourceLogic)]
    if not ingest_nodes:
        return
    cfg = graph.config
    proxies: Dict[int, CreditedChannel] = {}
    for n in ingest_nodes:
        logic = n.logic
        logic.node_name = n.name
        logic.cancel_token = graph._cancel
        logic.dead_letters = graph.dead_letters
        if logic.controller.latency_target_ms is None \
                and cfg.latency_target_ms:
            logic.controller.latency_target_ms = cfg.latency_target_ms
        if not logic.credits_explicit \
                and cfg.ingest_credits != logic.gate.budget:
            logic.gate.resize(cfg.ingest_credits)
            logic.coalescer.stage_cap = cfg.ingest_credits
            # the AIMD ceiling was derived from the default budget at
            # logic init; track the configured one
            logic.controller.set_max_batch(
                max(cfg.ingest_credits, logic.controller.max_batch))
        graph._cancel.register(logic.gate)
        graph._cancel.register(logic.coalescer)
        consumers: Dict[int, object] = {}
        for outlet in n.outlets:
            for di, (ch, pid) in enumerate(outlet.dests):
                if getattr(ch, "is_wire_sender", False):
                    # distributed plane: a cross-worker destination has
                    # its OWN credit window spanning the socket
                    # (distributed/transport.py); the in-process proxy
                    # would starve -- its releases happen in another
                    # process
                    continue
                proxy = proxies.get(id(ch))
                if proxy is None:
                    proxy = proxies[id(ch)] = CreditedChannel(ch)
                    for cn in nodes:        # consumer reads the proxy
                        if cn.channel is ch:
                            cn.channel = proxy
                for cn in nodes:
                    if cn.channel is proxy:
                        consumers[id(cn)] = cn
                proxy.bind_gate(pid, logic.gate)
                outlet.dests[di] = (proxy, pid)
        _bind_downstream(graph, logic, list(consumers.values()))


def _bind_downstream(graph, logic: IngestSourceLogic,
                     consumers: List) -> None:
    """Controller steering + pane pre-reduction for directly-fed device
    window engines.  A consumer the LEVEL2 compile pass fused is seen
    through its FIRST segment -- that is the logic the source's items
    actually enter (later segments receive window results, not raw
    tuples, so they do not constrain granularity)."""
    from ..operators.tpu.win_seq_tpu import WinSeqTPULogic
    from ..runtime.node import FusedLogic

    def entry_logic(c):
        if isinstance(c.logic, FusedLogic):
            return c.logic.segments[0].logic
        return c.logic

    engines = [entry_logic(c) for c in consumers
               if isinstance(entry_logic(c), WinSeqTPULogic)]
    for eng in engines:
        logic.controller.bind_engine(eng)
    if logic.pre_reduce_mode in (False, None) or not consumers:
        return
    if len(engines) != len(consumers):
        return  # some consumer sees raw tuples: cannot change granularity
    if graph.mode != Mode.DEFAULT:
        return  # collectors would reorder/renumber pseudo-tuples
    if not all(_pane_sum_eligible(e) for e in engines):
        return
    panes = {math.gcd(e.win_len, e.slide_len) for e in engines}
    if len(panes) != 1:
        return
    pane = panes.pop()
    if pane < MIN_PREREDUCE_PANE:
        return
    logic.coalescer.pre_reduce = PanePreReducer(pane, bin_col="ts")


def _pane_sum_eligible(eng) -> bool:
    """True when collapsing tuples to per-pane ``sum`` partials is
    provably result-identical for this engine: pane-aligned TB window
    extents (pane divides win and slide by construction), identity
    window-id config, no renumbering/delay, and a combine for which
    pane partials are exact (sum)."""
    cfg = eng.config
    return (eng.engine.kind == "sum"
            and eng.role == Role.SEQ
            and eng.win_type == WinType.TB
            and eng.triggering_delay == 0
            and not eng.renumbering
            and cfg.n_outer == 1 and cfg.n_inner == 1
            and cfg.id_outer == 0 and cfg.id_inner == 0)
