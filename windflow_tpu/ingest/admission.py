"""Admission control: what happens when ingestion outruns the graph.

Without a policy, an overloaded ingest source simply stops reading its
transport (credit exhaustion + a full staging buffer) -- correct, but
it pushes the problem to the peer.  A service that must stay live
under overload instead *sheds*: it admits what the pipeline can absorb
and quarantines the rest, visibly.

Policies (selected via ``SourceBuilder.with_admission``):

* ``drop_newest`` -- arriving tuples are shed while the stage is full;
  the backlog keeps its arrival order (protects the oldest data).
* ``drop_oldest`` -- the oldest staged tuples are evicted to admit the
  arrival (protects freshness: the steady state tracks the stream
  head, the right policy for monitoring/alerting feeds).
* ``sample`` -- a seeded-uniform subset of the arrival sized to the
  free stage space is admitted; under sustained overload the admitted
  stream is an unbiased sample of the input.

Every shed tuple is counted and quarantined (a bounded sample of the
shed batches, with exact counts) in the graph's ``DeadLetterStore``
under a :class:`ShedTuples` marker error, so overload is a measurable
event, never silent loss.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

POLICY_DROP_NEWEST = "drop_newest"
POLICY_DROP_OLDEST = "drop_oldest"
POLICY_SAMPLE = "sample"
ADMISSION_POLICIES = (POLICY_DROP_NEWEST, POLICY_DROP_OLDEST, POLICY_SAMPLE)


class ShedTuples(RuntimeError):
    """Marker error attached to dead-letter entries for shed tuples."""

    def __init__(self, policy: str, count: int):
        super().__init__(f"admission policy {policy!r} shed {count} tuples")
        self.policy = policy
        self.count = count


class AdmissionConfig:
    """Overload behaviour of one ingest source replica."""

    __slots__ = ("policy", "max_wait_ms", "seed", "_rng")

    def __init__(self, policy: str, max_wait_ms: float = 0.0,
                 seed: int = 0):
        if policy not in ADMISSION_POLICIES:
            raise ValueError(
                f"unknown admission policy {policy!r}; expected one of "
                f"{ADMISSION_POLICIES}")
        self.policy = policy
        # grace period: how long an arrival may wait for stage space
        # before the policy sheds (0 = shed immediately on overload)
        self.max_wait_ms = max_wait_ms
        self.seed = seed
        self._rng = np.random.default_rng(seed)

    def sample_take(self, n_incoming: int, n_free: int) -> Optional[np.ndarray]:
        """``sample`` policy: seeded-uniform row indices (sorted, so
        the admitted subset keeps arrival order) sized to the free
        stage space; None admits everything."""
        if n_free >= n_incoming:
            return None
        if n_free <= 0:
            return np.empty(0, np.intp)
        idx = self._rng.choice(n_incoming, size=n_free, replace=False)
        idx.sort()
        return idx
