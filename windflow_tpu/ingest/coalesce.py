"""Chunk coalescing and double-buffered staging for ingest sources.

The transport thread (the source replica's generation loop) *stages*
decoded chunks; a dedicated flusher thread *ships* them: it coalesces
staged chunks up to the controller's target batch size, optionally
pre-reduces them, acquires credits and emits into the graph.  The
bounded stage between the two is the double buffer -- the transport
fills the next batch while the previous one pays the credit wait and
the channel put, so socket reads overlap host->device staging exactly
like the window engine's dispatcher overlaps host batching with device
execution (docs/ARCHITECTURE.md decision 4, applied at the ingest
boundary).

Overload behaviour at the stage is the admission policy's job
(`admission.py`): without one, a full stage blocks the transport
(credit-style backpressure all the way to the peer); with one, the
policy sheds and the shed tuples are quarantined via the owner's shed
callback.

``PanePreReducer`` is the ingest-side instance of the architecture's
"ship partials, not tuples" rule: when the source feeds a device
window engine whose combine is a pane-decomposable ``sum`` over
pane-aligned TB windows (`wiring.py` proves this at graph start), each
coalesced batch collapses to one partial per touched (key, pane)
before it ever crosses the channel -- host->engine traffic shrinks by
the pane length while every window result stays bit-identical, because
window extents are pane-aligned (pane = gcd(win, slide) divides both).
"""
from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from ..core.tuples import TupleBatch
from ..resilience.cancel import GraphCancelled
from .admission import POLICY_DROP_NEWEST, POLICY_DROP_OLDEST, AdmissionConfig


class PanePreReducer:
    """Collapse a columnar batch to per-(key, pane) ``sum`` partials.

    ``bin_col`` is the column the downstream engine windows on ("ts"
    for TB windows).  The pseudo-tuple for pane *p* carries
    ``id = ts = p * pane`` (the pane start), which lies in exactly the
    windows that contain the pane, so the engine's firing frontier and
    window membership are unchanged at pane granularity.  Multiple
    partials for one pane (chunk boundaries mid-pane) are fine: the
    engine's pane accumulators combine them like any other tuples.
    """

    __slots__ = ("pane", "bin_col", "_native")

    # beyond this ratio of dense-grid size to batch length the bincount
    # grid would be mostly empty and allocation-bound: pass through
    MAX_GRID_EXPANSION = 4

    def __init__(self, pane: int, bin_col: str = "ts"):
        if pane < 1:
            raise ValueError("pane must be >= 1")
        self.pane = pane
        self.bin_col = bin_col
        from ..runtime.native import native_available
        self._native = native_available()

    def reduce(self, batch: TupleBatch) -> TupleBatch:
        n = len(batch)
        if n == 0:
            return batch
        keys = batch.key
        if self._native and keys.dtype == np.int64:
            # fused native pass (runtime/native.py): min/max scan +
            # dense-grid accumulate, no numpy temporaries
            from ..runtime.native import pane_prereduce
            out = pane_prereduce(keys, batch[self.bin_col],
                                 batch["value"], self.pane)
            if out is not None:
                k, p, s = out
                return TupleBatch({"key": k, "id": p, "ts": p, "value": s})
        bins = batch[self.bin_col] // self.pane
        kmin, kmax = int(keys.min()), int(keys.max())
        bmin, bmax = int(bins.min()), int(bins.max())
        krange = kmax - kmin + 1
        brange = bmax - bmin + 1
        grid = krange * brange
        if grid > self.MAX_GRID_EXPANSION * n + 1024:
            return batch  # sparse key/pane domain: not worth a dense grid
        comp = (keys - kmin) * brange + (bins - bmin)
        sums = np.bincount(comp, weights=batch["value"], minlength=grid)
        counts = np.bincount(comp, minlength=grid)
        nz = np.nonzero(counts)[0]
        pane_ids = (nz % brange + bmin) * self.pane
        return TupleBatch({
            "key": nz // brange + kmin,
            "id": pane_ids,
            "ts": pane_ids,
            "value": sums[nz],
        })


class ChunkCoalescer:
    """Stage + flusher pair owned by one ingest source replica."""

    def __init__(self, gate, controller, *,
                 admission: Optional[AdmissionConfig] = None,
                 stage_cap: Optional[int] = None,
                 shed_cb: Optional[Callable] = None,
                 on_emit: Optional[Callable] = None,
                 coalesce: bool = True):
        self.gate = gate
        self.controller = controller
        self.admission = admission
        # stage bound (tuples): defaults to one credit budget, so total
        # source-side buffering is <= stage + one budget in channels
        self.stage_cap = stage_cap or gate.budget
        self.shed_cb = shed_cb
        self.on_emit = on_emit          # (raw_cum, batch_len, t) hook
        self.coalesce = coalesce
        self.pre_reduce: Optional[PanePreReducer] = None
        self._cond = threading.Condition()
        self._items: deque = deque()    # staged TupleBatches (raw)
        self._staged = 0                # staged tuples
        self._oldest_t: Optional[float] = None
        self._closed = False
        self._poisoned = False
        self._busy = False              # flusher holds popped chunks
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._emit = None
        # -- counters ---------------------------------------------------
        self.tuples_staged = 0
        self.tuples_emitted = 0         # post-pre-reduce
        self.raw_emitted = 0            # pre-pre-reduce (transport tuples)
        self.batches_emitted = 0
        self.peak_staged = 0

    # -- lifecycle ------------------------------------------------------
    def ensure_started(self, emit) -> None:
        if self._thread is None:
            self._emit = emit
            self._thread = threading.Thread(
                target=self._run, name="windflow-ingest-flush", daemon=True)
            self._thread.start()

    def check_error(self) -> None:
        err = self._error
        if err is not None:
            self._error = None
            raise err

    def close(self) -> None:
        """EOS: flush everything staged, stop the flusher, surface any
        deferred flusher error."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.check_error()

    def abort(self) -> None:
        """Error-path teardown: stop the flusher without flushing."""
        self.poison()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def poison(self) -> None:
        """CancelToken hook: wake the producer and the flusher."""
        with self._cond:
            self._poisoned = True
            self._cond.notify_all()

    # -- producer side (transport thread) -------------------------------
    def put(self, batch: TupleBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        adm = self.admission
        with self._cond:
            # a dead flusher can never drain the stage: the wait loops
            # below must break on its stored error or put() blocks the
            # transport thread forever with check_error() unreachable
            # an over-cap batch is admitted once the stage is EMPTY
            # (the credit gate's min(n, budget) rule mirrored here): a
            # transport frame larger than the cap must not deadlock
            if adm is None:
                while self._staged + n > self.stage_cap \
                        and self._staged > 0 \
                        and not self._poisoned and self._error is None:
                    self._cond.wait(0.1)
            elif self._staged + n > self.stage_cap and self._staged > 0:
                # grace period before shedding, so micro-bursts ride out
                deadline = _time.monotonic() + adm.max_wait_ms / 1e3
                while self._staged + n > self.stage_cap \
                        and self._staged > 0 \
                        and not self._poisoned and self._error is None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        batch, n = self._apply_admission_locked(batch, n)
                        break
                    self._cond.wait(min(remaining, 0.1))
            if self._poisoned:
                raise GraphCancelled("ingest stage poisoned")
        self.check_error()
        with self._cond:
            if n == 0:
                return
            if not self._items:
                self._oldest_t = _time.monotonic()
            self._items.append(batch)
            self._staged += n
            self.tuples_staged += n
            if self._staged > self.peak_staged:
                self.peak_staged = self._staged
            self._cond.notify_all()

    def _apply_admission_locked(self, batch: TupleBatch, n: int):
        """Overload: shed per policy; returns the (possibly shrunk)
        admissible batch.  Caller holds the lock."""
        adm = self.admission
        if adm.policy == POLICY_DROP_NEWEST:
            self._shed(batch, n, adm.policy)
            return batch, 0
        if adm.policy == POLICY_DROP_OLDEST:
            # evict staged tuples until the arrival fits; an over-cap
            # arrival is admitted whole once the stage is empty (same
            # rule as the blocking path)
            while self._items and self._staged + n > self.stage_cap:
                old = self._items.popleft()
                self._staged -= len(old)
                self._shed(old, len(old), adm.policy)
            return batch, n
        # sample: admit a seeded-uniform subset sized to the free space
        free = self.stage_cap - self._staged
        idx = adm.sample_take(n, free)
        if idx is None:
            return batch, n
        kept = batch.take(idx)
        shed_n = n - len(kept)
        if shed_n:
            self._shed(batch, shed_n, adm.policy)
        return kept, len(kept)

    def _shed(self, batch, n, policy) -> None:
        if self.shed_cb is not None:
            self.shed_cb(batch, n, policy)

    # -- flusher side ----------------------------------------------------
    def _pop_coalesced_locked(self) -> List[TupleBatch]:
        target = self.controller.target_batch()
        out: List[TupleBatch] = []
        got = 0
        while self._items and (got == 0 or
                               (self.coalesce and got < target)):
            nxt = self._items[0]
            if got and got + len(nxt) > target * 2:
                break  # would badly overshoot: leave it for the next batch
            self._items.popleft()
            out.append(nxt)
            got += len(nxt)
        self._staged -= got
        self._oldest_t = _time.monotonic() if self._items else None
        return out

    def _run(self) -> None:
        emit = self._emit
        try:
            while True:
                with self._cond:
                    while not self._items and not self._closed \
                            and not self._poisoned:
                        self._cond.wait(0.05)
                    if self._poisoned:
                        return
                    if not self._items:
                        if self._closed:
                            return
                        continue
                    # partial batch: hold for more unless the deadline
                    # or EOS forces it out
                    if (self.coalesce and not self._closed
                            and self._staged
                            < self.controller.target_batch()):
                        age = _time.monotonic() - (self._oldest_t
                                                   or _time.monotonic())
                        if age < self.controller.flush_deadline_s():
                            self._cond.wait(0.005)
                            continue
                    chunks = self._pop_coalesced_locked()
                    self._busy = True
                    self._cond.notify_all()
                try:
                    self._ship(chunks, emit)
                finally:
                    with self._cond:
                        self._busy = False
                        self._cond.notify_all()
        except GraphCancelled:
            return  # clean unwind; the node loop raises on its side too
        except BaseException as e:
            self._error = e
            with self._cond:
                self._cond.notify_all()

    def _ship(self, chunks: List[TupleBatch], emit) -> None:
        raw_n = sum(len(c) for c in chunks)
        if self.pre_reduce is not None:
            # reduce each chunk before any concatenation: the raw
            # columns are never copied, only the (pane-sized) partials
            chunks = [self.pre_reduce.reduce(c) for c in chunks]
        batch = chunks[0] if len(chunks) == 1 else _concat(chunks)
        # backpressure happens inside emit: each CreditedChannel.put
        # spends credits per actual delivery (credits.py)
        emit(batch)
        self.raw_emitted += raw_n
        self.tuples_emitted += len(batch)
        self.batches_emitted += 1
        if self.on_emit is not None:
            self.on_emit(self.raw_emitted, len(batch), _time.perf_counter())

    # -- live-checkpoint barrier hook ------------------------------------
    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until nothing is staged or mid-ship; True if there was
        anything in flight (the quiesce barrier loops on True)."""
        deadline = _time.monotonic() + timeout
        had = False
        with self._cond:
            while (self._items or self._busy) and not self._poisoned:
                had = True
                if _time.monotonic() > deadline:
                    raise RuntimeError("ingest stage failed to drain")
                self._cond.wait(0.01)
        return had

    def staged(self) -> int:
        with self._cond:
            return self._staged


def _concat(chunks: List[TupleBatch]) -> TupleBatch:
    names = chunks[0].cols.keys()
    return TupleBatch({k: np.concatenate([c.cols[k] for c in chunks])
                       for k in names})
