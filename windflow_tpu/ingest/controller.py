"""Latency-targeting adaptive microbatch controller (AIMD).

Static ``microbatch`` / ``inflight_depth`` knobs force one operating
point onto every load level: big batches amortize per-batch overhead
but park tuples in staging, small batches bound latency but starve the
columnar plane.  For ingest-fed runs this controller replaces them
with a classic AIMD loop (the TCP congestion-control shape, which
Flink's buffer debloating and adaptive batching schemes also use)
against an explicit ``RuntimeConfig.latency_target_ms`` budget:

* the **signal** is the queue-residency latency of emitted batches
  (spend -> release time measured by the :class:`~.credits.CreditGate`),
  i.e. how long ingested data waits before the downstream operator
  takes it -- the component of end-to-end latency the ingest plane
  controls;
* while the observed p-high latency stays under budget, batch size
  grows **additively** (amortizing per-batch costs) and the flush
  interval relaxes toward its cap;
* one over-budget adjustment window **multiplicatively** halves both,
  so bursts drain quickly and the operating point oscillates just
  under the target.

The controller also steers the downstream device window engine for
ingest-fed graphs: `wiring.py` binds any directly-fed
``WinSeqTPULogic`` and the controller rewrites its
``max_batch_delay_ms`` launch bound to a fraction of the latency
budget, so the engine's launch cadence and the ingest batch cadence
track the same target instead of two hand-tuned constants.
"""
from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import List, Optional, Tuple

DEFAULT_MIN_BATCH = 1 << 10
DEFAULT_MAX_BATCH = 1 << 20
DEFAULT_FLUSH_MS = 5.0
MAX_FLUSH_MS = 100.0
# fraction of the latency budget granted to the engine's launch delay
ENGINE_DELAY_FRACTION = 0.25


class MicrobatchController:
    """AIMD on (coalesced batch size, flush interval) vs a latency
    target.  Thread-safe: samples arrive from the consumer thread
    (credit releases), decisions are read from the source/flusher
    thread."""

    def __init__(self, latency_target_ms: Optional[float] = None,
                 min_batch: int = DEFAULT_MIN_BATCH,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 initial_batch: Optional[int] = None,
                 adjust_interval_s: float = 0.1,
                 percentile: float = 0.95):
        self.latency_target_ms = latency_target_ms
        self.min_batch = max(1, min_batch)
        self.max_batch = max(self.min_batch, max_batch)
        self.batch_size = min(self.max_batch,
                              initial_batch or (self.min_batch * 4))
        self.flush_interval_ms = DEFAULT_FLUSH_MS
        self.adjust_interval_s = adjust_interval_s
        self.percentile = percentile
        self._lock = threading.Lock()
        self._samples: List[float] = []
        self._last_adjust = _time.monotonic()
        # additive step: a fraction of the span so convergence does not
        # depend on the absolute batch scale
        self._step = max(self.min_batch,
                         (self.max_batch - self.min_batch) // 32)
        # (monotonic time, batch_size) decision trace for the
        # monitoring JSON / web UI: a ROLLING window (maxlen), so a
        # long-running source keeps its most recent decisions instead
        # of freezing at the first 4096 (the old append-guard behaviour)
        self.trace: deque = deque([(_time.monotonic(), self.batch_size)],
                                  maxlen=4096)
        self.adjustments = 0

    # -- signal (called by CreditGate.release, consumer thread) --------
    def observe(self, latency_s: float) -> None:
        with self._lock:
            if len(self._samples) < 4096:
                self._samples.append(latency_s)
            now = _time.monotonic()
            if now - self._last_adjust >= self.adjust_interval_s:
                self._adjust_locked(now)

    def _adjust_locked(self, now: float) -> None:
        samples = self._samples
        if not samples:
            return
        self._samples = []
        self._last_adjust = now
        if self.latency_target_ms is None:
            return  # no budget: keep the static operating point
        samples.sort()
        p_high = samples[min(len(samples) - 1,
                             int(len(samples) * self.percentile))]
        target_s = self.latency_target_ms / 1e3
        if p_high > target_s:
            # multiplicative decrease: drain the backlog fast
            self.batch_size = max(self.min_batch, self.batch_size // 2)
            self.flush_interval_ms = max(0.5, self.flush_interval_ms / 2)
        else:
            # additive increase: feel for the throughput ceiling
            self.batch_size = min(self.max_batch,
                                  self.batch_size + self._step)
            self.flush_interval_ms = min(
                MAX_FLUSH_MS, self.latency_target_ms * 0.5,
                self.flush_interval_ms * 1.25)
        self.adjustments += 1
        self.trace.append((now, self.batch_size))

    # -- decisions (read by the source / flusher thread) ---------------
    def target_batch(self) -> int:
        return self.batch_size

    def set_max_batch(self, max_batch: int) -> None:
        """Pre-start rebudget (wiring mirrors a credit-gate resize here
        so a RuntimeConfig-sized budget also widens the AIMD ceiling)."""
        self.max_batch = max(self.min_batch, max_batch)
        self.batch_size = min(self.batch_size, self.max_batch)
        self._step = max(self.min_batch,
                         (self.max_batch - self.min_batch) // 32)

    def flush_deadline_s(self) -> float:
        return self.flush_interval_ms / 1e3

    # -- downstream engine steering (wiring.py) ------------------------
    def bind_engine(self, engine_logic) -> None:
        """Rewrite a directly-fed device window engine's static launch
        bound from the shared latency budget (ingest-fed runs only:
        graphs without an ingest source keep their configured knobs)."""
        if self.latency_target_ms is None:
            return
        delay = max(0.5, self.latency_target_ms * ENGINE_DELAY_FRACTION)
        engine_logic.max_batch_delay_ms = min(
            engine_logic.max_batch_delay_ms, delay)

    def trace_tail(self, n: int = 32) -> List[Tuple[float, int]]:
        with self._lock:
            return list(self.trace)[-n:]
