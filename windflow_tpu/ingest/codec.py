"""Wire codec for columnar tuple frames (the SocketSource protocol).

One frame carries one ``TupleBatch`` as a length-prefixed columnar
payload -- the network twin of the in-process struct-of-arrays
currency, so a decoded frame enters the batch plane zero-copy (each
column is a view over the receive buffer):

    [magic 'WFB1'][u32 payload_len] payload:
        [u16 n_cols] then per column:
            [u8 name_len][name utf-8][u8 dtype tag][u32 byte_len][raw LE]

Supported dtypes cover the control columns (int64) and the usual
payload columns; anything else must be mapped by the producer.  The
:class:`StreamDecoder` is incremental: feed it arbitrary byte chunks
off a non-blocking socket and it yields complete batches as they
frame up.
"""
from __future__ import annotations

import struct
from typing import List, Optional

import numpy as np

from ..core.tuples import TupleBatch

MAGIC = b"WFB1"
_HEADER = struct.Struct("<4sI")

_DTYPE_TAGS = {
    np.dtype("<i8"): 0, np.dtype("<f8"): 1,
    np.dtype("<i4"): 2, np.dtype("<f4"): 3,
}
_TAG_DTYPES = {v: k for k, v in _DTYPE_TAGS.items()}


def encode_batch(batch: TupleBatch) -> bytes:
    """One framed wire message for ``batch``."""
    parts = [struct.pack("<H", len(batch.cols))]
    for name, col in batch.cols.items():
        col = np.ascontiguousarray(col)
        if col.dtype not in _DTYPE_TAGS:
            # normalize exotic ints/floats instead of refusing the batch
            col = col.astype(np.float64 if col.dtype.kind == "f"
                             else np.int64)
        raw = col.tobytes()
        nb = name.encode("utf-8")
        if len(nb) > 255:
            raise ValueError(f"column name too long: {name!r}")
        parts.append(struct.pack("<B", len(nb)))
        parts.append(nb)
        parts.append(struct.pack("<BI", _DTYPE_TAGS[col.dtype], len(raw)))
        parts.append(raw)
    payload = b"".join(parts)
    return _HEADER.pack(MAGIC, len(payload)) + payload


def decode_batch(payload: bytes) -> TupleBatch:
    """Decode one frame payload (without the 8-byte header)."""
    view = memoryview(payload)
    (n_cols,) = struct.unpack_from("<H", view, 0)
    off = 2
    cols = {}
    for _ in range(n_cols):
        (name_len,) = struct.unpack_from("<B", view, off)
        off += 1
        name = bytes(view[off:off + name_len]).decode("utf-8")
        off += name_len
        tag, nbytes = struct.unpack_from("<BI", view, off)
        off += 5
        if tag not in _TAG_DTYPES:
            raise ValueError(f"unknown dtype tag {tag} in frame")
        cols[name] = np.frombuffer(view[off:off + nbytes],
                                   dtype=_TAG_DTYPES[tag])
        off += nbytes
    return TupleBatch(cols)


class StreamDecoder:
    """Incremental frame decoder over a byte stream."""

    def __init__(self, max_frame_bytes: int = 1 << 28):
        self._buf = bytearray()
        self.max_frame_bytes = max_frame_bytes
        self.frames_decoded = 0

    def feed(self, data: bytes) -> List[TupleBatch]:
        """Append received bytes; return every now-complete batch."""
        self._buf.extend(data)
        out: List[TupleBatch] = []
        while True:
            frame = self._next_frame()
            if frame is None:
                return out
            out.append(frame)

    def _next_frame(self) -> Optional[TupleBatch]:
        if len(self._buf) < _HEADER.size:
            return None
        magic, length = _HEADER.unpack_from(bytes(self._buf[:_HEADER.size]))
        if magic != MAGIC:
            raise ValueError(f"bad frame magic {magic!r} (stream desync)")
        if length > self.max_frame_bytes:
            raise ValueError(f"frame of {length} bytes exceeds the "
                             f"{self.max_frame_bytes} limit")
        end = _HEADER.size + length
        if len(self._buf) < end:
            return None
        # copy the payload out so decoded columns do not pin (or get
        # corrupted by) the growing receive buffer
        payload = bytes(self._buf[_HEADER.size:end])
        del self._buf[:end]
        self.frames_decoded += 1
        return decode_batch(payload)

    def pending_bytes(self) -> int:
        return len(self._buf)
