"""Deprecation shim: the wire codec moved to
:mod:`windflow_tpu.distributed.wire`.

The ingest plane's framed-TCP protocol and the inter-worker shuffle
transport (docs/DISTRIBUTED.md) share one codec; it lives with the
distributed plane now.  This module keeps the historical import path
(``windflow_tpu.ingest.codec``) working: the frozen legacy surface
(``encode_batch``/``decode_batch``/``StreamDecoder``/``MAGIC``)
re-exports silently -- existing callers must not start warning on a
pure code move -- while any NEW wire-layer name reached through this
path warns once per process, pointing the caller at the canonical
``windflow_tpu.distributed.wire`` home.
"""
from __future__ import annotations

import warnings

from ..distributed.wire import (  # noqa: F401  (re-exported surface)
    MAGIC, StreamDecoder, decode_batch, encode_batch,
)

_warned = False


def _warn_moved() -> None:
    global _warned
    if not _warned:
        _warned = True
        warnings.warn(
            "windflow_tpu.ingest.codec moved to "
            "windflow_tpu.distributed.wire; update imports "
            "(the old path keeps working for now)",
            DeprecationWarning, stacklevel=3)


def __getattr__(name):  # anything beyond the frozen legacy surface
    from ..distributed import wire as _wire
    if hasattr(_wire, name):
        _warn_moved()
        return getattr(_wire, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
