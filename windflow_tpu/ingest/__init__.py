"""Adaptive ingestion plane: the boundary between the outside world and
the graph's source nodes (docs/INGEST.md).

The reference treats sources as first-class operators whose only flow
control is blocking on a full bounded queue (source.hpp:175-252 over
FastFlow's FF_BOUNDED_BUFFER).  windflow_tpu's ingest plane makes
admission an explicit, measurable subsystem:

* **sources** (`sources.py`): a non-blocking TCP :class:`SocketSource`
  speaking the framed `codec` protocol, an :class:`AsyncGeneratorSource`
  driving an ``async`` generator, and a timestamp-faithful
  :class:`ReplaySource` with rate control (``speedup``), deterministic
  under a seed so it composes with the resilience ``FaultPlan`` harness;
* **credit-based backpressure** (`credits.py`): each source replica
  holds a :class:`CreditGate` budget replenished as the downstream
  channel drains -- replacing silent blocking with measurable flow
  control (the Flink credit-based flow-control analogue);
* an **adaptive microbatch controller** (`controller.py`): AIMD on
  coalesced batch size / flush interval against
  ``RuntimeConfig.latency_target_ms``, replacing the static
  ``microbatch`` / launch-delay knobs for ingest-fed runs;
* **admission control** (`admission.py`): overload policies
  (``drop_newest`` / ``drop_oldest`` / ``sample``) that quarantine shed
  tuples into the graph ``DeadLetterStore`` instead of buffering
  without bound.

Wiring happens at ``PipeGraph.start`` (`wiring.py`): outlet channels
are wrapped so consumer ``get``s return credits, gates and stages are
registered with the graph CancelToken (cancellation unblocks a source
mid-recv), and the controller binds to downstream device window
engines.
"""
from .admission import (ADMISSION_POLICIES, AdmissionConfig, ShedTuples)
# codec promoted to the shared wire module (distributed/wire.py); the
# names stay re-exported here for the historical surface
from ..distributed.wire import StreamDecoder, decode_batch, encode_batch
from .controller import MicrobatchController
from .credits import CreditGate, CreditedChannel
from .feed import FeedSource, ParallelColumnFeeder
from .sources import (AsyncGeneratorSource, IngestSourceLogic, ReplaySource,
                      SocketSource)

__all__ = [
    "ADMISSION_POLICIES", "AdmissionConfig", "ShedTuples",
    "StreamDecoder", "decode_batch", "encode_batch",
    "MicrobatchController", "CreditGate", "CreditedChannel",
    "FeedSource", "ParallelColumnFeeder",
    "AsyncGeneratorSource", "IngestSourceLogic", "ReplaySource",
    "SocketSource",
]
