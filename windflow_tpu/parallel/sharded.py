"""Multi-chip sharded window aggregation: the distributed execution plane.

This is the TPU-native replacement for scaling strategies the reference
implements as thread farms (SURVEY.md §2.4), mapped onto a
('key', 'win') mesh:

* **Key_Farm / Key_FFAT across chips** (BASELINE config #4): per-key
  series and window state are sharded over the 'key' axis; each shard
  runs the same batched window program locally; no cross-chip traffic
  in steady state (keys are independent) -- like data parallelism.
* **Win_MapReduce across chips** (BASELINE config #5): each window's
  tuples are striped over the 'win' axis; every chip computes a stripe
  partial and the window result is a ``psum`` over 'win' riding ICI --
  like tensor/sequence parallelism.
* **Pane_Farm across chips** (BASELINE config #3): chips hold
  consecutive time-chunks; pane partials are computed locally and
  window combines read neighbour panes via ``all_gather`` over 'win' --
  the two-level blockwise reduction.

Everything is expressed with ``shard_map`` over a Mesh so XLA lowers the
collectives; the host runtime feeds per-shard batches (one WinSeqTPU
replica per shard keeps the batching protocol unchanged).
"""
from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np


@functools.lru_cache(maxsize=None)
def _sharded_programs(mesh_id: int, win_len: int, slide_len: int):
    """Build the jitted multi-chip streaming step for a given mesh.

    Returns ``step(values, starts, ends, stripe_values, pane_values)``
    computing, in one compiled program:
      1. key-sharded sliding-window sums     [K_shards, B]    (KF path)
      2. psum-combined striped window sums   [B2]             (WMR path)
      3. pane partials + gathered window combine              (PF path)
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def shard_map(f, mesh, in_specs, out_specs):
        # check_vma off: outputs replicated via collectives (all_gather/
        # psum) that the static replication checker cannot always infer
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)

    mesh = _MESHES[mesh_id]

    def kf_shard(values, starts, ends):
        # [1, T] values, [1, B] extents on this shard
        c = jnp.concatenate([jnp.zeros((1, 1), values.dtype),
                             jnp.cumsum(values, axis=1)], axis=1)
        return jnp.take_along_axis(c, ends, axis=1) - \
            jnp.take_along_axis(c, starts, axis=1)

    def wmr_shard(stripe):
        # [K_loc, 1, B2, W_stripe]: sum own stripe then psum over 'win'
        partial = jnp.sum(stripe, axis=-1)
        return jax.lax.psum(partial, "win")

    def pf_shard(pane_vals):
        # [K_loc, 1, P_loc, pane]: local pane partials (PLQ), then the
        # full pane timeline via all_gather over 'win' (WLQ input)
        partials = jnp.sum(pane_vals, axis=-1)          # [K_loc, 1, P_loc]
        allp = jax.lax.all_gather(partials, "win", axis=1, tiled=True)
        return allp.reshape(allp.shape[0], -1)           # [K_loc, P_tot]

    kf = shard_map(kf_shard, mesh=mesh,
                   in_specs=(P("key", None), P("key", None), P("key", None)),
                   out_specs=P("key", None))

    wmr = shard_map(wmr_shard, mesh=mesh,
                    in_specs=(P("key", "win", None, None),),
                    out_specs=P("key", None, None))

    pf = shard_map(pf_shard, mesh=mesh,
                   in_specs=(P("key", "win", None, None),),
                   out_specs=P("key", None))

    @jax.jit
    def step(values, starts, ends, stripe_values, pane_values):
        kf_out = kf(values, starts, ends)
        wmr_out = wmr(stripe_values)
        pane_partials = pf(pane_values)
        # WLQ: combine panes into sliding windows on the gathered axis
        pane_len = pane_values.shape[-1]
        wpp = max(1, win_len // pane_len)   # panes per window
        spp = max(1, slide_len // pane_len)  # panes per slide
        n_windows = max(1, (pane_partials.shape[1] - wpp) // spp + 1)
        idx = (jnp.arange(n_windows)[:, None] * spp
               + jnp.arange(wpp)[None, :])
        pf_out = jnp.sum(pane_partials[:, idx], axis=-1)
        return kf_out, wmr_out, pf_out

    return step


_MESHES: Dict[int, Any] = {}


def pairwise_fold(x, combine, neutral, xp):
    """Log-depth pairwise combine tree along the LAST axis (associative
    by the FFAT contract).  ``xp`` is numpy for the host PLQ or
    jax.numpy inside a traced program -- one implementation serves both
    halves of the __host__ __device__ combine contract."""
    while x.shape[-1] > 1:
        if x.shape[-1] % 2:
            pad = xp.full(x.shape[:-1] + (1,), neutral, x.dtype)
            x = xp.concatenate([x, pad], axis=-1)
        x = xp.asarray(combine(x[..., 0::2], x[..., 1::2]))
    return x[..., 0]


def _resolve_kind(kind):
    """Normalize a mesh combine spec to (name, combine, neutral, lift).

    ``kind`` is a builtin name ('sum'/'count'/'mean'/'max'/'min') or an
    FFAT spec -- either the single-chip 3-tuple ('ffat', combine,
    neutral) that farms_tpu._ffat_kind produces (lift rides separately
    there) or the mesh 4-tuple ('ffat', lift, combine, neutral) with a
    columnar lift.  The combine must work on numpy scalars AND jnp
    arrays -- the mesh twin of the reference's __host__ __device__
    combine contract (flatfat_gpu.hpp:68-82)."""
    if isinstance(kind, tuple) and kind and kind[0] == "ffat":
        if len(kind) == 4:
            _, lift, combine, neutral = kind
        elif len(kind) == 3:
            lift, (_, combine, neutral) = None, kind
        else:
            raise ValueError(
                "FFAT mesh kind must be ('ffat', combine, neutral) or "
                "('ffat', lift, combine, neutral)")
        return "ffat", combine, float(neutral), lift
    if kind == "max":
        import jax.numpy as jnp
        return "max", jnp.maximum, float("-inf"), None
    if kind == "min":
        import jax.numpy as jnp
        return "min", jnp.minimum, float("inf"), None
    if kind in ("sum", "count", "mean"):
        return kind, None, 0.0, None
    raise ValueError(f"unknown mesh window kind: {kind!r}")


class ShardedWindowEngine:
    """Key-sharded multi-chip window engine (the distributed twin of
    WindowComputeEngine).  Holds the mesh; each call runs the full
    sharded step (KF + WMR + PF paths) as one XLA program with
    collectives over ICI.

    ``kind`` selects the combine (see _resolve_kind): invertible kinds
    run prefix-scan differencing per shard; max/min and FFAT
    lift+combine build a per-shard device FlatFAT and answer every
    extent with a range query (the key_farm_gpu.hpp arbitrary-functor
    surface at mesh scale)."""

    def __init__(self, mesh, win_len: int, slide_len: int, kind="sum"):
        self.mesh = mesh
        self.win_len = win_len
        self.slide_len = slide_len
        self.kind, self.combine, self.neutral, self.lift = \
            _resolve_kind(kind)
        mesh_id = id(mesh)
        _MESHES[mesh_id] = mesh
        self._step = _sharded_programs(mesh_id, win_len, slide_len)

    @property
    def n_key_shards(self) -> int:
        return self.mesh.shape["key"]

    @property
    def n_win_shards(self) -> int:
        return self.mesh.shape["win"]

    def step(self, values, starts, ends, stripe_values, pane_values):
        """One sharded streaming step; see _sharded_programs."""
        return self._step(values, starts, ends, stripe_values, pane_values)

    def compute_pf_ring(self, pane_values, pane_len: int):
        """Ring sequence-parallel pane combine: the ppermute alternative
        to the all_gather PF path for long timelines.

        The pane timeline is sharded in consecutive chunks over 'win'
        (chip w holds panes [w*P_loc, (w+1)*P_loc)).  Sliding windows
        starting in a chip's chunk need at most ``wpp - 1`` panes from
        its right neighbours, fetched with ``hops`` one-step neighbour
        ``ppermute``s -- O(hops * P_loc) ICI traffic per chip instead of
        the all_gather's O(P_total), the ring-attention communication
        pattern applied to the window axis.  Windows overrunning the
        global timeline end are masked to the combine's neutral (0).

        pane_values: [K, W_shards * P_loc, pane_len] sharded
        ('key', 'win') on axis 0/1.  Returns [K, W_shards * P_loc // spp]
        window sums, 'key'-sharded, windows in global time order.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        wpp = max(1, self.win_len // pane_len)    # panes per window
        spp = max(1, self.slide_len // pane_len)  # panes per slide
        W = self.n_win_shards
        p_total = pane_values.shape[1]
        p_loc = p_total // W
        if p_loc % spp:
            raise ValueError(
                f"panes per shard ({p_loc}) must be a multiple of the "
                f"slide ({spp} panes) for the ring layout")
        hops = min(W - 1, -(-(wpp - 1) // p_loc))  # ceil, capped at ring
        n_loc_wins = p_loc // spp

        if self.kind == "mean":
            raise ValueError("PaneFarmMesh does not support 'mean' "
                             "(pane partials are not mean-decomposable "
                             "without a count channel)")
        key = (id(self.mesh), wpp, spp, W, p_loc, pane_len, self.kind)
        if getattr(self, "_ring_key", None) != key:
            perm = [(i, (i - 1) % W) for i in range(W)]
            kind, comb = self.kind, self.combine

            neutral = self.neutral

            def fold(x, axis):
                # combine-fold along one axis: one-op reductions for the
                # builtins; a log-depth pairwise tree for a custom FFAT
                # combine (associative by contract) so a wide window
                # extent costs O(log w) HLO ops, not a serial chain
                if kind in ("sum", "count"):
                    return jnp.sum(x, axis=axis)
                if kind == "max":
                    return jnp.max(x, axis=axis)
                if kind == "min":
                    return jnp.min(x, axis=axis)
                return pairwise_fold(jnp.moveaxis(x, axis, -1), comb,
                                     neutral, jnp)

            def ring_shard(pane_vals):
                # [K, P_loc, pane_len] per shard
                partials = fold(pane_vals, -1)             # [K, P_loc]
                blocks = [partials]
                cur = partials
                for _ in range(hops):
                    # chip w receives chip (w+1)'s block: one ring hop
                    cur = jax.lax.ppermute(cur, "win", perm)
                    blocks.append(cur)
                ext = jnp.concatenate(blocks, axis=-1)
                starts_l = jnp.arange(n_loc_wins) * spp
                idx = starts_l[:, None] + jnp.arange(wpp)[None, :]
                # clamp only protects windows masked below (for every
                # valid window g_start + wpp <= p_total implies the
                # extent fits inside ext)
                idx = jnp.minimum(idx, ext.shape[-1] - 1)
                wins = fold(ext[:, idx], -1)               # [K, n_loc]
                # mask windows whose extent passes the global end (their
                # ring reads wrapped around to chip 0)
                w_id = jax.lax.axis_index("win")
                g_start = w_id * p_loc + starts_l
                ok = g_start + wpp <= p_total
                return jnp.where(ok[None, :], wins, 0.0)

            self._ring = jax.jit(jax.shard_map(
                ring_shard, mesh=self.mesh,
                in_specs=(P("key", "win", None),),
                out_specs=P("key", "win"), check_vma=False))
            self._ring_key = key
        sh = NamedSharding(self.mesh, P("key", "win", None))
        return self._ring(jax.device_put(pane_values, sh))

    def compute_wmr(self, stripes):
        """Striped window combines over 'win' (the Win_MapReduce
        distribution as a standalone program, used by
        operators.tpu.wmr_mesh.WinMapReduceMesh).

        ``stripes``: [K_rows, W_shards, B, stripe_len] — window b of row
        k holds its tuples round-robin striped over the 'win' axis
        (WinMap_Emitter's per-key round robin, wm_nodes.hpp:62), padded
        with the combine's neutral.  Each chip folds its stripe locally
        (the MAP stage); the cross-stripe REDUCE rides ICI as a psum /
        pmax / pmin for the builtins, or an all_gather + log-depth
        pairwise combine for a custom FFAT fold.  Returns [K_rows, B]
        full window results."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.kind == "mean":
            raise ValueError("WinMapReduceMesh does not support 'mean' "
                             "(stripe partials carry no count channel)")
        if not hasattr(self, "_wmr_only"):
            import jax.numpy as jnp
            kind, comb, neutral = self.kind, self.combine, self.neutral

            def wmr_shard(stripe):
                # [K_loc, 1, B, stripe_len] on this chip
                if kind in ("sum", "count"):
                    return jax.lax.psum(jnp.sum(stripe, axis=-1), "win")
                if kind == "max":
                    return jax.lax.pmax(jnp.max(stripe, axis=-1), "win")
                if kind == "min":
                    return jax.lax.pmin(jnp.min(stripe, axis=-1), "win")
                partial = pairwise_fold(stripe, comb, neutral, jnp)
                allp = jax.lax.all_gather(partial, "win", axis=1,
                                          tiled=True)     # [K_loc, W, B]
                out = pairwise_fold(jnp.moveaxis(allp, 1, -1), comb,
                                    neutral, jnp)          # [K_loc, B]
                return out[:, None, :]

            self._wmr_only = jax.jit(jax.shard_map(
                wmr_shard, mesh=self.mesh,
                in_specs=(P("key", "win", None, None),),
                out_specs=P("key", None, None), check_vma=False))
        sh = NamedSharding(self.mesh, P("key", "win", None, None))
        out = self._wmr_only(jax.device_put(stripes, sh))
        return out[:, 0, :]

    def compute_kf(self, values, starts, ends):
        """Key-sharded window combines (the Key_Farm-across-chips path
        used by operators.tpu.mesh_farm).  ``values`` is [K_shards, T]
        (T a power of two), extents are [K_shards, B]; everything
        sharded over 'key'."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if not hasattr(self, "_kf_only"):
            import jax.numpy as jnp
            kind, comb, neutral = self.kind, self.combine, self.neutral

            def kf_shard(v, s, e):
                if kind == "count":
                    return (e - s).astype(v.dtype)
                if kind in ("sum", "mean"):
                    c = jnp.concatenate([jnp.zeros((1, 1), v.dtype),
                                         jnp.cumsum(v, axis=1)], axis=1)
                    out = jnp.take_along_axis(c, e, axis=1) - \
                        jnp.take_along_axis(c, s, axis=1)
                    if kind == "mean":
                        out = out / jnp.maximum(e - s, 1)
                    return out
                # max/min/ffat: per-row device FlatFAT + range queries
                from ..ops.flatfat_jax import _programs
                build, _upd, query = _programs(comb, neutral, v.shape[1])

                def one(row, ss, ee):
                    return query(build(row), ss, ee, ee > ss)

                out = jax.vmap(one)(v, s, e)
                return jnp.where(e > s, out, 0)

            self._kf_only = jax.jit(jax.shard_map(
                kf_shard, mesh=self.mesh,
                in_specs=(P("key", None), P("key", None), P("key", None)),
                out_specs=P("key", None), check_vma=False))
        sh = NamedSharding(self.mesh, P("key", None))
        return self._kf_only(jax.device_put(values, sh),
                             jax.device_put(starts, sh),
                             jax.device_put(ends, sh))

    def example_inputs(self, T: int = 64, B: int = 8, keys_per_shard: int = 2,
                       stripe_w: int = 8, panes_per_shard: int = 4,
                       pane_len: int = 4):
        """Tiny correctly-sharded inputs for compile checks/dry runs."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        K = self.n_key_shards
        W = self.n_win_shards
        rng = np.random.default_rng(0)
        values = rng.normal(size=(K, T)).astype(np.float32)
        starts = np.tile(np.arange(B, dtype=np.int32) * 4, (K, 1))
        ends = starts + np.int32(self.win_len)
        stripe = rng.normal(
            size=(K * keys_per_shard, W, B, stripe_w)).astype(np.float32)
        pane = rng.normal(
            size=(K * keys_per_shard, W, panes_per_shard,
                  pane_len)).astype(np.float32)
        dev = lambda x, spec: jax.device_put(
            x, NamedSharding(self.mesh, spec))
        return (dev(values, P("key", None)),
                dev(starts, P("key", None)),
                dev(ends, P("key", None)),
                dev(stripe, P("key", "win", None, None)),
                dev(pane, P("key", "win", None, None)))
