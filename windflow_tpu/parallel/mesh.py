"""Device-mesh helpers for multi-chip execution.

The reference has no network backend at all -- its fabric is FastFlow
queues in one process (SURVEY.md §5 last bullet).  windflow_tpu scales
past one chip the TPU way: a ``jax.sharding.Mesh`` with named axes,
shardings annotated per array, and XLA inserting the collectives over
ICI/DCN.  Axis conventions used throughout:

* ``key``  -- key-shard axis: per-key window state is sharded by key
  hash (the Key_Farm / Key_FFAT distribution, ≈ data parallelism);
* ``win``  -- intra-window axis: one window's tuples are striped and
  partials psum-combined (the Win_MapReduce distribution, ≈
  tensor/sequence parallelism).
"""
from __future__ import annotations

from typing import Optional, Tuple


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Tuple[str, str] = ("key", "win"),
              win_axis: int = 1):
    """Build a 2-D ('key', 'win') mesh over the available devices.

    ``win_axis`` chips cooperate on each window (psum over 'win'); the
    remaining devices shard the key space.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if n % win_axis != 0:
        raise ValueError(f"{n} devices not divisible by win_axis={win_axis}")
    arr = np.array(devices).reshape(n // win_axis, win_axis)
    return Mesh(arr, axis_names)


def make_multihost_mesh(win_axis: int = 1,
                        axis_names: Tuple[str, str] = ("key", "win")):
    """Multi-host ('key', 'win') mesh with DCN/ICI-aware layout.

    Keys are independent sub-streams (no steady-state cross-key
    traffic), so the 'key' axis is laid across hosts -- its rare
    collectives may ride DCN.  The 'win' axis carries the psum /
    all_gather / ppermute combines of WMR / PF / ring paths, so it is
    kept inside one host's slice where the collectives ride ICI
    (the scaling-book rule: bandwidth-hungry axes on ICI, between-host
    axes on DCN).

    Single-process runs fall back to ``make_mesh`` over local devices.
    Multi-host runs require ``jax.distributed.initialize()`` first (one
    process per host, standard JAX multi-host bootstrap).
    """
    import jax

    n_procs = jax.process_count()
    if n_procs == 1:
        return make_mesh(win_axis=win_axis, axis_names=axis_names)
    import numpy as np
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    local = jax.local_device_count()
    if local % win_axis != 0:
        raise ValueError(
            f"{local} local devices not divisible by win_axis={win_axis}")
    n_slices = len({getattr(d, "slice_index", None)
                    for d in jax.devices()})
    if n_slices == n_procs:
        # hybrid mesh: first axis split across hosts (DCN), second
        # within (ICI); axis order matches (key, win).  Genuine
        # topology errors propagate -- only the no-slice-topology case
        # below uses the process-grouped layout.
        dev_mesh = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(local // win_axis, win_axis),
            dcn_mesh_shape=(n_procs, 1),
        )
    else:
        # no per-process slice topology exposed (e.g. the forced-host-
        # platform CPU backend of the 2-process DCN exercise): group
        # devices by process so every 'win' row stays inside one
        # process -- the same locality the hybrid mesh provides
        devs = sorted(jax.devices(),
                      key=lambda d: (d.process_index, d.id))
        dev_mesh = np.array(devs).reshape(-1, win_axis)
    return Mesh(dev_mesh, axis_names)


def key_sharding(mesh, rank: int = 1):
    """NamedSharding placing axis 0 on 'key' (per-key state layout)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P("key", *([None] * (rank - 1)))
    return NamedSharding(mesh, spec)
