"""windflow_tpu: a TPU-native data-stream-processing framework.

Brand-new design with the capabilities of the reference C++/CUDA
library (see SURVEY.md): PipeGraph/MultiPipe graphs of streaming
operators -- map/filter/flatmap/accumulate/sink plus the full family of
parallel sliding-window operators (Win_Seq, Win_Farm, Key_Farm,
Pane_Farm, Win_MapReduce, FlatFAT-based FFAT variants) -- where batched
window computation lowers to XLA/Pallas programs and multi-chip scaling
uses jax.sharding over a TPU mesh instead of CUDA kernels.

Public surface (umbrella import, the analogue of windflow.hpp:33-50 /
windflow_gpu.hpp:34-42):

    import windflow_tpu as wf
    g = wf.PipeGraph("app", wf.Mode.DEFAULT)
    src = wf.SourceBuilder(gen).with_parallelism(2).build()
    ...
"""
from .core import (Mode, WinType, OptLevel, RoutingMode, Pattern, WinEvent,
                   OrderingMode, Role, WinOperatorConfig, RuntimeConfig,
                   DurabilityConfig, ElasticSpec, BasicRecord, TupleBatch,
                   EOS, TriggererCB,
                   TriggererTB, Window, StreamArchive, FlatFAT, Iterable,
                   Shipper, RuntimeContext, LocalStorage, Expr, F)

__version__ = "0.1.0"

# Graph / operator / builder layers are imported lazily below as they are
# built up; keeping this umbrella import cheap (no jax import at package
# import time -- device code loads on first use).


def __getattr__(name):
    # Lazy exports: graph + builders (host plane) and TPU builders.
    from importlib import import_module
    lazy = {
        "PipeGraph": "windflow_tpu.graph.pipegraph",
        "NodeFailureError": "windflow_tpu.graph.pipegraph",
        "MultiPipe": "windflow_tpu.graph.multipipe",
        # failure containment (resilience/; docs/RESILIENCE.md)
        "StallError": "windflow_tpu.resilience",
        "GraphCancelled": "windflow_tpu.resilience",
        "FaultPlan": "windflow_tpu.resilience",
        "InjectedFailure": "windflow_tpu.resilience",
        "DeadLetterStore": "windflow_tpu.resilience",
        "DeadLetterEntry": "windflow_tpu.resilience",
        # adaptive ingestion plane (ingest/; docs/INGEST.md)
        "SocketSource": "windflow_tpu.ingest",
        "ReplaySource": "windflow_tpu.ingest",
        "AsyncGeneratorSource": "windflow_tpu.ingest",
        "CreditGate": "windflow_tpu.ingest",
        "MicrobatchController": "windflow_tpu.ingest",
        "AdmissionConfig": "windflow_tpu.ingest",
        "ShedTuples": "windflow_tpu.ingest",
        "encode_batch": "windflow_tpu.ingest",
        "decode_batch": "windflow_tpu.ingest",
        "StreamDecoder": "windflow_tpu.ingest",
        # audit plane (audit/; docs/OBSERVABILITY.md "Audit plane")
        "GraphAuditor": "windflow_tpu.audit",
        "SpaceSavingSketch": "windflow_tpu.audit",
        # diagnosis plane (diagnosis/; docs/OBSERVABILITY.md
        # "Diagnosis plane")
        "DiagnosisPlane": "windflow_tpu.diagnosis",
        "build_report": "windflow_tpu.diagnosis",
        "render_text": "windflow_tpu.diagnosis",
        # elastic scaling plane (elastic/; docs/ELASTIC.md)
        "ElasticityConfig": "windflow_tpu.elastic",
        "ElasticController": "windflow_tpu.elastic",
        "RescaleEvent": "windflow_tpu.elastic",
        "RescaleError": "windflow_tpu.elastic",
        "LoadReport": "windflow_tpu.elastic",
        # distributed runtime plane (distributed/; docs/DISTRIBUTED.md)
        "DistributedSpec": "windflow_tpu.distributed",
        "run_distributed": "windflow_tpu.distributed",
        "WorkerFailure": "windflow_tpu.distributed",
        "plan_partition": "windflow_tpu.distributed",
        "merge_stats": "windflow_tpu.distributed",
        "wire_table": "windflow_tpu.distributed",
        "check_wire_conservation": "windflow_tpu.distributed",
        "MsgDecoder": "windflow_tpu.distributed",
        # multi-tenant serving plane (serving/; docs/SERVING.md)
        "Server": "windflow_tpu.serving",
        "TenantSpec": "windflow_tpu.serving",
        "TenantHandle": "windflow_tpu.serving",
        "TenantState": "windflow_tpu.serving",
        "AdmissionError": "windflow_tpu.serving",
        "ArbiterConfig": "windflow_tpu.serving",
        "CrossTenantArbiter": "windflow_tpu.serving",
        # durability plane (durability/; docs/RESILIENCE.md
        # "Exactly-once epochs")
        "EpochCoordinator": "windflow_tpu.durability",
        "EpochStore": "windflow_tpu.durability",
        "EpochBarrier": "windflow_tpu.durability",
        "EpochTaggedStore": "windflow_tpu.durability",
        "run_with_epochs": "windflow_tpu.durability",
        "restore_epoch": "windflow_tpu.durability",
        # event-time relational plane (eventtime/; docs/EVENTTIME.md)
        "Watermark": "windflow_tpu.eventtime",
        "watermarked": "windflow_tpu.eventtime",
        "WatermarkedSource": "windflow_tpu.eventtime",
        "watermark_of": "windflow_tpu.audit.progress",
        "EventTimeWindow": "windflow_tpu.eventtime",
        "SessionWindow": "windflow_tpu.eventtime",
        "IntervalJoin": "windflow_tpu.eventtime",
        "WindowJoin": "windflow_tpu.eventtime",
        "Sided": "windflow_tpu.eventtime",
        "side_tagger": "windflow_tpu.eventtime",
        "tag_side": "windflow_tpu.eventtime",
        "LEFT": "windflow_tpu.eventtime",
        "RIGHT": "windflow_tpu.eventtime",
        "StreamQuery": "windflow_tpu.eventtime",
        "query": "windflow_tpu.eventtime",
        # mesh-scale operators + mesh construction (multi-chip plane)
        "KeyFarmMesh": "windflow_tpu.operators.tpu.mesh_farm",
        "PaneFarmMesh": "windflow_tpu.operators.tpu.pane_mesh",
        "WinMapReduceMesh": "windflow_tpu.operators.tpu.wmr_mesh",
        "WinSeqFFATResident": "windflow_tpu.operators.tpu.ffat_resident",
        "make_mesh": "windflow_tpu.parallel.mesh",
        "make_multihost_mesh": "windflow_tpu.parallel.mesh",
    }
    builder_names = (
        "SourceBuilder", "FilterBuilder", "MapBuilder", "FlatMapBuilder",
        "AccumulatorBuilder", "SinkBuilder", "WinSeqBuilder",
        "WinFarmBuilder", "KeyFarmBuilder", "PaneFarmBuilder",
        "WinMapReduceBuilder", "WinSeqFFATBuilder", "KeyFFATBuilder",
    )
    tpu_builder_names = (
        "WinSeqTPUBuilder", "WinFarmTPUBuilder", "KeyFarmTPUBuilder",
        "PaneFarmTPUBuilder", "WinMapReduceTPUBuilder",
        "WinSeqFFATTPUBuilder", "KeyFFATTPUBuilder",
    )
    if name in lazy:
        return getattr(import_module(lazy[name]), name)
    if name in builder_names:
        return getattr(import_module("windflow_tpu.builders.builders"), name)
    if name in tpu_builder_names:
        return getattr(import_module("windflow_tpu.builders.builders_tpu"), name)
    raise AttributeError(f"module 'windflow_tpu' has no attribute {name!r}")
