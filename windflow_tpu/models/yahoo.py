"""Yahoo Streaming Benchmark: the flagship application/model.

The reference's BASELINE config #5 is the "Yahoo Streaming Benchmark
(ad-campaign windowed join+count)" style workload running on its GPU
window operators (tests/mp_tests_gpu fixtures).  This module provides
the same application twice:

1. ``build_pipeline`` -- the full framework graph on the columnar
   plane: BatchSource (ad events) -> BatchFilter (views only) ->
   BatchMap (ad -> campaign join) -> KeyFarmTPU (windowed count per
   campaign) -> sink.

2. ``make_step`` -- the flagship *compiled step*: one jitted XLA
   program computing per-campaign windowed counts for a batch of
   events (the single-chip forward step exported by __graft_entry__).
"""
from __future__ import annotations

import functools

import numpy as np

VIEW, CLICK, PURCHASE = 0, 1, 2


def synth_events(n_events: int, n_ads: int, seed: int = 0,
                 ts_start: int = 0):
    """Columnar synthetic ad-event stream: (ad_id, event_type, ts)."""
    rng = np.random.default_rng(seed)
    return {
        "ad_id": rng.integers(0, n_ads, n_events, dtype=np.int64),
        "event_type": rng.integers(0, 3, n_events, dtype=np.int64),
        "ts": ts_start + np.arange(n_events, dtype=np.int64),
    }


def make_campaign_map(n_ads: int, n_campaigns: int,
                      seed: int = 1) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, n_campaigns, n_ads, dtype=np.int64)


def build_pipeline(graph, n_events: int, n_ads: int = 1000,
                   n_campaigns: int = 100, win_len: int = 10_000,
                   slide_len: int = 10_000, batch_size: int = 65536,
                   device_batch: int = 4096, sink=None,
                   source_parallelism: int = 1, key_parallelism: int = 1,
                   placement: str = "device"):
    """Wire the Yahoo app into ``graph``; returns the campaign map."""
    import windflow_tpu as wf
    from ..core.tuples import TupleBatch
    from ..operators.batch_ops import BatchFilter, BatchMap, BatchSource
    from ..operators.tpu.farms_tpu import KeyFarmTPU

    campaign_of_ad = make_campaign_map(n_ads, n_campaigns)
    # pre-generated event pool, re-timestamped per batch: the metric is
    # pipeline throughput, not host RNG throughput (mp_tests sources
    # pre-fill their input vectors the same way)
    pool = synth_events(batch_size, n_ads, seed=0)
    ones = np.ones(batch_size, np.float64)
    state = {}  # per-replica batch cursors (replicas share this closure)

    def source(ctx):
        # replica r emits every par-th BATCH of the global timeline:
        # timestamps stay globally increasing with disorder bounded by
        # ~par batches (DETERMINISTIC mode makes multi-replica runs
        # exact; disjoint per-replica ts ranges would instead interleave
        # epoch-apart timestamps into the TB windows)
        ridx = ctx.get_replica_index()
        st = state.setdefault(ridx, {"b": ridx})
        base = st["b"] * batch_size
        if base >= n_events:
            return None
        n = min(batch_size, n_events - base)
        ts = base + pool["ts"][:n]
        st["b"] += max(1, source_parallelism)
        return TupleBatch({
            "key": pool["ad_id"][:n], "id": ts, "ts": ts,
            "value": ones[:n],
            "event_type": pool["event_type"][:n],
        })

    def views_only(batch):
        return batch["event_type"] == VIEW

    def join_campaign(batch):
        return batch.with_cols(key=campaign_of_ad[batch.key])

    counter = KeyFarmTPU(
        "count", win_len, slide_len, wf.WinType.TB,
        parallelism=key_parallelism, batch_len=device_batch,
        name="campaign_count", emit_batches=True, placement=placement)
    pipe = graph.add_source(BatchSource(source, source_parallelism))
    pipe.chain(BatchFilter(views_only)) \
        .chain(BatchMap(join_campaign)) \
        .add(counter)
    if sink is not None:
        from ..operators.basic_ops import Sink
        pipe.add_sink(Sink(sink, name="count_sink"))
    return campaign_of_ad


@functools.lru_cache(maxsize=None)
def make_step(n_campaigns: int, n_windows: int, win_len: int):
    """Jittable forward step: batch of events -> per-campaign windowed
    view counts [n_campaigns, n_windows].

    TPU shape notes: one scatter-add over a [C * W] accumulator --
    static shapes, no data-dependent control flow; XLA fuses the
    filter/join/gather chain.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(campaign_of_ad, ad_id, event_type, ts, counts):
        campaign = campaign_of_ad[ad_id]
        win = jnp.clip(ts // win_len, 0, n_windows - 1)
        is_view = (event_type == VIEW).astype(counts.dtype)
        flat_idx = campaign * n_windows + win
        counts = counts.reshape(-1).at[flat_idx].add(is_view)
        return counts.reshape(n_campaigns, n_windows)

    return step


def example_step_args(n_events: int = 4096, n_ads: int = 1000,
                      n_campaigns: int = 100, n_windows: int = 8,
                      win_len: int = 1024):
    ev = synth_events(n_events, n_ads)
    campaign_of_ad = make_campaign_map(n_ads, n_campaigns)
    counts = np.zeros((n_campaigns, n_windows), np.float32)
    return (campaign_of_ad, ev["ad_id"], ev["event_type"],
            ev["ts"] % (n_windows * win_len), counts)
