"""NEXMark-style query set: the second benchmark application family.

The reference ships its workloads as self-checking test pipelines
(tests/mp_tests_*); the NEXMark auction queries are the streaming
community's standard benchmark shapes, expressed here on the columnar
plane with the device window operators:

* Q1 currency conversion -- stateless BatchMap (price * rate)
* Q2 selection           -- stateless BatchFilter (auction id set)
* Q5 hot items           -- per-auction sliding-window bid counts,
                            KeyFarmTPU 'count' (key_farm_gpu.hpp shape)
* Q7 highest bid         -- global per-window maximum price,
                            WinSeqTPU 'max' (win_seq_gpu.hpp shape)

With the event-time relational plane (eventtime/; docs/EVENTTIME.md)
the remaining relational queries complete the set, each with a numpy
oracle (``qN_oracle``) that doubles as the eager baseline twin for the
bench gate:

* Q3 local item suggestion -- persons |><| auctions on seller
                              (incremental full-history IntervalJoin)
* Q4 average price per category -- auctions |><| bids per window,
                              closing price = per-auction max, averaged
                              per category (WindowJoin + window agg)
* Q6 average selling price per seller -- same join, averaged per seller
* Q8 monitor new users -- persons |><| auctions-by-seller per window
                              (who registered AND sold in the window)

Synthetic bid stream: (auction, bidder, price, ts), ts dense; persons
and auctions streams carry dense event times over the same axis.
"""
from __future__ import annotations

import numpy as np

DOL_TO_EUR = 0.9


def synth_bids(n_bids: int, n_auctions: int = 1000, seed: int = 7,
               ts_start: int = 0):
    """Columnar synthetic bid stream (NEXMark generator analogue)."""
    rng = np.random.default_rng(seed)
    return {
        "auction": rng.integers(0, n_auctions, n_bids, dtype=np.int64),
        "bidder": rng.integers(0, 10_000, n_bids, dtype=np.int64),
        "price": rng.integers(1, 10_000, n_bids).astype(np.float64),
        "ts": ts_start + np.arange(n_bids, dtype=np.int64),
    }


def bid_batches(n_bids: int, batch_size: int = 65_536,
                n_auctions: int = 1000, seed: int = 7):
    """BatchSource body emitting the synthetic bid stream as
    TupleBatches keyed by auction (price in the value column)."""
    from ..core.tuples import TupleBatch

    pool = synth_bids(batch_size, n_auctions, seed)
    state = {"sent": 0}

    def source(ctx):
        i = state["sent"]
        if i >= n_bids:
            return None
        n = min(batch_size, n_bids - i)
        ts = i + pool["ts"][:n]
        state["sent"] = i + n
        return TupleBatch({
            "key": pool["auction"][:n], "id": ts, "ts": ts,
            "value": pool["price"][:n],
            "bidder": pool["bidder"][:n],
        })

    return source


def q1_currency(batch):
    """Q1: dollar -> euro conversion (BatchMap body)."""
    return batch.with_cols(value=batch["value"] * DOL_TO_EUR)


def make_q2_selection(auction_ids):
    """Q2: keep only bids on the given auctions (BatchFilter body)."""
    wanted = np.asarray(sorted(auction_ids), dtype=np.int64)

    def q2(batch):
        return np.isin(batch.key, wanted)

    return q2


def build_q5_hot_items(graph, n_bids: int, win_len: int, slide_len: int,
                       sink, n_auctions: int = 1000,
                       batch_size: int = 65_536, device_batch: int = 4096,
                       parallelism: int = 1, inflight_depth: int = None,
                       placement: str = "device"):
    """Q5: per-auction bid counts over sliding time windows.  The
    'hottest item' reduction is the sink's fold (max over each window
    epoch); the windowed counts are the device-parallel part.
    ``placement`` feeds the cost-based planner (docs/PLANNER.md):
    'auto' lets it pick the device or host lane per measured costs."""
    import windflow_tpu as wf
    from ..operators.basic_ops import Sink
    from ..operators.batch_ops import BatchSource
    from ..operators.tpu.farms_tpu import KeyFarmTPU

    from ..operators.tpu.win_seq_tpu import DEFAULT_INFLIGHT_DEPTH
    counter = KeyFarmTPU("count", win_len, slide_len, wf.WinType.TB,
                         parallelism=parallelism, batch_len=device_batch,
                         name="q5_counts", emit_batches=True,
                         inflight_depth=(inflight_depth
                                         or DEFAULT_INFLIGHT_DEPTH),
                         placement=placement)
    graph.add_source(BatchSource(
        bid_batches(n_bids, batch_size, n_auctions))) \
        .add(counter).add_sink(Sink(sink, name="q5_sink"))
    return graph


def build_q7_highest_bid(graph, n_bids: int, win_len: int, sink,
                         n_auctions: int = 1000,
                         batch_size: int = 65_536,
                         device_batch: int = 4096,
                         inflight_depth: int = None,
                         placement: str = "device"):
    """Q7: highest price per tumbling window across ALL bids.  Bids are
    funneled onto one key (the reference expresses global windows the
    same way: a single keyed substream), Q1-converted first."""
    from ..core.tuples import TupleBatch
    from ..operators.basic_ops import Sink
    from ..operators.batch_ops import BatchMap, BatchSource
    from ..operators.tpu.win_seq_tpu import WinSeqTPU
    from ..core.basic import WinType

    def to_global_key(batch):
        return TupleBatch({
            "key": np.zeros(len(batch), np.int64),
            "id": batch.id, "ts": batch.ts,
            "value": batch["value"] * DOL_TO_EUR,
        })

    from ..operators.tpu.win_seq_tpu import DEFAULT_INFLIGHT_DEPTH
    op = WinSeqTPU("max", win_len, win_len, WinType.TB,
                   batch_len=device_batch, name="q7_max",
                   inflight_depth=inflight_depth or DEFAULT_INFLIGHT_DEPTH,
                   placement=placement)
    graph.add_source(BatchSource(
        bid_batches(n_bids, batch_size, n_auctions))) \
        .chain(BatchMap(to_global_key)) \
        .add(op).add_sink(Sink(sink, name="q7_sink"))
    return graph


# ---------------------------------------------------------------------------
# Relational queries on the event-time plane (eventtime/;
# docs/EVENTTIME.md): Q3 / Q4 / Q6 / Q8
# ---------------------------------------------------------------------------

def synth_persons(n: int, n_cities: int = 10, seed: int = 11,
                  ts_stride: int = 3):
    """Synthetic person registrations: person ids dense (= join key for
    Q3/Q8), a city attribute, event time ``i * ts_stride``."""
    rng = np.random.default_rng(seed)
    return {
        "person": np.arange(n, dtype=np.int64),
        "city": rng.integers(0, n_cities, n, dtype=np.int64),
        "ts": np.arange(n, dtype=np.int64) * ts_stride,
    }


def synth_auctions(n: int, n_sellers: int = 100, n_categories: int = 8,
                   seed: int = 13, ts_stride: int = 2):
    """Synthetic auction openings: auction ids dense, a seller drawn
    from the person id space, a category, event time ``i * ts_stride``."""
    rng = np.random.default_rng(seed)
    return {
        "auction": np.arange(n, dtype=np.int64),
        "seller": rng.integers(0, n_sellers, n, dtype=np.int64),
        "category": rng.integers(0, n_categories, n, dtype=np.int64),
        "ts": np.arange(n, dtype=np.int64) * ts_stride,
    }


def _record_source(keys, tss, values, every: int = 32,
                   skew: float = None):
    """Watermarked shipper-style source over parallel arrays (one
    record per step; the event-time queries are record-plane)."""
    from ..core.tuples import BasicRecord
    from ..eventtime import watermarked

    n = len(keys)
    state = {"i": 0}

    def body(shipper):
        i = state["i"]
        if i >= n:
            return False
        shipper.push(BasicRecord(int(keys[i]), i, int(tss[i]), values[i]))
        state["i"] = i + 1
        return True

    if skew is None:
        skew = 0.0
    return watermarked(body, every=every, skew=skew)


def build_q3_local_items(graph, persons, auctions, sink,
                         cities=(0, 1), category: int = 2,
                         parallelism: int = 1):
    """Q3: for persons in ``cities``, the auctions of category
    ``category`` they sell -- an incremental full-history join
    (persons |><| auctions on seller; unbounded IntervalJoin, so
    neither side is ever evicted).  Sinked records: key = person id,
    value = (city, auction id)."""
    import windflow_tpu as wf
    from ..eventtime import LEFT, RIGHT, IntervalJoin, tag_side
    from ..operators.basic_ops import Sink

    p_keep = np.isin(persons["city"], np.asarray(cities, dtype=np.int64))
    a_keep = auctions["category"] == category
    pp = graph.add_source(wf.SourceBuilder(_record_source(
        persons["person"][p_keep], persons["ts"][p_keep],
        persons["city"][p_keep])).build())
    pa = graph.add_source(wf.SourceBuilder(_record_source(
        auctions["seller"][a_keep], auctions["ts"][a_keep],
        auctions["auction"][a_keep])).build())
    pp.chain(tag_side(LEFT))
    pa.chain(tag_side(RIGHT))
    merged = pp.merge(pa)
    merged.add(IntervalJoin(float("-inf"), float("inf"),
                            join_fn=lambda city, auc: (int(city),
                                                       int(auc)),
                            parallelism=parallelism, name="q3_join"))
    merged.add_sink(Sink(sink, name="q3_sink"))
    return graph


def q3_oracle(persons, auctions, cities=(0, 1), category: int = 2):
    """Numpy oracle / eager baseline twin for Q3: the sorted multiset
    of (person, city, auction) matches."""
    p_keep = np.isin(persons["city"], np.asarray(cities, dtype=np.int64))
    a_keep = auctions["category"] == category
    by_seller = {}
    for pid, city in zip(persons["person"][p_keep],
                         persons["city"][p_keep]):
        by_seller.setdefault(int(pid), []).append(int(city))
    out = []
    for seller, auc in zip(auctions["seller"][a_keep],
                           auctions["auction"][a_keep]):
        for city in by_seller.get(int(seller), ()):
            out.append((int(seller), city, int(auc)))
    return sorted(out)


def _closing_price_agg(pairs):
    """Q4/Q6 window aggregate over (auction, price) pairs: closing
    price = max bid per auction, averaged over the auctions seen."""
    best = {}
    for auc, price in pairs:
        if auc not in best or price > best[auc]:
            best[auc] = price
    return sum(best.values()) / len(best)


def _build_auction_bid_join(graph, auctions, bids, win_len,
                            out_key, parallelism):
    """Shared Q4/Q6 front: auctions |><| bids on auction id per
    tumbling window; the joined record carries ((re-key attr),
    (auction, price)) so the downstream window can re-key."""
    import windflow_tpu as wf
    from ..eventtime import LEFT, RIGHT, WindowJoin, tag_side

    # left value = the re-key attribute (category or seller)
    pa = graph.add_source(wf.SourceBuilder(_record_source(
        auctions["auction"], auctions["ts"],
        auctions[out_key])).build())
    pb = graph.add_source(wf.SourceBuilder(_record_source(
        bids["auction"], bids["ts"], bids["price"])).build())
    pa.chain(tag_side(LEFT))
    pb.chain(tag_side(RIGHT))
    merged = pa.merge(pb)
    merged.add(WindowJoin(
        win_len, join_fn=lambda attr, price: (int(attr), float(price)),
        parallelism=parallelism, name="ab_join"))
    return merged


def _rekey_joined(merged, name):
    """Re-key the joined (attr, price) record stream by attr, keeping
    (auction-key, price) as the value for the closing-price agg."""
    from ..operators.basic_ops import FlatMap
    from ..core.tuples import BasicRecord

    def rekey(rec, shipper):
        attr, price = rec.value
        shipper.push(BasicRecord(attr, rec.id, rec.ts,
                                 (rec.key, price)))
    merged.chain(FlatMap(rekey, name=name))
    return merged


def build_q4_avg_price(graph, auctions, bids, win_len, sink,
                       parallelism: int = 1):
    """Q4: average closing price per CATEGORY over tumbling windows.
    auctions |><| bids on auction id per window, closing price =
    per-auction max, averaged per category.  Sinked records:
    key = category, ts = window start, value = average."""
    from ..eventtime import EventTimeWindow
    from ..operators.basic_ops import Sink

    merged = _build_auction_bid_join(graph, auctions, bids, win_len,
                                     "category", parallelism)
    _rekey_joined(merged, "q4_by_category")
    merged.add(EventTimeWindow(_closing_price_agg, win_len,
                               parallelism=parallelism,
                               name="q4_avg"))
    merged.add_sink(Sink(sink, name="q4_sink"))
    return graph


def build_q6_avg_seller(graph, auctions, bids, win_len, sink,
                        parallelism: int = 1):
    """Q6: average selling price per SELLER over tumbling windows --
    the Q4 join re-keyed by seller.  Sinked records: key = seller,
    ts = window start, value = average closing price."""
    from ..eventtime import EventTimeWindow
    from ..operators.basic_ops import Sink

    merged = _build_auction_bid_join(graph, auctions, bids, win_len,
                                     "seller", parallelism)
    _rekey_joined(merged, "q6_by_seller")
    merged.add(EventTimeWindow(_closing_price_agg, win_len,
                               parallelism=parallelism,
                               name="q6_avg"))
    merged.add_sink(Sink(sink, name="q6_sink"))
    return graph


def _q4q6_oracle(auctions, bids, win_len, attr):
    """Shared Q4/Q6 oracle: {(attr, win_start): avg closing price}
    where a (auction, bid) pair joins when both land in the window."""
    a_wins = {}
    for auc, at, ts in zip(auctions["auction"], auctions[attr],
                           auctions["ts"]):
        a_wins[(int(auc), int(ts) // win_len * win_len)] = int(at)
    best = {}
    for auc, price, ts in zip(bids["auction"], bids["price"],
                              bids["ts"]):
        w = int(ts) // win_len * win_len
        at = a_wins.get((int(auc), w))
        if at is None:
            continue
        k = (at, w, int(auc))
        if k not in best or price > best[k]:
            best[k] = float(price)
    sums = {}
    for (at, w, _auc), price in best.items():
        s = sums.setdefault((at, w), [0.0, 0])
        s[0] += price
        s[1] += 1
    return {k: v[0] / v[1] for k, v in sums.items()}


def q4_oracle(auctions, bids, win_len):
    return _q4q6_oracle(auctions, bids, win_len, "category")


def q6_oracle(auctions, bids, win_len):
    return _q4q6_oracle(auctions, bids, win_len, "seller")


def build_q8_new_users(graph, persons, auctions, win_len, sink,
                       parallelism: int = 1, source_of=None):
    """Q8: monitor new users -- persons who registered AND opened an
    auction in the same tumbling window (persons |><| auctions
    re-keyed by seller).  Sinked records: key = person id, ts =
    window start, value = (city, auction id).  ``source_of(keys, tss,
    values)`` overrides the watermarked record source -- bench.py
    injects stamped sources to measure watermark-to-result latency."""
    import windflow_tpu as wf
    from ..eventtime import LEFT, RIGHT, WindowJoin, tag_side
    from ..operators.basic_ops import Sink

    if source_of is None:
        source_of = _record_source
    pp = graph.add_source(wf.SourceBuilder(source_of(
        persons["person"], persons["ts"], persons["city"])).build())
    pa = graph.add_source(wf.SourceBuilder(source_of(
        auctions["seller"], auctions["ts"],
        auctions["auction"])).build())
    pp.chain(tag_side(LEFT))
    pa.chain(tag_side(RIGHT))
    merged = pp.merge(pa)
    merged.add(WindowJoin(
        win_len, join_fn=lambda city, auc: (int(city), int(auc)),
        parallelism=parallelism, name="q8_join"))
    merged.add_sink(Sink(sink, name="q8_sink"))
    return graph


def q8_oracle(persons, auctions, win_len):
    """Numpy oracle / baseline twin for Q8: sorted multiset of
    (person, win_start, city, auction)."""
    by_pw = {}
    for pid, city, ts in zip(persons["person"], persons["city"],
                             persons["ts"]):
        w = int(ts) // win_len * win_len
        by_pw.setdefault((int(pid), w), []).append(int(city))
    out = []
    for seller, auc, ts in zip(auctions["seller"],
                               auctions["auction"], auctions["ts"]):
        w = int(ts) // win_len * win_len
        for city in by_pw.get((int(seller), w), ()):
            out.append((int(seller), w, city, int(auc)))
    return sorted(out)


# eager baseline twins for the bench gate (tools/bench_gate.py): the
# oracles ARE the single-threaded reference implementations, exposed
# under the twin names the bench rows cite
q3_baseline = q3_oracle
q4_baseline = q4_oracle
q6_baseline = q6_oracle
q8_baseline = q8_oracle
