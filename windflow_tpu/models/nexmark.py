"""NEXMark-style query set: the second benchmark application family.

The reference ships its workloads as self-checking test pipelines
(tests/mp_tests_*); the NEXMark auction queries are the streaming
community's standard benchmark shapes, expressed here on the columnar
plane with the device window operators:

* Q1 currency conversion -- stateless BatchMap (price * rate)
* Q2 selection           -- stateless BatchFilter (auction id set)
* Q5 hot items           -- per-auction sliding-window bid counts,
                            KeyFarmTPU 'count' (key_farm_gpu.hpp shape)
* Q7 highest bid         -- global per-window maximum price,
                            WinSeqTPU 'max' (win_seq_gpu.hpp shape)

Synthetic bid stream: (auction, bidder, price, ts), ts dense.
"""
from __future__ import annotations

import numpy as np

DOL_TO_EUR = 0.9


def synth_bids(n_bids: int, n_auctions: int = 1000, seed: int = 7,
               ts_start: int = 0):
    """Columnar synthetic bid stream (NEXMark generator analogue)."""
    rng = np.random.default_rng(seed)
    return {
        "auction": rng.integers(0, n_auctions, n_bids, dtype=np.int64),
        "bidder": rng.integers(0, 10_000, n_bids, dtype=np.int64),
        "price": rng.integers(1, 10_000, n_bids).astype(np.float64),
        "ts": ts_start + np.arange(n_bids, dtype=np.int64),
    }


def bid_batches(n_bids: int, batch_size: int = 65_536,
                n_auctions: int = 1000, seed: int = 7):
    """BatchSource body emitting the synthetic bid stream as
    TupleBatches keyed by auction (price in the value column)."""
    from ..core.tuples import TupleBatch

    pool = synth_bids(batch_size, n_auctions, seed)
    state = {"sent": 0}

    def source(ctx):
        i = state["sent"]
        if i >= n_bids:
            return None
        n = min(batch_size, n_bids - i)
        ts = i + pool["ts"][:n]
        state["sent"] = i + n
        return TupleBatch({
            "key": pool["auction"][:n], "id": ts, "ts": ts,
            "value": pool["price"][:n],
            "bidder": pool["bidder"][:n],
        })

    return source


def q1_currency(batch):
    """Q1: dollar -> euro conversion (BatchMap body)."""
    return batch.with_cols(value=batch["value"] * DOL_TO_EUR)


def make_q2_selection(auction_ids):
    """Q2: keep only bids on the given auctions (BatchFilter body)."""
    wanted = np.asarray(sorted(auction_ids), dtype=np.int64)

    def q2(batch):
        return np.isin(batch.key, wanted)

    return q2


def build_q5_hot_items(graph, n_bids: int, win_len: int, slide_len: int,
                       sink, n_auctions: int = 1000,
                       batch_size: int = 65_536, device_batch: int = 4096,
                       parallelism: int = 1, inflight_depth: int = None,
                       placement: str = "device"):
    """Q5: per-auction bid counts over sliding time windows.  The
    'hottest item' reduction is the sink's fold (max over each window
    epoch); the windowed counts are the device-parallel part.
    ``placement`` feeds the cost-based planner (docs/PLANNER.md):
    'auto' lets it pick the device or host lane per measured costs."""
    import windflow_tpu as wf
    from ..operators.basic_ops import Sink
    from ..operators.batch_ops import BatchSource
    from ..operators.tpu.farms_tpu import KeyFarmTPU

    from ..operators.tpu.win_seq_tpu import DEFAULT_INFLIGHT_DEPTH
    counter = KeyFarmTPU("count", win_len, slide_len, wf.WinType.TB,
                         parallelism=parallelism, batch_len=device_batch,
                         name="q5_counts", emit_batches=True,
                         inflight_depth=(inflight_depth
                                         or DEFAULT_INFLIGHT_DEPTH),
                         placement=placement)
    graph.add_source(BatchSource(
        bid_batches(n_bids, batch_size, n_auctions))) \
        .add(counter).add_sink(Sink(sink, name="q5_sink"))
    return graph


def build_q7_highest_bid(graph, n_bids: int, win_len: int, sink,
                         n_auctions: int = 1000,
                         batch_size: int = 65_536,
                         device_batch: int = 4096,
                         inflight_depth: int = None,
                         placement: str = "device"):
    """Q7: highest price per tumbling window across ALL bids.  Bids are
    funneled onto one key (the reference expresses global windows the
    same way: a single keyed substream), Q1-converted first."""
    from ..core.tuples import TupleBatch
    from ..operators.basic_ops import Sink
    from ..operators.batch_ops import BatchMap, BatchSource
    from ..operators.tpu.win_seq_tpu import WinSeqTPU
    from ..core.basic import WinType

    def to_global_key(batch):
        return TupleBatch({
            "key": np.zeros(len(batch), np.int64),
            "id": batch.id, "ts": batch.ts,
            "value": batch["value"] * DOL_TO_EUR,
        })

    from ..operators.tpu.win_seq_tpu import DEFAULT_INFLIGHT_DEPTH
    op = WinSeqTPU("max", win_len, win_len, WinType.TB,
                   batch_len=device_batch, name="q7_max",
                   inflight_depth=inflight_depth or DEFAULT_INFLIGHT_DEPTH,
                   placement=placement)
    graph.add_source(BatchSource(
        bid_batches(n_bids, batch_size, n_auctions))) \
        .chain(BatchMap(to_global_key)) \
        .add(op).add_sink(Sink(sink, name="q7_sink"))
    return graph
