"""The five BASELINE benchmark configurations as library pipelines.

BASELINE.json "configs" (see BASELINE.md): each function wires the
corresponding workload into a PipeGraph and returns the collector used
as its oracle.  These are the canonical "models" of the framework --
streaming applications exercising each parallelization strategy.

1. config_cpu_multipipe      -- map -> filter -> tumbling CB window sum
                                (mp_tests_cpu style, host engines)
2. config_win_seq_tpu        -- keyed sliding TB incremental sum,
                                device-batched (Win_Seq_GPU analogue)
3. config_pane_farm_tpu      -- pane partial agg + window combine,
                                PLQ on device
4. config_key_farm_tpu       -- key-sharded windows, device-batched
                                (the 8-chip version is
                                parallel/sharded.ShardedWindowEngine)
5. config_yahoo              -- Yahoo-style ad-campaign windowed count
                                (models/yahoo.build_pipeline)
"""
from __future__ import annotations

import threading



class ResultCollector:
    def __init__(self):
        self.lock = threading.Lock()
        self.count = 0
        self.total = 0.0

    def __call__(self, item):
        if item is None:
            return
        from ..core.tuples import TupleBatch
        with self.lock:
            if isinstance(item, TupleBatch):
                self.count += len(item)
                self.total += float(item["value"].sum())
            else:
                self.count += 1
                self.total += item.value


def config_cpu_multipipe(graph, n_events=100_000, n_keys=16, win=1000):
    """Config #1: host-engine MultiPipe map->filter->tumbling CB sum."""
    import windflow_tpu as wf
    from ..utils.synthetic import ordered_keyed_stream

    coll = ResultCollector()

    def double(t):
        t.value *= 2.0

    def keep(t):
        return True

    def sum_win(gwid, it, result):
        result.value = sum(t.value for t in it)

    graph.add_source(wf.SourceBuilder(
        ordered_keyed_stream(n_keys, n_events // n_keys)).build()) \
        .chain(wf.MapBuilder(double).build()) \
        .chain(wf.FilterBuilder(keep).build()) \
        .add(wf.KeyFarmBuilder(sum_win).with_parallelism(2)
             .with_cb_windows(win, win).build()) \
        .add_sink(wf.SinkBuilder(coll).build())
    return coll


def config_win_seq_tpu(graph, n_events=1_000_000, n_keys=32,
                       win=4096, slide=2048, batch=4096):
    """Config #2: keyed sliding TB sum on the device engine."""
    from ..operators.basic_ops import Sink
    from ..operators.batch_ops import BatchSource
    from ..operators.tpu.win_seq_tpu import WinSeqTPU
    from ..core.basic import WinType
    from ..utils.synthetic import batch_stream

    coll = ResultCollector()
    op = WinSeqTPU("sum", win, slide, WinType.TB, batch_len=batch,
                   emit_batches=True)
    graph.add_source(BatchSource(batch_stream(n_events, n_keys))) \
        .add(op).add_sink(Sink(coll))
    return coll


def config_pane_farm_tpu(graph, n_events=1_000_000, n_keys=32,
                         win=4096, slide=2048, batch=4096):
    """Config #3: pane partial aggregation (device) + window combine."""
    from ..operators.basic_ops import Sink
    from ..operators.batch_ops import BatchSource
    from ..operators.tpu.farms_tpu import PaneFarmTPU
    from ..core.basic import WinType
    from ..utils.synthetic import batch_stream

    coll = ResultCollector()

    def host_comb(gwid, it, result):
        result.value = sum(t.value for t in it)

    op = PaneFarmTPU("sum", host_comb, win, slide, WinType.TB,
                     plq_parallelism=2, wlq_parallelism=1, plq_on_tpu=True,
                     batch_len=batch)
    graph.add_source(BatchSource(batch_stream(n_events, n_keys))) \
        .add(op).add_sink(Sink(coll))
    return coll


def config_key_farm_tpu(graph, n_events=1_000_000, n_keys=64,
                        win=4096, slide=2048, batch=4096, parallelism=4):
    """Config #4 (single-host form): key-sharded device windows.  The
    across-chips version of this config is ShardedWindowEngine
    (parallel/sharded.py) -- key shards over the mesh, psum combines."""
    from ..operators.basic_ops import Sink
    from ..operators.batch_ops import BatchSource
    from ..operators.tpu.farms_tpu import KeyFarmTPU
    from ..core.basic import WinType
    from ..utils.synthetic import batch_stream

    coll = ResultCollector()
    op = KeyFarmTPU("sum", win, slide, WinType.TB, parallelism=parallelism,
                    batch_len=batch, emit_batches=True)
    graph.add_source(BatchSource(batch_stream(n_events, n_keys))) \
        .add(op).add_sink(Sink(coll))
    return coll


def config_yahoo(graph, n_events=1_000_000, **kw):
    """Config #5: Yahoo Streaming Benchmark (see models/yahoo.py)."""
    from .yahoo import build_pipeline

    coll = ResultCollector()
    build_pipeline(graph, n_events, sink=coll, **kw)
    return coll


ALL_CONFIGS = {
    "cpu_multipipe": config_cpu_multipipe,
    "win_seq_tpu": config_win_seq_tpu,
    "pane_farm_tpu": config_pane_farm_tpu,
    "key_farm_tpu": config_key_farm_tpu,
    "yahoo": config_yahoo,
}
