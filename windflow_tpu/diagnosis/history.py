"""Rolling gauge history: a bounded ring of periodic snapshot rows so
trends are queryable in-process (docs/OBSERVABILITY.md "Diagnosis
plane").

Every diagnosis tick (riding the monitor/auditor cadence, rate-limited
by ``RuntimeConfig.diagnosis_interval_s``) appends one row of the
gauges an operator actually trends on; the ring
(``RuntimeConfig.history_len`` rows) serializes columnar into the
stats-JSON ``History`` block -- timestamps once, one array per series
-- which is exactly the shape the web UI's sparklines and the anomaly
detector consume.  Nothing here touches the item path: every value is
a counter delta or a gauge read the runtime already keeps.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

# serialized series, in display order
SERIES = (
    # sink-consumed RESULTS/s over the tick window (items, not tuples:
    # one emitted TupleBatch counts once, the same unit as the
    # dashboard's result-rate tile -- on the batch plane multiply by
    # the batch size for tuples/s)
    "throughput_rps",
    "e2e_p50_us",          # merged traced end-to-end latency
    "e2e_p99_us",
    "frontier_lag_ms",     # most held-back operator (audit plane)
    "queue_depth",         # tuples parked across all inbound channels
    "credit_wait_s",       # cumulative source credit-wait
    "mem_kb",              # process RSS
    "pool_kb",             # ColumnPool arena bytes held (KiB)
    "pool_buffers",        # ColumnPool buffers held
)


class GaugeHistory:
    """Bounded ring of (t, {series: value}) snapshot rows."""

    def __init__(self, maxlen: int):
        self.rows: deque = deque(maxlen=max(2, int(maxlen)))

    def append(self, t: float, values: Dict[str, float]) -> None:
        self.rows.append((t, values))

    def __len__(self) -> int:
        return len(self.rows)

    def series(self, name: str) -> List[float]:
        return [v.get(name, 0.0) for _t, v in self.rows]

    def last(self, name: str) -> Optional[float]:
        if not self.rows:
            return None
        return self.rows[-1][1].get(name)

    def block(self) -> Optional[dict]:
        """The stats-JSON ``History`` block (columnar; timestamps are
        unix seconds rounded to ms)."""
        rows = list(self.rows)
        if not rows:
            return None
        return {
            "Len": len(rows),
            "T": [round(t, 3) for t, _v in rows],
            "Series": {name: [round(v.get(name, 0.0), 3) for _t, v in rows]
                       for name in SERIES},
        }
