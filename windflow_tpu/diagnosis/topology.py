"""Operator-level topology extraction for the diagnosis plane.

The stats JSON reports operators as a flat list, which is enough for
counters but not for a root-cause walk: "who feeds whom" is what turns
a set of pressured gauges into a named bottleneck.  This module reads
the *wired* graph once (channels + fused segment chains, the same
objects the auditor walks) and publishes the operator-level edge list
into the stats JSON ``Topology`` block, so the walk works identically
on a live graph, a dashboard report and an offline dump.

Edges are ``[producer_op, consumer_op, kind]`` with kind ``channel``
(a real bounded queue sits between them -- the queueing gauges apply)
or ``fused`` (LEVEL2 segments inside one replica thread -- no queue,
pressure propagates as service time).  Operator names match the stats
records (replica suffixes stripped), so gauge lookup is a dict hit.
"""
from __future__ import annotations

from typing import List

from ..audit.ledger import _op_of, unwrap


def _op_chain(node) -> List[str]:
    """The ordered operator names living inside one runtime node: the
    fused segment chain, or the single operator itself."""
    from ..runtime.node import FusedLogic
    if isinstance(node.logic, FusedLogic):
        return [_op_of(seg.name) for seg in node.logic.segments]
    return [_op_of(node.name)]


def operator_edges(graph) -> List[List[str]]:
    """Operator-level edge list of the wired graph.  Stable across
    elastic rescales (replica counts change, operators do not)."""
    nodes = graph._all_nodes()
    owner = {}
    for n in nodes:
        if n.channel is not None:
            owner[id(unwrap(n.channel))] = n
    seen = set()
    edges: List[List[str]] = []

    def add(a: str, b: str, kind: str) -> None:
        if a != b and (a, b) not in seen:
            seen.add((a, b))
            edges.append([a, b, kind])

    for n in nodes:
        chain = _op_chain(n)
        for a, b in zip(chain, chain[1:]):
            add(a, b, "fused")
        for o in n.outlets:
            for ch, _pid in o.dests:
                c = owner.get(id(unwrap(ch)))
                if c is None or c is n:
                    continue
                add(chain[-1], _op_chain(c)[0], "channel")
    # distributed plane (distributed/wiring.py): cross-worker edges --
    # the consumer lives in another process, so the channel walk above
    # cannot see it; the wiring recorded the operator pair instead.
    # Kind "wire": no local queue, pressure propagates through the
    # credit window.
    for a, b, kind in getattr(graph, "_wire_topology", ()):
        add(a, b, kind)
    return edges


def ancestors_of(edges, start: str) -> set:
    """Every operator upstream of ``start`` (inclusive) over the edge
    list -- the candidate set of a per-sink bottleneck walk."""
    preds = {}
    for a, b, _k in edges:
        preds.setdefault(b, []).append(a)
    out = {start}
    stack = [start]
    while stack:
        for p in preds.get(stack.pop(), ()):
            if p not in out:
                out.add(p)
                stack.append(p)
    return out


def depth_ranks(edges) -> dict:
    """Longest-path-from-root rank per operator (the web UI's layout
    rule): higher rank == more downstream.  Used to pick the most
    downstream pressured operator when backpressure cascades."""
    rank = {}
    names = {n for e in edges for n in e[:2]}
    for name in names:
        rank.setdefault(name, 0)
    for _ in range(len(names) + 1):
        changed = False
        for a, b, _k in edges:
            if rank[b] < rank[a] + 1:
                rank[b] = rank[a] + 1
                changed = True
        if not changed:
            break
    return rank


def sinks_of(edges, operators) -> List[str]:
    """Operators with no outgoing edge (falls back to the last listed
    operator when the dump carries no topology)."""
    outs = {a for a, _b, _k in edges}
    named = [op for op in operators if op not in outs] if edges else []
    if named:
        return named
    return list(operators)[-1:]


def sources_of(edges, operators) -> List[str]:
    ins = {b for _a, b, _k in edges}
    named = [op for op in operators if op not in ins] if edges else []
    if named:
        return named
    return list(operators)[:1]
