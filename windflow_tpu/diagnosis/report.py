"""The doctor report: one structured answer to "where does the time
go and who is the bottleneck" (docs/OBSERVABILITY.md "Diagnosis
plane").

:func:`build_report` is a pure function of a stats-JSON dict (plus an
optional flight-event list), so the same code produces the report

* live, via ``PipeGraph.explain()``,
* server-side, at the dashboard's ``GET /explain``,
* offline, from a stats-JSON / flight-JSONL dump directory
  (``python -m windflow_tpu.doctor``).

It prefers the precomputed ``Diagnosis`` block a diagnosing runtime
published, and degrades gracefully on older dumps: the bottleneck walk
and the attribution fold are recomputed from ``Operators``/``Topology``
and ``Trace_records`` when the block is missing, and every block is
optional (``Schema_version`` tolerance is the loader contract).

:func:`render_text` turns the report into the aligned plain-text the
doctor CLI prints.
"""
from __future__ import annotations

from typing import List, Optional

from .attribution import CLASSES, attribution_from_stats
from .bottleneck import bottleneck_from_stats

# flight events echoed into the report
FLIGHT_TAIL = 8


def build_report(stats: dict, flight: Optional[list] = None) -> dict:
    """Fold one stats-JSON dict (any schema version, blocks optional)
    into the structured doctor report."""
    stats = stats or {}
    if flight is None:
        flight = stats.get("Flight") or []
    diag = stats.get("Diagnosis") or {}
    bottleneck = diag.get("Bottleneck") or bottleneck_from_stats(stats)
    attribution = diag.get("Attribution") or attribution_from_stats(stats)
    anomalies = diag.get("Anomalies") or []
    cons = stats.get("Conservation")
    conservation = None
    if cons:
        conservation = {
            "Balanced": bool(cons.get("Edges_balanced")),
            "Violations": int(cons.get("Violations_total", 0) or 0),
            "Final_check": bool(cons.get("Final_check")),
        }
    skew = stats.get("Skew") or {}
    hot = []
    for h in (skew.get("Hot_keys") or []):
        if not (h.get("share") or 0) > 0:
            continue
        key = (h.get("top") or [[None]])[0][0]
        entry = {"operator": h.get("operator"),
                 "share": h.get("share"), "key": key}
        # tiered stores name the tier holding each hot key
        # (auditor._probe_tiers); absent on non-tiered graphs
        tier = (h.get("tiers") or {}).get(str(key))
        if tier is not None:
            entry["tier"] = tier
        hot.append(entry)
    # per-tier keyed-state totals (schema v9 census extras): tiered
    # stores report hot/warm/cold, device-lane window engines report
    # their resident forest bytes under "device" (audit/census.py;
    # windflow_keyed_state_bytes{tier=...} renders the same rows)
    tier_tot: dict = {}
    for row in (skew.get("Census") or []):
        for tier, kb in (row.get("tiers") or {}).items():
            keys, nbytes = ((int(kb[0] or 0), int(kb[1] or 0))
                            if isinstance(kb, (list, tuple))
                            else (0, int(kb or 0)))
            t = tier_tot.setdefault(tier, [0, 0])
            t[0] += keys
            t[1] += nbytes
    state_tiers = {t: {"keys": v[0], "bytes": v[1]}
                   for t, v in sorted(tier_tot.items())} or None
    hist = stats.get("History") or {}
    series = hist.get("Series") or {}
    history = None
    if hist.get("Len"):
        def last(name):
            vals = series.get(name) or []
            return vals[-1] if vals else None
        history = {"Ticks": hist.get("Len"),
                   "Throughput_rps": last("throughput_rps"),
                   "E2e_p99_us": last("e2e_p99_us"),
                   "Frontier_lag_ms": last("frontier_lag_ms"),
                   "Queue_depth": last("queue_depth"),
                   # memory-pressure evidence (SLO plane satellite):
                   # process RSS + ColumnPool arena occupancy
                   "Mem_kb": last("mem_kb"),
                   "Pool_kb": last("pool_kb")}
    slo_blk = stats.get("Slo")
    slo = None
    if slo_blk:
        slo = {
            "Objectives": slo_blk.get("Objectives"),
            "Target": slo_blk.get("Target"),
            "Breached": bool(slo_blk.get("Breached")),
            "Breaches_total": int(slo_blk.get("Breaches_total", 0) or 0),
            "Burn_rate_fast": float(slo_blk.get("Burn_rate_fast", 0)
                                    or 0.0),
            "Burn_rate_slow": float(slo_blk.get("Burn_rate_slow", 0)
                                    or 0.0),
            "Budget_burned": float(slo_blk.get("Budget_burned", 0)
                                   or 0.0),
            "Violating": list(slo_blk.get("Violating") or ()),
            "Values": dict(slo_blk.get("Values") or {}),
        }
    failures = [e for e in flight
                if e.get("kind") in ("node_failure", "stall")]
    # serving plane (serving/; docs/SERVING.md): cross-tenant arbiter
    # decisions involving this graph -- the doctor names victim,
    # donor, action and evidence for every one
    arbitrations = [{
        "t": e.get("t"),
        "victim": e.get("victim"),
        "donor": e.get("donor"),
        "action": e.get("action"),
        "detail": e.get("detail"),
        "evidence": e.get("evidence"),
    } for e in flight if e.get("kind") == "arbitration"]
    # online re-planning (graph/replanner.py; docs/PLANNER.md): lane
    # flips with the measured evidence that forced them
    replacements = [{
        "t": e.get("t"),
        "operator": e.get("operator"),
        "old": e.get("old"),
        "new": e.get("new"),
        "trigger": e.get("trigger"),
        "evidence": e.get("evidence"),
    } for e in flight if e.get("kind") == "replacement"]
    # supervised replica self-healing (durability/supervision.py): the
    # doctor names every heal attempt -- node, backoff, rewind epoch --
    # and whether the supervisor eventually escalated
    heals = [{
        "t": e.get("t"),
        "node": e.get("node"),
        "attempt": e.get("attempt"),
        "delay_s": e.get("delay_s"),
        "epoch": e.get("epoch"),
        "outcome": e.get("outcome"),
        "error": e.get("error"),
    } for e in flight if e.get("kind") == "replica_restart"]
    # tolerant-reader fallbacks (durability/store.py): a torn manifest
    # or a missing delta blob made the restart walk back to an older
    # fully-loadable epoch instead of crashing
    fallbacks = [{
        "t": e.get("t"),
        "epoch": e.get("epoch"),
        "reason": e.get("reason"),
    } for e in flight if e.get("kind") == "epoch_abort"
        and e.get("reason") in ("manifest_corrupt", "blob_missing")]
    # tiered keyed state (state/; docs/RESILIENCE.md "Tiered state &
    # memory pressure"): admission-control sheds under the byte budget
    # and spill batches re-warmed by a full disk
    pressure = [{
        "t": e.get("t"),
        "kind": e.get("kind"),
        "node": e.get("node"),
        "shed": e.get("shed"),
        "keys": e.get("keys"),
        "budget": e.get("budget"),
        "mem_bytes": e.get("mem_bytes"),
        "error": e.get("error"),
    } for e in flight if e.get("kind") in ("state_pressure",
                                           "spill_abort")]
    # disk-full epoch aborts (durability/coordinator.py): the commit
    # degraded -- last committed epoch kept, graph stayed up
    disk_full = [{
        "t": e.get("t"),
        "epoch": e.get("epoch"),
        "final": e.get("final"),
        "error": e.get("error"),
    } for e in flight if e.get("kind") == "epoch_abort"
        and e.get("reason") == "disk_full"]
    # scheduler plane (scheduler/; docs/SERVING.md "Global
    # scheduler"): the worker's placement/lease block plus every
    # fleet-level decision in flight -- placements, crash re-placings,
    # structured rejections, worker deaths -- so the doctor explains
    # WHY a tenant sits where it does (or was refused)
    sched_blk = stats.get("Scheduler")
    scheduler = None
    if sched_blk:
        dev = sched_blk.get("Devices") or {}
        scheduler = {
            "Worker": sched_blk.get("Worker"),
            "Fair_share": bool(sched_blk.get("Fair_share")),
            "Sched_wait_s": float(sched_blk.get("Sched_wait_s", 0)
                                  or 0.0),
            "Placements": list(sched_blk.get("Placements") or ()),
            "Device_contended": bool(dev.get("Contended")),
            "Device_holders": int(dev.get("Holders", 0) or 0),
        }
    sched_events = [{
        "t": e.get("t"),
        "kind": e.get("kind"),
        "tenant": e.get("tenant"),
        "worker": e.get("worker"),
        "operators": e.get("operators"),
        "reason": e.get("reason"),
        "hint": e.get("hint"),
    } for e in flight if e.get("kind") in (
        "sched_place", "sched_replace", "sched_rejected",
        "worker_death")]
    dur = stats.get("Durability")
    durability = None
    if dur:
        durability = {
            "Committed_epoch": int(dur.get("Committed_epoch", 0) or 0),
            "Epoch_lag_s": float(dur.get("Epoch_lag_s", 0) or 0),
            "Last_commit_s": float(dur.get("Last_commit_s", 0) or 0),
            "Commits": int(dur.get("Commits", 0) or 0),
            "Aborts": int(dur.get("Aborts", 0) or 0),
            "Stalled": bool(dur.get("Stalled")),
            "Restored_from": dur.get("Restored_from"),
            "Delta": bool(dur.get("Delta")),
            "Last_commit_bytes": int(dur.get("Last_commit_bytes", 0)
                                     or 0),
        }
    report = {
        "Graph": stats.get("PipeGraph_name", "?"),
        "Schema_version": stats.get("Schema_version"),
        "Verdict": "",
        "Bottleneck": bottleneck,
        "Attribution": attribution,
        "Anomalies": anomalies,
        "Anomalies_total": diag.get("Anomalies_total", len(anomalies)),
        "Slo": slo,
        "Scheduler": scheduler,
        "Scheduler_events": sched_events[-FLIGHT_TAIL:],
        "Conservation": conservation,
        "Durability": durability,
        "Hot_keys": hot,
        "State_tiers": state_tiers,
        "History": history,
        "Failures": failures,
        "Arbitrations": arbitrations[-FLIGHT_TAIL:],
        "Replacements": replacements[-FLIGHT_TAIL:],
        "Replica_restarts": heals[-FLIGHT_TAIL:],
        "Recovery_fallbacks": fallbacks[-FLIGHT_TAIL:],
        "State_pressure": pressure[-FLIGHT_TAIL:],
        "Disk_full": disk_full[-FLIGHT_TAIL:],
        "Flight_tail": list(flight)[-FLIGHT_TAIL:],
    }
    report["Verdict"] = _verdict(report)
    return report


def _verdict(report: dict) -> str:
    """One-line human summary, worst news first."""
    parts: List[str] = []
    if report["Failures"]:
        kinds = sorted({e.get("kind") for e in report["Failures"]})
        parts.append(f"FAILED ({', '.join(kinds)})")
    cons = report["Conservation"]
    if cons and cons["Violations"]:
        parts.append(f"{cons['Violations']} conservation violation(s)")
    slo = report.get("Slo")
    if slo and slo["Breached"]:
        b = slo["Budget_burned"] * 100
        parts.append("SLO VIOLATED: "
                     + _slo_detail(slo, report.get("History"))
                     + ", budget "
                     + (f"{b:.0f}%" if b >= 1 else "<1%")
                     + " burned")
    dur = report.get("Durability")
    if dur and dur["Stalled"]:
        # stalled epochs: barriers stopped reaching the sinks (a
        # wedged operator, a parked source, a dead branch) -- the
        # recovery point is frozen even though the graph may look live
        parts.append(f"epochs STALLED (committed "
                     f"{dur['Committed_epoch']}, oldest uncommitted "
                     f"{dur['Epoch_lag_s']:.1f}s old)")
    disk_full = report.get("Disk_full") or []
    if disk_full:
        last = disk_full[-1]
        parts.append(f"DISK FULL: {len(disk_full)} epoch commit(s) "
                     f"aborted, degraded to last committed epoch "
                     f"(graph stayed up; last abort at epoch "
                     f"{last.get('epoch')})")
    pressure = report.get("State_pressure") or []
    sheds = [p for p in pressure if p.get("kind") == "state_pressure"]
    if sheds:
        dropped = sum(int(p.get("shed") or 0) for p in sheds)
        parts.append(f"STATE PRESSURE: {dropped} key(s) shed to the "
                     f"dead-letter store under the byte budget "
                     f"(last at {sheds[-1].get('node')})")
    spill_aborts = [p for p in pressure if p.get("kind") == "spill_abort"]
    if spill_aborts:
        parts.append(f"{len(spill_aborts)} spill batch(es) re-warmed "
                     f"in memory (spill disk full at "
                     f"{spill_aborts[-1].get('node')})")
    heals = report.get("Replica_restarts") or []
    if heals:
        if any(h.get("outcome") == "escalated" for h in heals):
            parts.append(f"replica self-heal ESCALATED at "
                         f"{heals[-1].get('node')} "
                         f"(attempt {heals[-1].get('attempt')})")
        else:
            last = heals[-1]
            parts.append(f"{len(heals)} supervised replica restart(s) "
                         f"(healed, last {last.get('node')} rewound to "
                         f"epoch {last.get('epoch')})")
    fb = report.get("Recovery_fallbacks") or []
    if fb:
        parts.append(f"recovery fell back past {len(fb)} unreadable "
                     f"snapshot(s) ({fb[-1].get('reason')})")
    sched_ev = report.get("Scheduler_events") or []
    deaths = [e for e in sched_ev if e.get("kind") == "worker_death"]
    if deaths:
        replaced = [e for e in sched_ev
                    if e.get("kind") == "sched_replace"]
        parts.append(f"worker {deaths[-1].get('worker')} DIED "
                     f"({len(replaced)} tenant(s) re-placed)")
    rejected = [e for e in sched_ev
                if e.get("kind") == "sched_rejected"]
    if rejected:
        last = rejected[-1]
        what = last.get("tenant") or last.get("operators")
        parts.append(f"scheduler REJECTED {what}"
                     + (f" ({last['reason']})"
                        if last.get("reason") else ""))
    bn = report["Bottleneck"] or {}
    if bn.get("Operator"):
        if bn.get("Verdict") == "input_bound":
            parts.append(f"input-bound at {bn['Operator']}")
        else:
            parts.append(f"bottleneck: {bn['Operator']} "
                         f"(score {bn.get('Score', 0):.2f}, "
                         f"{bn.get('Verdict')})")
    n_anom = len(report["Anomalies"])
    if n_anom:
        parts.append(f"{n_anom} active regression(s)")
    if cons and not cons["Violations"] and cons["Balanced"]:
        parts.append("ledger balanced")
    return "; ".join(parts) if parts else "no diagnosis signals"


def _slo_detail(slo: dict, history: Optional[dict]) -> str:
    """Human phrasing of the violating objectives, citing the last
    judged gauge value (the Slo block's ``Values``; the History row is
    the fallback for older dumps)."""
    obj = slo.get("Objectives") or {}
    vals = slo.get("Values") or {}
    hist = history or {}

    def ms(v):
        return f"{float(v):g} ms"

    out = []
    for name in slo.get("Violating") or ():
        if name == "e2e_p99":
            cur = vals.get("e2e_p99_ms") or (
                (hist.get("E2e_p99_us") or 0) / 1e3 or None)
            out.append("e2e p99 "
                       + (ms(cur) + " > " if cur else "over ")
                       + ms(obj.get("p99_ms", 0)))
        elif name == "throughput":
            cur = vals.get("throughput_rps",
                           hist.get("Throughput_rps"))
            out.append("throughput "
                       + (f"{float(cur):g}" + " < " if cur is not None
                          else "under ")
                       + f"{float(obj.get('min_throughput_rps', 0)):g}"
                       " rps")
        elif name == "frontier_lag":
            cur = vals.get("frontier_lag_ms",
                           hist.get("Frontier_lag_ms"))
            out.append("frontier lag "
                       + (ms(cur) + " > " if cur else "over ")
                       + ms(float(obj.get("max_frontier_lag_s", 0))
                            * 1e3))
        else:
            out.append(name)
    return ", ".join(out) if out else "error budget burning " \
        f"{slo.get('Burn_rate_fast', 0):g}x"


def _pct(v) -> str:
    return f"{(v or 0) * 100:5.1f}%"


def render_text(report: dict) -> str:
    """Aligned plain-text rendering (the doctor CLI output)."""
    out: List[str] = []
    out.append(f"== doctor: {report.get('Graph', '?')} "
               f"(schema {report.get('Schema_version')}) ==")
    out.append(f"verdict: {report.get('Verdict')}")
    bn = report.get("Bottleneck") or {}
    if bn.get("Operator"):
        out.append("")
        out.append(f"bottleneck: {bn['Operator']}  "
                   f"score={bn.get('Score', 0):.2f}  "
                   f"verdict={bn.get('Verdict')}")
        ev = bn.get("Evidence") or {}
        if ev:
            out.append(f"  depth_frac={ev.get('depth_frac')}  "
                       f"sustained={ev.get('sustained_depth')}  "
                       f"hwm_frac={ev.get('hwm_frac')}  "
                       f"frontier_lag_ms={ev.get('frontier_lag_ms')}  "
                       f"svc_us={ev.get('service_time_us')}")
        for row in bn.get("Sinks") or []:
            if row is not bn:
                out.append(f"  sink {row.get('sink')}: "
                           f"{row.get('operator')} "
                           f"({row.get('verdict')}, "
                           f"score {row.get('score', 0):.2f})")
    attr = report.get("Attribution")
    if attr:
        out.append("")
        out.append(f"attribution ({attr.get('Traces')} traces, "
                   f"e2e p50 {attr.get('E2e_p50_ms')} ms / "
                   f"p99 {attr.get('E2e_p99_ms')} ms, "
                   f"share sum {attr.get('Share_sum')}):")
        cls = attr.get("Classes") or {}
        tail = attr.get("Classes_tail") or {}
        out.append("  class              all     tail(p90+)")
        for c in CLASSES:
            out.append(f"  {c:<17}{_pct(cls.get(c))}  {_pct(tail.get(c))}")
        ops = attr.get("Operators") or []
        if ops:
            out.append("  operator breakdown (share of traced time):")
            for row in ops[:8]:
                rc = row.get("classes") or {}
                detail = " ".join(f"{c.split('_')[-1]}={_pct(rc.get(c)).strip()}"
                                  for c in CLASSES if (rc.get(c) or 0) >= 0.0005)
                out.append(f"    {_pct(row.get('share'))}  "
                           f"{row.get('operator')}  [{detail}]")
    anoms = report.get("Anomalies") or []
    if anoms:
        out.append("")
        out.append("active regressions:")
        for a in anoms:
            out.append(f"  {a.get('series')}: {a.get('value')} outside "
                       f"{a.get('band')}")
    slo = report.get("Slo")
    if slo:
        out.append("")
        obj = ", ".join(f"{k}={v:g}" for k, v in
                        (slo.get("Objectives") or {}).items())
        out.append(f"slo [{obj}] target={slo.get('Target')}: "
                   + ("BREACHED" if slo.get("Breached") else "ok")
                   + f"  burn fast={slo.get('Burn_rate_fast', 0):g}x "
                   f"slow={slo.get('Burn_rate_slow', 0):g}x  "
                   f"budget {slo.get('Budget_burned', 0) * 100:.0f}% "
                   f"burned  episodes={slo.get('Breaches_total', 0)}")
    cons = report.get("Conservation")
    if cons:
        out.append("")
        out.append(f"conservation: balanced={cons['Balanced']} "
                   f"violations={cons['Violations']} "
                   f"final={cons['Final_check']}")
    dur = report.get("Durability")
    if dur:
        restored = dur.get("Restored_from")
        out.append(f"epochs: committed={dur['Committed_epoch']} "
                   f"commits={dur['Commits']} aborts={dur['Aborts']} "
                   f"lag={dur['Epoch_lag_s']:.1f}s "
                   f"stalled={dur['Stalled']}"
                   + (f" restored_from={restored}"
                      if restored is not None else "")
                   + (f" delta_commit_bytes="
                      f"{dur.get('Last_commit_bytes')}"
                      if dur.get("Delta") else ""))
    sched = report.get("Scheduler")
    sched_ev = report.get("Scheduler_events") or []
    if sched or sched_ev:
        out.append("")
        if sched:
            out.append(
                f"scheduler: worker={sched.get('Worker')} "
                f"fair_share={sched.get('Fair_share')} "
                f"sched_wait={sched.get('Sched_wait_s', 0):.3f}s "
                f"placements={len(sched.get('Placements') or ())}"
                + (f"  chip CONTENDED "
                   f"({sched.get('Device_holders')} holders)"
                   if sched.get("Device_contended") else ""))
            for p in sched.get("Placements") or ():
                out.append(f"  tenant {p.get('Tenant')} @ worker "
                           f"{p.get('Worker')}: {p.get('State')} "
                           f"credits={p.get('Credits')} "
                           f"prio={p.get('Priority')} "
                           f"weight={p.get('Weight')} "
                           f"devices={p.get('Devices')}")
        for e in sched_ev:
            fields = " ".join(
                f"{k}={v}" for k, v in e.items()
                if k not in ("t", "kind", "hint") and v is not None)
            out.append(f"  [{e.get('t')}] {e.get('kind')} {fields}")
            if e.get("hint"):
                out.append(f"    hint: {e['hint']}")
    arbs = report.get("Arbitrations") or []
    if arbs:
        out.append("")
        out.append("arbitrations (cross-tenant):")
        for a in arbs:
            line = f"  [{a.get('t')}] {a.get('donor')} -> " \
                   f"{a.get('victim')}: {a.get('action')}"
            if a.get("detail"):
                line += f": {a['detail']}"
            out.append(line)
    reps = report.get("Replacements") or []
    if reps:
        out.append("")
        out.append("lane replacements (online re-planning):")
        for r in reps:
            line = f"  [{r.get('t')}] {r.get('operator')}: " \
                   f"{r.get('old')} -> {r.get('new')} " \
                   f"({r.get('trigger')})"
            ev = r.get("evidence") or {}
            if ev.get("measured_ms") is not None:
                line += (f": measured {ev['measured_ms']} ms/launch vs "
                         f"rtt floor {ev.get('rtt_floor_ms')} ms, "
                         f"projected device "
                         f"{ev.get('device_rate_tps')} t/s vs host "
                         f"{ev.get('host_rate_tps')} t/s")
            out.append(line)
    heals = report.get("Replica_restarts") or []
    if heals:
        out.append("")
        out.append("replica restarts (supervised self-healing):")
        for h in heals:
            if h.get("outcome") == "escalated":
                out.append(f"  [{h.get('t')}] {h.get('node')}: heal "
                           f"ESCALATED on attempt {h.get('attempt')}: "
                           f"{h.get('error')}")
            else:
                out.append(f"  [{h.get('t')}] {h.get('node')}: attempt "
                           f"{h.get('attempt')} after "
                           f"{h.get('delay_s')}s backoff, rewound to "
                           f"epoch {h.get('epoch')} ({h.get('error')})")
    fb = report.get("Recovery_fallbacks") or []
    if fb:
        out.append("")
        out.append("recovery fallbacks (torn/missing snapshot data):")
        for e in fb:
            out.append(f"  [{e.get('t')}] epoch {e.get('epoch')} "
                       f"unreadable ({e.get('reason')}) -- fell back "
                       f"to an older fully-loadable cut")
    pressure = report.get("State_pressure") or []
    disk_full = report.get("Disk_full") or []
    if pressure or disk_full:
        out.append("")
        out.append("tiered state & disk pressure:")
        for e in disk_full:
            out.append(f"  [{e.get('t')}] epoch {e.get('epoch')} commit "
                       f"aborted: disk full -- kept last committed "
                       f"epoch, graph stayed up ({e.get('error')})")
        for e in pressure:
            if e.get("kind") == "state_pressure":
                out.append(f"  [{e.get('t')}] {e.get('node')}: shed "
                           f"{e.get('shed')} key(s) to dead letters "
                           f"(mem {e.get('mem_bytes')}B over budget "
                           f"{e.get('budget')}B)")
            else:
                out.append(f"  [{e.get('t')}] {e.get('node')}: spill "
                           f"batch of {e.get('keys')} key(s) re-warmed "
                           f"-- spill disk full ({e.get('error')})")
    tiers = report.get("State_tiers") or {}
    if tiers:
        out.append("keyed-state tiers: " + ", ".join(
            f"{t}={v['keys']} key(s)/{v['bytes']}B"
            for t, v in tiers.items()))
    hot = report.get("Hot_keys") or []
    if hot:
        out.append("hot keys: " + ", ".join(
            f"{h['operator']} key={h['key']} share={h['share']}"
            + (f" tier={h['tier']}" if h.get("tier") else "")
            for h in hot[:4]))
    hist = report.get("History")
    if hist:
        out.append(f"history: {hist['Ticks']} ticks, last sink rate "
                   f"{hist['Throughput_rps']} results/s, e2e p99 "
                   f"{hist['E2e_p99_us']} us, frontier lag "
                   f"{hist['Frontier_lag_ms']} ms"
                   + (f", rss {hist['Mem_kb']:.0f} KiB"
                      f" (pool {hist.get('Pool_kb') or 0:.0f} KiB)"
                      if hist.get("Mem_kb") else ""))
    tail = report.get("Flight_tail") or []
    if tail:
        out.append("")
        out.append("flight tail:")
        for e in tail:
            fields = " ".join(f"{k}={v}" for k, v in e.items()
                              if k not in ("t", "kind"))
            out.append(f"  [{e.get('t')}] {e.get('kind')} {fields}")
    return "\n".join(out)
