"""Critical-path latency attribution (docs/OBSERVABILITY.md
"Diagnosis plane").

The telemetry plane closes sampled end-to-end traces with one
``(operator, t_arrive, t_done)`` hop stamp per operator crossed plus a
``@device``-suffixed hop spanning each device submit -> result-on-host
crossing (operators/tpu/win_seq_tpu.py).  This module folds those
records into an *attribution*: every microsecond of a traced e2e
interval is assigned to exactly one hop class --

* ``service``          -- host time inside some operator's ``svc``;
* ``queueing``         -- time covered by no hop: parked in a channel
                          (plus the upstream batch-flush skew) before
                          the next operator's arrival;
* ``device_transport`` -- the per-launch transport floor slice of a
                          device hop (``rtt_floor_ms`` from the
                          placement planner);
* ``device_compute``   -- the rest of the device hop.

Attribution is an interval sweep: the trace's ``[0, e2e]`` span is cut
at every hop boundary and each elementary slice goes to the *innermost*
covering hop (the one with the latest arrival -- under LEVEL2 fusion an
upstream segment's hop interval contains its downstream segments'
inline work, so innermost == the segment actually executing).  Slices
covered by no hop are queueing, charged to the operator whose hop
starts next.  By construction the per-class totals sum to exactly the
traced e2e time, which is what makes the breakdown table's shares sum
to ~100%.

Aggregation keeps a bounded ring of per-trace breakdowns and reports
two cohorts: *all* traces (the p50-ish view) and the *tail* cohort
(traces at or above the p90 e2e -- what the p99 is made of).
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

from ..audit.ledger import _op_of

# hop classes, in display order
CLASSES = ("service", "queueing", "wire", "device_transport",
           "device_compute")
# suffix the device engines stamp on their dispatcher hops
DEVICE_HOP_SUFFIX = "@device"
# suffix the shuffle transport stamps on cross-worker crossings
# (distributed/wire.rebuild_trace): the whole hop is wire residency
WIRE_HOP_SUFFIX = "@wire"
# per-trace breakdowns kept for aggregation
MAX_TRACES = 256
# operator rows kept in the breakdown table
MAX_OPERATOR_ROWS = 16


def trace_breakdown(rec: dict,
                    rtt_floor_ms: Optional[float] = None) -> Optional[dict]:
    """Attribute one serialized trace record (``Trace_records`` row:
    ``{"e2e_ms", "hops": [[name, arrive_ms, done_ms], ...]}``) into
    per-class / per-operator milliseconds.  Returns None for records
    with no usable span."""
    try:
        if rec.get("partial"):
            # producer-side fragment of a trace that crossed a wire
            # edge: its span never closed at a sink HERE, so folding
            # it would double-charge the hops the consumer-side record
            # (same trace id) already accounts for.  The merge stitches
            # fragments back into the closed record instead.
            return None
        e2e = float(rec.get("e2e_ms") or 0.0)
        raw_hops = rec.get("hops") or []
    except AttributeError:
        return None
    if e2e <= 0.0:
        return None
    ivs = []  # (arrive, done, operator, kind: ""|"device"|"wire")
    for hop in raw_hops:
        try:
            name, a, d = hop[0], float(hop[1]), float(hop[2])
        except (TypeError, ValueError, IndexError):
            continue
        name = str(name)
        if name.endswith(DEVICE_HOP_SUFFIX):
            kind = "device"
            op = _op_of(name[:-len(DEVICE_HOP_SUFFIX)])
        elif name.endswith(WIRE_HOP_SUFFIX):
            kind = "wire"
            op = _op_of(name[:-len(WIRE_HOP_SUFFIX)])
        else:
            kind = ""
            op = _op_of(name)
        # clamp into the traced span: fused upstream segments stamp
        # their hops moments AFTER the sink closes (entries unwind
        # outward), so done can exceed e2e by scheduler noise
        a = min(max(0.0, a), e2e)
        d = min(max(a, d), e2e)
        ivs.append((a, d, op, kind))
    per_class: Dict[str, float] = dict.fromkeys(CLASSES, 0.0)
    per_op: Dict[str, Dict[str, float]] = {}

    def charge(op: str, cls: str, ms: float) -> None:
        per_class[cls] += ms
        row = per_op.get(op)
        if row is None:
            row = per_op[op] = dict.fromkeys(CLASSES, 0.0)
        row[cls] += ms

    starts = sorted((a, op) for a, _d, op, _dev in ivs)
    bounds = sorted({0.0, e2e,
                     *(a for a, _d, _o, _v in ivs),
                     *(d for _a, d, _o, _v in ivs)})
    for t1, t2 in zip(bounds, bounds[1:]):
        dur = t2 - t1
        if dur <= 0.0:
            continue
        covering = [iv for iv in ivs if iv[0] <= t1 and iv[1] >= t2]
        if covering:
            # innermost: latest arrival (a device/wire hop wins a tie
            # -- it is the more specific statement about the time)
            a, d, op, kind = max(covering,
                                 key=lambda iv: (iv[0], bool(iv[3])))
            if kind == "device":
                hop_ms = max(d - a, 1e-9)
                tfrac = min(1.0, (rtt_floor_ms or 0.0) / hop_ms)
                charge(op, "device_transport", dur * tfrac)
                charge(op, "device_compute", dur * (1.0 - tfrac))
            elif kind == "wire":
                charge(op, "wire", dur)
            else:
                charge(op, "service", dur)
        else:
            # gap: queueing before the next hop to start (every arrival
            # is a sweep boundary, so none lies strictly inside the
            # slice); a trailing gap belongs to the close path
            nxt = next((op for a, op in starts if a >= t2 - 1e-9), None)
            charge(nxt if nxt is not None else "(close)", "queueing", dur)
    return {"e2e_ms": e2e, "classes": per_class, "operators": per_op}


def _shares(rows: List[dict]) -> dict:
    total = sum(r["e2e_ms"] for r in rows)
    if total <= 0.0:
        return {c: 0.0 for c in CLASSES}
    return {c: round(sum(r["classes"][c] for r in rows) / total, 4)
            for c in CLASSES}


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class AttributionAccumulator:
    """Bounded ring of per-trace breakdowns + the report-time fold."""

    def __init__(self, maxlen: int = MAX_TRACES):
        self._rows: deque = deque(maxlen=max(1, maxlen))

    def add(self, breakdown: Optional[dict]) -> None:
        if breakdown is not None:
            self._rows.append(breakdown)

    def __len__(self) -> int:
        return len(self._rows)

    def block(self) -> Optional[dict]:
        """The stats-JSON ``Attribution`` block: e2e percentiles of the
        folded traces, per-class shares for the all-traces and tail
        cohorts, and the per-operator breakdown table (share of total
        traced time, split by class).  Shares are fractions of traced
        e2e time and sum to ~1.0 per cohort."""
        rows = list(self._rows)
        if not rows:
            return None
        e2es = sorted(r["e2e_ms"] for r in rows)
        p90 = _percentile(e2es, 0.90)
        tail = [r for r in rows if r["e2e_ms"] >= p90] or rows
        total = sum(r["e2e_ms"] for r in rows)
        ops: Dict[str, Dict[str, float]] = {}
        for r in rows:
            for op, cls_ms in r["operators"].items():
                agg = ops.setdefault(op, dict.fromkeys(CLASSES, 0.0))
                for c in CLASSES:
                    agg[c] += cls_ms[c]
        op_rows = []
        for op, cls_ms in ops.items():
            ms = sum(cls_ms.values())
            op_rows.append({
                "operator": op,
                "share": round(ms / total, 4) if total else 0.0,
                "classes": {c: round(cls_ms[c] / total, 4) if total
                            else 0.0 for c in CLASSES},
            })
        op_rows.sort(key=lambda r: -r["share"])
        classes = _shares(rows)
        return {
            "Traces": len(rows),
            "E2e_p50_ms": round(_percentile(e2es, 0.50), 3),
            "E2e_p99_ms": round(_percentile(e2es, 0.99), 3),
            "Classes": classes,
            "Classes_tail": _shares(tail),
            "Operators": op_rows[:MAX_OPERATOR_ROWS],
            "Share_sum": round(sum(classes.values()), 4),
        }


def attribution_from_stats(stats: dict) -> Optional[dict]:
    """Offline fallback: rebuild the Attribution block straight from a
    stats-JSON dump's ``Trace_records`` (older dumps carry no
    precomputed ``Diagnosis.Attribution``).  The rtt floor comes from
    the recorded placement decisions when any carry one."""
    recs = stats.get("Trace_records") or []
    rtt = None
    for p in stats.get("Placements") or []:
        if isinstance(p, dict) and p.get("rtt_floor_ms") is not None:
            rtt = float(p["rtt_floor_ms"])
            break
    acc = AttributionAccumulator()
    for rec in recs:
        acc.add(trace_breakdown(rec, rtt))
    return acc.block()
