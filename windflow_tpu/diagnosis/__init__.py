"""Diagnosis plane: critical-path latency attribution, backpressure
root-cause analysis, rolling gauge history, online regression
detection and the doctor report (docs/OBSERVABILITY.md "Diagnosis
plane").

The telemetry plane (PR 7) measures and the audit plane (PR 9)
verifies; this package *explains*: which hop class (service /
queueing / device transport / device compute) each traced microsecond
went to, which operator is the root cause behind a pressured sink,
how the gauges trended, and whether any series just broke its
EWMA+MAD band.  One :class:`DiagnosisPlane` per graph
(``RuntimeConfig.diagnosis``, on by default), ticking on the existing
monitor/auditor cadences; :func:`build_report` is the pure fold every
surface shares (``PipeGraph.explain()``, the dashboard ``/explain``
endpoint, the ``python -m windflow_tpu.doctor`` CLI).
"""
from .anomaly import RegressionMonitor
from .attribution import (AttributionAccumulator, attribution_from_stats,
                          trace_breakdown)
from .bottleneck import bottleneck_from_stats, find_bottlenecks
from .history import GaugeHistory
from .plane import DiagnosisPlane
from .report import build_report, render_text
from .topology import operator_edges

__all__ = [
    "DiagnosisPlane",
    "build_report", "render_text",
    "trace_breakdown", "AttributionAccumulator", "attribution_from_stats",
    "find_bottlenecks", "bottleneck_from_stats",
    "GaugeHistory", "RegressionMonitor",
    "operator_edges",
]
