"""DiagnosisPlane: the per-graph diagnosis coordinator
(docs/OBSERVABILITY.md "Diagnosis plane").

One per started PipeGraph when ``RuntimeConfig.diagnosis`` is on (the
default).  It owns no thread: ``maybe_tick`` rides the cadences that
already exist -- the monitoring reporter (1 Hz), the auditor pass
(``audit_interval_s``) and on-demand ``PipeGraph.explain()`` calls --
rate-limited to ``diagnosis_interval_s`` so stacked callers cannot
multiply the cost.  A tick is pure observation: counter deltas, gauge
reads, and folding traces the telemetry plane already closed.

Per tick it

* drains newly-closed trace records into the critical-path
  :class:`~windflow_tpu.diagnosis.attribution.AttributionAccumulator`,
* appends one row to the rolling :class:`GaugeHistory` ring,
* feeds the throughput / e2e-p99 / frontier-lag series through the
  EWMA+MAD :class:`RegressionMonitor` (band breaks become
  ``regression`` flight events),
* re-runs the backpressure root-cause walk over the live gauges
  (keeping a per-operator EWMA of depth_frac so the verdict survives
  the end-of-run drain),
* publishes the ``Diagnosis`` and ``History`` stats-JSON blocks.

The elastic controller reads :meth:`bottleneck_score` as its
attribution-aware scale signal (docs/ELASTIC.md).
"""
from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Dict, Optional

from .anomaly import RegressionMonitor
from .attribution import AttributionAccumulator, trace_breakdown
from .bottleneck import find_bottlenecks
from .history import GaugeHistory
from .topology import operator_edges

# EWMA weight of the per-operator sustained depth_frac signal
SUSTAINED_ALPHA = 0.35
# trace keys remembered for dedup (the stats ring holds 16)
SEEN_TRACES = 64
# a closed trace is folded only once it is at least this old: fused
# upstream segments stamp their hops moments AFTER the sink closes
# (entries unwind outward), and an eager fold would charge their
# service time to queueing -- and the dedup key would freeze the
# truncated record forever
TRACE_SETTLE_S = 0.05
# anomaly series -> breach direction
WATCHED = (("throughput_rps", "low"),
           ("e2e_p99_us", "high"),
           ("frontier_lag_ms", "high"))


class DiagnosisPlane:
    def __init__(self, graph):
        self.graph = graph
        cfg = graph.config
        self.interval_s = max(0.05, float(cfg.diagnosis_interval_s))
        self.history = GaugeHistory(cfg.history_len)
        self.attribution = AttributionAccumulator()
        self.monitor = RegressionMonitor(k=cfg.anomaly_band_k,
                                         warmup=cfg.anomaly_warmup)
        # SLO plane (slo/; docs/OBSERVABILITY.md "SLO plane"): the
        # burn-rate tracker rides this tick -- no thread of its own
        self.slo = None
        if getattr(cfg, "slo", None) is not None:
            from ..slo import SloTracker
            self.slo = SloTracker(cfg.slo)
        self.edges = operator_edges(graph)
        self.ticks = 0
        self._lock = threading.Lock()
        self._last_tick = 0.0
        self._seen = set()
        self._seen_order: deque = deque(maxlen=SEEN_TRACES)
        self._last_sink_inputs: Optional[int] = None
        self._last_t: Optional[float] = None
        self._sustained: Dict[str, float] = {}
        self._scores: Dict[str, float] = {}
        self._rtt_ms: Optional[float] = None
        self._rtt_probed = False
        self._sink_ops = None

    # -- signals for other planes --------------------------------------
    def bottleneck_score(self, operator: str) -> float:
        """Pressure score of ``operator`` from the latest walk (0.0 =
        unknown / unpressured) -- the elastic controller's
        attribution-aware scale signal."""
        return self._scores.get(operator, 0.0)

    # -- tick ----------------------------------------------------------
    def maybe_tick(self, force: bool = False) -> bool:
        now = _time.monotonic()
        if not force and now - self._last_tick < self.interval_s:
            return False
        with self._lock:
            if not force and now - self._last_tick < self.interval_s:
                return False
            self._last_tick = now
            try:
                self._tick(now)
            except Exception:  # pragma: no cover - diagnosis must
                import traceback  # never take the graph down
                traceback.print_exc()
        return True

    def _rtt_floor_ms(self) -> Optional[float]:
        """Transport floor for the device transport/compute split:
        the planner's recorded decisions first, the (cached) probe as
        a fallback once a device hop actually shows up."""
        if self._rtt_ms is not None:
            return self._rtt_ms
        for p in getattr(self.graph, "placements", None) or []:
            if isinstance(p, dict) and p.get("rtt_floor_ms") is not None:
                self._rtt_ms = float(p["rtt_floor_ms"])
                return self._rtt_ms
        if not self._rtt_probed:
            self._rtt_probed = True
            try:
                from ..graph.planner import rtt_floor_ms
                self._rtt_ms = float(rtt_floor_ms())
            except Exception:
                self._rtt_ms = None
        return self._rtt_ms

    def _drain_traces(self) -> None:
        stats = self.graph.stats
        pairs = list(stats.trace_records)
        # t_end stamps share perf_counter with the hop stamps
        cutoff = _time.perf_counter() - TRACE_SETTLE_S
        fresh = []
        for ctx, t_end in pairs:
            if t_end > cutoff:
                continue  # still unwinding; next tick folds it
            key = (id(ctx), t_end)
            if key in self._seen:
                continue
            fresh.append((key, ctx, t_end))
        rtt = None
        if fresh:
            rtt = self._rtt_floor_ms()
        for key, ctx, t_end in fresh:
            if len(self._seen_order) == self._seen_order.maxlen:
                self._seen.discard(self._seen_order[0])
            self._seen.add(key)
            self._seen_order.append(key)
            self.attribution.add(trace_breakdown(ctx.to_dict(t_end), rtt))

    def _operator_rows(self):
        """Minimal stats-JSON-shaped operator rows straight from the
        live records (gauge-grade reads; the lock only guards the
        records dict against a concurrent rescale registration)."""
        stats = self.graph.stats
        with stats.lock:
            items = [(name, list(reps))
                     for name, reps in stats.records.items()]
        rows = []
        for name, reps in items:
            rows.append({"Operator_name": name, "Replicas": [
                {"Queue_depth": r.queue_depth,
                 "Queue_high_watermark": r.queue_high_watermark,
                 "Frontier_lag_ms": r.frontier_lag_ms,
                 "Credit_wait_s": r.credit_wait_s,
                 "Service_time_usec": r.service_time_us}
                for r in reps]})
        return rows

    def _gauges(self) -> Dict[str, float]:
        from ..monitoring.stats import get_mem_usage_kb
        from ..telemetry.histogram import LogHistogram
        g = self.graph
        stats = g.stats
        if self._sink_ops is None:
            outs = {a for a, _b, _k in self.edges}
            named = {n for e in self.edges for n in e[:2]}
            self._sink_ops = {n for n in named if n not in outs}
        sink_inputs = 0
        with stats.lock:
            recs = [(name, list(reps))
                    for name, reps in stats.records.items()]
            e2e = None
            if stats.histograms:
                e2e = LogHistogram.merged(
                    r.e2e_hist for _n, rs in recs for r in rs)
                if stats.e2e_extra is not None:
                    e2e.merge_from(stats.e2e_extra)
        depth = wait = lag = 0.0
        for name, reps in recs:
            for r in reps:
                depth += r.queue_depth
                wait += r.credit_wait_s
                if r.frontier_lag_ms > lag:
                    lag = r.frontier_lag_ms
            if name in self._sink_ops:
                sink_inputs += sum(r.inputs_received for r in reps)
        now = _time.monotonic()
        tput = 0.0
        if self._last_t is not None and now > self._last_t:
            tput = max(0, sink_inputs - (self._last_sink_inputs or 0)) \
                / (now - self._last_t)
        self._last_t = now
        self._last_sink_inputs = sink_inputs
        # ColumnPool arena occupancy: memory-pressure evidence next to
        # the process RSS (docs/OBSERVABILITY.md "SLO plane")
        pool = getattr(g, "buffer_pool", None)
        ps = pool.stats() if pool is not None else None
        return {
            # results/s: sink items (one TupleBatch counts once), the
            # dashboard result-rate unit -- NOT tuples/s on the batch
            # plane (see diagnosis/history.py SERIES)
            "throughput_rps": round(tput, 1),
            "e2e_p50_us": e2e.percentile(0.50) if e2e is not None else 0.0,
            "e2e_p99_us": e2e.percentile(0.99) if e2e is not None else 0.0,
            "frontier_lag_ms": round(lag, 1),
            "queue_depth": depth,
            "credit_wait_s": round(wait, 3),
            "mem_kb": get_mem_usage_kb(),
            "pool_kb": (ps["bytes"] // 1024) if ps else 0,
            "pool_buffers": ps["buffers"] if ps else 0,
        }

    def _tick(self, now: float) -> None:
        g = self.graph
        g.refresh_gauges()
        self._drain_traces()
        rows = self._operator_rows()
        gauges = self._gauges()
        wall = _time.time()
        self.history.append(wall, gauges)
        for series, direction in WATCHED:
            ev = self.monitor.update(series, gauges[series], direction,
                                     wall)
            if ev is not None:
                kind = ev.pop("event")
                g.flight.record(kind, **ev)
        # SLO plane: judge this gauge row against the declared
        # objectives and advance the burn-rate windows; breach /
        # recovery episodes land in the flight ring
        if self.slo is not None:
            ev = self.slo.update(wall, gauges)
            if ev is not None:
                kind = ev.pop("event")
                g.flight.record(kind, **ev)
            g.stats.set_slo(self.slo.block())
        pool = getattr(g, "buffer_pool", None)
        g.stats.set_pool({
            "Buffers": gauges["pool_buffers"],
            "Bytes": gauges["pool_kb"] * 1024,
            "Hits": pool.hits, "Misses": pool.misses,
        } if pool is not None else None)
        cap = g.config.queue_capacity
        for row in rows:
            name = row["Operator_name"]
            reps = row["Replicas"]
            d = sum(r["Queue_depth"] for r in reps) \
                / (max(1, cap) * max(1, len(reps)))
            prev = self._sustained.get(name, 0.0)
            self._sustained[name] = prev + SUSTAINED_ALPHA * (
                min(1.0, d) - prev)
        attribution = self.attribution.block()
        bottleneck = find_bottlenecks(rows, self.edges, cap,
                                      self._sustained, attribution)
        self._scores = {r["operator"]: r["score"]
                        for r in bottleneck.get("Sinks", [])
                        if r.get("operator")}
        # online re-planner (graph/replanner.py): decision-only here --
        # measures launch deltas and queues any lane flip onto its own
        # worker thread (a flip quiesces the graph for seconds and
        # must not stall this cadence)
        rp = getattr(g, "replanner", None)
        if rp is not None:
            rp.tick()
        self.ticks += 1
        block = {
            "Ticks": self.ticks,
            "Queue_capacity": cap,
            "Rtt_floor_ms": self._rtt_ms,
            "Bottleneck": bottleneck,
            "Attribution": attribution,
            "Anomalies": self.monitor.active(),
            "Anomalies_total": self.monitor.opened_total,
            "Sustained_depth": {k: round(v, 4)
                                for k, v in self._sustained.items()
                                if v >= 0.005},
        }
        g.stats.set_diagnosis(block, self.history.block())
