"""Online regression/anomaly detection: EWMA+MAD bands over gauge
series (docs/OBSERVABILITY.md "Diagnosis plane").

Per watched series the monitor keeps two exponentially-weighted
estimates -- the level (EWMA of the value) and the spread (EWMA of the
absolute deviation, the streaming stand-in for a MAD) -- and a band of
``level +/- k * 1.4826 * spread`` (the MAD-to-sigma constant, so ``k``
reads in sigmas for roughly-normal noise).  The spread is floored at a
fraction of the level so a perfectly steady warmup cannot produce a
zero-width band that flags the first wobble.

Direction matters: throughput regresses *below* its band, latency and
frontier lag regress *above*.  A breach must persist ``BREACH_TICKS``
consecutive ticks to open an episode (debounce) and the series must
read in-band ``CLEAR_TICKS`` consecutive ticks to close it.  While an
episode is open the baselines adapt at ``alpha / 8`` -- slow enough
that the flag survives long enough to be seen, fast enough that a
legitimate new operating point (a rescale, a workload shift) re-centers
the band instead of alarming forever.

Episodes surface as ``FlightRecorder("regression")`` events (opened)
and ``regression_cleared`` (closed), the ``Diagnosis.Anomalies`` list
in the stats JSON, and the ``windflow_regressions_active`` gauge on
``/metrics``.
"""
from __future__ import annotations

from typing import Dict, List, Optional

# MAD -> sigma for normal noise
MAD_SIGMA = 1.4826
# consecutive out-of-band ticks before an episode opens
BREACH_TICKS = 2
# consecutive in-band ticks before it closes
CLEAR_TICKS = 3
# spread floor as a fraction of the level (plus an absolute epsilon)
SPREAD_FLOOR_FRAC = 0.05


class _SeriesState:
    __slots__ = ("level", "spread", "n", "active", "breaches", "clears",
                 "since", "last_value", "last_band")

    def __init__(self):
        self.level = 0.0
        self.spread = 0.0
        self.n = 0
        self.active = False
        self.breaches = 0
        self.clears = 0
        self.since = 0.0
        self.last_value = 0.0
        self.last_band = (0.0, 0.0)


class RegressionMonitor:
    """EWMA+MAD band state over named series.  ``update`` returns an
    event dict when an episode opens or closes, else None."""

    def __init__(self, k: float = 4.0, warmup: int = 12,
                 alpha: float = 0.2):
        self.k = max(0.5, float(k))
        self.warmup = max(2, int(warmup))
        self.alpha = min(1.0, max(0.01, float(alpha)))
        self._state: Dict[str, _SeriesState] = {}
        self.opened_total = 0

    def _band(self, st: _SeriesState) -> tuple:
        spread = max(st.spread,
                     SPREAD_FLOOR_FRAC * abs(st.level), 1e-9)
        w = self.k * MAD_SIGMA * spread
        return (st.level - w, st.level + w)

    def update(self, name: str, value: float, direction: str,
               now: float) -> Optional[dict]:
        """``direction``: 'low' flags a value below the band
        (throughput), 'high' a value above it (latency, lag)."""
        st = self._state.get(name)
        if st is None:
            st = self._state[name] = _SeriesState()
        st.last_value = value
        if st.n < self.warmup:
            # prime the baselines; the first sample seeds them outright
            a = 1.0 if st.n == 0 else self.alpha
            st.level += a * (value - st.level)
            st.spread += a * (abs(value - st.level) - st.spread)
            st.n += 1
            st.last_band = self._band(st)
            return None
        lo, hi = self._band(st)
        st.last_band = (lo, hi)
        breached = value < lo if direction == "low" else value > hi
        event = None
        if breached:
            st.clears = 0
            st.breaches += 1
            if not st.active and st.breaches >= BREACH_TICKS:
                st.active = True
                st.since = now
                self.opened_total += 1
                event = {"event": "regression", "series": name,
                         "value": round(value, 3),
                         "band": [round(lo, 3), round(hi, 3)],
                         "direction": direction}
        else:
            st.breaches = 0
            if st.active:
                st.clears += 1
                if st.clears >= CLEAR_TICKS:
                    st.active = False
                    event = {"event": "regression_cleared", "series": name,
                             "value": round(value, 3)}
            st.clears = 0 if not st.active else st.clears
        # adapt: full alpha in-band, alpha/8 on any breached tick or
        # open episode -- a full-rate update on the FIRST breach tick
        # would re-center the band past the step before the debounce
        # tick can confirm it (the episode would never open)
        a = self.alpha / 8.0 if (st.active or breached) else self.alpha
        st.level += a * (value - st.level)
        st.spread += a * (abs(value - st.level) - st.spread)
        st.n += 1
        return event

    def active(self) -> List[dict]:
        """Currently-open episodes (the ``Anomalies`` block)."""
        out = []
        for name, st in self._state.items():
            if st.active:
                out.append({"series": name,
                            "value": round(st.last_value, 3),
                            "band": [round(st.last_band[0], 3),
                                     round(st.last_band[1], 3)],
                            "since": round(st.since, 3)})
        return out
