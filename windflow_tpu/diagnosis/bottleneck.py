"""Backpressure root-cause walk: name the single dominant bottleneck
operator per sink (docs/OBSERVABILITY.md "Diagnosis plane").

Bounded queues make backpressure *cascade*: once the true bottleneck's
inbound queue fills, its producers block on put, their queues fill, and
within seconds every edge upstream of the slow operator reads
pressured.  The walk therefore does not pick the *most* pressured
operator -- it picks the most **downstream** pressured ancestor of each
sink: the operator whose inbound edge is backed up while everything
below it is starved is where the time is actually going.

Evidence per operator (aggregated over replicas, all of it already in
the stats JSON -- the walk is a pure function usable live, on a
dashboard report, or on an offline dump):

* ``depth_frac``     -- inbound channel depth / bounded capacity (the
                        live signal);
* ``sustained_depth``-- the diagnosis plane's EWMA of depth_frac over
                        its ticks (survives the end-of-run drain, so a
                        post-run dump still names the operator);
* ``lag_norm``       -- frontier lag normalized against 1 s (the audit
                        plane's "held back while work was pending").

``score = max(depth, 0.9*sustained, 0.7*lag)``; an operator is
*pressured* at score >= PRESSURE_MIN.  The peak-depth high-watermark
is reported as evidence but deliberately kept OUT of the score: every
upstream microbatch flush legitimately spikes a healthy consumer's
inbound queue to capacity, so a cumulative peak would name fast sinks
over the operator that is actually slow.  No pressured ancestor means
the pipeline is keeping up -- the verdict is ``input_bound`` and the
sink's source is named instead (the stream is the limit, not the
graph), unless the critical-path attribution shows one operator
holding the traced time (``service_bound``).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .topology import ancestors_of, depth_ranks, sinks_of, sources_of

# score at/above which an operator counts as pressured
PRESSURE_MIN = 0.15
# score from which the verdict upgrades from "mild" to "backpressure"
PRESSURE_HIGH = 0.5
# frontier lag that saturates the lag evidence term (ms)
LAG_REF_MS = 1000.0
# attributed service share from which an operator is service-bound
# (the no-queue evidence: a fully-fused chain has no channels to back
# up, but the critical-path attribution still names where time goes)
SERVICE_BOUND_SHARE = 0.4


def operator_evidence(op: dict, capacity: int,
                      sustained: Optional[float] = None) -> dict:
    """Fold one stats-JSON operator row into the evidence dict."""
    reps = op.get("Replicas") or []
    cap = max(1, int(capacity or 1)) * max(1, len(reps))
    depth = sum(int(r.get("Queue_depth", 0) or 0) for r in reps)
    hwm = max((int(r.get("Queue_high_watermark", 0) or 0)
               for r in reps), default=0)
    lag = max((float(r.get("Frontier_lag_ms", 0) or 0.0)
               for r in reps), default=0.0)
    wait = sum(float(r.get("Credit_wait_s", 0) or 0.0) for r in reps)
    svc = [float(r.get("Service_time_usec", 0) or 0.0) for r in reps]
    lat = (op.get("Latency") or {}).get("service") or {}
    return {
        "depth": depth,
        "depth_frac": round(min(1.0, depth / cap), 4),
        "hwm_frac": round(min(1.0, hwm / max(1, int(capacity or 1))), 4),
        "sustained_depth": round(float(sustained or 0.0), 4),
        "frontier_lag_ms": round(lag, 1),
        "credit_wait_s": round(wait, 3),
        "service_time_us": round(sum(svc) / len(svc), 1) if svc else 0.0,
        "service_p99_us": lat.get("p99_us", 0.0),
    }


def pressure_score(ev: dict) -> float:
    lag_norm = min(1.0, ev["frontier_lag_ms"] / LAG_REF_MS)
    return round(max(ev["depth_frac"],
                     0.9 * ev["sustained_depth"],
                     0.7 * lag_norm), 4)


def find_bottlenecks(operators: List[dict], edges: List[List[str]],
                     capacity: int,
                     sustained: Optional[Dict[str, float]] = None,
                     attribution: Optional[dict] = None) -> dict:
    """The ``Diagnosis.Bottleneck`` block: one row per sink (most
    downstream pressured ancestor, or input_bound) plus the dominant
    row overall.  When no queue evidence exists (nothing pressured --
    e.g. the whole chain fused into one replica) the critical-path
    ``attribution`` breaks the tie: an operator holding >=
    ``SERVICE_BOUND_SHARE`` of the traced time is named
    ``service_bound``."""
    sustained = sustained or {}
    by_name = {op.get("Operator_name", ""): op for op in operators}
    evidence = {name: operator_evidence(op, capacity, sustained.get(name))
                for name, op in by_name.items()}
    scores = {name: pressure_score(ev) for name, ev in evidence.items()}
    ranks = depth_ranks(edges)
    rows = []
    for sink in sinks_of(edges, by_name):
        cands = [n for n in ancestors_of(edges, sink) if n in scores]
        pressured = [n for n in cands if scores[n] >= PRESSURE_MIN]
        if pressured:
            # most downstream pressured ancestor; score breaks rank ties
            best = max(pressured,
                       key=lambda n: (ranks.get(n, 0), scores[n]))
            verdict = ("backpressure" if scores[best] >= PRESSURE_HIGH
                       else "mild_pressure")
            rows.append({"sink": sink, "operator": best,
                         "score": scores[best], "verdict": verdict,
                         "evidence": evidence[best]})
        else:
            srcs = [s for s in sources_of(edges, by_name) if s in cands]
            src = max(srcs, key=lambda n: scores.get(n, 0.0), default=None)
            rows.append({"sink": sink, "operator": src,
                         "score": scores.get(src, 0.0) if src else 0.0,
                         "verdict": "input_bound",
                         "evidence": evidence.get(src) if src else None})
    top = max((r for r in rows if r["verdict"] != "input_bound"),
              key=lambda r: r["score"], default=None)
    if top is None and attribution:
        # no queue evidence anywhere: fall back to where the traced
        # time actually went (excluding pure queueing rows)
        cand = next((r for r in attribution.get("Operators") or []
                     if (r.get("classes") or {}).get("queueing", 0.0)
                     < r.get("share", 0.0)), None)
        if cand and cand.get("share", 0.0) >= SERVICE_BOUND_SHARE:
            top = {"sink": None, "operator": cand["operator"],
                   "score": round(cand["share"], 4),
                   "verdict": "service_bound",
                   "evidence": {"attributed_share": cand["share"],
                                "classes": cand.get("classes")}}
            rows = rows + [top]
    if top is None:
        top = max(rows, key=lambda r: r["score"], default=None)
    return {
        "Sinks": rows,
        "Operator": top["operator"] if top else None,
        "Score": top["score"] if top else 0.0,
        "Verdict": top["verdict"] if top else "no_data",
        "Evidence": top["evidence"] if top else None,
    }


def bottleneck_from_stats(stats: dict) -> Optional[dict]:
    """Offline fallback: rebuild the Bottleneck block from a stats-JSON
    dump (uses the dump's own Topology and Queue_capacity when present;
    tolerates their absence in older dumps)."""
    operators = stats.get("Operators")
    if not operators:
        return None
    diag = stats.get("Diagnosis") or {}
    topo = stats.get("Topology") or {}
    from ..core.basic import DEFAULT_QUEUE_CAPACITY
    cap = int(diag.get("Queue_capacity") or DEFAULT_QUEUE_CAPACITY)
    sustained = diag.get("Sustained_depth") or {}
    from .attribution import attribution_from_stats
    attribution = diag.get("Attribution") or attribution_from_stats(stats)
    return find_bottlenecks(operators, topo.get("Edges") or [],
                            cap, sustained, attribution)
