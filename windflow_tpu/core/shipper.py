"""Shipper: push interface for Source / FlatMap user logic.

Re-design of reference ``wf/shipper.hpp`` (push :85-103).  The reference
wraps ``ff_send_out``; here the shipper appends to the emitting node's
out-buffer (which the runtime flushes through the operator's emitter as
a micro-batch -- the TPU-first adaptation of per-tuple sends).
"""
from __future__ import annotations

from typing import Any, Callable


class Shipper:
    __slots__ = ("_sink", "delivered")

    def __init__(self, sink: Callable[[Any], None]):
        self._sink = sink
        self.delivered = 0

    def push(self, item: Any) -> None:
        self._sink(item)
        self.delivered += 1

    # reference exposes the count (shipper.hpp getNumDelivered)
    def num_delivered(self) -> int:
        return self.delivered
