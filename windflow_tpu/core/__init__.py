"""L1 core abstractions (reference wf/ L1: SURVEY.md §2.1)."""
from .basic import (Mode, WinType, OptLevel, RoutingMode, Pattern, WinEvent,
                    OrderingMode, Role, WinOperatorConfig, RuntimeConfig,
                    DurabilityConfig, ElasticSpec,
                    DEFAULT_BATCH_SIZE_TB, current_time_usecs)
from .tuples import WFRecord, BasicRecord, TupleBatch, EOS
from .window import TriggererCB, TriggererTB, Window, classify_cb, classify_tb
from .archive import StreamArchive
from .flatfat import FlatFAT
from .iterable import Iterable
from .shipper import Shipper
from .context import RuntimeContext, LocalStorage
from .meta import arity, is_rich, with_context, default_hash
from .expr import Expr, F
from . import win_assign

__all__ = [
    "Mode", "WinType", "OptLevel", "RoutingMode", "Pattern", "WinEvent",
    "OrderingMode", "Role", "WinOperatorConfig", "RuntimeConfig",
    "DurabilityConfig",
    "ElasticSpec",
    "DEFAULT_BATCH_SIZE_TB", "current_time_usecs",
    "WFRecord", "BasicRecord", "TupleBatch", "EOS",
    "TriggererCB", "TriggererTB", "Window", "classify_cb", "classify_tb",
    "StreamArchive", "FlatFAT", "Iterable", "Shipper",
    "RuntimeContext", "LocalStorage",
    "arity", "is_rich", "with_context", "default_hash", "win_assign",
    "Expr", "F",
]
