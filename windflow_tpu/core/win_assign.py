"""Distributed window-id assignment arithmetic.

These pure functions reproduce -- bit-exactly, since the determinism
oracles depend on them -- the gwid/initial-id math the reference embeds
in its hot loops:

* ``first_gwid_key`` / ``initial_id``: win_seq.hpp:348-357
* last/first containing window: win_seq.hpp:381-411, wf_nodes.hpp:156-181
* WF worker multicast set: wf_nodes.hpp:182-191
* PLQ result renumbering: win_seq.hpp:483-487

They are dependency-free and unit-tested directly (SURVEY.md §4
"implication": the reference never unit-tests these; we do).
"""
from __future__ import annotations

import math
from typing import List, Tuple

from .basic import Role, WinOperatorConfig


def first_gwid_of_key(hashcode: int, cfg: WinOperatorConfig) -> int:
    """gwid of the first window of this key owned by this engine replica
    (win_seq.hpp:349)."""
    inner = (cfg.id_inner - (hashcode % cfg.n_inner) + cfg.n_inner) % cfg.n_inner
    outer = (cfg.id_outer - (hashcode % cfg.n_outer) + cfg.n_outer) % cfg.n_outer
    return inner * cfg.n_outer + outer


def initial_id_of_key(hashcode: int, cfg: WinOperatorConfig, role: Role) -> int:
    """Initial id/timestamp of the keyed substream reaching this replica
    (win_seq.hpp:350-357).  WLQ/REDUCE see renumbered inner streams, so
    only the inner offset applies."""
    outer = ((cfg.id_outer - (hashcode % cfg.n_outer) + cfg.n_outer) % cfg.n_outer) * cfg.slide_outer
    inner = ((cfg.id_inner - (hashcode % cfg.n_inner) + cfg.n_inner) % cfg.n_inner) * cfg.slide_inner
    if role in (Role.WLQ, Role.REDUCE):
        return inner
    return outer + inner


def gwid_of_lwid(first_gwid_key: int, lwid: int, cfg: WinOperatorConfig) -> int:
    """Translate a local window id to the global one (win_seq.hpp:420)."""
    return first_gwid_key + lwid * cfg.n_outer * cfg.n_inner


def last_window_of(id_: int, initial_id: int, win_len: int, slide_len: int) -> int:
    """Local id of the last window containing tuple ``id_``; -1 if (for
    hopping windows) the tuple falls in a gap (win_seq.hpp:381-411)."""
    if win_len >= slide_len:  # sliding or tumbling
        return int(math.ceil((id_ + 1 - initial_id) / slide_len)) - 1
    # hopping: windows leave gaps
    n = (id_ - initial_id) // slide_len
    off = id_ - initial_id
    if off < n * slide_len or off >= n * slide_len + win_len:
        return -1
    return n


def window_range_of(id_: int, initial_id: int, win_len: int,
                    slide_len: int) -> Tuple[int, int]:
    """[first_w, last_w] local window ids containing tuple ``id_``
    (wf_nodes.hpp:156-181); (-1,-1) if none (hopping gap)."""
    if win_len >= slide_len:
        if id_ + 1 - initial_id < win_len:
            first_w = 0
        else:
            first_w = int(math.ceil((id_ + 1 - win_len - initial_id) / slide_len))
        last_w = int(math.ceil((id_ + 1 - initial_id) / slide_len)) - 1
        return first_w, last_w
    n = (id_ - initial_id) // slide_len
    off = id_ - initial_id
    if n * slide_len <= off < n * slide_len + win_len:
        return n, n
    return -1, -1


def wf_destinations(hashcode: int, first_w: int, last_w: int,
                    pardegree: int) -> List[int]:
    """Win_Farm multicast set: window lwid ``w`` of a key whose first
    window starts at worker ``hash % pardegree`` lives on worker
    ``(hash % pardegree + w) % pardegree``; at most ``pardegree``
    distinct workers receive the tuple (wf_nodes.hpp:182-191)."""
    start = hashcode % pardegree
    out = []
    w = first_w
    while w <= last_w and len(out) < pardegree:
        out.append((start + w) % pardegree)
        w += 1
    return out


def plq_renumbered_id(hashcode: int, emit_counter: int,
                      cfg: WinOperatorConfig) -> int:
    """Id given to a PLQ pane result so the WLQ sees a dense per-key
    sequence (win_seq.hpp:484)."""
    return ((cfg.id_inner - (hashcode % cfg.n_inner) + cfg.n_inner) % cfg.n_inner) \
        + emit_counter * cfg.n_inner


def pane_length(win_len: int, slide_len: int) -> int:
    """Pane size = gcd(win, slide) (Li et al. SIGMOD'05; pane_farm.hpp)."""
    return math.gcd(win_len, slide_len)
