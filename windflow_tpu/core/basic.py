"""Core enums, constants and configuration for windflow_tpu.

TPU-native re-design of the reference's ``wf/basic.hpp`` (enums at
basic.hpp:86-135, WinOperatorConfig at basic.hpp:154-184, GPU batching
defaults at basic.hpp:77-80).  Everything the reference spreads over
compile-time macros + builder parameters is folded into one runtime
config surface here (SURVEY.md §5 "Config / flag system").
"""
from __future__ import annotations

import enum
import os
import time
from dataclasses import dataclass, field
from typing import Any, Optional


class Mode(enum.Enum):
    """Execution modes of a PipeGraph (reference basic.hpp:86).

    DEFAULT        -- streams assumed ordered per source; no reordering plane.
    DETERMINISTIC  -- ordering collectors (watermark-by-min priority queues)
                      inserted before every operator (ref ordering_node.hpp).
    PROBABILISTIC  -- K-slack collectors; late tuples may be dropped
                      (ref kslack_node.hpp).
    """

    DEFAULT = 0
    DETERMINISTIC = 1
    PROBABILISTIC = 2


class WinType(enum.Enum):
    """Window model (reference basic.hpp:89): count-based or time-based."""

    CB = 0
    TB = 1


class OptLevel(enum.IntEnum):
    """Optimization levels (basic.hpp:92).

    Composite window operators take an ``opt_level`` per builder
    (LEVEL1 strips internal collectors, LEVEL2 thread-fuses their
    stages).  The same enum also grades the **graph compile pass**
    (:mod:`windflow_tpu.graph.fuse`, ``RuntimeConfig.opt_level``):
    at LEVEL2 -- the default -- ``PipeGraph.start`` fuses maximal runs
    of adjacent single-producer FORWARD stages into single replicas
    (the ``ff_comb`` fusion of multipipe.hpp:345-390, applied
    automatically graph-wide)."""

    LEVEL0 = 0  # no optimization
    LEVEL1 = 1  # strip internal collectors where ordering is not required
    LEVEL2 = 2  # fuse distribution via tree emitters / stage fusion


class RoutingMode(enum.Enum):
    """How an operator receives its inputs (basic.hpp:95)."""

    NONE = 0
    FORWARD = 1
    KEYBY = 2
    COMPLEX = 3


class Pattern(enum.Enum):
    """Operator kinds (basic.hpp:98-123); used for diagnostics/diagrams."""

    SOURCE = 0
    FILTER = 1
    MAP = 2
    FLATMAP = 3
    ACCUMULATOR = 4
    SINK = 5
    WIN_SEQ = 6
    WIN_FARM = 7
    KEY_FARM = 8
    PANE_FARM = 9
    WIN_MAPREDUCE = 10
    WIN_SEQFFAT = 11
    KEY_FFAT = 12
    WIN_SEQ_TPU = 13
    WIN_FARM_TPU = 14
    KEY_FARM_TPU = 15
    PANE_FARM_TPU = 16
    WIN_MAPREDUCE_TPU = 17
    WIN_SEQFFAT_TPU = 18
    KEY_FFAT_TPU = 19


class WinEvent(enum.Enum):
    """Events raised by a window on a new tuple (basic.hpp:126)."""

    OLD = 0       # tuple precedes the window extent
    IN = 1        # tuple belongs to the window
    DELAYED = 2   # TB only: past the extent but within the triggering delay
    FIRED = 3     # tuple proves the window complete
    BATCHED = 4   # window already handed to a device batch


class OrderingMode(enum.Enum):
    """What field the ordering collector sorts on (basic.hpp:129)."""

    ID = 0
    TS = 1
    TS_RENUMBERING = 2


class Role(enum.Enum):
    """Role of a windowed engine inside a composite operator (basic.hpp:132)."""

    SEQ = 0
    PLQ = 1
    WLQ = 2
    MAP = 3
    REDUCE = 4


# Defaults mirroring reference basic.hpp:74-83, re-targeted at TPU batching.
DEFAULT_BATCH_SIZE_TB = 1000      # initial device batch for TB windows
DEFAULT_UPDATE_INTERVAL_USEC = 100_000
DEFAULT_QUEUE_CAPACITY = 2048     # bounded SPSC queue capacity (backpressure)
DEFAULT_MICROBATCH = 256          # host-plane micro-batch (tuples per queue item)


def current_time_usecs() -> int:
    """Monotonic microseconds (reference basic.hpp:51-71 clock helpers)."""
    return time.monotonic_ns() // 1000


def current_time_nsecs() -> int:
    return time.monotonic_ns()


@dataclass
class WinOperatorConfig:
    """Distributed window-id assignment parameters (basic.hpp:154-184).

    A windowed engine replica inside a composite operator learns which
    global windows it owns from (id, n, slide) pairs at two nesting
    levels ("outer" = the enclosing farm, "inner" = the stage inside).
    The gwid/initial-id arithmetic consuming these lives in
    ``core.win_assign`` (reference win_seq.hpp:348-357).
    """

    id_outer: int = 0
    n_outer: int = 1
    slide_outer: int = 0
    id_inner: int = 0
    n_inner: int = 1
    slide_inner: int = 0


@dataclass(frozen=True)
class ElasticSpec:
    """Per-operator elasticity declaration (builders
    ``.with_elasticity(min, max, target_util)``; docs/ELASTIC.md).

    The elastic controller keeps the operator's replica count inside
    ``[min_replicas, max_replicas]``, steering toward ``target_util``
    busy fraction per replica.  Manual ``PipeGraph.rescale`` calls are
    bounded by the same interval."""

    min_replicas: int
    max_replicas: int
    target_util: float = 0.75


@dataclass(frozen=True)
class DurabilityConfig:
    """Exactly-once epoch configuration (durability/;
    docs/RESILIENCE.md "Exactly-once epochs").

    ``RuntimeConfig.durability = DurabilityConfig(...)`` turns on the
    epoch coordinator: aligned barrier markers are injected at every
    source replica each ``epoch_interval_s``, ride the channel planes
    as control items, and snapshot each replica's state as they pass --
    WITHOUT stopping the graph.  Each epoch atomically commits
    {per-replica state, per-source offsets, epoch id} as a manifest
    under ``path`` (write-temp + fsync + atomic rename), keeping the
    newest ``retained`` manifests.  An epoch older than
    ``stall_factor x epoch_interval_s`` without a commit flags the
    ``Stalled`` gauge (and the doctor verdict)."""

    epoch_interval_s: float = 1.0
    path: str = "epochs"
    retained: int = 3
    stall_factor: float = 5.0
    # incremental (delta) snapshots: keyed replica state is serialized
    # as content-addressed blobs beside the manifest and manifests
    # reference unchanged blobs from prior epochs instead of
    # re-pickling them -- commit cost becomes O(changed keys).  Each
    # replica's manifest entry is a blob CHAIN (base + per-epoch
    # deltas); after ``delta_chain_max`` links the encoder compacts the
    # chain back to a fresh base.  Unreferenced blobs are GCed with the
    # manifests that referenced them (honoring ``retained``).  Off by
    # default: full re-pickle per epoch, the schema-1 manifest shape.
    delta: bool = False
    delta_chain_max: int = 8
    # strict exactly-once: a source without a state_dict (offset not
    # checkpointable) is a hard RuntimeError at attach instead of a
    # RuntimeWarning, so exactly-once cannot silently degrade to
    # replay-from-start (docs/RESILIENCE.md)
    strict: bool = False


@dataclass(frozen=True)
class StateTierConfig:
    """Tiered keyed-state tuning (state/; docs/RESILIENCE.md "Tiered
    state & memory pressure").  Only consulted when
    ``RuntimeConfig.state_budget_bytes`` is set; the defaults are the
    tested operating point, so most graphs never touch this."""

    # budget fractions where demotion (hot -> warm pickles) and disk
    # spill (warm -> cold segments) start; past the budget itself the
    # store SHEDS coldest keys into dead_letters (state_pressure)
    demote_frac: float = 0.7
    spill_frac: float = 0.85
    # optional hard cap on live hot objects per replica (None = bytes
    # budget only)
    hot_max_keys: Optional[int] = None
    # store operations between maintenance passes on the replica thread
    maintain_every: int = 64
    # cold keys per spill segment file
    spill_batch: int = 256


@dataclass(frozen=True)
class SupervisionConfig:
    """Replica self-healing policy (durability/supervision.py;
    docs/RESILIENCE.md "Supervised replica restart").

    ``RuntimeConfig.supervision = SupervisionConfig(...)`` arms the
    replica supervisor for operators marked ``.with_restartable()``: a
    replica crash there no longer cancels the graph -- the supervisor
    quiesces through the rescale machinery, rebuilds the replica from
    the last committed epoch's state slice and resumes, with bounded
    jittered exponential backoff between attempts.  Only when
    ``max_restarts`` attempts are exhausted does the failure escalate
    to the graph-level ``NodeFailureError`` path.  Requires the
    durability plane (``RuntimeConfig.durability``): without committed
    epochs there is no consistent state slice to rebuild from."""

    max_restarts: int = 3
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    jitter: float = 0.5
    # deterministic backoff jitter for tests; None seeds from the OS
    seed: Optional[int] = None


@dataclass
class RuntimeConfig:
    """Global runtime knobs (folds the reference's macro set: README
    "Macros" -- TRACE_WINDFLOW, FF_BOUNDED_BUFFER, DEFAULT_BUFFER_CAPACITY,
    BLOCKING_MODE, NO_DEFAULT_MAPPING, DASHBOARD_MACHINE/PORT, LOG_DIR)."""

    mode: Mode = Mode.DEFAULT
    tracing: bool = False
    # second tracing level: raw channel stats (puts/gets/high-watermark)
    # dumped at wait_end -- the -DTRACE_FASTFLOW analogue
    # (pipegraph.hpp:711-733)
    trace_runtime: bool = False
    bounded_queues: bool = True
    queue_capacity: int = DEFAULT_QUEUE_CAPACITY
    microbatch: int = DEFAULT_MICROBATCH
    dashboard_machine: str = "localhost"
    dashboard_port: int = 20207
    log_dir: str = "log"
    # prefer the C++ host runtime when built; WINDFLOW_NATIVE=0 forces
    # the pure-Python plane (the CI matrix's second job)
    use_native_runtime: bool = field(default_factory=lambda: os.environ.get(
        "WINDFLOW_NATIVE", "1") != "0")
    # lower fully-declared record chains (Expr filters/maps + builtin
    # window + sink) onto the native C++ record pipeline at run()
    native_record_lowering: bool = True
    # -- failure containment (resilience/; docs/RESILIENCE.md) ----------
    # stall watchdog: cancel/dump when no channel makes progress for
    # this many seconds (None/0 = disabled)
    watchdog_timeout_s: Optional[float] = None
    # True: the watchdog cancels the graph (wait_end raises StallError);
    # False: it only dumps the channel/thread report and re-arms
    watchdog_cancel: bool = True
    # after a cancellation, how long wait_end waits for each replica
    # thread still stuck in user code before abandoning it
    cancel_grace_s: float = 5.0
    # resilience.faults.FaultPlan bound to the graph at start() (tests)
    fault_plan: Any = None
    # -- ingestion plane (ingest/; docs/INGEST.md) ----------------------
    # end-to-end latency budget for ingest-fed runs: the adaptive
    # microbatch controller AIMDs coalesced batch size / flush interval
    # against it and rewrites directly-fed device engines' launch
    # delay, replacing the static microbatch knobs (None = keep the
    # static operating point)
    latency_target_ms: Optional[float] = None
    # default per-source-replica credit budget (tuples outstanding in
    # outlet channels before the transport stops reading)
    ingest_credits: int = 1 << 16
    # -- graph compile pass (graph/fuse.py; docs/RUNTIME.md) ------------
    # LEVEL2 (default): PipeGraph.start fuses maximal runs of adjacent
    # single-producer FORWARD stages into one replica thread each,
    # preserving per-segment error policies / stats / faults /
    # checkpoint state.  Set LEVEL0 (or LEVEL1) to opt out.
    opt_level: "OptLevel" = OptLevel.LEVEL2
    # whole-partition device step (graph/device_step.py; ROADMAP item
    # 3): at LEVEL2, device-placed segments additionally lower to
    # chunk-granular launch control -- forward edges merge into
    # device-eligible consumers (source heads included) and every
    # device-lane window engine launches ONCE per ingest chunk instead
    # of per trigger site.  WINDFLOW_DEVICE_STEP=0 (or False here)
    # opts out; a LEVEL0/LEVEL1 opt_level disables it implicitly.
    device_step: bool = field(
        default_factory=lambda: os.environ.get(
            "WINDFLOW_DEVICE_STEP", "1") != "0")
    # per-graph column-buffer pool (core/tuples.ColumnPool): partition
    # sub-batches, SynthChunk materialization and ingest staging reuse
    # arena buffers instead of allocating per batch.  False = every
    # batch allocates fresh numpy columns (the pre-pool behaviour).
    buffer_pool: bool = True
    # -- telemetry plane (telemetry/; docs/OBSERVABILITY.md) ------------
    # deterministic 1-in-N source sampling period for end-to-end
    # latency tracing (trace contexts + residency/e2e histograms).
    # Active only under ``tracing``; 0 keeps the counter surface but
    # disables every per-item trace stamp (the bitwise-identical
    # operating point).  Sources can override per operator via
    # ``SourceBuilder.with_tracing(sample_rate)``.
    trace_sample: int = 128
    # bounded structured-event ring (telemetry/recorder.py): rescales,
    # placements, batch resizes, credit stalls, sheds, svc failures,
    # checkpoint epochs, conservation violations, frontier stalls.
    # Dumped as JSONL on watchdog stalls, node failures and failed
    # final conservation checks.  0 disables recording.
    flight_recorder_events: int = 512
    # -- audit plane (audit/; docs/OBSERVABILITY.md) --------------------
    # online flow-conservation ledger + progress/frontier tracking +
    # keyed-state census: a GraphAuditor thread proves per-edge
    # transport conservation while the graph runs (and exactly at
    # wait_end), publishes per-operator frontiers/lag, and reports key
    # skew.  False disables the auditor and all per-delivery ledger
    # accounting (the pre-audit hot path).
    audit: bool = True
    # seconds between online audit passes (ledger check + frontier
    # propagation + census refresh)
    audit_interval_s: float = 0.25
    # a pending operator whose frontier does not advance for this long
    # while upstream frontiers moved is reported as a stalled frontier
    # (flight-recorder `frontier_stall` + stats flag)
    frontier_stall_s: float = 5.0
    # hot-key sketch capacity per KEYBY emitter (space-saving top-K)
    audit_topk: int = 16
    # -- diagnosis plane (diagnosis/; docs/OBSERVABILITY.md) ------------
    # critical-path latency attribution + backpressure root-cause walk
    # + rolling gauge history + EWMA/MAD regression detection, ticking
    # on the monitor/auditor cadences and published as the Diagnosis /
    # History stats-JSON blocks (PipeGraph.explain(), the dashboard
    # /explain endpoint and `python -m windflow_tpu.doctor` read them).
    # Purely observational: off restores the pre-diagnosis report shape
    # with bitwise-identical results either way.
    diagnosis: bool = True
    # minimum seconds between diagnosis ticks (stacked callers --
    # monitor, auditor, explain() -- are rate-limited to this)
    diagnosis_interval_s: float = 1.0
    # rolling gauge-history ring length (snapshot rows kept per graph)
    history_len: int = 120
    # regression band half-width in (MAD-derived) sigmas, and the
    # samples a fresh series feeds its baseline before the band arms
    anomaly_band_k: float = 4.0
    anomaly_warmup: int = 12
    # dashboard-less snapshot fallback (monitoring/monitor.py): keep at
    # most this many *_stats.json snapshot files in log_dir (rotation
    # deletes the oldest); <= 0 keeps every file (the pre-rotation
    # behaviour)
    snapshot_keep: int = 16
    # -- online re-planning (graph/replanner.py; docs/PLANNER.md
    # "Resident state & online re-planning") ----------------------------
    # The start-time placement decision becomes a running hypothesis:
    # a re-planner riding the diagnosis tick compares each auto-placed
    # window engine's MEASURED per-launch wall (and its attribution
    # split into device transport vs compute) against the cost model's
    # projection, and when they contradict it for ``replan_ticks``
    # consecutive ticks, swaps that engine's lane device<->host mid-run
    # through the quiesce/migrate path with zero lost tuples -- a
    # ``replacement`` flight event doctor explains.  Off by default:
    # flipping lanes mid-run trades determinism of the operating point
    # for adaptivity, which is an operator's call.
    replan: bool = False
    # consecutive contradicting diagnosis ticks before a lane flip
    replan_ticks: int = 3
    # -- elastic scaling plane (elastic/; docs/ELASTIC.md) --------------
    # elastic.controller.ElasticityConfig tuning the load-driven
    # controller (sample period, EWMA alpha, cooldown, hysteresis,
    # backlog trigger), or None for the defaults.  The controller only
    # starts when some operator declared .with_elasticity(...); setting
    # ``ElasticityConfig(enabled=False)`` keeps it off while manual
    # PipeGraph.rescale(...) calls stay available.
    elasticity: Any = None
    # -- durability plane (durability/; docs/RESILIENCE.md) -------------
    # DurabilityConfig turning on exactly-once epoch barriers: aligned
    # snapshot markers injected at sources each epoch_interval_s,
    # per-replica state captured as they pass (no graph-wide quiesce),
    # atomically-committed epoch manifests, and the transactional /
    # idempotent sink contract (SinkBuilder.with_exactly_once).  None
    # (the default) keeps the pre-durability hot path untouched.
    durability: Any = None
    # -- tiered keyed state (state/; docs/RESILIENCE.md "Tiered state
    # & memory pressure") -----------------------------------------------
    # hard per-graph budget for in-memory keyed state, split evenly
    # across the replicas whose logics expose enable_tiered_state
    # (AccumulatorLogic today).  Approaching a replica's share demotes
    # LRU keys to pickled host bytes, then spills the oldest to
    # crash-safe disk segments under <log_dir>/state_spill/; past the
    # hard ceiling the coldest keys are SHED into dead_letters with a
    # state_pressure flight event -- degraded and loud, never an
    # allocator crash.  None (the default) keeps every keyed store a
    # plain in-memory dict (the pre-tiering hot path).
    state_budget_bytes: Optional[int] = None
    # StateTierConfig tuning the watermarks/batching, or None for the
    # defaults
    state_tiers: Any = None
    # SupervisionConfig arming supervised replica self-healing for
    # operators marked .with_restartable(): replica crashes there are
    # healed in place from the last committed epoch instead of failing
    # the graph (durability/supervision.py; docs/RESILIENCE.md).
    # Requires ``durability``.  None (the default) keeps today's
    # fail-fast path for every replica.
    supervision: Any = None
    # -- SLO plane (slo/; docs/OBSERVABILITY.md "SLO plane") ------------
    # slo.SloConfig declaring this graph's objectives (e2e p99 budget,
    # throughput floor, frontier-lag ceiling).  Evaluated continuously
    # on the diagnosis tick with multi-window error-budget burn-rate
    # accounting: breaches open slo_breach/slo_recovered flight
    # episodes, surface as the Slo stats block, windflow_slo_* metrics
    # and a worst-news-first doctor verdict line.  None (the default)
    # keeps the plane off; PipeGraph.with_slo(...) is the builder-style
    # way to set it.
    slo: Any = None
    # -- distributed runtime plane (distributed/; docs/DISTRIBUTED.md) --
    # distributed.DistributedSpec partitioning this graph across worker
    # processes: PipeGraph.start prunes to the worker's own partition
    # and carries every cross-worker edge over the credit-backpressured
    # shuffle transport.  None (the default) = single-process graph;
    # normally set by the worker entry point, not by hand.
    distributed: Any = None
    # -- global-scheduler plane (scheduler/; docs/SERVING.md) -----------
    # a scheduler.leases.FairShareLease gating this graph's consume
    # loops so co-resident tenants in one worker share cores by
    # weighted credit instead of the OS scheduler.  Bound to every
    # runtime node at start; a lease-less graph (the default) pays
    # nothing.  Normally set by a fair-share Server, not by hand.
    sched_lease: Any = None
