"""Callable-signature deduction for builders.

The reference deduces tuple/result types and plain-vs-rich variants from
C++ overload sets (``wf/meta.hpp:50-766``, ``wf/meta_gpu.hpp:48-74``).
Python has runtime introspection instead: we classify user callables by
arity -- a callable taking one parameter more than the operator's base
signature is "rich" and receives a RuntimeContext as its last argument
(API file: every operator lists a plain and a rich variant).

Return-value conventions replace the reference's pointer/optional
variants (API:19-33):
* Filter: return truthy/falsy (in-place predicate) or None-vs-result
  (transforming filter) -- ``None`` drops the tuple like an empty
  ``std::optional``.
* Map: return None (in-place mutation) or a new record.
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable


def arity(fn: Callable) -> int:
    """Number of positional parameters of ``fn`` (functors count
    ``__call__``; bound methods exclude self)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return -1
    n = 0
    for p in sig.parameters.values():
        if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                      inspect.Parameter.POSITIONAL_OR_KEYWORD):
            n += 1
        elif p.kind == inspect.Parameter.VAR_POSITIONAL:
            return -1  # *args: cannot deduce; treated as plain
    return n


def is_rich(fn: Callable, base_arity: int) -> bool:
    """True iff ``fn`` takes base_arity+1 params (the RuntimeContext)."""
    a = arity(fn)
    if a == base_arity:
        return False
    if a == base_arity + 1:
        return True
    if a == -1:
        return False
    raise TypeError(
        f"callable {fn!r} has {a} positional params; expected "
        f"{base_arity} (plain) or {base_arity + 1} (rich)")


def with_context(fn: Callable, base_arity: int, context) -> Callable:
    """Normalize plain/rich callables to the plain signature by binding
    the RuntimeContext when the callable is rich."""
    if is_rich(fn, base_arity):
        @functools.wraps(fn)
        def bound(*args):
            return fn(*args, context)
        return bound
    return fn


def default_hash(key: Any) -> int:
    """Deterministic key hash used for KEYBY routing and window
    assignment.  ``std::hash`` in the reference (standard_emitter.hpp:
    88-99); here stable across runs and processes (Python's str hash is
    salted, so route ints through identity and strings through FNV-1a)."""
    if isinstance(key, (int,)):
        return key if key >= 0 else -key
    if isinstance(key, bytes):
        data = key
    else:
        data = str(key).encode()
    h = 0xcbf29ce484222325
    for b in data:
        h = ((h ^ b) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
    return h
