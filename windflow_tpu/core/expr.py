"""Expression descriptors for record fields.

A tiny algebra over the tuple control-field contract (``key``, ``id``,
``ts``, ``value``) that one definition serves every execution plane:

* **scalar plane** -- ``to_callable()`` gives the plain-Python
  record function (the reference's C++ functor analog);
* **columnar plane** -- ``to_batch()`` evaluates vectorized over a
  ``TupleBatch``'s numpy columns;
* **native plane** -- ``match_*`` helpers pattern-match the expression
  onto the C++ record-pipeline stage descriptors
  (native/record_pipeline.cpp), letting source->map->filter->window->
  sink chains run record-at-a-time in C++ end-to-end.

The reference compiles arbitrary C++ functors into each operator
(meta.hpp overload sets); a Python framework cannot, so expressions are
the declared, loweable subset -- arbitrary Python callables remain
accepted everywhere and simply pin the graph to the Python planes.

Usage::

    from windflow_tpu import F
    Map(F.value * 2 + 1)            # value <- value*2 + 1
    Filter(F.value % 4 == 0)        # keep when predicate holds
    Map((F.id * 1.0).as_value())    # value <- id
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

_FIELDS = ("key", "id", "ts", "value")

# binary ops: (python fn, symbol)
_OPS = {
    "add": (lambda a, b: a + b, "+"),
    "sub": (lambda a, b: a - b, "-"),
    "mul": (lambda a, b: a * b, "*"),
    "div": (lambda a, b: a / b, "/"),
    "mod": (lambda a, b: a % b, "%"),
    "eq": (lambda a, b: a == b, "=="),
    "ne": (lambda a, b: a != b, "!="),
    "lt": (lambda a, b: a < b, "<"),
    "le": (lambda a, b: a <= b, "<="),
    "gt": (lambda a, b: a > b, ">"),
    "ge": (lambda a, b: a >= b, ">="),
}
_CMPS = ("eq", "ne", "lt", "le", "gt", "ge")


class Expr:
    """Immutable expression tree node."""

    __slots__ = ("op", "a", "b")

    def __init__(self, op: str, a, b=None):
        self.op = op    # 'field' | 'const' | binary op name
        self.a = a      # field name / constant / left Expr
        self.b = b      # right Expr (binary only)

    # -- construction sugar -------------------------------------------
    def _bin(self, op, other, swap=False):
        o = other if isinstance(other, Expr) else Expr("const", other)
        return Expr(op, o, self) if swap else Expr(op, self, o)

    def __add__(self, o): return self._bin("add", o)
    def __radd__(self, o): return self._bin("add", o, True)
    def __sub__(self, o): return self._bin("sub", o)
    def __rsub__(self, o): return self._bin("sub", o, True)
    def __mul__(self, o): return self._bin("mul", o)
    def __rmul__(self, o): return self._bin("mul", o, True)
    def __truediv__(self, o): return self._bin("div", o)
    def __mod__(self, o): return self._bin("mod", o)
    def __eq__(self, o): return self._bin("eq", o)      # type: ignore
    def __ne__(self, o): return self._bin("ne", o)      # type: ignore
    def __lt__(self, o): return self._bin("lt", o)
    def __le__(self, o): return self._bin("le", o)
    def __gt__(self, o): return self._bin("gt", o)
    def __ge__(self, o): return self._bin("ge", o)
    __hash__ = None  # mutable-compare semantics; not a dict key

    def __repr__(self):
        if self.op == "field":
            return f"F.{self.a}"
        if self.op == "const":
            return repr(self.a)
        return f"({self.a!r} {_OPS[self.op][1]} {self.b!r})"

    # -- evaluation ---------------------------------------------------
    def eval_record(self, rec) -> Any:
        if self.op == "field":
            return getattr(rec, self.a)
        if self.op == "const":
            return self.a
        return _OPS[self.op][0](self.a.eval_record(rec),
                                self.b.eval_record(rec))

    def eval_columns(self, cols) -> Any:
        """Vectorized evaluation over a dict/TupleBatch of columns."""
        if self.op == "field":
            return cols[self.a]
        if self.op == "const":
            return self.a
        return _OPS[self.op][0](self.a.eval_columns(cols),
                                self.b.eval_columns(cols))

    def to_callable(self) -> Callable[[Any], Any]:
        return self.eval_record

    # -- structure queries (used by the native matcher) ---------------
    def is_field(self, name=None) -> bool:
        return self.op == "field" and (name is None or self.a == name)

    def const_value(self) -> Optional[float]:
        return self.a if self.op == "const" else None


class _FieldNS:
    """``F.value`` / ``F.key`` / ``F.id`` / ``F.ts``."""

    def __getattr__(self, name: str) -> Expr:
        if name not in _FIELDS:
            raise AttributeError(
                f"unknown record field {name!r} (have {_FIELDS})")
        return Expr("field", name)


F = _FieldNS()


# ---------------------------------------------------------------------------
# Native-descriptor pattern matching
# ---------------------------------------------------------------------------

def match_affine(e: Expr) -> Optional[Tuple[str, float, float, bool]]:
    """Match e == field*scale + offset (or field*field*scale + offset
    with both fields 'value').  Returns (field, scale, offset, square)
    or None."""
    # invariant: original == scale * e + offset
    scale, offset = 1.0, 0.0
    while True:
        if e.op == "add" and e.b.op == "const":
            offset += scale * e.b.a
            e = e.a
        elif e.op == "add" and e.a.op == "const":
            offset += scale * e.a.a
            e = e.b
        elif e.op == "sub" and e.b.op == "const":
            offset -= scale * e.b.a
            e = e.a
        elif e.op == "sub" and e.a.op == "const":
            offset += scale * e.a.a
            scale = -scale
            e = e.b
        elif e.op == "mul" and e.b.op == "const":
            scale *= e.b.a
            e = e.a
        elif e.op == "mul" and e.a.op == "const":
            scale *= e.a.a
            e = e.b
        elif e.op == "div" and e.b.op == "const" and e.b.a != 0:
            scale /= e.b.a
            e = e.a
        else:
            break
    if e.op == "field":
        return (e.a, scale, offset, False)
    if (e.op == "mul" and e.a.is_field("value") and e.b.is_field("value")):
        return ("value", scale, offset, True)
    return None


def match_predicate(e: Expr):
    """Match a filter predicate onto a native FILTER descriptor.

    Returns one of
      ("mod_eq", field, m, r)         --  field % m == r
      (cmp, field, const)             --  field cmp const,
                                          cmp in lt/le/gt/ge/eq
    or None if not representable.
    """
    if e.op not in _CMPS:
        return None
    lhs, rhs = e.a, e.b
    if lhs.op == "const" and rhs.op != "const":
        lhs, rhs = rhs, lhs
        flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
        e_op = flip.get(e.op, e.op)
    else:
        e_op = e.op
    if rhs.op != "const":
        return None
    c = rhs.a
    # (field % m) == r
    if (e_op == "eq" and lhs.op == "mod" and lhs.a.op == "field"
            and lhs.b.op == "const"):
        return ("mod_eq", lhs.a.a, int(lhs.b.a), int(c))
    if e_op == "ne":
        return None  # no native != descriptor
    # affine(field) cmp const  ->  field cmp (const-offset)/scale
    m = match_affine(lhs)
    if m is None or m[3]:
        return None
    field, scale, offset, _ = m
    if scale == 0:
        return None
    c2 = (c - offset) / scale
    if scale < 0:
        flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
        e_op = flip.get(e_op, e_op)
    if e_op == "eq":
        return ("eq", field, c2)
    return (e_op, field, c2)
