"""Tuple contract and the columnar batch type.

The reference imposes a structural contract on user types:
``getControlFields() -> (key, id, ts)`` / ``setControlFields(key,id,ts)``
(used e.g. at win_seq.hpp:331-333; test type mp_tests_gpu/mp_common.hpp:44-81).
We keep that contract for the record-oriented plane and add the thing the
reference cannot have: a **columnar TupleBatch** -- the native currency of
the TPU plane.  A stream here is a sequence of batches (struct-of-arrays),
which is what XLA wants; single records exist only at the API edge.
"""
from __future__ import annotations

import sys
import threading
from typing import Any, Dict, Iterator, Optional, Protocol, Tuple, \
    runtime_checkable

import numpy as np


class ColumnPool:
    """Arena of reusable numpy column buffers (per PipeGraph).

    ``take(n, dtype)`` returns a length-``n`` view over a pooled
    power-of-two buffer.  Reuse is **refcount-driven**: the pool keeps a
    strong reference to every base buffer it handed out; a buffer whose
    only remaining referent is the pool itself (every downstream view
    of it has died) is free and gets re-lent.  No explicit release call
    exists, so a consumer holding a batch alive can never have its
    columns scribbled over -- the safety property an explicit-free
    arena cannot give a Python dataflow.

    The per-(dtype, bucket) freelists are bounded (``max_per_bucket``)
    so a burst of in-flight batches degrades to plain allocation
    instead of growing the arena without bound.
    """

    __slots__ = ("_lock", "_buckets", "max_per_bucket", "hits", "misses")

    # refcount of a free base buffer: the bucket list + the loop local
    # + the getrefcount argument
    _FREE_RC = 3

    def __init__(self, max_per_bucket: int = 32):
        self._lock = threading.Lock()
        self._buckets: Dict[Tuple[str, int], list] = {}
        self.max_per_bucket = max_per_bucket
        self.hits = 0
        self.misses = 0

    def take(self, n: int, dtype) -> np.ndarray:
        """A length-``n`` uninitialized view over a pooled buffer."""
        dt = np.dtype(dtype)
        if n <= 0:
            return np.empty(0, dt)
        cap = 1 << (int(n) - 1).bit_length()
        key = (dt.str, cap)
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is not None:
                for buf in bucket:
                    # free iff nothing outside this pool references it
                    if sys.getrefcount(buf) <= self._FREE_RC:
                        self.hits += 1
                        return buf[:n]
            self.misses += 1
            buf = np.empty(cap, dt)
            if bucket is None:
                bucket = self._buckets[key] = []
            if len(bucket) < self.max_per_bucket:
                bucket.append(buf)
            return buf[:n]

    def stats(self) -> dict:
        with self._lock:
            held = sum(len(b) for b in self._buckets.values())
            held_bytes = sum(buf.nbytes for b in self._buckets.values()
                             for buf in b)
        return {"buffers": held, "bytes": held_bytes,
                "hits": self.hits, "misses": self.misses}

    def drain(self) -> int:
        """Release the arena: drop the pool's strong references to
        every pooled base buffer, returning the byte count let go.
        Buffers with live outside views survive exactly as long as
        those views do (refcounting, not the pool, owns them now); the
        pool stays usable and simply re-allocates on the next take.
        The serving plane calls this at tenant teardown so repeated
        submit/evict cycles reclaim arena memory (docs/SERVING.md)."""
        with self._lock:
            released = sum(buf.nbytes for b in self._buckets.values()
                           for buf in b)
            self._buckets.clear()
        return released


@runtime_checkable
class WFRecord(Protocol):
    """Structural contract every user record type must satisfy."""

    def get_control_fields(self) -> Tuple[Any, int, int]:
        """Return (key, id, ts)."""
        ...

    def set_control_fields(self, key: Any, tid: int, ts: int) -> None:
        ...


class SynthChunk:
    """A descriptor slice of the declared synthetic law
    (operators/synth.SyntheticSource): events [start, start + n) with
    key = e % n_keys, id = ts = e // n_keys,
    value = (e % vmod) * vscale + voff.

    A stream item like TupleBatch: consumers that own a native engine
    fold it without materializing the columns; the runtime materializes
    it transparently at every other plane boundary (RtNode dispatch,
    multi-destination outlets)."""

    # ``trace`` stays UNSET (not None-initialized) so untraced chunks
    # pay zero construction cost; telemetry reads it via getattr-with-
    # default (telemetry/trace.py)
    __slots__ = ("start", "n", "n_keys", "vmod", "vscale", "voff", "trace")

    def __init__(self, start, n, n_keys, vmod, vscale, voff):
        self.start = start
        self.n = n
        self.n_keys = n_keys
        self.vmod = vmod
        self.vscale = vscale
        self.voff = voff

    def __len__(self):
        return self.n

    def materialize(self, pool: Optional[ColumnPool] = None) -> "TupleBatch":
        tr = getattr(self, "trace", None)
        if pool is None:
            idx = self.start + np.arange(self.n)
            ids = idx // self.n_keys
            out = TupleBatch({
                "key": idx % self.n_keys, "id": ids, "ts": ids,
                "value": (idx % self.vmod).astype(np.float64) * self.vscale
                         + self.voff})
            if tr is not None:
                out.trace = tr
            return out
        # pooled lane: all columns come from the graph arena;
        # np.ufunc(..., out=) writes them in place (no fresh allocation
        # per chunk)
        n = self.n
        idx = pool.take(n, np.int64)
        idx[:] = np.arange(self.start, self.start + n)
        keys = np.mod(idx, self.n_keys, out=pool.take(n, np.int64))
        res = np.mod(idx, self.vmod, out=pool.take(n, np.int64))
        ids = np.floor_divide(idx, self.n_keys, out=idx)  # idx is scratch
        vals = np.multiply(res, self.vscale, out=pool.take(n, np.float64),
                           casting="unsafe")
        if self.voff:
            np.add(vals, self.voff, out=vals)
        out = TupleBatch({"key": keys, "id": ids, "ts": ids, "value": vals})
        if tr is not None:
            out.trace = tr
        return out


class BasicRecord:
    """Convenience record: key/id/ts control fields + a float value.

    Mirrors the reference test fixture tuple (mp_common.hpp:44-81) but is
    a library type so users do not have to define one for simple streams.
    """

    # ``trace`` stays unset unless the telemetry plane attaches a
    # context (telemetry/trace.py); no per-record construction cost
    __slots__ = ("key", "id", "ts", "value", "trace")

    def __init__(self, key: Any = 0, tid: int = 0, ts: int = 0, value: float = 0.0):
        self.key = key
        self.id = tid
        self.ts = ts
        self.value = value

    def get_control_fields(self):
        return (self.key, self.id, self.ts)

    def set_control_fields(self, key, tid, ts):
        self.key = key
        self.id = tid
        self.ts = ts

    def __repr__(self):
        return f"BasicRecord(key={self.key}, id={self.id}, ts={self.ts}, value={self.value})"


class TupleBatch:
    """Columnar micro-batch of tuples: dict of equal-length numpy columns.

    Required columns: ``key`` (int64), ``id`` (int64), ``ts`` (int64).
    Any number of payload columns (e.g. ``value``).  This is the unit that
    flows over host queues on the batch plane and the host-side staging
    format for device transfers (the TPU analogue of the reference's
    pinned-buffer batch assembly, win_seq_gpu.hpp:552-596).
    """

    # ``trace`` carries a sampled telemetry TraceContext end to end
    # (telemetry/trace.py); it stays unset on untraced batches (getattr
    # default read) so batch construction pays nothing for it
    __slots__ = ("cols", "trace")

    CONTROL = ("key", "id", "ts")

    def __init__(self, cols: Dict[str, np.ndarray]):
        for c in self.CONTROL:
            if c not in cols:
                raise ValueError(f"TupleBatch missing control column '{c}'")
        n = len(cols["key"])
        for name, col in cols.items():
            if len(col) != n:
                raise ValueError(f"column '{name}' length {len(col)} != {n}")
        self.cols = cols

    # -- construction ------------------------------------------------------
    @classmethod
    def from_records(cls, records, payload=("value",)) -> "TupleBatch":
        keys, ids, tss = [], [], []
        pay = {p: [] for p in payload}
        for r in records:
            k, i, t = r.get_control_fields()
            keys.append(k)
            ids.append(i)
            tss.append(t)
            for p in payload:
                pay[p].append(getattr(r, p))
        cols = {
            "key": np.asarray(keys, dtype=np.int64),
            "id": np.asarray(ids, dtype=np.int64),
            "ts": np.asarray(tss, dtype=np.int64),
        }
        for p in payload:
            cols[p] = np.asarray(pay[p])
        return cls(cols)

    @classmethod
    def empty_like(cls, other: "TupleBatch") -> "TupleBatch":
        return cls({k: v[:0] for k, v in other.cols.items()})

    # -- accessors ---------------------------------------------------------
    def __len__(self) -> int:
        return len(self.cols["key"])

    @property
    def key(self) -> np.ndarray:
        return self.cols["key"]

    @property
    def id(self) -> np.ndarray:
        return self.cols["id"]

    @property
    def ts(self) -> np.ndarray:
        return self.cols["ts"]

    def __getitem__(self, name: str) -> np.ndarray:
        return self.cols[name]

    def payload_names(self):
        return [c for c in self.cols if c not in self.CONTROL]

    # -- transforms --------------------------------------------------------
    def take(self, idx, pool: Optional[ColumnPool] = None) -> "TupleBatch":
        """Row subset.  Slices stay zero-copy views; boolean masks are
        converted to indices once and gathered with np.take, which is
        4-5x faster than boolean fancy indexing repeated per column
        (the filter stages live on this path).  A contiguous index run
        ships as a slice view (zero copies); with ``pool`` the gathered
        columns reuse arena buffers instead of allocating.  A riding
        trace context propagates to every sub-batch (KEYBY partitions
        keep their sampled path traced)."""
        if isinstance(idx, slice):
            return self._carry(
                TupleBatch({k: v[idx] for k, v in self.cols.items()}))
        idx = np.asarray(idx)
        if idx.dtype == np.bool_:
            if len(idx) != len(self):
                raise IndexError(
                    f"boolean mask length {len(idx)} != batch "
                    f"length {len(self)}")
            idx = np.nonzero(idx)[0]
        elif idx.size == 0:
            idx = idx.astype(np.intp)   # e.g. a bare [] (float64)
        n = len(idx)
        if n > 1 and int(idx[-1]) - int(idx[0]) == n - 1 \
                and bool((np.diff(idx) == 1).all()):
            # contiguous ascending run: zero-copy view instead of a
            # gather (the cheap first/last guard gates the O(n) check)
            lo = int(idx[0])
            return self._carry(TupleBatch({k: v[lo:lo + n]
                                           for k, v in self.cols.items()}))
        if pool is None:
            return self._carry(TupleBatch({k: np.take(v, idx, axis=0)
                                           for k, v in self.cols.items()}))
        out = {}
        for k, v in self.cols.items():
            if v.base is not None and not v.flags.owndata \
                    and not v.flags.c_contiguous:
                out[k] = np.take(v, idx, axis=0)  # odd layout: let numpy
                continue
            out[k] = np.take(v, idx, axis=0, out=pool.take(n, v.dtype))
        return self._carry(TupleBatch(out))

    def _carry(self, out: "TupleBatch") -> "TupleBatch":
        """Propagate a riding trace context onto a derived batch."""
        tr = getattr(self, "trace", None)
        if tr is not None:
            out.trace = tr
        return out

    def concat(self, other: "TupleBatch") -> "TupleBatch":
        out = TupleBatch(
            {k: np.concatenate([v, other.cols[k]]) for k, v in self.cols.items()}
        )
        # either side's context rides on (self's stamp wins: it entered
        # the stream earlier, so the merged batch's latency is honest)
        tr = getattr(self, "trace", None) or getattr(other, "trace", None)
        if tr is not None:
            out.trace = tr
        return out

    def with_cols(self, **cols) -> "TupleBatch":
        out = dict(self.cols)
        out.update(cols)
        return self._carry(TupleBatch(out))

    def records(self, cls=BasicRecord) -> Iterator[Any]:
        """Materialize records at the API edge (slow path, tests only)."""
        names = self.payload_names()
        for i in range(len(self)):
            r = cls(self.cols["key"][i].item(), self.cols["id"][i].item(),
                    self.cols["ts"][i].item())
            for p in names:
                if hasattr(r, p):
                    setattr(r, p, self.cols[p][i].item())
            yield r

    def __repr__(self):
        return f"TupleBatch(n={len(self)}, cols={list(self.cols)})"


class EOS:
    """End-of-stream marker carried over host queues.

    The reference encodes EOS as a flagged refcounted wrapper
    (meta.hpp:770-783, ``isEOSMarker``); here it is a first-class queue
    item optionally carrying the per-key last tuples a WF emitter needs
    to broadcast (wf_nodes.hpp:207-227).
    """

    __slots__ = ("payload",)

    def __init__(self, payload=None):
        self.payload = payload

    def __repr__(self):
        return "EOS()"
