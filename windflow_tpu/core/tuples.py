"""Tuple contract and the columnar batch type.

The reference imposes a structural contract on user types:
``getControlFields() -> (key, id, ts)`` / ``setControlFields(key,id,ts)``
(used e.g. at win_seq.hpp:331-333; test type mp_tests_gpu/mp_common.hpp:44-81).
We keep that contract for the record-oriented plane and add the thing the
reference cannot have: a **columnar TupleBatch** -- the native currency of
the TPU plane.  A stream here is a sequence of batches (struct-of-arrays),
which is what XLA wants; single records exist only at the API edge.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, Protocol, Tuple, runtime_checkable

import numpy as np


@runtime_checkable
class WFRecord(Protocol):
    """Structural contract every user record type must satisfy."""

    def get_control_fields(self) -> Tuple[Any, int, int]:
        """Return (key, id, ts)."""
        ...

    def set_control_fields(self, key: Any, tid: int, ts: int) -> None:
        ...


class SynthChunk:
    """A descriptor slice of the declared synthetic law
    (operators/synth.SyntheticSource): events [start, start + n) with
    key = e % n_keys, id = ts = e // n_keys,
    value = (e % vmod) * vscale + voff.

    A stream item like TupleBatch: consumers that own a native engine
    fold it without materializing the columns; the runtime materializes
    it transparently at every other plane boundary (RtNode dispatch,
    multi-destination outlets)."""

    __slots__ = ("start", "n", "n_keys", "vmod", "vscale", "voff")

    def __init__(self, start, n, n_keys, vmod, vscale, voff):
        self.start = start
        self.n = n
        self.n_keys = n_keys
        self.vmod = vmod
        self.vscale = vscale
        self.voff = voff

    def __len__(self):
        return self.n

    def materialize(self) -> "TupleBatch":
        idx = self.start + np.arange(self.n)
        ids = idx // self.n_keys
        return TupleBatch({
            "key": idx % self.n_keys, "id": ids, "ts": ids,
            "value": (idx % self.vmod).astype(np.float64) * self.vscale
                     + self.voff})


class BasicRecord:
    """Convenience record: key/id/ts control fields + a float value.

    Mirrors the reference test fixture tuple (mp_common.hpp:44-81) but is
    a library type so users do not have to define one for simple streams.
    """

    __slots__ = ("key", "id", "ts", "value")

    def __init__(self, key: Any = 0, tid: int = 0, ts: int = 0, value: float = 0.0):
        self.key = key
        self.id = tid
        self.ts = ts
        self.value = value

    def get_control_fields(self):
        return (self.key, self.id, self.ts)

    def set_control_fields(self, key, tid, ts):
        self.key = key
        self.id = tid
        self.ts = ts

    def __repr__(self):
        return f"BasicRecord(key={self.key}, id={self.id}, ts={self.ts}, value={self.value})"


class TupleBatch:
    """Columnar micro-batch of tuples: dict of equal-length numpy columns.

    Required columns: ``key`` (int64), ``id`` (int64), ``ts`` (int64).
    Any number of payload columns (e.g. ``value``).  This is the unit that
    flows over host queues on the batch plane and the host-side staging
    format for device transfers (the TPU analogue of the reference's
    pinned-buffer batch assembly, win_seq_gpu.hpp:552-596).
    """

    __slots__ = ("cols",)

    CONTROL = ("key", "id", "ts")

    def __init__(self, cols: Dict[str, np.ndarray]):
        for c in self.CONTROL:
            if c not in cols:
                raise ValueError(f"TupleBatch missing control column '{c}'")
        n = len(cols["key"])
        for name, col in cols.items():
            if len(col) != n:
                raise ValueError(f"column '{name}' length {len(col)} != {n}")
        self.cols = cols

    # -- construction ------------------------------------------------------
    @classmethod
    def from_records(cls, records, payload=("value",)) -> "TupleBatch":
        keys, ids, tss = [], [], []
        pay = {p: [] for p in payload}
        for r in records:
            k, i, t = r.get_control_fields()
            keys.append(k)
            ids.append(i)
            tss.append(t)
            for p in payload:
                pay[p].append(getattr(r, p))
        cols = {
            "key": np.asarray(keys, dtype=np.int64),
            "id": np.asarray(ids, dtype=np.int64),
            "ts": np.asarray(tss, dtype=np.int64),
        }
        for p in payload:
            cols[p] = np.asarray(pay[p])
        return cls(cols)

    @classmethod
    def empty_like(cls, other: "TupleBatch") -> "TupleBatch":
        return cls({k: v[:0] for k, v in other.cols.items()})

    # -- accessors ---------------------------------------------------------
    def __len__(self) -> int:
        return len(self.cols["key"])

    @property
    def key(self) -> np.ndarray:
        return self.cols["key"]

    @property
    def id(self) -> np.ndarray:
        return self.cols["id"]

    @property
    def ts(self) -> np.ndarray:
        return self.cols["ts"]

    def __getitem__(self, name: str) -> np.ndarray:
        return self.cols[name]

    def payload_names(self):
        return [c for c in self.cols if c not in self.CONTROL]

    # -- transforms --------------------------------------------------------
    def take(self, idx) -> "TupleBatch":
        """Row subset.  Slices stay zero-copy views; boolean masks are
        converted to indices once and gathered with np.take, which is
        4-5x faster than boolean fancy indexing repeated per column
        (the filter stages live on this path)."""
        if isinstance(idx, slice):
            return TupleBatch({k: v[idx] for k, v in self.cols.items()})
        idx = np.asarray(idx)
        if idx.dtype == np.bool_:
            if len(idx) != len(self):
                raise IndexError(
                    f"boolean mask length {len(idx)} != batch "
                    f"length {len(self)}")
            idx = np.nonzero(idx)[0]
        elif idx.size == 0:
            idx = idx.astype(np.intp)   # e.g. a bare [] (float64)
        return TupleBatch({k: np.take(v, idx, axis=0)
                           for k, v in self.cols.items()})

    def concat(self, other: "TupleBatch") -> "TupleBatch":
        return TupleBatch(
            {k: np.concatenate([v, other.cols[k]]) for k, v in self.cols.items()}
        )

    def with_cols(self, **cols) -> "TupleBatch":
        out = dict(self.cols)
        out.update(cols)
        return TupleBatch(out)

    def records(self, cls=BasicRecord) -> Iterator[Any]:
        """Materialize records at the API edge (slow path, tests only)."""
        names = self.payload_names()
        for i in range(len(self)):
            r = cls(self.cols["key"][i].item(), self.cols["id"][i].item(),
                    self.cols["ts"][i].item())
            for p in names:
                if hasattr(r, p):
                    setattr(r, p, self.cols[p][i].item())
            yield r

    def __repr__(self):
        return f"TupleBatch(n={len(self)}, cols={list(self.cols)})"


class EOS:
    """End-of-stream marker carried over host queues.

    The reference encodes EOS as a flagged refcounted wrapper
    (meta.hpp:770-783, ``isEOSMarker``); here it is a first-class queue
    item optionally carrying the per-key last tuples a WF emitter needs
    to broadcast (wf_nodes.hpp:207-227).
    """

    __slots__ = ("payload",)

    def __init__(self, payload=None):
        self.payload = payload

    def __repr__(self):
        return "EOS()"
