"""Iterable: read-only random-access view of a window's content.

Re-design of reference ``wf/iterable.hpp`` (ctor :73, begin/end/size
:80-122, operator[]/at :131-176).  Handed to non-incremental window
functions; backed by a list slice view (archive storage) without copying.
"""
from __future__ import annotations

from typing import Any, Sequence


class Iterable:
    __slots__ = ("_items", "_lo", "_hi")

    def __init__(self, items: Sequence[Any], lo: int = 0, hi: int = None):
        self._items = items
        self._lo = lo
        self._hi = len(items) if hi is None else hi

    def __len__(self) -> int:
        return self._hi - self._lo

    def size(self) -> int:
        return len(self)

    def __iter__(self):
        for i in range(self._lo, self._hi):
            yield self._items[i]

    def __getitem__(self, i: int) -> Any:
        if i < 0 or i >= len(self):
            raise IndexError(i)  # bounds-checked like Iterable::at (:161-176)
        return self._items[self._lo + i]

    def at(self, i: int) -> Any:
        return self[i]
