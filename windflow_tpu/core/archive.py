"""StreamArchive: per-key ordered buffer with range queries and purge.

Re-design of reference ``wf/stream_archive.hpp`` (insert :60-71, purge
:74-80, getWinRange :106-127, getDistance :133-150).  The reference keeps
a ``std::deque`` ordered by a comparator and does insertion sort via
``lower_bound``; we do the same with ``bisect`` over a list keyed by a
sort key extracted once per record (cheaper than calling a comparator
O(log n) times per insert in Python).
"""
from __future__ import annotations

import bisect
from typing import Any, Callable, List, Tuple


class StreamArchive:
    """Ordered archive of records for one operator replica.

    ``sort_key(t)`` returns the ordering field -- tuple id for CB
    windows, timestamp for TB windows (matching the comparator choice in
    win_seq.hpp init).
    """

    __slots__ = ("sort_key", "_keys", "_items")

    def __init__(self, sort_key: Callable[[Any], int]):
        self.sort_key = sort_key
        self._keys: List[int] = []
        self._items: List[Any] = []

    def __len__(self) -> int:
        return len(self._items)

    def insert(self, t: Any) -> None:
        """Ordered insert (stream_archive.hpp:60-71). Ties keep arrival
        order (insert after equals, like upper-bound on equal keys keeps
        the reference's not-less-than placement stable for our purposes)."""
        k = self.sort_key(t)
        i = bisect.bisect_right(self._keys, k)
        self._keys.insert(i, k)
        self._items.insert(i, t)

    def purge(self, t: Any) -> int:
        """Drop every record strictly older than ``t``'s sort key
        (stream_archive.hpp:74-80).  Returns number purged."""
        k = self.sort_key(t)
        i = bisect.bisect_left(self._keys, k)
        del self._keys[:i]
        del self._items[:i]
        return i

    def purge_key(self, k: int) -> int:
        i = bisect.bisect_left(self._keys, k)
        del self._keys[:i]
        del self._items[:i]
        return i

    def win_range(self, t_s: Any, t_e: Any = None) -> Tuple[int, int]:
        """Index range [lo, hi) of records with sort key in
        [key(t_s), key(t_e)) -- the window extent query
        (stream_archive.hpp:106-127).  With ``t_e=None`` the range is
        open-ended (EOS flush, win_seq.hpp:539-543)."""
        lo = bisect.bisect_left(self._keys, self.sort_key(t_s))
        hi = len(self._keys) if t_e is None else bisect.bisect_left(
            self._keys, self.sort_key(t_e))
        return lo, hi

    def range_by_keys(self, k_lo: int, k_hi: int) -> Tuple[int, int]:
        """[lo, hi) covering sort keys in [k_lo, k_hi)."""
        return (bisect.bisect_left(self._keys, k_lo),
                bisect.bisect_left(self._keys, k_hi))

    def distance(self, t_s: Any, t_e: Any = None) -> int:
        lo, hi = self.win_range(t_s, t_e)
        return hi - lo

    def slice(self, lo: int, hi: int) -> List[Any]:
        return self._items[lo:hi]

    def items(self) -> List[Any]:
        return self._items

    def end(self) -> int:
        return len(self._items)
