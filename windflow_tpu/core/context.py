"""RuntimeContext + LocalStorage for "rich" user functions.

Re-design of reference ``wf/context.hpp`` (:49-102) and
``wf/local_storage.hpp`` (get :68-83, put :92-108, remove :116-124).
A rich callable receives the replica's parallelism, its index, and a
typed per-replica key-value store with default-construct-on-get.
"""
from __future__ import annotations

from typing import Any, Callable, Dict


class LocalStorage:
    __slots__ = ("_store",)

    def __init__(self):
        self._store: Dict[str, Any] = {}

    def get(self, name: str, factory: Callable[[], Any] = None) -> Any:
        """Return the value under ``name``; if absent and a factory is
        given, default-construct it first (local_storage.hpp:68-83)."""
        if name not in self._store and factory is not None:
            self._store[name] = factory()
        return self._store.get(name)

    def put(self, name: str, value: Any) -> None:
        self._store[name] = value

    def remove(self, name: str) -> None:
        self._store.pop(name, None)

    def is_contained(self, name: str) -> bool:
        return name in self._store

    def __len__(self) -> int:
        return len(self._store)


class RuntimeContext:
    __slots__ = ("parallelism", "replica_index", "storage")

    def __init__(self, parallelism: int = 1, replica_index: int = 0):
        self.parallelism = parallelism
        self.replica_index = replica_index
        self.storage = LocalStorage()

    def get_parallelism(self) -> int:
        return self.parallelism

    def get_replica_index(self) -> int:
        return self.replica_index

    def get_local_storage(self) -> LocalStorage:
        return self.storage
