"""FlatFAT: flat fixed-size aggregator tree for incremental windows.

Re-design of reference ``wf/flatfat.hpp`` (prefix :81-105, suffix
:108-132, update :135-154, insert :210-294, remove :297-361, getResult
:364-390) -- the algorithm is Tangwongsan et al., "General Incremental
Sliding-Window Aggregation", VLDB 2015 (cited at flatfat.hpp:31-32).

A complete binary tree over a ring buffer of ``n`` leaves (n = power of
two): O(log n) amortized insert/evict, window result in O(log n),
supporting **non-commutative** combines by always folding leaves in
logical (oldest -> newest) order -- when the ring wraps, the result is
``suffix(front..end) ⊕ prefix(begin..back)``.

The host/CPU twin lives here; the device twin (tree in HBM, level-wise
Pallas/XLA updates mirroring flatfat_gpu.hpp's three kernels) lives in
``windflow_tpu.ops.flatfat_jax``.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence


class FlatFAT:
    """Aggregator tree over values of an arbitrary type.

    Parameters
    ----------
    combine : (a, b) -> c            associative (not nec. commutative)
    empty   : () -> c                identity element factory
    n_leaves: ring capacity; rounded up to a power of two.
    """

    __slots__ = ("combine", "empty", "n", "tree", "front", "back", "count")

    def __init__(self, combine: Callable[[Any, Any], Any],
                 empty: Callable[[], Any], n_leaves: int):
        n = 1
        while n < max(2, n_leaves):
            n <<= 1
        self.combine = combine
        self.empty = empty
        self.n = n
        # heap layout: internal nodes [1, n), leaves [n, 2n)
        self.tree: List[Any] = [empty() for _ in range(2 * n)]
        self.front = 0   # ring index of the oldest element
        self.back = 0    # ring index one past the newest element
        self.count = 0

    # -- internals ---------------------------------------------------------
    def _update_paths(self, positions: Sequence[int]) -> None:
        """Recompute ancestors of the touched leaves level by level
        (the bulk-update strategy of flatfat.hpp:242-294: each level is
        refreshed once however many leaves changed under it)."""
        level = {(self.n + p) >> 1 for p in positions}
        while level:
            nxt = set()
            for node in level:
                self.tree[node] = self.combine(self.tree[2 * node],
                                               self.tree[2 * node + 1])
                if node > 1:
                    nxt.add(node >> 1)
            level = nxt

    def _range_query(self, lo: int, hi: int) -> Any:
        """Ordered fold of leaves [lo, hi] inclusive, O(log n), preserving
        left-to-right order for non-commutative combines (the role of
        prefix/suffix in flatfat.hpp:81-132)."""
        if lo > hi:
            return self.empty()
        left_parts: List[Any] = []
        right_parts: List[Any] = []
        lo += self.n
        hi += self.n + 1
        while lo < hi:
            if lo & 1:
                left_parts.append(self.tree[lo])
                lo += 1
            if hi & 1:
                hi -= 1
                right_parts.append(self.tree[hi])
            lo >>= 1
            hi >>= 1
        out: Optional[Any] = None
        for part in left_parts + right_parts[::-1]:
            out = part if out is None else self.combine(out, part)
        return out if out is not None else self.empty()

    # -- public API --------------------------------------------------------
    def __len__(self) -> int:
        return self.count

    @property
    def capacity(self) -> int:
        return self.n

    def insert(self, value: Any) -> None:
        self.insert_bulk([value])

    def insert_bulk(self, values: Sequence[Any]) -> None:
        """Append values at the back of the ring (flatfat.hpp:210-294)."""
        if self.count + len(values) > self.n:
            raise OverflowError("FlatFAT capacity exceeded")
        touched = []
        for v in values:
            self.tree[self.n + self.back] = v
            touched.append(self.back)
            self.back = (self.back + 1) % self.n
            self.count += 1
        self._update_paths(touched)

    def remove(self, k: int = 1) -> None:
        """Evict the k oldest values (flatfat.hpp:297-361)."""
        if k > self.count:
            raise IndexError("removing more than present")
        touched = []
        for _ in range(k):
            self.tree[self.n + self.front] = self.empty()
            touched.append(self.front)
            self.front = (self.front + 1) % self.n
            self.count -= 1
        self._update_paths(touched)

    def get_result(self) -> Any:
        """Fold of all live values in logical order (flatfat.hpp:364-390)."""
        if self.count == 0:
            return self.empty()
        back_incl = (self.back - 1) % self.n
        if self.front <= back_incl:
            return self._range_query(self.front, back_incl)
        # wrapped: suffix (front..n-1) then prefix (0..back_incl)
        return self.combine(self._range_query(self.front, self.n - 1),
                            self._range_query(0, back_incl))
