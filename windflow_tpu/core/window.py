"""Window state machines: count-based and time-based triggerers.

Re-design of the reference's ``wf/window.hpp`` (Triggerer_CB at
window.hpp:48-80, Triggerer_TB at window.hpp:83-121, Window at
window.hpp:124-306).  The semantics are kept bit-exact because the
distributed determinism oracles depend on them; the representation is
new (plain Python + a vectorized numpy twin used by the batch plane).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from .basic import WinEvent, WinType


@dataclass(frozen=True)
class TriggererCB:
    """Count-based triggerer (for in-order keyed substreams).

    Window ``lwid`` spans tuple identifiers
    ``[initial_id + lwid*slide, initial_id + lwid*slide + win_len)``
    (reference window.hpp:68-79).
    """

    win_len: int
    slide_len: int
    lwid: int
    initial_id: int

    def __call__(self, tid: int) -> WinEvent:
        lo = self.initial_id + self.lwid * self.slide_len
        if tid < lo:
            return WinEvent.OLD
        if tid <= lo + self.win_len - 1:
            return WinEvent.IN
        return WinEvent.FIRED


@dataclass(frozen=True)
class TriggererTB:
    """Time-based triggerer (tolerates out-of-order input within the
    triggering delay).  Window ``lwid`` spans timestamps
    ``[start + lwid*slide, start + lwid*slide + win_len)``; tuples past
    the extent but within ``triggering_delay`` raise DELAYED
    (reference window.hpp:106-120)."""

    win_len: int
    slide_len: int
    lwid: int
    starting_ts: int
    triggering_delay: int = 0

    def __call__(self, ts: int) -> WinEvent:
        lo = self.starting_ts + self.lwid * self.slide_len
        if ts < lo:
            return WinEvent.OLD
        if ts < lo + self.win_len:
            return WinEvent.IN
        if ts < lo + self.win_len + self.triggering_delay:
            return WinEvent.DELAYED
        return WinEvent.FIRED


@dataclass
class Window:
    """Per-(key, lwid) window accumulator (reference window.hpp:124-306).

    Tracks the result record, the number of IN tuples, the boundary
    tuples used for archive range queries, and the batched flag used by
    the device path.  ``result`` is created by ``result_factory`` and
    carries control fields via the tuple contract (core.tuples).
    """

    key: Any
    lwid: int
    gwid: int
    triggerer: Any
    win_type: WinType
    win_len: int
    slide_len: int
    result: Any = None
    no_tuples: int = 0
    batched: bool = False
    first_tuple: Optional[Any] = None
    last_tuple: Optional[Any] = None
    _result_initialized: bool = field(default=False, repr=False)

    def init_result(self, result: Any) -> None:
        """Seed the result's control fields (reference window.hpp:160-168):
        CB -> (key, gwid, 0); TB -> (key, gwid, gwid*slide + win_len - 1)."""
        self.result = result
        if self.win_type == WinType.CB:
            result.set_control_fields(self.key, self.gwid, 0)
        else:
            result.set_control_fields(
                self.key, self.gwid, self.gwid * self.slide_len + self.win_len - 1
            )

    def on_tuple(self, t: Any) -> WinEvent:
        """Evaluate the window against a new tuple (window.hpp:186-251)."""
        if self.batched:
            return WinEvent.BATCHED
        key, tid, ts = t.get_control_fields()
        if self.win_type == WinType.CB:
            event = self.triggerer(tid)
            if event == WinEvent.IN:
                self.no_tuples += 1
                if self.first_tuple is None:
                    self.first_tuple = t
                    # CB result timestamp = most recent IN tuple's ts
                    rk, rid, _ = self.result.get_control_fields()
                    self.result.set_control_fields(rk, rid, ts)
                else:
                    rk, rid, rts = self.result.get_control_fields()
                    if rts < ts:
                        self.result.set_control_fields(rk, rid, ts)
            elif event == WinEvent.FIRED:
                if self.last_tuple is None:
                    self.last_tuple = t
            else:
                raise AssertionError("OLD event on an in-order CB stream")
            return event
        else:
            event = self.triggerer(ts)
            if event == WinEvent.IN:
                self.no_tuples += 1
                if self.first_tuple is None or ts < self.first_tuple.get_control_fields()[2]:
                    self.first_tuple = t  # oldest IN tuple
            elif event in (WinEvent.DELAYED, WinEvent.FIRED):
                if self.last_tuple is None or ts < self.last_tuple.get_control_fields()[2]:
                    self.last_tuple = t  # oldest tuple past the extent
            return event

    def set_batched(self) -> None:
        self.batched = True


# ---------------------------------------------------------------------------
# Vectorized twins used by the columnar/TPU plane.  Given arrays of tuple
# ids (or timestamps) and a window index, classify all tuples at once.
# These keep identical boundary semantics to the scalar triggerers above.
# ---------------------------------------------------------------------------

def classify_cb(ids: np.ndarray, win_len: int, slide_len: int, lwid: int,
                initial_id: int) -> np.ndarray:
    """Vectorized TriggererCB: returns WinEvent values as int8 array."""
    lo = initial_id + lwid * slide_len
    out = np.full(ids.shape, WinEvent.FIRED.value, dtype=np.int8)
    out[ids < lo] = WinEvent.OLD.value
    out[(ids >= lo) & (ids <= lo + win_len - 1)] = WinEvent.IN.value
    return out


def classify_tb(ts: np.ndarray, win_len: int, slide_len: int, lwid: int,
                starting_ts: int, triggering_delay: int = 0) -> np.ndarray:
    """Vectorized TriggererTB."""
    lo = starting_ts + lwid * slide_len
    out = np.full(ts.shape, WinEvent.FIRED.value, dtype=np.int8)
    out[ts < lo + win_len + triggering_delay] = WinEvent.DELAYED.value
    out[ts < lo + win_len] = WinEvent.IN.value
    out[ts < lo] = WinEvent.OLD.value
    return out
