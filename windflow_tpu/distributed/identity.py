"""Worker identity of a distributed-runtime process.

Every worker process of a distributed PipeGraph run sets
``WINDFLOW_WORKER_ID`` before building its graph (distributed/worker.py
does it first thing); log-producing surfaces that key their file names
by ``<pid>_<graph>`` add the worker component through
:func:`worker_suffix`, so two workers of the same graph on one box --
and a worker restarted into a recycled pid -- can never clobber each
other's ``log/*_stats.json`` / ``*_flight.jsonl`` artifacts, and an
offline reader (the doctor's ``--merge``) can group files per worker.

Dependency-free on purpose: monitoring and telemetry import this from
below the distributed plane.
"""
from __future__ import annotations

import os
from typing import Optional

ENV_WORKER_ID = "WINDFLOW_WORKER_ID"


def worker_id() -> Optional[int]:
    """This process's worker id, or None outside a distributed run."""
    raw = os.environ.get(ENV_WORKER_ID)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def worker_suffix() -> str:
    """File-name component: ``"_w<id>"`` in a worker, else ``""``."""
    wid = worker_id()
    return "" if wid is None else f"_w{wid}"
