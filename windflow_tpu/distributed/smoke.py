"""Two-process localhost smoke of the distributed runtime::

    python -m windflow_tpu.distributed.smoke [n_tuples]
    python -m windflow_tpu.distributed.smoke --live [n_tuples]

Default mode builds a tiny keyed pipeline (source -> KEYBY accumulator
-> sink), runs it once in-process and once as a real 2-worker run over
the shuffle transport, and asserts the distributed results are
identical and every wire edge balanced.

``--live`` smokes the mission-control plane (docs/OBSERVABILITY.md
"Live cluster view" / "SLO plane"): a 2-worker run with a deliberately
slow REMOTE operator is polled MID-RUN through the coordinator's
ClusterObserver ``/cluster`` endpoint -- zero stats files read -- and
the exit asserts the live merged doctor verdict named the remote
bottleneck (worker-annotated) and an ``slo_breach`` episode opened
within seconds of onset; then ``doctor --watch --once`` renders the
same view through the CLI.  CI runs both modes in both channel-plane
jobs; exit 0 == the zero-to-distributed(-and-observed) path works on
this box.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

N_KEYS = 8


def _records(n):
    # absolute import: under ``python -m`` this module is __main__ and
    # the workers re-load it straight from the source file, where a
    # relative import has no package context
    from windflow_tpu.core.tuples import BasicRecord
    for i in range(n):
        yield BasicRecord(i % N_KEYS, i // N_KEYS, i, float(i % 13))


def _build_ops(g, n, sink_fn):
    import windflow_tpu as wf

    it = iter(_records(n))

    def src(shipper):
        for rec in it:
            shipper.push(rec)
            return True
        return False

    def fold(t, acc):
        acc.value += t.value

    g.add_source(wf.SourceBuilder(src).with_name("smoke_src").build()) \
        .add(wf.AccumulatorBuilder(fold).with_name("smoke_fold")
             .with_parallelism(2).build()) \
        .add_sink(wf.SinkBuilder(sink_fn).with_name("smoke_sink").build())
    return g


def smoke_build(g):
    """Worker-side build (imported by both worker processes)."""
    n = int(os.environ.get("WINDFLOW_SMOKE_N", "20000"))
    out_path = os.environ["WINDFLOW_SMOKE_OUT"]
    out = []

    def sink(rec):
        if rec is None:
            with open(out_path, "w") as f:
                json.dump(sorted(out), f)
        else:
            out.append([rec.key, rec.id, rec.value])

    _build_ops(g, n, sink)


def _local_run(n):
    import windflow_tpu as wf
    out = []

    def sink(rec):
        if rec is not None:
            out.append([rec.key, rec.id, rec.value])

    g = wf.PipeGraph("smoke_local")
    _build_ops(g, n, sink)
    g.run()
    return sorted(out)


def live_build(g):
    """Worker-side build of the --live mode: fast source -> KEYBY
    deliberately slow map (the partition planner cuts at the KEYBY
    edge, so the slow operator lands on the REMOTE worker) -> sink."""
    import time

    import windflow_tpu as wf
    from windflow_tpu.core.tuples import BasicRecord
    n = int(os.environ.get("WINDFLOW_SMOKE_N", "6000"))
    it = iter(range(n))

    def src(shipper):
        for i in it:
            shipper.push(BasicRecord(i % N_KEYS, i // N_KEYS, i,
                                     float(i % 13)))
            return True
        return False

    def slow(t):
        time.sleep(0.001)
        return t

    seen = []

    def sink(rec):
        if rec is not None:
            seen.append(1)

    g.add_source(wf.SourceBuilder(src).with_name("live_src").build()) \
        .add(wf.MapBuilder(slow).with_name("live_slow")
             .with_key_by().build()) \
        .add_sink(wf.SinkBuilder(sink).with_name("live_sink").build())


def live_config(worker_id):
    import windflow_tpu as wf
    from windflow_tpu.slo import SloConfig
    # traced (e2e p99 observable), a hopelessly tight p99 budget so the
    # slow operator burns the error budget immediately, fast diagnosis
    # ticks so detection rides a sub-second cadence
    return wf.RuntimeConfig(
        tracing=True, trace_sample=16, diagnosis_interval_s=0.2,
        slo=SloConfig(p99_ms=0.5, target=0.9, fast_burn=5.0),
        log_dir=os.environ.get("WINDFLOW_SMOKE_LOG", "log"))


def _live_main(n: int) -> int:
    from windflow_tpu.distributed.runtime import run_distributed
    with tempfile.TemporaryDirectory(
            prefix="windflow_live_smoke_") as td:
        return _live_run(td, n, run_distributed)


def _live_run(td: str, n: int, run_distributed) -> int:
    import threading
    import time
    import urllib.request
    workdir = os.path.join(td, "work")
    os.environ["WINDFLOW_SMOKE_N"] = str(n)
    os.environ["WINDFLOW_SMOKE_LOG"] = os.path.join(td, "log")
    box = {}

    def runner():
        try:
            box["report"] = run_distributed(
                live_build, n_workers=2, config_fn=live_config,
                graph_name="live_smoke", workdir=workdir,
                timeout_s=240.0)
        except BaseException as e:  # surfaced after the poll loop
            box["error"] = e

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    # find the observer endpoint (written by the coordinator), then
    # poll /cluster until the live merged verdict names the remote
    # bottleneck AND an slo_breach episode is open -- all MID-RUN,
    # reading zero stats files
    obs_path = os.path.join(workdir, "observer.json")
    deadline = time.monotonic() + 120.0
    url = None
    while url is None and time.monotonic() < deadline:
        try:
            with open(obs_path) as f:
                url = json.load(f)["http"] + "/cluster"
        except (OSError, ValueError, KeyError):
            time.sleep(0.1)
    if url is None:
        print("live smoke: observer endpoint never appeared",
              file=sys.stderr)
        return 1
    named_at = breach_at = None
    onset = time.monotonic()
    while (named_at is None or breach_at is None) \
            and time.monotonic() < deadline and t.is_alive():
        time.sleep(0.25)
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                doc = json.loads(r.read().decode())
        except (OSError, ValueError):
            continue
        merged = doc.get("merged") or {}
        rep = doc.get("report") or {}
        bn = rep.get("Bottleneck") or {}
        ops = {op.get("Operator_name"): op.get("Worker")
               for op in merged.get("Operators") or ()}
        if named_at is None and bn.get("Operator") \
                and "live_slow" in bn["Operator"] \
                and ops.get(bn["Operator"]) is not None \
                and ops.get("pipe0/live_src") is not None \
                and ops[bn["Operator"]] != ops["pipe0/live_src"]:
            named_at = time.monotonic()
        if breach_at is None and any(
                e.get("kind") == "slo_breach"
                for e in merged.get("Flight") or ()):
            breach_at = time.monotonic()
    mid_run = t.is_alive()
    # the CLI's watch mode against the SAME live endpoint, while the
    # run is still going (one refresh; the in-place loop is the same
    # code path)
    from windflow_tpu.doctor import main as doctor_main
    watch_rc = doctor_main(["--watch", url, "--once"]) if mid_run else 0
    t.join(timeout=240.0)
    if "error" in box:
        print(f"live smoke: run failed: {box['error']}", file=sys.stderr)
        return 1
    if named_at is None or breach_at is None or watch_rc != 0:
        print(f"live smoke: FAILED -- remote bottleneck named: "
              f"{named_at is not None}, slo_breach seen: "
              f"{breach_at is not None}, watch rc={watch_rc} "
              f"(mid_run={mid_run})",
              file=sys.stderr)
        return 1
    rep = box["report"]
    rc = doctor_main([*rep["stats_paths"], "--merge"])
    if rc != 0:
        print("live smoke: doctor --merge failed", file=sys.stderr)
        return 1
    slo = (rep.get("live_merged") or {}).get("Slo") or {}
    print(f"live smoke: OK -- remote bottleneck named live in "
          f"{named_at - onset:.1f}s, slo_breach in "
          f"{breach_at - onset:.1f}s (mid_run={mid_run}, "
          f"budget {slo.get('Budget_burned', 0) * 100:.0f}% burned)")
    return 0


def main(argv=None) -> int:
    from windflow_tpu.distributed.observe import check_wire_conservation
    from windflow_tpu.distributed.runtime import run_distributed
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--live":
        return _live_main(int(argv[1]) if len(argv) > 1 else 8000)
    n = int(argv[0]) if argv else 20000
    expect = _local_run(n)
    with tempfile.TemporaryDirectory() as td:
        out_path = os.path.join(td, "smoke_out.json")
        os.environ["WINDFLOW_SMOKE_N"] = str(n)
        os.environ["WINDFLOW_SMOKE_OUT"] = out_path
        report = run_distributed(smoke_build, n_workers=2,
                                 graph_name="smoke",
                                 workdir=os.path.join(td, "work"),
                                 timeout_s=120.0)
        with open(out_path) as f:
            got = json.load(f)
        violations = check_wire_conservation(report["worker_stats"])
        wire = (report["merged"].get("Wire") or {}).get("Edges") or []
        if got != expect:
            print(f"smoke: MISMATCH ({len(got)} vs {len(expect)} rows)",
                  file=sys.stderr)
            return 1
        if violations or not all(r["balanced"] for r in wire):
            print(f"smoke: wire imbalance {violations}", file=sys.stderr)
            return 1
    print(f"smoke: OK -- {n} tuples, {len(expect)} sink rows bitwise "
          f"equal across 2 workers; {len(wire)} wire edge(s) balanced")
    return 0


if __name__ == "__main__":
    sys.exit(main())
