"""Two-process localhost smoke of the distributed runtime::

    python -m windflow_tpu.distributed.smoke [n_tuples]

Builds a tiny keyed pipeline (source -> KEYBY accumulator -> sink),
runs it once in-process and once as a real 2-worker run over the
shuffle transport, and asserts the distributed results are identical
and every wire edge balanced.  CI runs this in both channel-plane
jobs; exit 0 == the zero-to-distributed path works on this box.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

N_KEYS = 8


def _records(n):
    # absolute import: under ``python -m`` this module is __main__ and
    # the workers re-load it straight from the source file, where a
    # relative import has no package context
    from windflow_tpu.core.tuples import BasicRecord
    for i in range(n):
        yield BasicRecord(i % N_KEYS, i // N_KEYS, i, float(i % 13))


def _build_ops(g, n, sink_fn):
    import windflow_tpu as wf

    it = iter(_records(n))

    def src(shipper):
        for rec in it:
            shipper.push(rec)
            return True
        return False

    def fold(t, acc):
        acc.value += t.value

    g.add_source(wf.SourceBuilder(src).with_name("smoke_src").build()) \
        .add(wf.AccumulatorBuilder(fold).with_name("smoke_fold")
             .with_parallelism(2).build()) \
        .add_sink(wf.SinkBuilder(sink_fn).with_name("smoke_sink").build())
    return g


def smoke_build(g):
    """Worker-side build (imported by both worker processes)."""
    n = int(os.environ.get("WINDFLOW_SMOKE_N", "20000"))
    out_path = os.environ["WINDFLOW_SMOKE_OUT"]
    out = []

    def sink(rec):
        if rec is None:
            with open(out_path, "w") as f:
                json.dump(sorted(out), f)
        else:
            out.append([rec.key, rec.id, rec.value])

    _build_ops(g, n, sink)


def _local_run(n):
    import windflow_tpu as wf
    out = []

    def sink(rec):
        if rec is not None:
            out.append([rec.key, rec.id, rec.value])

    g = wf.PipeGraph("smoke_local")
    _build_ops(g, n, sink)
    g.run()
    return sorted(out)


def main(argv=None) -> int:
    from windflow_tpu.distributed.observe import check_wire_conservation
    from windflow_tpu.distributed.runtime import run_distributed
    argv = sys.argv[1:] if argv is None else argv
    n = int(argv[0]) if argv else 20000
    expect = _local_run(n)
    with tempfile.TemporaryDirectory() as td:
        out_path = os.path.join(td, "smoke_out.json")
        os.environ["WINDFLOW_SMOKE_N"] = str(n)
        os.environ["WINDFLOW_SMOKE_OUT"] = out_path
        report = run_distributed(smoke_build, n_workers=2,
                                 graph_name="smoke",
                                 workdir=os.path.join(td, "work"),
                                 timeout_s=120.0)
        with open(out_path) as f:
            got = json.load(f)
        violations = check_wire_conservation(report["worker_stats"])
        wire = (report["merged"].get("Wire") or {}).get("Edges") or []
        if got != expect:
            print(f"smoke: MISMATCH ({len(got)} vs {len(expect)} rows)",
                  file=sys.stderr)
            return 1
        if violations or not all(r["balanced"] for r in wire):
            print(f"smoke: wire imbalance {violations}", file=sys.stderr)
            return 1
    print(f"smoke: OK -- {n} tuples, {len(expect)} sink rows bitwise "
          f"equal across 2 workers; {len(wire)} wire edge(s) balanced")
    return 0


if __name__ == "__main__":
    sys.exit(main())
