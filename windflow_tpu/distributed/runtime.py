"""Worker-process orchestration of a distributed PipeGraph
(docs/DISTRIBUTED.md "Running a distributed graph").

The model mirrors ``run_with_epochs``: the user provides a BUILD
function (top-level, importable -- each worker imports and calls it
against a fresh graph, so nothing needs to pickle) and optionally a
CONFIG factory ``config_fn(worker_id) -> RuntimeConfig`` next to it.
:func:`run_distributed` is the coordinator: it allocates loopback
endpoints, spawns one clean ``python -m windflow_tpu.distributed.worker``
process per worker (no JAX / no parent state inherited -- a worker
only imports what its partition runs), waits for them, and merges the
per-worker stats JSON dumps into one graph view whose cross-process
wire books must balance.

With ``RuntimeConfig.durability`` set, the coordinator is also the
restart loop: each worker commits its partition's epoch manifests
under ``<path>/w<i>``; on a worker death (a crash, or an injected
``FaultPlan.kill_worker``) every process is reaped and the whole graph
restarts from the newest epoch committed by EVERY worker -- a globally
consistent cut, because aligned barriers crossed the wire before any
worker committed them.
"""
from __future__ import annotations

import inspect
import json
import os
import socket
import subprocess
import sys
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .wiring import KILL_EXIT


@dataclass
class DistributedSpec:
    """Per-worker distributed-runtime parameters
    (``RuntimeConfig.distributed``)."""

    worker_id: int
    n_workers: int
    # shuffle-server endpoint per worker, index == worker id
    endpoints: Sequence[Tuple[str, int]]
    # operator-substring -> worker pins, merged over .with_worker
    assignment: Optional[Dict[str, int]] = None
    # credit window of each wire edge (tuples outstanding past the
    # consumer's bounded channel)
    wire_credits: int = 1 << 15
    # transparent reconnect budget per sender before the edge fails
    wire_reconnects: int = 2
    # how long a receiver waits for a producer to come back before the
    # edge counts as lost (graph cancels)
    reconnect_grace_s: float = 2.0
    connect_timeout_s: float = 15.0
    # live cluster view (observe.py): the coordinator's ClusterObserver
    # ingest endpoint -- when set, the wiring attaches a StatsPusher
    # that pushes stats + flight deltas every push_interval_s
    observe_endpoint: Optional[Tuple[str, int]] = None
    push_interval_s: float = 0.5
    extra: dict = field(default_factory=dict)


class WorkerFailure(RuntimeError):
    """One or more workers exited abnormally past the restart budget."""

    def __init__(self, msg: str, exit_codes=None, logs=None):
        super().__init__(msg)
        self.exit_codes = exit_codes or {}
        self.logs = logs or {}


def _callable_ref(fn: Callable) -> Dict[str, str]:
    """(file, qualname) reference a worker can import without pickling.
    Lambdas/closures are rejected loudly -- the build function runs in
    another process."""
    name = getattr(fn, "__qualname__", getattr(fn, "__name__", ""))
    if not name or "<" in name:
        raise ValueError(
            f"distributed build/config functions must be importable "
            f"top-level functions, not {name or fn!r} "
            "(docs/DISTRIBUTED.md)")
    try:
        path = inspect.getfile(fn)
    except TypeError as e:
        raise ValueError(
            f"cannot locate source file of {name} for worker import"
        ) from e
    return {"file": os.path.abspath(path), "name": name,
            "module": getattr(fn, "__module__", None)}


def _load_ref(ref: Dict[str, str]) -> Callable:
    """Worker-side import: prefer the real module path (package files
    keep their relative imports), fall back to loading the source file
    directly (test files / scripts that are not importable as modules
    in a fresh interpreter)."""
    import importlib
    import importlib.util
    mod = None
    modname = ref.get("module")
    if modname and modname != "__main__":
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            mod = None
    if mod is None:
        alias = "_windflow_dist_" + os.path.basename(
            ref["file"]).replace(".", "_")
        mod = sys.modules.get(alias)
        if mod is None:
            spec = importlib.util.spec_from_file_location(alias,
                                                          ref["file"])
            mod = importlib.util.module_from_spec(spec)
            sys.modules[alias] = mod
            spec.loader.exec_module(mod)
    obj = mod
    for part in ref["name"].split("."):
        obj = getattr(obj, part)
    return obj


def free_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    """``n`` currently-free TCP ports (best-effort: bound then released,
    so a race is possible but the spawn follows immediately)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


# ---------------------------------------------------------------------------
# worker side (invoked by distributed/worker.py with the spec JSON)
# ---------------------------------------------------------------------------

def _worker_durability(cfg, worker_id: int):
    """Re-root the manifest store per worker: one partition, one
    manifest stream."""
    import dataclasses
    if cfg.durability is None:
        return None
    cfg.durability = dataclasses.replace(
        cfg.durability,
        path=os.path.join(cfg.durability.path, f"w{worker_id}"))
    return cfg.durability


def _restore_worker(graph, store, epoch: int, plan, worker_id: int,
                    overrides: Optional[dict] = None) -> int:
    """Load this worker's slice of epoch ``epoch`` into an unstarted
    graph.  The manifest was written by the same partition, so its
    stateful-name set must equal the owned stateful set -- a silent
    partial restore would desync the workers.  ``overrides``
    (operator name -> new parallelism, from
    ``run_distributed(parallelism_overrides=...)``) lifts named replica
    groups out of that contract and repartitions their keyed state
    through the elastic ``hash % n`` owner function, PROVIDED the
    whole group lives on this worker -- a group split across workers
    cannot be repartitioned from one worker's manifest alone."""
    import pickle
    from ..utils.checkpoint import (_is_stateful, _override_for,
                                    _replica_group, _repartition_group)
    from ..durability.delta import load_into
    payload = store.load(epoch)
    states = payload.get("states") or {}
    owned_stateful = set()
    loaded = 0
    owned_nodes = {}
    for n in graph._all_nodes():
        if plan.get(n.name) != worker_id:
            continue
        if not _is_stateful(n.logic):
            continue
        owned_stateful.add(n.name)
        owned_nodes[n.name] = n
    missing = owned_stateful - set(states)
    foreign = set(states) - owned_stateful
    handled = set()
    if (missing or foreign) and overrides:
        groups = set()
        for name in list(missing) + list(foreign):
            prefix, _idx = _replica_group(name)
            if prefix is not None and _override_for(prefix, overrides):
                groups.add(prefix)
        for prefix in sorted(groups):
            off_worker = [n.name for n in graph._all_nodes()
                          if _replica_group(n.name)[0] == prefix
                          and plan.get(n.name) != worker_id]
            if off_worker:
                raise RuntimeError(
                    f"parallelism override for {prefix!r} needs the "
                    f"whole replica group on worker {worker_id}, but "
                    f"{sorted(off_worker)} are placed elsewhere -- pin "
                    "the operator to one worker to restore it into a "
                    "different parallelism (docs/DISTRIBUTED.md)")
            manifest_names = sorted(
                n for n in states if _replica_group(n)[0] == prefix)
            group_logics = sorted(
                ((_replica_group(nm)[1], nd.logic)
                 for nm, nd in owned_nodes.items()
                 if _replica_group(nm)[0] == prefix),
                key=lambda t: t[0])
            if not manifest_names or not group_logics:
                continue
            _repartition_group(
                prefix, f"epoch manifest (epoch {epoch})", states,
                pickle.loads, manifest_names, group_logics)
            loaded += len(group_logics)
            handled.update(manifest_names)
            handled.update(nm for nm in owned_nodes
                           if _replica_group(nm)[0] == prefix)
            missing -= {nm for nm in missing
                        if _replica_group(nm)[0] == prefix}
            foreign -= set(manifest_names)
    if missing or foreign:
        raise RuntimeError(
            f"epoch manifest (epoch {epoch}) does not match worker "
            f"{worker_id}'s partition: missing states {sorted(missing)}, "
            f"foreign states {sorted(foreign)} -- was the graph or the "
            "partition changed between restarts? (docs/DISTRIBUTED.md)")
    for name, n in owned_nodes.items():
        if name in handled:
            continue
        blob = states.get(name)
        if blob is not None:
            load_into(n.logic, pickle.loads(blob))
            loaded += 1
    return loaded


def worker_main(spec_doc: dict) -> int:
    """One worker process: build, partition, restore, run, dump."""
    from ..core.basic import RuntimeConfig
    from .identity import ENV_WORKER_ID
    from .partition import plan_partition
    wid = int(spec_doc["worker_id"])
    os.environ[ENV_WORKER_ID] = str(wid)
    build = _load_ref(spec_doc["build"])
    config_fn = (_load_ref(spec_doc["config"])
                 if spec_doc.get("config") else None)
    cfg = config_fn(wid) if config_fn is not None else RuntimeConfig()
    dcfg = _worker_durability(cfg, wid)
    observe = spec_doc.get("observe")
    cfg.distributed = DistributedSpec(
        worker_id=wid,
        n_workers=int(spec_doc["n_workers"]),
        endpoints=[tuple(e) for e in spec_doc["endpoints"]],
        assignment=spec_doc.get("assignment") or None,
        observe_endpoint=(observe[0], int(observe[1]))
        if observe else None,
        **(spec_doc.get("wire") or {}))
    from ..graph.pipegraph import PipeGraph
    g = PipeGraph(spec_doc.get("graph_name", "dist"), config=cfg)
    build(g)
    restore = spec_doc.get("restore_epoch")
    if restore:
        from ..durability.store import EpochStore
        plan = plan_partition(g)
        store = EpochStore(dcfg.path, dcfg.retained)
        n = _restore_worker(g, store, int(restore), plan, wid,
                            overrides=spec_doc.get("overrides") or None)
        g._epoch_restored = int(restore)
        g.flight.record("epoch_restore", epoch=int(restore), replicas=n,
                        worker=wid, attempt=spec_doc.get("attempt", 0))
    stats_path = spec_doc.get("stats_path")
    try:
        g.run()
        return 0
    except BaseException:
        import traceback
        traceback.print_exc()
        return 1
    finally:
        if stats_path:
            try:
                g.refresh_gauges()
                with open(stats_path, "w") as f:
                    f.write(g.stats.to_json(
                        g.get_num_dropped_tuples(),
                        g.dead_letters.count(),
                        flight_events=g.flight.snapshot()))
            except Exception:
                pass  # post-mortem dump is best-effort


# ---------------------------------------------------------------------------
# coordinator side
# ---------------------------------------------------------------------------

def _common_epoch(dcfg, n_workers: int) -> Optional[int]:
    """Newest epoch committed by EVERY worker (the globally consistent
    restore point), or None when any worker has nothing loadable."""
    from ..durability.store import EpochStore
    floor = None
    for w in range(n_workers):
        store = EpochStore(os.path.join(dcfg.path, f"w{w}"),
                           dcfg.retained)
        e, _payload = store.latest()
        if e is None:
            return None
        floor = e if floor is None else min(floor, e)
    return floor


def run_distributed(build: Callable, n_workers: int = 2, *,
                    config_fn: Optional[Callable] = None,
                    graph_name: str = "dist",
                    assignment: Optional[Dict[str, int]] = None,
                    workdir: Optional[str] = None,
                    max_restarts: int = 0,
                    timeout_s: float = 300.0,
                    wire: Optional[dict] = None,
                    observe: bool = True,
                    parallelism_overrides: Optional[dict] = None) -> dict:
    """Run ``build`` as one PipeGraph across ``n_workers`` processes.

    Returns a report dict: per-worker stats paths, the merged one-graph
    view (:func:`~.observe.merge_stats`), attempts taken, and per-worker
    exit codes.  Raises :class:`WorkerFailure` when workers still fail
    past ``max_restarts``.

    With ``observe`` (the default) the coordinator also runs a live
    :class:`~.observe.ClusterObserver`: workers push stats + flight
    deltas to it mid-run, the continuously-merged view (and its doctor
    report) is served at ``GET /cluster``, and the endpoint is written
    to ``<workdir>/observer.json`` so tools -- notably ``python -m
    windflow_tpu.doctor --watch <url>`` -- can find it while the run
    is still going.  The observer survives restart attempts, so the
    live view spans a kill-restart cycle.
    """
    from .observe import ClusterObserver, merge_stats
    build_ref = _callable_ref(build)
    config_ref = _callable_ref(config_fn) if config_fn else None
    workdir = workdir or os.path.join("log", f"dist_{graph_name}")
    os.makedirs(workdir, exist_ok=True)
    dcfg = config_fn(0).durability if config_fn else None
    observer = None
    if observe:
        observer = ClusterObserver()
        observer.start()
        observer.serve_http()
        with open(os.path.join(workdir, "observer.json"), "w") as f:
            json.dump({"http": observer.http_url,
                       "ingest": [observer.host, observer.port]}, f)
    attempts = 0
    history: List[Dict[int, int]] = []
    while True:
        ports = free_ports(n_workers)
        endpoints = [["127.0.0.1", p] for p in ports]
        restore = (_common_epoch(dcfg, n_workers)
                   if dcfg is not None and attempts > 0 else None)
        procs: Dict[int, subprocess.Popen] = {}
        logs: Dict[int, str] = {}
        stats_paths: Dict[int, str] = {}
        for w in range(n_workers):
            spec_doc = {
                "worker_id": w, "n_workers": n_workers,
                "endpoints": endpoints,
                "build": build_ref, "config": config_ref,
                "graph_name": graph_name,
                "assignment": assignment,
                "stats_path": os.path.join(
                    workdir, f"stats_w{w}.json"),
                "restore_epoch": restore,
                "attempt": attempts,
                "overrides": parallelism_overrides,
                "wire": wire or {},
                "observe": ([observer.host, observer.port]
                            if observer is not None else None),
            }
            stats_paths[w] = spec_doc["stats_path"]
            logs[w] = os.path.join(workdir, f"worker_{w}.log")
            env = dict(os.environ)
            env["WINDFLOW_WORKER_ID"] = str(w)
            # restart context for build-side effect writers (e.g. an
            # epoch-keyed sink file that supersedes a crashed attempt's
            # uncommitted tail at read time)
            env["WINDFLOW_DIST_ATTEMPT"] = str(attempts)
            env["WINDFLOW_DIST_RESTORE"] = str(restore or 0)
            # the workers must import THIS windflow_tpu regardless of
            # the coordinator's cwd / install mode
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            env["PYTHONPATH"] = pkg_root + os.pathsep + \
                env.get("PYTHONPATH", "")
            with open(logs[w], "ab") as logf:
                logf.write(f"==== attempt {attempts} ====\n".encode())
                procs[w] = subprocess.Popen(
                    [sys.executable, "-m",
                     "windflow_tpu.distributed.worker",
                     json.dumps(spec_doc)],
                    stdout=logf, stderr=subprocess.STDOUT, env=env,
                    cwd=os.getcwd())
        deadline = _time.monotonic() + timeout_s
        codes: Dict[int, int] = {}
        try:
            while len(codes) < n_workers:
                for w, p in procs.items():
                    if w in codes:
                        continue
                    rc = p.poll()
                    if rc is not None:
                        codes[w] = rc
                if _time.monotonic() > deadline:
                    if observer is not None:
                        observer.stop()
                    raise WorkerFailure(
                        f"distributed run timed out after {timeout_s}s "
                        f"(exited: {codes})", codes, logs)
                if any(rc != 0 for rc in codes.values()) \
                        and len(codes) < n_workers:
                    # one worker died: give peers a moment to observe
                    # the broken wire and unwind, then reap them
                    grace = _time.monotonic() + 20.0
                    while len(codes) < n_workers \
                            and _time.monotonic() < grace:
                        for w, p in procs.items():
                            if w not in codes and p.poll() is not None:
                                codes[w] = p.returncode
                        _time.sleep(0.05)
                    for w, p in procs.items():
                        if w not in codes:
                            p.terminate()
                            try:
                                codes[w] = p.wait(timeout=10.0)
                            except subprocess.TimeoutExpired:
                                # wedged past SIGTERM (native code):
                                # hard-kill; the exception contract
                                # stays WorkerFailure, never a raw
                                # TimeoutExpired
                                p.kill()
                                codes[w] = p.wait(timeout=10.0)
                    break
                _time.sleep(0.05)
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
                    try:
                        p.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        pass  # unkillable zombie: reporting still wins
        history.append(dict(codes))
        if all(rc == 0 for rc in codes.values()):
            stats = []
            for w in range(n_workers):
                try:
                    with open(stats_paths[w]) as f:
                        stats.append(json.load(f))
                except (OSError, ValueError):
                    stats.append(None)
            live_merged = None
            observer_info = None
            if observer is not None:
                # the live view's final fold (what --watch last saw),
                # next to the authoritative file-based merge below
                live_merged = observer.merged()
                observer_info = {"url": observer.http_url,
                                 "pushes": observer.pushes}
                observer.stop()
            return {
                "attempts": attempts + 1,
                "exit_codes": history,
                "stats_paths": [stats_paths[w] for w in range(n_workers)],
                "worker_stats": stats,
                "merged": merge_stats([s for s in stats if s]),
                "live_merged": live_merged,
                "observer": observer_info,
                "logs": [logs[w] for w in range(n_workers)],
            }
        attempts += 1
        if attempts > max_restarts:
            tails = {}
            for w, lp in logs.items():
                try:
                    with open(lp, errors="replace") as f:
                        tails[w] = f.read()[-2000:]
                except OSError:
                    tails[w] = ""
            killed = [w for w, rc in codes.items() if rc == KILL_EXIT]
            if observer is not None:
                observer.stop()
            raise WorkerFailure(
                f"distributed run failed after {attempts} attempt(s): "
                f"exit codes {codes}"
                + (f" (injected kill on worker(s) {killed})"
                   if killed else ""),
                codes, tails)
