"""Operator-to-worker partitioning of one logical PipeGraph
(docs/DISTRIBUTED.md "Partitioning").

Every worker process builds the SAME wired graph (the user's build
function is deterministic by contract) and runs this planner over it,
so all workers agree on ownership without shipping a plan: the plan is
a pure function of the wired topology, the ``.with_worker(i)`` pins
and the spec's assignment overrides.

The cut rule follows the fusion pass's grain: nodes connected by any
edge that is NOT a shuffle edge stay **co-located** (fused FORWARD
runs, farm collectors, broadcast/splitting/window-multicast wiring --
none of those can cross a process without changing semantics or
wasting a hop), and only KEYBY shuffle edges -- whose routing is a
pure ``hash % n`` of the item, independent of which process computes
it -- are eligible cut points.  An explicit ``.with_worker(i)`` pin
additionally cuts the edge between two differently-pinned operators
(the fusion pass refuses to fuse across such a pin for the same
reason).

Groups are assigned to workers deterministically: pinned groups go
where they point; unpinned groups go to the least-loaded worker (by
node count, ties to the lowest id) in topology order.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..audit.ledger import unwrap
from ..runtime.emitters import StandardEmitter


class PartitionError(ValueError):
    """Inconsistent pins / unpartitionable graph."""


def _pin_of(node, overrides: Optional[Dict[str, int]]) -> Optional[int]:
    """Effective pin of one (pre-fusion) node: spec assignment
    overrides beat builder pins.  Longest matching substring wins
    (then lexicographic, for determinism), so a more specific override
    -- {"fold": 0, "fold_heavy": 1} -- is never shadowed by its
    prefix."""
    if overrides:
        for sub in sorted(overrides, key=lambda s: (-len(s), s)):
            if sub in node.name:
                return int(overrides[sub])
    return getattr(node, "worker_pin", None)


def _is_shuffle_edge(outlet) -> bool:
    """True when the edge routed by ``outlet`` may cross processes:
    per-key hash routing is location-independent by construction --
    the KEYBY StandardEmitter and the Key_Farm emitter under its
    default ``hash % n`` (a custom routing callable might close over
    process-local state, so it pins its stage to its producers)."""
    from ..runtime.win_routing import KFEmitter
    em = outlet.emitter
    if type(em) is StandardEmitter:
        return bool(getattr(em, "keyed", False))
    if isinstance(em, KFEmitter):
        return bool(getattr(em, "_default_routing", False))
    return False


def plan_partition(graph, n_workers: Optional[int] = None,
                   overrides: Optional[Dict[str, int]] = None
                   ) -> Dict[str, int]:
    """Compute (and memoize on ``graph._dist_plan``) the node-name ->
    worker-id assignment of an UNSTARTED, fully wired graph.  Runs
    before the fusion pass; the fusion pass consults the plan so fused
    nodes never straddle workers."""
    spec = getattr(graph.config, "distributed", None)
    if n_workers is None:
        n_workers = int(getattr(spec, "n_workers", 1) or 1)
    if overrides is None:
        overrides = dict(getattr(spec, "assignment", None) or {})
    nodes = graph._all_nodes()
    index = {id(n): i for i, n in enumerate(nodes)}
    consumer = {}
    for n in nodes:
        if n.channel is not None:
            consumer[id(unwrap(n.channel))] = n
    pins = {id(n): _pin_of(n, overrides) for n in nodes}
    for nid, pin in pins.items():
        if pin is not None and not 0 <= pin < n_workers:
            raise PartitionError(
                f"with_worker({pin}) is outside the worker range "
                f"[0, {n_workers})")

    # union-find over co-location constraints
    parent = {id(n): id(n) for n in nodes}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    for n in nodes:
        for o in n.outlets:
            for ch, _pid in o.dests:
                c = consumer.get(id(unwrap(ch)))
                if c is None or c is n:
                    continue
                pa, pb = pins[id(n)], pins[id(c)]
                pinned_apart = (pa is not None and pb is not None
                                and pa != pb)
                if pinned_apart:
                    continue  # explicit cut, even on a FORWARD edge
                if not _is_shuffle_edge(o):
                    union(id(n), id(c))

    groups: Dict[int, List] = {}
    for n in nodes:
        groups.setdefault(find(id(n)), []).append(n)
    ordered = sorted(groups.values(),
                     key=lambda members: min(index[id(m)] for m in members))

    load = [0] * n_workers
    plan: Dict[str, int] = {}
    for members in ordered:
        gp = {pins[id(m)] for m in members if pins[id(m)] is not None}
        if len(gp) > 1:
            named = sorted(m.name for m in members
                           if pins[id(m)] is not None)
            raise PartitionError(
                "conflicting .with_worker pins inside one co-located "
                f"group (members {named} pin to {sorted(gp)}); only "
                "KEYBY shuffle edges can cut between workers "
                "(docs/DISTRIBUTED.md)")
        w = gp.pop() if gp else min(range(n_workers),
                                    key=lambda i: (load[i], i))
        load[w] += len(members)
        for m in members:
            plan[m.name] = w
    graph._dist_plan = plan
    return plan


def node_owner(node, plan: Dict[str, int]) -> int:
    """Owner of one (possibly fused) runtime node under ``plan``.  A
    fused node's segments must agree -- the fusion pass guarantees it;
    this assert is the defense against a pass regression."""
    from ..runtime.node import FusedLogic
    if isinstance(node.logic, FusedLogic):
        owners = {plan[seg.name] for seg in node.logic.segments
                  if seg.name in plan}
        if len(owners) != 1:
            raise PartitionError(
                f"fused node {node.name!r} straddles workers "
                f"{sorted(owners)}; the fusion pass must not fuse "
                "across the partition")
        return owners.pop()
    return plan[node.name]
