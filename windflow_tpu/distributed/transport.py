"""Credit-backpressured shuffle transport: cross-worker PipeGraph edges
over non-blocking TCP (docs/DISTRIBUTED.md "Shuffle transport").

One **edge** = one consumer replica's inbound channel.  When the
partition plan puts a producer and that consumer in different workers,
the producer's outlet destination is swapped for a
:class:`RemoteEdgeSender` (same channel duck type the runtime already
speaks: ``put``/``put_many``/``close``/``poison`` plus the counter
surface the audit ledger reads), and the consumer's worker runs a
:class:`ShuffleServer` whose receiver threads decode frames back into
the real channel.  Everything an in-process edge carries rides the
frames: data batches, scalar records, ``EpochBarrier`` control items,
per-producer EOS -- so fusion, alignment, audit books and EOS
propagation behave identically on both sides of the wire.

Backpressure is PR 2's credit protocol extended across the socket: the
sender spends a :class:`~windflow_tpu.ingest.credits.CreditGate`
budget per tuple and the receiver grants credits back only AFTER the
item landed in the consumer's bounded channel -- a slow remote
consumer therefore throttles the remote producer exactly like an
in-process ``CreditedChannel`` (and the kernel's flow control never
needs to buffer more than the credit window).

Reliability: data-plane frames are sequenced per (edge, producer
worker); the sender keeps a replay buffer of unacked frames (bounded
by the credit window) and, on a transport error, reconnects with a
resume HELLO -- the receiver replies with its acked sequence, the
sender retransmits the rest, and the receiver drops duplicates below
its high-water mark: no loss, no duplication across reconnects.  An
*injected* wire drop (``FaultPlan.drop_link``) skips the socket write
while still counting intent, which is exactly the divergence the
conservation surfaces must flag: the receiver sees the sequence gap
immediately and the producer's STATS trailer at edge close pins the
exact edge and tuple count.
"""
from __future__ import annotations

import random
import socket
import struct
import threading
import time as _time
import zlib
from collections import deque
from typing import Dict, Optional

from ..audit.ledger import _op_of
from ..ingest.credits import CreditGate
from ..resilience.cancel import GraphCancelled
from . import wire

# socket pacing: short timeouts keep every blocking call cancellable
_POLL_S = 0.1
_SEND_TIMEOUT_S = 5.0

# reconnect backoff envelope (RemoteEdgeSender._send_frame): base
# doubles per attempt up to the cap, then a multiplicative jitter of up
# to +50% spreads simultaneous retries apart
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 0.8
_BACKOFF_JITTER = 0.5


def backoff_delay(attempt: int, rng: random.Random) -> float:
    """Delay in seconds before reconnect ``attempt`` (0-based):
    capped exponential with multiplicative jitter."""
    d = min(_BACKOFF_CAP_S, _BACKOFF_BASE_S * (2 ** max(0, attempt)))
    return d * (1.0 + _BACKOFF_JITTER * rng.random())


class WireError(ConnectionError):
    """A shuffle edge broke beyond the reconnect budget."""


def _recv_some(sock) -> Optional[bytes]:
    """One poll-bounded recv; None on timeout, b'' on clean EOF."""
    try:
        return sock.recv(1 << 20)
    except socket.timeout:
        return None


class _PartialTraceView:
    """A live producer-side view of a trace that left this worker over
    a wire edge.  Serializes like a closed trace record but flagged
    ``partial``: attribution skips it (its span never reached a sink
    here), while the cross-worker merge
    (distributed/observe.stitch_traces) joins it by trace id into the
    consumer-side record that closed the same trace.  The view wraps
    the LIVE context, so hops stamped moments after the frame header
    was snapshotted -- fused upstream segments unwind outward through
    the send -- still make the producer's record and therefore the
    stitched cluster-wide one."""

    __slots__ = ("ctx", "edge")

    is_partial = True

    def __init__(self, ctx, edge: str):
        self.ctx = ctx
        self.edge = edge

    def to_dict(self, t_end: float) -> dict:
        d = self.ctx.to_dict(t_end)
        d["partial"] = True
        d["wire_edge"] = self.edge
        return d


class RemoteEdgeSender:
    """Producer-side half of one shuffle edge: a channel-duck-typed
    object the owning worker's outlets deliver into.

    Counter contract (audit/ledger.py): ``puts`` counts accepted items,
    ``gets`` acked ones, ``depth``/``qsize`` the unacked replay buffer
    -- so the per-edge books close locally at ``wait_end`` exactly like
    a bounded channel's (everything accepted was either acked or is
    demonstrably in the replay buffer).
    """

    is_wire_sender = True

    def __init__(self, edge: str, host: str, port: int, graph,
                 pids, spec, runtime=None):
        self.edge = edge                      # consumer node name
        self.edge_name = f"wire:{edge}"       # ledger / flight label
        self.consumer_op = _op_of(edge)       # diagnosis topology hint
        self.host = host
        self.port = port
        self.graph = graph
        self.spec = spec
        self.runtime = runtime
        self.gate = CreditGate(int(getattr(spec, "wire_credits", 1 << 15)))
        self._lock = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._seq = 0                         # next data-plane sequence
        self._unacked: deque = deque()        # (seq, frame, credits)
        self._acked_seq = 0
        self._pids = set(int(p) for p in pids)
        self._closed = set()
        self._finals = 0              # final barriers shipped (one/pid)
        self._barrier_seen: Dict[int, int] = {}
        self._barrier_acked = set()
        self._cancelled = False
        self._reader: Optional[threading.Thread] = None
        # link fault state (FaultPlan.drop_link / delay_link)
        self.faults = None
        # durability plane (set by EpochCoordinator.rewire)
        self.epoch_coord = None
        # -- counters (ledger surface + cross-process conservation) ----
        self.puts = 0
        self.gets = 0
        self.high_watermark = 0
        # running tuple sum of the replay buffer (gauge-grade read by
        # block(); maintained under the lock by _ship/_apply_ack so
        # the stats path never takes the send lock -- a reconnecting
        # producer may hold it for seconds)
        self.unacked_tuples = 0
        self.tuples_sent = 0
        self.frames_sent = 0
        self.barriers_sent = 0
        self.frames_dropped = 0
        self.reconnects = 0
        self.capacity = None
        # reconnect backoff (jittered exponential, _send_frame): seeded
        # per edge so a cluster of senders losing one consumer does not
        # retry in lockstep, yet each run's delay sequence is
        # reproducible from the edge name
        self._backoff_rng = random.Random(
            zlib.crc32(self.edge_name.encode("utf-8")))

    # -- channel duck type ---------------------------------------------
    @property
    def n_producers(self) -> int:
        return len(self._pids)

    @property
    def depth(self) -> int:
        return len(self._unacked)

    def qsize(self) -> int:
        return len(self._unacked)

    @property
    def poisoned(self) -> bool:
        return self._cancelled

    def put(self, producer_id: int, item) -> None:
        # credits are the cross-process backpressure: block here until
        # the remote consumer's grants catch up (cancel-aware).  The
        # cost is known before encoding, so a traced item's send stamp
        # is taken after any credit wait, not before it.  It must
        # mirror decode_item's grant exactly: batches cost their
        # length, everything else (records -- even ones with __len__ --
        # barriers, markers) costs 1, or the asymmetry would leak the
        # gate dry.
        from ..core.tuples import SynthChunk, TupleBatch
        if isinstance(item, (TupleBatch, SynthChunk)):
            cost = max(1, len(item))
        else:
            cost = 1
        self.gate.acquire(cost)
        ctx = getattr(item, "trace", None)
        kind, payload, cost = wire.encode_item(
            item, getattr(self.graph, "buffer_pool", None))
        self._ship(kind, producer_id, payload, cost,
                   barrier=item if kind == wire.MSG_BARRIER else None)
        if ctx is not None and getattr(ctx, "trace_id", None) \
                and kind in (wire.MSG_DATA, wire.MSG_RECORD):
            # producer-side PARTIAL trace record: the trace continues
            # on the consumer worker, but this worker's share of it --
            # including hops that land after the frame header snapshot
            # -- must survive into the merged cluster view (separate
            # bounded ring: never evicts locally-closed records)
            self.graph.stats.add_trace_partial(
                (_PartialTraceView(ctx, self.edge),
                 _time.perf_counter()))
        if self.runtime is not None and kind != wire.MSG_BARRIER:
            self.runtime.count_transport(cost)

    def put_many(self, producer_id: int, items) -> None:
        for item in items:
            self.put(producer_id, item)

    def close(self, producer_id: int) -> None:
        """Per-producer EOS.  Bypasses the credit gate (like a bounded
        channel's close): a producer must always be able to announce
        its end of stream."""
        with self._lock:
            if self._cancelled:
                return
            self._closed.add(int(producer_id))
            last = self._closed >= self._pids
        self._ship(wire.MSG_EOS, producer_id, b"", 0)
        if last:
            import json
            trailer = json.dumps({
                "tuples": self.tuples_sent, "frames": self.frames_sent,
                "barriers": self.barriers_sent}).encode("utf-8")
            self._ship(wire.MSG_STATS, 0, struct.pack("<H", 0) + trailer,
                       0)

    def poison(self) -> None:
        """Graph cancellation: unblock the gate, tell the peer, drop
        the socket.  Deliberately LOCK-FREE: a producer thread may be
        holding ``self._lock`` inside a reconnect loop for many
        seconds, and ``CancelToken.cancel`` poisons its registrants
        serially -- blocking here would stall the whole graph's
        teardown.  The flag write is atomic; the in-flight thread's
        cancel checks trip on it, and closing the socket snapshot
        (without nulling the field -- the owner handles that) unwedges
        a blocked sendall."""
        if self._cancelled:
            return
        self._cancelled = True
        self.gate.poison()
        s = self._sock
        if s is not None:
            try:
                s.sendall(wire.encode_msg(
                    wire.MSG_CANCEL, 0, 0, b"producer graph cancelled"))
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # -- shipping ------------------------------------------------------
    def _ship(self, kind: int, pid: int, payload: bytes, cost: int,
              barrier=None) -> None:
        with self._lock:
            if self._cancelled:
                raise GraphCancelled(f"{self.edge_name} poisoned")
            self._seq += 1
            seq = self._seq
            frame = wire.encode_msg(kind, int(pid), seq, payload)
            # EOS/STATS are control traffic: the bounded channels they
            # mirror count neither (close() is not a put), so the
            # ledger's channel book must not see them either
            counted = kind not in (wire.MSG_STATS, wire.MSG_EOS)
            # data_cost: TUPLES in this frame (what tuples_sent counts)
            # -- the live merge bounds a delivery shortfall by the
            # replay buffer's tuple sum, so the unit must match
            data_cost = cost if kind in (wire.MSG_DATA,
                                         wire.MSG_RECORD) else 0
            self._unacked.append((seq, frame, counted, cost, data_cost))
            self.unacked_tuples += data_cost
            if len(self._unacked) > self.high_watermark:
                self.high_watermark = len(self._unacked)
            if counted:
                self.puts += 1
            self.frames_sent += 1
            if kind in (wire.MSG_DATA, wire.MSG_RECORD):
                self.tuples_sent += cost
            dropped = False
            f = self.faults
            if f is not None:
                if f.drop_frame(self.frames_sent):
                    dropped = True
                    self.frames_dropped += 1
                    self.graph.flight.record(
                        "wire_drop_injected", edge=self.edge,
                        frame=self.frames_sent)
                f.maybe_delay(self.frames_sent)
            if dropped:
                # the frame is gone for good: hand its credits back so
                # the loss surfaces in the conservation books, not as a
                # wedged credit window (a dropped batch >= the budget
                # would otherwise block the producer forever)
                if cost:
                    self.gate.release(cost)
            else:
                self._send_frame(frame)
            if barrier is not None:
                self.barriers_sent += 1
                self._track_barrier(barrier)

    def _track_barrier(self, b) -> None:
        """Ack epoch ``e`` to the local coordinator once every live
        local producer forwarded its barrier -- this edge then acts as
        the epoch's sink on this worker (the real alignment happens on
        the consumer's side of the wire)."""
        coord = self.epoch_coord
        if b.final:
            # callers ship exactly one final barrier per (outlet dest)
            # = per pid (RtNode.run broadcast_final)
            self._finals += 1
        else:
            self._barrier_seen[b.epoch] = \
                self._barrier_seen.get(b.epoch, 0) + 1
        if coord is None:
            return
        live = max(1, len(self._pids) - self._finals)
        for e, n in list(self._barrier_seen.items()):
            if n >= live and e not in self._barrier_acked:
                self._barrier_acked.add(e)
                coord.sink_ack(e, self.edge_name)
        if self._finals >= len(self._pids):
            coord.node_finished(self.edge_name, {})

    def _send_frame(self, frame: bytes) -> None:
        attempts = int(getattr(self.spec, "wire_reconnects", 2))
        attempt = 0
        while True:
            try:
                self._ensure_open()
                self._sock.sendall(frame)
                return
            except OSError as e:
                if self._cancelled:
                    raise GraphCancelled(f"{self.edge_name} poisoned")
                self._close_sock()
                if attempts <= 0:
                    raise WireError(
                        f"shuffle edge {self.edge!r} to "
                        f"{self.host}:{self.port} failed after "
                        f"{self.frames_sent} frames: {e}") from e
                attempts -= 1
                self.reconnects += 1
                # jittered exponential backoff before the reconnect: a
                # consumer worker restarting must not be hammered at a
                # fixed 50 ms cadence by every surviving sender at once
                # (the jitter de-synchronizes them; the per-edge seeded
                # RNG keeps each run's delay sequence reproducible).
                # _ensure_open resumes + retransmits; the loop then
                # re-sends THIS frame (it is the newest unacked one,
                # so the resume already retransmitted it -- dedup by
                # sequence makes the extra copy harmless)
                delay = backoff_delay(attempt, self._backoff_rng)
                attempt += 1
                self.graph.flight.record(
                    "wire_reconnect_backoff", edge=self.edge_name,
                    attempt=attempt, delay_s=round(delay, 4),
                    error=repr(e))
                _time.sleep(delay)

    def _ensure_open(self) -> None:
        if self._sock is not None:
            return
        import json
        deadline = _time.monotonic() + float(
            getattr(self.spec, "connect_timeout_s", 10.0))
        last: Optional[Exception] = None
        while True:
            if self._cancelled:
                raise GraphCancelled(f"{self.edge_name} poisoned")
            try:
                s = socket.create_connection((self.host, self.port),
                                             timeout=0.25)
                break
            except OSError as e:
                last = e
                if _time.monotonic() > deadline:
                    raise WireError(
                        f"shuffle edge {self.edge!r}: cannot connect "
                        f"to {self.host}:{self.port}") from last
                _time.sleep(0.05)
        s.settimeout(_SEND_TIMEOUT_S)
        resume = self._acked_seq > 0 or self._seq > 0
        hello = json.dumps({
            "edge": self.edge,
            "worker": int(getattr(self.spec, "worker_id", -1)),
            "pids": sorted(self._pids),
            "resume": bool(resume),
            "graph": self.graph.name,
        }).encode("utf-8")
        s.sendall(wire.encode_msg(wire.MSG_HELLO, 0, 0, hello))
        if resume:
            self._resync(s)
        self._sock = s
        self._start_reader()

    def _resync(self, s: socket.socket) -> None:
        """Resume handshake: the receiver replies with its acked
        sequence; retransmit every newer unacked frame in order."""
        dec = wire.MsgDecoder()
        deadline = _time.monotonic() + float(
            getattr(self.spec, "connect_timeout_s", 10.0))
        acked = None
        while acked is None:
            if _time.monotonic() > deadline:
                raise WireError(
                    f"shuffle edge {self.edge!r}: no resume ack")
            data = _recv_some(s)
            if data == b"":
                raise WireError(
                    f"shuffle edge {self.edge!r}: peer closed during "
                    "resume")
            if not data:
                continue
            for kind, _pid, _seq, payload in dec.feed(data):
                if kind == wire.MSG_CREDIT:
                    _tuples, acked = wire.decode_credit(payload)
                    break
                if kind == wire.MSG_CANCEL:
                    raise GraphCancelled(
                        f"{self.edge_name}: peer cancelled")
        # the acked prefix was delivered on the DEAD connection, so its
        # credit grants are gone with it -- release those costs here
        # (release is clamped at the budget, so a grant that DID land
        # before the drop can at worst over-credit harmlessly, never
        # leak the window smaller on every reconnect)
        self._apply_ack(0, acked, release_popped=True)
        for _seq, frame, _counted, _cost, _dc in list(self._unacked):
            s.sendall(frame)

    def _start_reader(self) -> None:
        t = threading.Thread(target=self._reader_loop, daemon=True,
                             name=f"windflow-wire-tx-{self.edge}")
        self._reader = t
        t.start()

    def _reader_loop(self) -> None:
        """Credit/cancel pump for the current connection; exits when
        the socket dies (the next put reconnects) or the edge is done."""
        sock = self._sock
        if sock is None:
            return
        sock.settimeout(_POLL_S)
        dec = wire.MsgDecoder()
        while not self._cancelled:
            if sock is not self._sock:
                return  # superseded by a reconnect
            try:
                data = _recv_some(sock)
            except OSError:
                return
            if data is None:
                if self._done():
                    self._close_sock(sock)
                    return
                continue
            if data == b"":
                return  # peer closed; next put reconnects if needed
            try:
                msgs = dec.feed(data)
            except ValueError:
                return
            for kind, _pid, _seq, payload in msgs:
                if kind == wire.MSG_CREDIT:
                    tuples, acked = wire.decode_credit(payload)
                    self._apply_ack(tuples, acked)
                elif kind == wire.MSG_CANCEL:
                    reason = payload.decode("utf-8", "replace")
                    self._cancelled = True
                    self.gate.poison()
                    self.graph._cancel.cancel(
                        WireError(f"{self.edge_name}: consumer worker "
                                  f"cancelled ({reason})"),
                        origin=self.edge_name)
                    return
            if self._done():
                self._close_sock(sock)
                return

    def _apply_ack(self, tuples: int, acked_seq: int,
                   release_popped: bool = False) -> None:
        with self._lock:
            if acked_seq > self._acked_seq:
                self._acked_seq = acked_seq
            popped = 0
            popped_cost = 0
            while self._unacked and self._unacked[0][0] <= acked_seq:
                _seq, _frame, counted, cost, data_cost = \
                    self._unacked.popleft()
                if counted:
                    popped += 1
                popped_cost += cost
                self.unacked_tuples -= data_cost
            self.gets += popped
        if release_popped and popped_cost:
            self.gate.release(popped_cost)
        if tuples:
            self.gate.release(tuples)

    def _done(self) -> bool:
        with self._lock:
            return self._closed >= self._pids and not self._unacked

    def _close_sock(self, only=None) -> None:
        with self._lock:
            s = self._sock
            if s is None or (only is not None and s is not only):
                return
            self._sock = None
        try:
            s.close()
        except OSError:
            pass

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for the peer to ack every shipped frame (the replay
        buffer drains), so the local ledger closes over this edge."""
        deadline = _time.monotonic() + timeout
        while self._unacked and not self._cancelled:
            if _time.monotonic() > deadline:
                return False
            _time.sleep(0.005)
        return True

    def block(self) -> dict:
        """One row of the stats-JSON ``Wire.out`` table.  Deliberately
        LOCK-FREE (gauge-grade reads): a producer thread may hold the
        send lock for seconds inside a reconnect loop, and the stats /
        live-push path must keep reporting exactly then."""
        return {
            "edge": self.edge, "to": (self.host, self.port),
            "tuples": self.tuples_sent, "frames": self.frames_sent,
            "barriers": self.barriers_sent,
            "dropped_frames": self.frames_dropped,
            "unacked": len(self._unacked),
            # tuple sum of the replay buffer: the live merge's
            # in-flight bound (frames != tuples on the batch plane)
            "unacked_tuples": max(0, self.unacked_tuples),
            "reconnects": self.reconnects,
            "credit_waits": self.gate.credit_waits,
            "credit_wait_s": round(self.gate.wait_time_s, 4),
        }


class _WireStream:
    """Per (edge, producer-worker) receive state: sequence high-water,
    gap accounting, the producer's trailer."""

    __slots__ = ("worker", "pids", "next_seq", "gaps", "frames",
                 "tuples", "barriers", "trailer", "resumed")

    def __init__(self, worker: int, pids):
        self.worker = worker
        self.pids = set(pids)
        self.next_seq = 1
        self.gaps = 0
        self.frames = 0
        self.tuples = 0
        self.barriers = 0
        self.trailer: Optional[dict] = None
        self.resumed = threading.Event()


class EdgeState:
    """Consumer-side registry entry for one inbound shuffle edge."""

    def __init__(self, edge: str, channel, expected: Dict[int, set]):
        self.edge = edge
        self.channel = channel               # the consumer's raw channel
        self.expected = expected             # worker -> pid set
        self.streams: Dict[int, _WireStream] = {}
        self.closed_pids = set()
        self.completed = False
        self.finished_reported = False
        self.lock = threading.Lock()

    def stream_for(self, worker: int, pids) -> _WireStream:
        with self.lock:
            st = self.streams.get(worker)
            if st is None:
                st = self.streams[worker] = _WireStream(worker, pids)
            else:
                st.resumed.set()
            return st

    @property
    def all_pids(self):
        return {p for pids in self.expected.values() for p in pids}

    def blocks(self):
        """Rows of the stats-JSON ``Wire.in`` table."""
        with self.lock:
            return [{
                "edge": self.edge, "from_worker": st.worker,
                "tuples": st.tuples, "frames": st.frames,
                "barriers": st.barriers, "gaps": st.gaps,
                "sender_tuples": (st.trailer or {}).get("tuples"),
                "sender_frames": (st.trailer or {}).get("frames"),
            } for st in self.streams.values()]


class ShuffleServer:
    """Per-worker listener: accepts producer connections, routes each
    (after its HELLO) to the edge it feeds, and pumps frames into the
    consumer channel with per-frame credit grants."""

    def __init__(self, graph, spec, edges: Dict[str, EdgeState],
                 runtime=None):
        self.graph = graph
        self.spec = spec
        self.edges = edges
        self.runtime = runtime
        self.grace_s = float(getattr(spec, "reconnect_grace_s", 2.0))
        host, port = spec.endpoints[spec.worker_id]
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(16)
        self._lsock.settimeout(_POLL_S)
        self.port = self._lsock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"windflow-shuffle-accept-w{spec.worker_id}")

    def start(self) -> None:
        self._accept_thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in list(self._threads):
            t.join(timeout=1.0)

    @property
    def _cancelled(self) -> bool:
        return self.graph._cancel.cancelled

    def _accept_loop(self) -> None:
        while not self._stop.is_set() and not self._cancelled:
            try:
                conn, _addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True,
                                 name="windflow-shuffle-rx")
            # prune finished connections (a flapping link would
            # otherwise grow this list one dead thread per reconnect)
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
            t.start()

    # -- one connection ------------------------------------------------
    def _serve(self, conn: socket.socket) -> None:
        import json
        conn.settimeout(_POLL_S)
        dec = wire.MsgDecoder()
        hello = None
        backlog = []   # frames decoded in the same chunk as the HELLO
        edge: Optional[EdgeState] = None
        st: Optional[_WireStream] = None
        try:
            while hello is None:
                if self._stop.is_set() or self._cancelled:
                    conn.close()
                    return
                data = _recv_some(conn)
                if data == b"":
                    conn.close()
                    return
                if not data:
                    continue
                msgs = dec.feed(data)
                for i, (kind, _pid, _seq, payload) in enumerate(msgs):
                    if kind == wire.MSG_HELLO:
                        hello = json.loads(payload.decode("utf-8"))
                        # the sender pipelines data right behind its
                        # HELLO: frames TCP coalesced into this chunk
                        # are already consumed from the decoder and
                        # must reach the pump, not the floor
                        backlog = msgs[i + 1:]
                        break
                    if kind == wire.MSG_CANCEL:
                        conn.close()
                        return
            edge = self.edges.get(hello.get("edge"))
            if edge is None:
                raise WireError(
                    f"HELLO for unknown shuffle edge "
                    f"{hello.get('edge')!r} (partition plans disagree?)")
            st = edge.stream_for(int(hello.get("worker", -1)),
                                 hello.get("pids") or ())
            if hello.get("resume"):
                conn.sendall(wire.encode_credit(0, st.next_seq - 1))
            self._pump(conn, dec, edge, st, backlog)
        except GraphCancelled:
            try:
                conn.sendall(wire.encode_msg(wire.MSG_CANCEL, 0, 0,
                                             b"consumer graph cancelled"))
            except OSError:
                pass
        except (OSError, ValueError, WireError) as e:
            self._broken(edge, st, e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _coordinator(self):
        """The consumer graph's EpochCoordinator, waiting out the start
        window: the server accepts early in ``PipeGraph.start`` while
        the durability plane is built near its end, and a barrier
        observed with no coordinator would silently break the
        follower's epoch plane.  Only blocks when the config PROMISES a
        coordinator; bounded and cancel-aware."""
        coord = getattr(self.graph, "durability", None)
        if coord is not None \
                or self.graph.config.durability is None:
            return coord
        deadline = _time.monotonic() + 30.0
        while coord is None:
            if self._stop.is_set() or self._cancelled \
                    or _time.monotonic() > deadline:
                return None
            _time.sleep(0.005)
            coord = getattr(self.graph, "durability", None)
        return coord

    def _pump(self, conn, dec, edge: EdgeState, st: _WireStream,
              backlog=None) -> None:
        while True:
            if backlog:
                msgs, backlog = backlog, None
            else:
                backlog = None
                if self._stop.is_set() or self._cancelled:
                    return
                data = _recv_some(conn)
                if data is None:
                    continue
                if data == b"":
                    # clean EOF: complete iff every pid of this stream
                    # closed; else treat as a drop (reconnect window)
                    with edge.lock:
                        done = st.pids <= edge.closed_pids
                    if not done:
                        raise WireError(
                            f"shuffle edge {edge.edge!r} from worker "
                            f"{st.worker} closed mid-stream after "
                            f"{st.frames} frames")
                    return
                msgs = dec.feed(data)
            grant = 0
            processed = False
            for kind, pid, seq, payload in msgs:
                if kind == wire.MSG_CANCEL:
                    reason = payload.decode("utf-8", "replace")
                    self.graph._cancel.cancel(
                        WireError(f"wire:{edge.edge}: producer worker "
                                  f"cancelled ({reason})"),
                        origin=f"wire:{edge.edge}")
                    raise GraphCancelled("peer cancelled")
                if kind not in wire.DATA_KINDS:
                    continue
                if seq < st.next_seq:
                    continue  # duplicate after a resume
                if seq > st.next_seq:
                    gap = seq - st.next_seq
                    st.gaps += gap
                    self.graph.flight.record(
                        "wire_gap", edge=edge.edge, worker=st.worker,
                        frames=gap, at_seq=seq)
                st.next_seq = seq + 1
                processed = True
                grant += self._deliver(edge, st, kind, pid, payload)
            if processed:
                try:
                    conn.sendall(wire.encode_credit(grant,
                                                    st.next_seq - 1))
                except OSError:
                    return

    def _deliver(self, edge: EdgeState, st: _WireStream, kind: int,
                 pid: int, payload: bytes) -> int:
        """One data-plane frame into the consumer channel; returns the
        credits to grant back."""
        import json
        st.frames += 1
        if kind == wire.MSG_EOS:
            with edge.lock:
                edge.closed_pids.add(pid)
                complete = edge.closed_pids >= edge.all_pids
            edge.channel.close(pid)
            if complete:
                self._edge_complete(edge, self._coordinator())
            return 0
        if kind == wire.MSG_STATS:
            _doc, body = wire._split_trace(payload)
            try:
                st.trailer = json.loads(body.decode("utf-8"))
            except ValueError:
                st.trailer = None
            self._check_trailer(edge, st)
            return 0
        item, cost = wire.decode_item(kind, payload, edge.edge)
        if kind == wire.MSG_BARRIER:
            st.barriers += 1
            coord = self._coordinator()
            if coord is not None and item.epoch >= 1 and not item.final:
                # BEFORE the put: the aligner's cut must find the
                # pending epoch registered
                coord.remote_epoch(item.epoch, f"wire:{edge.edge}",
                                   frontier=st.frames)
        else:
            st.tuples += cost
            if self.runtime is not None:
                self.runtime.count_transport(cost)
        edge.channel.put(pid, item)
        return cost

    def _edge_complete(self, edge: EdgeState, coord) -> None:
        with edge.lock:
            if edge.completed:
                return
            edge.completed = True
        if coord is not None and not edge.finished_reported:
            edge.finished_reported = True
            coord.node_finished(f"wire:{edge.edge}", {})

    def _check_trailer(self, edge: EdgeState, st: _WireStream) -> None:
        """The producer's delivery book against ours: any shortfall is
        a wire loss, flagged with the exact edge and tuple count (the
        cross-process twin of the ledger's lost_delivery rule)."""
        t = st.trailer
        if not t:
            return
        missing_t = int(t.get("tuples", 0) or 0) - st.tuples
        if missing_t <= 0 and st.gaps == 0:
            return
        v = {"kind": "lost_wire_delivery", "edge": edge.edge,
             "from_worker": st.worker, "count": max(missing_t, 0),
             "frames": st.gaps, "at": round(_time.time(), 6)}
        self.graph.flight.record(
            "conservation_violation",
            violation=v["kind"], edge=v["edge"], count=v["count"],
            frames=v["frames"], from_worker=st.worker)
        auditor = getattr(self.graph, "auditor", None)
        if auditor is not None:
            auditor.violations.append(v)

    def _broken(self, edge: Optional[EdgeState],
                st: Optional[_WireStream], err: Exception) -> None:
        """A connection died mid-stream: give the producer a reconnect
        window, then declare the edge lost (graph cancels, the failure
        propagates like a replica death)."""
        if edge is None or st is None:
            return
        if self._stop.is_set() or self._cancelled or edge.completed:
            return
        st.resumed.clear()
        if st.resumed.wait(self.grace_s):
            return  # the producer came back; its new thread took over
        if self._stop.is_set() or self._cancelled or edge.completed:
            return
        self.graph.flight.record("wire_broken", edge=edge.edge,
                                 worker=st.worker, error=str(err))
        self.graph._cancel.cancel(
            WireError(f"shuffle edge {edge.edge!r} from worker "
                      f"{st.worker} lost: {err}"),
            origin=f"wire:{edge.edge}")
