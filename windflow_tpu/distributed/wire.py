"""Shared wire codec: columnar batch frames + the shuffle message layer
(docs/DISTRIBUTED.md "Wire protocol").

Two framings live here:

* the **batch codec** (``encode_batch`` / ``decode_batch`` /
  :class:`StreamDecoder`) -- the ``WFB1`` frame the ingest plane's
  ``SocketSource`` has spoken since PR 2, promoted out of
  ``ingest/codec.py`` so the inter-worker shuffle transport and the
  ingest sources share ONE codec (``ingest.codec`` remains as a
  deprecation shim).  One frame carries one ``TupleBatch`` as a
  length-prefixed columnar payload -- the network twin of the
  in-process struct-of-arrays currency, so a decoded frame enters the
  batch plane zero-copy (each column is a view over the receive
  buffer)::

      [magic 'WFB1'][u32 payload_len] payload:
          [u16 n_cols] then per column:
              [u8 name_len][name utf-8][u8 dtype tag][u32 byte_len][raw LE]

* the **shuffle message layer** (``encode_msg`` / :class:`MsgDecoder`,
  ``WFM1`` frames) -- the framing of cross-worker PipeGraph edges
  (distributed/transport.py).  Every channel item of an in-process
  edge has a wire twin: data batches (the batch-codec payload),
  pickled record items, ``EpochBarrier`` control items, per-producer
  EOS -- plus the control traffic the in-process planes get for free:
  credit replenishment (backpressure), HELLO (edge identification /
  reconnect resume), CANCEL (cross-worker failure propagation) and a
  STATS trailer (the producer-side delivery book the consumer audits
  against)::

      [magic 'WFM1'][u8 kind][u16 pid][u64 seq][u32 payload_len][payload]

  ``pid`` is the producer id the item would have carried on the
  in-process channel (both sides build the same wired graph, so ids
  agree by construction).  ``seq`` numbers the data-plane stream per
  (edge, producer-worker) connection: receivers detect wire loss as
  sequence gaps, drop duplicates after a reconnect resume, and ack by
  sequence in every CREDIT frame so the sender can retire its bounded
  replay buffer.

Trace contexts (telemetry/trace.py) serialize into the data-frame
header: hop stamps are rebased onto the receiver's clock and the
crossing itself lands as an ``@wire``-suffixed hop, which the
diagnosis plane's attribution charges to the ``wire`` class.
"""
from __future__ import annotations

import json
import pickle
import struct
import time as _time
from typing import List, Optional, Tuple

import numpy as np

from ..core.tuples import TupleBatch
from ..runtime.queues import EpochBarrier
from ..telemetry.trace import MAX_HOPS, TraceContext

MAGIC = b"WFB1"
_HEADER = struct.Struct("<4sI")

_DTYPE_TAGS = {
    np.dtype("<i8"): 0, np.dtype("<f8"): 1,
    np.dtype("<i4"): 2, np.dtype("<f4"): 3,
}
_TAG_DTYPES = {v: k for k, v in _DTYPE_TAGS.items()}


def encode_batch_payload(batch: TupleBatch) -> bytes:
    """The columnar payload of one batch (no outer header) -- shared by
    the ingest frame and the shuffle DATA message."""
    parts = [struct.pack("<H", len(batch.cols))]
    for name, col in batch.cols.items():
        col = np.ascontiguousarray(col)
        if col.dtype not in _DTYPE_TAGS:
            # normalize exotic ints/floats instead of refusing the batch
            col = col.astype(np.float64 if col.dtype.kind == "f"
                             else np.int64)
        raw = col.tobytes()
        nb = name.encode("utf-8")
        if len(nb) > 255:
            raise ValueError(f"column name too long: {name!r}")
        parts.append(struct.pack("<B", len(nb)))
        parts.append(nb)
        parts.append(struct.pack("<BI", _DTYPE_TAGS[col.dtype], len(raw)))
        parts.append(raw)
    return b"".join(parts)


def encode_batch(batch: TupleBatch) -> bytes:
    """One framed ingest wire message for ``batch``."""
    payload = encode_batch_payload(batch)
    return _HEADER.pack(MAGIC, len(payload)) + payload


def decode_batch(payload: bytes) -> TupleBatch:
    """Decode one frame payload (without the 8-byte header)."""
    view = memoryview(payload)
    (n_cols,) = struct.unpack_from("<H", view, 0)
    off = 2
    cols = {}
    for _ in range(n_cols):
        (name_len,) = struct.unpack_from("<B", view, off)
        off += 1
        name = bytes(view[off:off + name_len]).decode("utf-8")
        off += name_len
        tag, nbytes = struct.unpack_from("<BI", view, off)
        off += 5
        if tag not in _TAG_DTYPES:
            raise ValueError(f"unknown dtype tag {tag} in frame")
        cols[name] = np.frombuffer(view[off:off + nbytes],
                                   dtype=_TAG_DTYPES[tag])
        off += nbytes
    return TupleBatch(cols)


class StreamDecoder:
    """Incremental ingest-frame decoder over a byte stream."""

    def __init__(self, max_frame_bytes: int = 1 << 28):
        self._buf = bytearray()
        self.max_frame_bytes = max_frame_bytes
        self.frames_decoded = 0

    def feed(self, data: bytes) -> List[TupleBatch]:
        """Append received bytes; return every now-complete batch."""
        self._buf.extend(data)
        out: List[TupleBatch] = []
        while True:
            frame = self._next_frame()
            if frame is None:
                return out
            out.append(frame)

    def _next_frame(self) -> Optional[TupleBatch]:
        if len(self._buf) < _HEADER.size:
            return None
        magic, length = _HEADER.unpack_from(bytes(self._buf[:_HEADER.size]))
        if magic != MAGIC:
            raise ValueError(f"bad frame magic {magic!r} (stream desync)")
        if length > self.max_frame_bytes:
            raise ValueError(f"frame of {length} bytes exceeds the "
                             f"{self.max_frame_bytes} limit")
        end = _HEADER.size + length
        if len(self._buf) < end:
            return None
        # copy the payload out so decoded columns do not pin (or get
        # corrupted by) the growing receive buffer
        payload = bytes(self._buf[_HEADER.size:end])
        del self._buf[:end]
        self.frames_decoded += 1
        return decode_batch(payload)

    def pending_bytes(self) -> int:
        return len(self._buf)


# ---------------------------------------------------------------------------
# Shuffle message layer (distributed/transport.py speaks this)
# ---------------------------------------------------------------------------

MSG_MAGIC = b"WFM1"
_MSG_HEADER = struct.Struct("<4sBHQI")  # magic, kind, pid, seq, len

# message kinds -- data plane (sequenced, credit-charged):
MSG_DATA = 1      # columnar TupleBatch (+ optional trace header)
MSG_RECORD = 2    # pickled scalar item / EOSMarker (+ optional trace)
MSG_BARRIER = 3   # EpochBarrier control item
MSG_EOS = 4       # per-producer end of stream
MSG_STATS = 7     # producer-side delivery-book trailer (per pid-less edge)
# control plane (unsequenced, free):
MSG_HELLO = 0     # connection open / reconnect resume (JSON)
MSG_CREDIT = 5    # consumer -> producer: tuples granted + acked seq
MSG_CANCEL = 6    # either direction: graph cancelled, reason utf-8

DATA_KINDS = frozenset((MSG_DATA, MSG_RECORD, MSG_BARRIER, MSG_EOS,
                        MSG_STATS))

_BARRIER_PAYLOAD = struct.Struct("<qB")
_CREDIT_PAYLOAD = struct.Struct("<IQ")


def encode_msg(kind: int, pid: int, seq: int, payload: bytes = b"") -> bytes:
    return _MSG_HEADER.pack(MSG_MAGIC, kind, pid, seq, len(payload)) \
        + payload


class MsgDecoder:
    """Incremental shuffle-message decoder: feed arbitrary byte chunks,
    get complete ``(kind, pid, seq, payload)`` messages.  Oversized
    frames and foreign magic raise -- a desynced stream must fail loud,
    never deliver garbage into a channel."""

    def __init__(self, max_frame_bytes: int = 1 << 28):
        self._buf = bytearray()
        self.max_frame_bytes = max_frame_bytes
        self.msgs_decoded = 0

    def feed(self, data: bytes) -> List[Tuple[int, int, int, bytes]]:
        self._buf.extend(data)
        out: List[Tuple[int, int, int, bytes]] = []
        while True:
            if len(self._buf) < _MSG_HEADER.size:
                return out
            magic, kind, pid, seq, length = _MSG_HEADER.unpack_from(
                bytes(self._buf[:_MSG_HEADER.size]))
            if magic != MSG_MAGIC:
                raise ValueError(
                    f"bad shuffle magic {magic!r} (stream desync)")
            if length > self.max_frame_bytes:
                raise ValueError(
                    f"shuffle frame of {length} bytes exceeds the "
                    f"{self.max_frame_bytes} limit")
            end = _MSG_HEADER.size + length
            if len(self._buf) < end:
                return out
            payload = bytes(self._buf[_MSG_HEADER.size:end])
            del self._buf[:end]
            self.msgs_decoded += 1
            out.append((kind, pid, seq, payload))

    def pending_bytes(self) -> int:
        return len(self._buf)


# -- trace serialization ----------------------------------------------------

def _trace_header(item) -> bytes:
    """``[u16 len][json]`` trace header of a data-plane payload; the
    zero-length header means untraced.  Times ship as offsets relative
    to the context's source stamp (perf_counter bases do not survive a
    process boundary) plus one wall-clock send stamp so the receiver
    can estimate the wire residency."""
    ctx = getattr(item, "trace", None)
    if ctx is None:
        return struct.pack("<H", 0)
    now = _time.perf_counter()
    doc = {
        "src": ctx.src,
        "id": getattr(ctx, "trace_id", None),
        "age_s": round(now - ctx.t0, 9),
        "last_s": round(ctx.last - ctx.t0, 9),
        "sent_unix": _time.time(),
        "hops": [[name, round(a - ctx.t0, 9), round(d - ctx.t0, 9), *rest]
                 for name, a, d, *rest in ctx.hops],
    }
    blob = json.dumps(doc).encode("utf-8")
    if len(blob) > 0xFFFF:  # pathological hop list: ship untraced
        return struct.pack("<H", 0)
    return struct.pack("<H", len(blob)) + blob


def _split_trace(payload: bytes) -> Tuple[Optional[dict], bytes]:
    (tlen,) = struct.unpack_from("<H", payload, 0)
    body = payload[2 + tlen:]
    if tlen == 0:
        return None, body
    try:
        doc = json.loads(payload[2:2 + tlen].decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None, body
    return doc, body


def rebuild_trace(doc: Optional[dict], edge: str,
                  arrival: Optional[float] = None) -> Optional[TraceContext]:
    """Reconstruct a TraceContext on the receiver's clock.  The wire
    residency (send wall stamp -> arrival wall stamp, clamped >= 0) is
    stamped as an ``{edge}@wire`` hop so attribution charges the
    crossing to the ``wire`` class; hop offsets rebase exactly, so
    per-operator shares survive the boundary (gauge-grade across hosts:
    the wall clocks must roughly agree)."""
    if doc is None:
        return None
    if arrival is None:
        arrival = _time.perf_counter()
    wire_s = max(0.0, _time.time() - float(doc.get("sent_unix") or 0.0))
    age = float(doc.get("age_s") or 0.0)
    last = float(doc.get("last_s") or 0.0)
    ctx = TraceContext(str(doc.get("src") or "?"),
                       arrival - age - wire_s,
                       trace_id=doc.get("id"))
    for hop in doc.get("hops") or ():
        try:
            name, a, d = hop[0], float(hop[1]), float(hop[2])
        except (TypeError, ValueError, IndexError):
            continue
        meta = hop[3] if len(hop) > 3 and isinstance(hop[3], dict) else None
        if len(ctx.hops) < MAX_HOPS:
            ctx.hops.append((str(name), ctx.t0 + a, ctx.t0 + d) if meta
                            is None else
                            (str(name), ctx.t0 + a, ctx.t0 + d, meta))
    ctx.hop(f"{edge}@wire", ctx.t0 + last + 1e-9, arrival)
    return ctx


# -- item <-> message -------------------------------------------------------

def encode_item(item, pool=None) -> Tuple[int, bytes, int]:
    """``(kind, payload, tuple_cost)`` of one channel item.  Batches go
    columnar; ``EpochBarrier`` control items ride a dedicated kind (so
    the receiver never unpickles them on the hot path); everything else
    -- scalar records, EOSMarkers -- pickles.  SynthChunk descriptors
    materialize at the boundary: their generator closures do not cross
    processes."""
    from ..core.tuples import SynthChunk
    if isinstance(item, SynthChunk):
        item = item.materialize(pool)
    if isinstance(item, TupleBatch):
        return (MSG_DATA,
                _trace_header(item) + encode_batch_payload(item),
                max(1, len(item)))
    if type(item) is EpochBarrier:
        return (MSG_BARRIER,
                _trace_header(None)
                + _BARRIER_PAYLOAD.pack(item.epoch, 1 if item.final else 0),
                1)
    trace = _trace_header(item)
    tr = getattr(item, "trace", None)
    if tr is not None:
        # the context must not pickle (thread-unsafe perf stamps; it is
        # re-built from the header on the other side)
        try:
            item.trace = None
        except AttributeError:
            pass
    try:
        blob = pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)
    finally:
        if tr is not None:
            try:
                item.trace = tr
            except AttributeError:
                pass
    return MSG_RECORD, trace + blob, 1


def decode_item(kind: int, payload: bytes, edge: str):
    """``(item, tuple_cost)`` of one data message (DATA/RECORD/BARRIER).
    The trace header, when present, is rebuilt onto the local clock and
    attached to the decoded item."""
    doc, body = _split_trace(payload)
    if kind == MSG_DATA:
        item = decode_batch(body)
        cost = max(1, len(item))
    elif kind == MSG_BARRIER:
        epoch, final = _BARRIER_PAYLOAD.unpack(body)
        return EpochBarrier(epoch, final=bool(final)), 1
    elif kind == MSG_RECORD:
        item = pickle.loads(body)
        cost = 1
    else:  # pragma: no cover - caller dispatches data kinds only
        raise ValueError(f"not a data message kind: {kind}")
    ctx = rebuild_trace(doc, edge)
    if ctx is not None:
        try:
            item.trace = ctx
        except AttributeError:
            pass
    return item, cost


def encode_credit(tuples: int, acked_seq: int) -> bytes:
    return encode_msg(MSG_CREDIT, 0, 0,
                      _CREDIT_PAYLOAD.pack(tuples, acked_seq))


def decode_credit(payload: bytes) -> Tuple[int, int]:
    return _CREDIT_PAYLOAD.unpack(payload)
