"""Distributed runtime plane: one PipeGraph across worker processes
(docs/DISTRIBUTED.md).

The production story for millions of users does not fit one process:
this package partitions a logical ``PipeGraph`` across N workers --
explicit ``.with_worker(i)`` pins plus an automatic cut that keeps
fused FORWARD runs co-located and only cuts KEYBY shuffle edges -- and
carries every cross-worker edge over a **credit-backpressured shuffle
transport** built on the shared wire codec (`wire.py`, promoted from
``ingest/codec.py``).  EOS, poison/cancel, ``EpochBarrier`` control
items and trace contexts all ride the frames, so the observability and
durability planes extend across the boundary: per-edge ledgers close
over each socket (`observe.merge_stats` composes the cross-process
conservation identity), attribution charges a ``wire`` hop class, and
``run_distributed`` restarts a killed worker fleet from the newest
globally-committed epoch.

Modules: `wire` (codec + message layer), `partition` (ownership plan),
`transport` (sender/server), `wiring` (graph-start application),
`runtime` (worker processes + coordinator), `observe` (merged view),
`identity` (worker id / log-name suffix).
"""
from __future__ import annotations

_LAZY = {
    "DistributedSpec": ".runtime",
    "run_distributed": ".runtime",
    "WorkerFailure": ".runtime",
    "free_ports": ".runtime",
    "worker_main": ".runtime",
    "plan_partition": ".partition",
    "PartitionError": ".partition",
    "node_owner": ".partition",
    "RemoteEdgeSender": ".transport",
    "ShuffleServer": ".transport",
    "EdgeState": ".transport",
    "WireError": ".transport",
    "distribute_graph": ".wiring",
    "DistRuntime": ".wiring",
    "KILL_EXIT": ".wiring",
    "merge_stats": ".observe",
    "wire_table": ".observe",
    "check_wire_conservation": ".observe",
    "worker_id": ".identity",
    "worker_suffix": ".identity",
    "encode_batch": ".wire",
    "decode_batch": ".wire",
    "StreamDecoder": ".wire",
    "MsgDecoder": ".wire",
    "encode_msg": ".wire",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    # lazy surface: the wire codec must import without dragging the
    # transport/process layers in (ingest imports it at package load)
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module
    return getattr(import_module(target, __name__), name)
