"""Worker-process entry point::

    python -m windflow_tpu.distributed.worker '<spec json>'

Spawned by :func:`windflow_tpu.distributed.run_distributed` (one
process per worker).  The spec carries the worker id, the shuffle
endpoints, importable references to the user's build/config functions
and the restore epoch -- see distributed/runtime.py.  Kept to a thin
shim so a clean interpreter imports only what the partition actually
runs (a host-only partition never touches JAX).
"""
from __future__ import annotations

import json
import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m windflow_tpu.distributed.worker "
              "'<spec json>'", file=sys.stderr)
        return 2
    from .runtime import worker_main
    return worker_main(json.loads(argv[0]))


if __name__ == "__main__":
    sys.exit(main())
