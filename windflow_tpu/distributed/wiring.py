"""Graph-start wiring of the distributed plane (called by
``PipeGraph.start`` when ``RuntimeConfig.distributed`` is set).

Every worker builds the full logical graph; this module then applies
the partition plan to ONE worker's copy:

1. nodes owned by other workers are pruned (their threads never start,
   their stats records leave the report);
2. every outlet destination pointing at a remote consumer is swapped
   for a :class:`~.transport.RemoteEdgeSender` (the producer ids the
   destination already registered are kept, so both sides agree on
   channel identity without negotiation);
3. a :class:`~.transport.ShuffleServer` is started when any owned
   consumer is fed from a remote worker, with the expected
   (worker, producer-id) sets derived from the same pruned wiring;
4. FaultPlan network actions bind to the transport (``drop_link`` /
   ``delay_link`` per sender, ``kill_worker`` on the worker's
   transport tuple clock), senders register with the CancelToken, and
   the durability plane learns the wire pseudo-sinks/sources so epoch
   barriers commit across the boundary.

Runs after the fusion pass (the plan is fusion-consistent by the
pass's partition barrier) and before the ingest wiring / audit
attachment, so credit proxies skip wire senders and the ledger's books
attach to the post-distribution destination set.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List

from ..audit.ledger import unwrap
from .partition import node_owner
from .transport import EdgeState, RemoteEdgeSender, ShuffleServer

# exit code of an injected kill_worker (distinct from failure=1 so the
# chaos suite can assert the kill fired, not a genuine crash)
KILL_EXIT = 17


class DistRuntime:
    """Per-worker handle on the live transport: senders, server, the
    kill_worker tuple clock and the stats-JSON ``Wire`` block."""

    def __init__(self, graph, spec, senders: Dict[str, RemoteEdgeSender],
                 server, kill_at=None):
        self.graph = graph
        self.spec = spec
        self.senders = senders
        self.server = server
        self.kill_at = kill_at
        # live cluster view (observe.py): the StatsPusher feeding the
        # coordinator's ClusterObserver, when the spec names one
        self.pusher = None
        self._lock = threading.Lock()
        self.transport_tuples = 0

    def count_transport(self, n: int) -> None:
        """The worker's transport tuple clock (sender + receiver side):
        the deterministic trigger of ``FaultPlan.kill_worker``."""
        with self._lock:
            self.transport_tuples += n
            fire = (self.kill_at is not None
                    and self.transport_tuples >= self.kill_at)
        if fire:
            self.graph.flight.record(
                "kill_worker_injected", worker=self.spec.worker_id,
                at_tuple=self.kill_at)
            os._exit(KILL_EXIT)

    def wire_block(self) -> dict:
        """The stats-JSON ``Wire`` block: producer- and consumer-side
        per-edge delivery books (the raw inputs of the cross-process
        conservation identity the merge closes)."""
        rows_in: List[dict] = []
        if self.server is not None:
            for edge in self.server.edges.values():
                rows_in.extend(edge.blocks())
        return {
            "Worker": self.spec.worker_id,
            "out": [s.block() for s in self.senders.values()],
            "in": rows_in,
            "transport_tuples": self.transport_tuples,
        }

    def stop(self, clean: bool = True) -> None:
        if clean:
            # generous but SHARED: a clean end legitimately waits out
            # a slow remote consumer draining the credit window (the
            # flush loop still exits early on poison/CANCEL), but one
            # deadline covers every sender -- K wedged edges must not
            # stack K x 60s past run_distributed's own timeout.  A
            # timeout surfaces as residual_items at the final check.
            import time as _t
            deadline = _t.monotonic() + 60.0
            for s in self.senders.values():
                s.flush(timeout=max(0.0, deadline - _t.monotonic()))
        for s in self.senders.values():
            s._close_sock()
        if self.server is not None:
            self.server.stop()
        if self.pusher is not None:
            # LAST: its stop() pushes one final frame, so the live
            # merged view carries the settled wire books
            self.pusher.stop()


def distribute_graph(graph) -> DistRuntime:
    """Apply the partition plan to this worker's copy of the graph."""
    spec = graph.config.distributed
    plan = graph._dist_plan
    me = int(spec.worker_id)
    if graph.elastic:
        # structured rejection (scheduler/errors.py): name the elastic
        # operators, the worker that owns them under the plan, and the
        # fleet-level path that DOES support elasticity -- plus a
        # sched_rejected flight event so doctor explains the refusal
        # instead of a bare traceback (ISSUE 20 satellite).
        from ..scheduler.errors import SchedulerError
        ops = sorted(graph.elastic)
        owners = sorted({node_owner(n, plan)
                         for n in graph._all_nodes()
                         if n.elastic_group in graph.elastic})
        owner = owners[0] if len(owners) == 1 else None
        hint = ("run the tenant under scheduler.FleetServer: the "
                "fleet places it WHOLE onto one worker, where rescale "
                "and the arbiter's elastic squeezes work unchanged "
                "(docs/SERVING.md 'Global scheduler')")
        graph.flight.record(
            "sched_rejected", operators=ops, worker=owner,
            workers=owners, path="scheduler.FleetServer", hint=hint)
        raise SchedulerError(
            f"distributed runtime: elastic operators {ops} are not "
            f"supported across workers (owned by worker"
            f"{'s' if len(owners) != 1 else ''} {owners}; "
            f"docs/DISTRIBUTED.md); {hint}",
            worker=owner, operators=ops, hint=hint)
    nodes = graph._all_nodes()
    owners = {id(n): node_owner(n, plan) for n in nodes}
    consumer = {}
    for n in nodes:
        if n.channel is not None:
            consumer[id(unwrap(n.channel))] = n

    if graph.config.durability is not None:
        src_owners = {owners[id(n)] for n in nodes if n.channel is None}
        if len(src_owners) > 1:
            raise RuntimeError(
                "distributed durability: all sources must live on ONE "
                f"worker (found sources on workers {sorted(src_owners)}); "
                "the epoch leader is the source worker and followers "
                "observe epochs off the wire (docs/DISTRIBUTED.md)")

    # -- pass 1: classify every edge ------------------------------------
    from ..diagnosis.topology import _op_chain
    out_pids: Dict[str, set] = {}          # edge -> local producer pids
    out_worker: Dict[str, int] = {}        # edge -> consumer's worker
    inbound: Dict[str, Dict[int, set]] = {}  # edge -> worker -> pids
    wire_edges = set()                     # (producer_op, consumer_op)
    for p in nodes:
        wp = owners[id(p)]
        for o in p.outlets:
            for ch, pid in o.dests:
                c = consumer.get(id(unwrap(ch)))
                if c is None or c is p:
                    continue
                wc = owners[id(c)]
                if wp == wc:
                    continue
                wire_edges.add((_op_chain(p)[-1], _op_chain(c)[0]))
                if wp == me:
                    out_pids.setdefault(c.name, set()).add(pid)
                    out_worker[c.name] = wc
                elif wc == me:
                    inbound.setdefault(c.name, {}).setdefault(
                        wp, set()).add(pid)

    # -- senders + dest swap --------------------------------------------
    fault_plan = getattr(graph.config, "fault_plan", None)
    kill_at = None
    if fault_plan is not None:
        kill_at = fault_plan.kill_tuple_for(me) \
            if hasattr(fault_plan, "kill_tuple_for") else None
    senders: Dict[str, RemoteEdgeSender] = {}
    runtime = DistRuntime(graph, spec, senders, None, kill_at)
    for edge, pids in out_pids.items():
        host, port = spec.endpoints[out_worker[edge]]
        s = RemoteEdgeSender(edge, host, int(port), graph, pids, spec,
                             runtime)
        if fault_plan is not None and hasattr(fault_plan, "for_link"):
            s.faults = fault_plan.for_link(edge)
        graph._cancel.register(s)
        senders[edge] = s
    for p in nodes:
        if owners[id(p)] != me:
            continue
        for o in p.outlets:
            for di, (ch, pid) in enumerate(o.dests):
                c = consumer.get(id(unwrap(ch)))
                if c is None or c is p:
                    continue
                if owners[id(c)] != me:
                    o.dests[di] = (senders[c.name], pid)

    # -- receivers -------------------------------------------------------
    server = None
    if inbound:
        by_name = {n.name: n for n in nodes}
        edges = {edge: EdgeState(edge, unwrap(by_name[edge].channel),
                                 per_worker)
                 for edge, per_worker in inbound.items()}
        server = ShuffleServer(graph, spec, edges, runtime)
        runtime.server = server
        server.start()

    # -- prune unowned nodes (threads, stats, sources) -------------------
    removed = [n for n in nodes if owners[id(n)] != me]
    removed_recs = set()
    from ..runtime.node import FusedLogic
    for n in removed:
        if n.stats is not None:
            removed_recs.add(id(n.stats))
        if isinstance(n.logic, FusedLogic):
            for seg in n.logic.segments:
                if seg.stats is not None:
                    removed_recs.add(id(seg.stats))
    for pipe in graph.pipes:
        pipe.nodes = [n for n in pipe.nodes if owners[id(n)] == me]
        pipe.tails = [t for t in pipe.tails
                      if id(t) in {id(n) for n in pipe.nodes}]
    if removed_recs:
        with graph.stats.lock:
            recs = graph.stats.records
            for op in list(recs):
                recs[op] = [r for r in recs[op]
                            if id(r) not in removed_recs]
                if not recs[op]:
                    del recs[op]

    # -- plane hooks -----------------------------------------------------
    graph.stats.worker = me
    graph._wire_out_edges = sorted(s.edge_name for s in senders.values())
    graph._wire_in_edges = sorted(f"wire:{e}" for e in inbound)
    # diagnosis topology: cross-worker operator edges (appended by
    # topology.operator_edges), so the merged report's bottleneck walk
    # crosses the boundary to a remote worker's operator
    graph._wire_topology = sorted([a, b, "wire"]
                                  for a, b in wire_edges)
    graph._dist = runtime
    # live cluster view (observe.py): push stats + flight deltas to
    # the coordinator's ClusterObserver mid-run, so the merged doctor
    # verdict is nameable without touching any stats file
    obs = getattr(spec, "observe_endpoint", None)
    if obs:
        from .observe import attach_pusher
        runtime.pusher = attach_pusher(
            graph, obs[0], int(obs[1]),
            float(getattr(spec, "push_interval_s", 0.5)))
    graph.flight.record(
        "distribute", worker=me, nodes=len(nodes) - len(removed),
        pruned=len(removed), wire_out=len(senders), wire_in=len(inbound))
    return runtime
