"""Cross-worker observability: merge per-worker stats into one graph
view -- offline from dumps, and LIVE over a side socket
(docs/DISTRIBUTED.md "One graph view", docs/OBSERVABILITY.md "Live
cluster view").

Each worker of a distributed run reports exactly like a single-process
graph -- same stats JSON, same Conservation/Diagnosis/Wire blocks,
plus a ``Worker`` id -- and this module folds N such dumps into the
ONE report the operator actually wants:

* **operators** concatenate (every operator lives on exactly one
  worker; its rows carry the worker id);
* **topology** edges union, including the ``wire`` edges each
  producer-side worker recorded, so the bottleneck walk crosses the
  process boundary and can name an operator on a REMOTE worker;
* the **cross-process conservation identity**: every wire edge's
  producer-side book (tuples/frames sent) must equal the consumer-side
  book (delivered) -- with per-worker ledgers already balanced
  per-edge, the composition proves end-to-end transport conservation;
  any shortfall is reported with the exact edge and tuple count;
* trace records concatenate, so the merged attribution charges the
  ``wire`` hop class alongside service/queueing/device.

``build_report`` (diagnosis/report.py) accepts the merged dict as-is:
the per-worker ``Diagnosis`` blocks are folded into their recompute
inputs (sustained-depth union), so the bottleneck/attribution are
re-derived over the whole graph rather than per partition.

Two further folds make the merged view *cluster-true*:

* **trace stitching** -- a trace that crosses a wire edge leaves a
  producer-side *partial* record (hops up to and past the send,
  flagged ``partial`` with the shared trace id) and a consumer-side
  *closed* record (the full rebuilt span).  :func:`stitch_traces`
  joins the per-worker records by id into single e2e records: the
  closed record is the base, producer-only hops (stamped after the
  frame header snapshot -- fused segments unwind outward) merge in,
  and the redundant fragments drop -- so the merged attribution
  charges every class exactly once and ``Share_sum`` stays ~1.0;
* **flight dedup** -- every flight event carries a per-process ``seq``
  (telemetry/recorder.py); folding overlapping per-worker rings (live
  pushes resend unacked tails, offline dumps may overlap snapshots)
  dedups by ``(worker, seq)`` so one episode never appears twice.

The LIVE half: each worker runs a :class:`StatsPusher` (attached by
the distributed wiring when the spec names an observe endpoint) that
pushes its stats JSON plus a bounded flight-delta frame to the
coordinator's :class:`ClusterObserver` over a cheap side socket; the
observer folds the latest per-worker states with ``merge_stats``
continuously and serves the merged view (plus its doctor report) at
HTTP ``GET /cluster`` -- which is what ``python -m windflow_tpu.doctor
--watch <addr>`` polls.  A remote bottleneck is therefore nameable
mid-run with zero stats files read.
"""
from __future__ import annotations

import json
import struct
import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional, Tuple

MAX_TRACES = 128
MAX_FLIGHT = 256
MAX_EDGE_ROWS = 128
# flight events kept per worker by the live observer
OBSERVER_FLIGHT_KEEP = 512
# flight-delta events shipped per push frame (bounded like the ring)
PUSH_FLIGHT_MAX = 256
# push frame: [u32 len][json]
_PUSH_HEADER = struct.Struct("<I")
_PUSH_MAX_BYTES = 1 << 26


def wire_table(stats_list: List[dict]) -> List[dict]:
    """Per-wire-edge cross-process delivery books: producer-side sums
    vs consumer-side sums."""
    sent: Dict[str, dict] = {}
    got: Dict[str, dict] = {}
    for stats in stats_list:
        wire = (stats or {}).get("Wire") or {}
        w = wire.get("Worker")
        for row in wire.get("out") or ():
            agg = sent.setdefault(row["edge"], {
                "tuples": 0, "frames": 0, "barriers": 0,
                "dropped_frames": 0, "unacked": 0, "from": []})
            agg["tuples"] += int(row.get("tuples", 0) or 0)
            agg["frames"] += int(row.get("frames", 0) or 0)
            agg["barriers"] += int(row.get("barriers", 0) or 0)
            agg["dropped_frames"] += int(row.get("dropped_frames", 0)
                                         or 0)
            # TUPLE sum of the replay buffer (frames != tuples on the
            # batch plane); rows from older runtimes carry neither
            # field and fold as 0 -> the strict identity applies
            agg["unacked"] += int(row.get("unacked_tuples", 0) or 0)
            agg["from"].append(w)
        for row in wire.get("in") or ():
            agg = got.setdefault(row["edge"], {
                "tuples": 0, "frames": 0, "barriers": 0, "gaps": 0,
                "on": w})
            agg["tuples"] += int(row.get("tuples", 0) or 0)
            agg["frames"] += int(row.get("frames", 0) or 0)
            agg["barriers"] += int(row.get("barriers", 0) or 0)
            agg["gaps"] += int(row.get("gaps", 0) or 0)
    rows = []
    for edge in sorted(set(sent) | set(got)):
        s = sent.get(edge) or {}
        g = got.get(edge) or {}
        st, gt = int(s.get("tuples", 0)), int(g.get("tuples", 0))
        # a LIVE fold (cluster observer pushes) legitimately sees
        # tuples in flight: sent counts them, delivered does not, and
        # the sender's unacked replay buffer bounds exactly how many --
        # a SHORTFALL within that bound is "settling", not a loss
        # (over-delivery never is: gt > st is flagged regardless).
        # Offline (post-flush) the buffer is empty and the old strict
        # identity applies.
        unacked = int(s.get("unacked", 0) or 0)
        rows.append({
            "edge": edge,
            "from_workers": sorted(x for x in s.get("from", [])
                                   if x is not None),
            "on_worker": g.get("on"),
            "tuples_sent": st, "tuples_delivered": gt,
            "frames_sent": int(s.get("frames", 0)),
            "frames_delivered": int(g.get("frames", 0)),
            "barriers_sent": int(s.get("barriers", 0)),
            "barriers_delivered": int(g.get("barriers", 0)),
            "dropped_frames": int(s.get("dropped_frames", 0)),
            "gaps": int(g.get("gaps", 0)),
            "in_flight": unacked,
            "missing_tuples": max(0, st - gt - unacked),
            "extra_tuples": max(0, gt - st),
            "settling": gt < st <= gt + unacked,
            "balanced": st == gt,
        })
    return rows


def stitch_traces(traces: List[dict]) -> List[dict]:
    """Join per-worker trace records by trace id into single e2e
    records (module docstring).  Records without an id (pre-stitching
    runtimes) pass through untouched; groups with no closed record
    keep their longest fragment (still flagged ``partial``, so
    attribution keeps skipping it)."""
    by_id: Dict[str, List[dict]] = {}
    out: List[dict] = []
    for rec in traces:
        if not isinstance(rec, dict):
            continue
        tid = rec.get("id")
        if not tid:
            out.append(rec)
            continue
        by_id.setdefault(tid, []).append(rec)
    for tid, group in by_id.items():
        closed = [r for r in group if not r.get("partial")]
        workers = sorted({r.get("worker") for r in group
                          if r.get("worker") is not None})
        if not closed:
            # the closing sink record fell off its worker's bounded
            # ring: keep one fragment for display, still partial
            out.append(max(group, key=lambda r: r.get("e2e_ms") or 0.0))
            continue
        base = dict(max(closed, key=lambda r: r.get("e2e_ms") or 0.0))
        names = {h[0] for h in base.get("hops") or ()
                 if isinstance(h, (list, tuple)) and h}
        extra = []
        for r in group:
            if r.get("partial"):
                for h in r.get("hops") or ():
                    try:
                        name = h[0]
                    except (TypeError, IndexError):
                        continue
                    if name not in names:
                        names.add(name)
                        extra.append(list(h))
        if extra:
            # hop offsets share the logical span start (the consumer
            # rebuilt t0 from the shipped age + wall send stamp), so
            # fragments merge positionally; attribution clamps any
            # residual clock-estimate skew into [0, e2e]
            hops = [list(h) for h in base.get("hops") or ()] + extra
            hops.sort(key=lambda h: (h[1:2] or [0.0])[0])
            base["hops"] = hops
            base["stitched"] = True
        if len(workers) > 1:
            base["workers"] = workers
        out.append(base)
    return out


def merge_stats(stats_list: List[dict], live: bool = False) -> dict:
    """Fold per-worker stats dicts into one graph view (see module
    docstring).  Tolerant: blocks are optional per worker, like every
    stats-JSON reader in the repo.

    ``live=True`` marks a fold of UNSYNCHRONIZED mid-run snapshots
    (the cluster observer's continuous merge): the producer's and
    consumer's books were captured at different instants, so a
    shortfall beyond the sender's replay buffer is snapshot skew, not
    evidence -- the merge then never *synthesizes* a wire-loss
    violation of its own (the per-worker ONLINE detectors -- receiver
    sequence gaps + the sender's STATS trailer -- remain the
    authoritative live loss reporters and their violations still fold
    in).  Offline (the default: settled post-run dumps) the strict
    identity applies."""
    stats_list = [s for s in stats_list if isinstance(s, dict)]
    if not stats_list:
        return {}
    first = stats_list[0]
    operators: List[dict] = []
    edges_seen = set()
    topology: List[List[str]] = []
    traces: List[dict] = []
    flight: List[dict] = []
    cons_rows: List[dict] = []
    violations: List[dict] = []
    sustained: Dict[str, float] = {}
    qcap: Optional[int] = None
    sums = {"Dropped_tuples": 0, "Svc_failures": 0,
            "Dead_letter_tuples": 0, "Shed_tuples": 0}
    edges_balanced = True
    final_check = True
    committed: Optional[int] = None
    workers: List[dict] = []
    slo_blocks: List[dict] = []
    pool_blocks: List[dict] = []
    sched_blocks: List[dict] = []
    flight_seen = set()
    for stats in stats_list:
        w = stats.get("Worker")
        workers.append({"Worker": w,
                        "PipeGraph_name": stats.get("PipeGraph_name")})
        for op in stats.get("Operators") or ():
            row = dict(op)
            row["Worker"] = w
            operators.append(row)
        topo = (stats.get("Topology") or {}).get("Edges") or []
        for e in topo:
            key = tuple(e[:2])
            if key not in edges_seen:
                edges_seen.add(key)
                topology.append(list(e))
        for rec in stats.get("Trace_records") or ():
            if isinstance(rec, dict):
                rec = dict(rec)
                rec.setdefault("worker", w)
            traces.append(rec)
        for ev in stats.get("Flight") or ():
            # dedup by (worker, seq): overlapping flight tails (live
            # pushes resend unacked deltas, offline snapshot dumps may
            # overlap) must never duplicate an episode in the merged
            # view.  Events without a seq (older runtimes) pass
            # through undeduped.
            seq = ev.get("seq")
            if seq is not None:
                key = (w, seq)
                if key in flight_seen:
                    continue
                flight_seen.add(key)
            ev = dict(ev)
            ev.setdefault("worker", w)
            flight.append(ev)
        if stats.get("Slo"):
            slo_blocks.append(stats["Slo"])
        if stats.get("Pool"):
            pool_blocks.append(stats["Pool"])
        sched = stats.get("Scheduler")
        if isinstance(sched, dict):
            sched = dict(sched)
            sched.setdefault("Worker", w)
            sched_blocks.append(sched)
        for k in sums:
            sums[k] += int(stats.get(k, 0) or 0)
        cons = stats.get("Conservation")
        if cons:
            edges_balanced = edges_balanced \
                and bool(cons.get("Edges_balanced"))
            final_check = final_check and bool(cons.get("Final_check"))
            cons_rows.extend(cons.get("Edges") or ())
            for v in cons.get("Violations") or ():
                v = dict(v)
                v.setdefault("worker", w)
                violations.append(v)
        diag = stats.get("Diagnosis") or {}
        for k, v in (diag.get("Sustained_depth") or {}).items():
            sustained[k] = max(sustained.get(k, 0.0), float(v or 0.0))
        if diag.get("Queue_capacity"):
            qcap = max(qcap or 0, int(diag["Queue_capacity"]))
        dur = stats.get("Durability")
        if dur is not None:
            c = int(dur.get("Committed_epoch", 0) or 0)
            committed = c if committed is None else min(committed, c)
    wire_rows = wire_table(stats_list)
    for row in wire_rows:
        if row["balanced"]:
            continue
        if live:
            # snapshot skew / in-flight tuples between unsynchronized
            # pushes; the per-worker ONLINE detectors own live loss
            # reporting (their violations fold in above)
            continue
        # OFFLINE (settled dumps): the strict identity applies -- a
        # post-run unacked residue IS a loss (the flush timed out on
        # genuinely undelivered tuples), so "settling" never excuses
        # an imbalance here.  The consumer worker usually flagged the
        # loss online already (STATS-trailer check); synthesize a
        # violation only when no per-worker book carried it, so one
        # loss never counts twice in the merged report
        edges_balanced = False
        if not any(v.get("kind") == "lost_wire_delivery"
                   and v.get("edge") == row["edge"]
                   for v in violations):
            violations.append({
                "kind": "lost_wire_delivery", "edge": row["edge"],
                "count": abs(row["tuples_sent"]
                             - row["tuples_delivered"]),
                "frames": (row["frames_sent"]
                           - row["frames_delivered"]),
            })
    flight.sort(key=lambda e: e.get("t", 0))
    from ..slo.plane import merge_slo
    # stitch cross-worker traces by id BEFORE bounding, so a closed
    # record near the cut cannot lose its producer fragment
    traces = stitch_traces(traces)
    merged = {
        "PipeGraph_name": first.get("PipeGraph_name", "?"),
        "Schema_version": first.get("Schema_version"),
        "Merged_workers": workers,
        "Operators": operators,
        "Operator_number": len(operators),
        "Topology": {"Edges": topology} if topology else None,
        "Trace_records": traces[-MAX_TRACES:],
        "Flight": flight[-MAX_FLIGHT:],
        "Conservation": {
            "Edges_balanced": edges_balanced,
            "Final_check": final_check,
            "Violations_total": len(violations),
            "Violations": violations,
            "Edges": cons_rows[:MAX_EDGE_ROWS],
            # wire edges already appear as the sender-side
            # "wire:<consumer>" ledger rows; only count ones the
            # per-worker books somehow missed
            "Edges_total": len(cons_rows) + sum(
                1 for r in wire_rows
                if f"wire:{r['edge']}"
                not in {c.get("edge") for c in cons_rows}),
        },
        "Wire": {
            "Edges": wire_rows,
            "Balanced": all(r["balanced"] for r in wire_rows),
            # live folds: in-flight-bounded shortfalls are settling,
            # not lost -- the strict Balanced stays the offline truth
            "Settling": any(r["settling"] for r in wire_rows),
        },
        # recompute inputs only: bottleneck/attribution re-derive over
        # the merged operator set (diagnosis/report.py offline path)
        "Diagnosis": {
            "Sustained_depth": sustained,
            "Queue_capacity": qcap,
        } if (sustained or qcap) else None,
        "Durability": ({"Committed_epoch": committed}
                       if committed is not None else None),
        # SLO plane: worst news wins across the fleet (slo/plane.py)
        "Slo": merge_slo(slo_blocks),
        "Pool": ({
            "Buffers": sum(int(p.get("Buffers", 0) or 0)
                           for p in pool_blocks),
            "Bytes": sum(int(p.get("Bytes", 0) or 0)
                         for p in pool_blocks),
        } if pool_blocks else None),
        # scheduler plane (scheduler/): per-worker blocks kept whole
        # (placement is per-worker truth, never re-derived here) plus
        # the two fleet-level aggregates readers actually chart
        "Scheduler": ({
            "Workers": sched_blocks,
            "Sched_wait_s": round(sum(
                float(b.get("Sched_wait_s", 0) or 0)
                for b in sched_blocks), 3),
            "Placements": [row for b in sched_blocks
                           for row in (b.get("Placements") or ())],
        } if sched_blocks else None),
    }
    merged.update(sums)
    return merged


def check_wire_conservation(stats_list: List[dict]) -> List[dict]:
    """The cross-process final check: every wire edge balanced to the
    tuple (post-run books: an unacked replay-buffer residue is a loss
    here, unlike in a live fold).  Returns violations ([] == the
    identity holds)."""
    return [{"kind": "lost_wire_delivery", "edge": r["edge"],
             "count": max(0, r["tuples_sent"] - r["tuples_delivered"])}
            for r in wire_table(stats_list) if not r["balanced"]]


# ---------------------------------------------------------------------------
# live cluster view: StatsPusher (worker side) -> ClusterObserver
# (coordinator side) over a cheap framed-JSON side socket
# ---------------------------------------------------------------------------

class ClusterObserver(threading.Thread):
    """Coordinator-side live view of a distributed run.

    Accepts worker push connections on a loopback TCP port, keeps the
    latest stats dict per worker plus a bounded accumulated flight
    ring (deltas dedup by ``(worker, pid, seq)`` so resent tails after
    a reconnect or a worker restart never duplicate an episode), and
    folds everything with :func:`merge_stats` on demand.
    :meth:`serve_http` exposes the merged view at ``GET /cluster`` --
    the endpoint ``python -m windflow_tpu.doctor --watch`` polls."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 flight_keep: int = OBSERVER_FLIGHT_KEEP):
        super().__init__(name="windflow-cluster-observer", daemon=True)
        import socket
        self._lsock = socket.create_server((host, port))
        self._lsock.settimeout(0.2)
        self.host = host
        self.port = self._lsock.getsockname()[1]
        self.flight_keep = flight_keep
        self.lock = threading.Lock()
        self.latest: Dict[int, dict] = {}       # worker -> stats dict
        self.flight: Dict[int, deque] = {}      # worker -> event ring
        self._flight_seen: Dict[int, deque] = {}  # dedup key memory
        self.updated: Dict[int, float] = {}
        # worker -> its latest push was the FINAL (settled-books) one;
        # until every worker is final, merged() folds in live mode
        self.final: Dict[int, bool] = {}
        self.pushes = 0
        self.http_port: Optional[int] = None
        self._httpd = None
        self._stop_evt = threading.Event()

    # -- ingest --------------------------------------------------------
    def run(self) -> None:
        import socket
        while not self._stop_evt.is_set():
            try:
                conn, _addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True,
                             name="windflow-observer-rx").start()

    def _serve(self, conn) -> None:
        import socket
        conn.settimeout(0.5)
        buf = bytearray()
        try:
            with conn:
                while not self._stop_evt.is_set():
                    try:
                        data = conn.recv(1 << 20)
                    except socket.timeout:
                        continue
                    if not data:
                        return
                    buf.extend(data)
                    while len(buf) >= _PUSH_HEADER.size:
                        (ln,) = _PUSH_HEADER.unpack_from(bytes(
                            buf[:_PUSH_HEADER.size]))
                        if ln > _PUSH_MAX_BYTES:
                            return  # desynced stream: drop the conn
                        end = _PUSH_HEADER.size + ln
                        if len(buf) < end:
                            break
                        payload = bytes(buf[_PUSH_HEADER.size:end])
                        del buf[:end]
                        try:
                            self.ingest(json.loads(payload))
                        except ValueError:
                            return
        except OSError:
            return

    def ingest(self, doc: dict) -> None:
        """Fold one push frame: ``{"pid": ..., "stats": {...}}`` where
        the stats dict's ``Flight`` holds only the delta events."""
        stats = doc.get("stats")
        if not isinstance(stats, dict):
            return
        pid = doc.get("pid")
        w = stats.get("Worker")
        wkey = -1 if w is None else int(w)
        delta = stats.pop("Flight", None) or ()
        with self.lock:
            self.latest[wkey] = stats
            self.updated[wkey] = _time.time()
            self.final[wkey] = bool(doc.get("final"))
            self.pushes += 1
            ring = self.flight.get(wkey)
            if ring is None:
                ring = self.flight[wkey] = deque(
                    maxlen=max(1, self.flight_keep))
                self._flight_seen[wkey] = deque(
                    maxlen=max(1, self.flight_keep))
            seen = self._flight_seen[wkey]
            seen_set = set(seen)
            for ev in delta:
                seq = ev.get("seq")
                if seq is not None:
                    key = (pid, seq)
                    if key in seen_set:
                        continue
                    seen.append(key)
                    seen_set.add(key)
                ring.append(ev)

    # -- fold ----------------------------------------------------------
    def worker_stats(self) -> List[dict]:
        """Latest per-worker stats dicts with their accumulated flight
        rings re-attached (what ``merge_stats`` consumes).  The ring
        was already deduped by ``(pid, seq)`` at ingest, so the events
        are RE-sequenced here: a restarted worker process reuses seqs
        from 1, and handing the raw values to ``merge_stats`` would
        let its ``(worker, seq)`` dedup swallow the new attempt's
        events as duplicates of the old one's."""
        with self.lock:
            return [dict(stats,
                         Flight=[dict(ev, seq=i + 1) for i, ev in
                                 enumerate(self.flight.get(w) or ())])
                    for w, stats in sorted(self.latest.items())]

    def merged(self) -> dict:
        with self.lock:
            settled = bool(self.latest) and all(
                self.final.get(w) for w in self.latest)
        return merge_stats(self.worker_stats(), live=not settled)

    # -- HTTP ----------------------------------------------------------
    def serve_http(self, port: int = 0):
        """Serve ``GET /cluster`` (and every other path): the merged
        stats dict, its doctor report, and per-worker liveness meta as
        one JSON object."""
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer
        obs = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                from ..diagnosis.report import build_report
                merged = obs.merged()
                rep = build_report(merged, merged.get("Flight")) \
                    if merged else None
                with obs.lock:
                    meta = {str(w): {"updated": obs.updated.get(w)}
                            for w in obs.latest}
                    pushes = obs.pushes
                body = json.dumps({
                    "merged": merged, "report": rep,
                    "workers": meta, "pushes": pushes,
                    "now": round(_time.time(), 3),
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        httpd = ThreadingHTTPServer((self.host, port), Handler)
        self.http_port = httpd.server_address[1]
        self._httpd = httpd
        threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="windflow-observer-http").start()
        return httpd

    @property
    def http_url(self) -> Optional[str]:
        if self.http_port is None:
            return None
        return f"http://{self.host}:{self.http_port}"

    def stop(self) -> None:
        self._stop_evt.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()  # release the listening fd now
        self.join(timeout=2.0)


class StatsPusher(threading.Thread):
    """Worker-side live reporter: every ``interval_s`` it refreshes
    the gauges, rides the diagnosis tick (rate-limited internally, so
    stacking on the monitor cadence cannot multiply the cost), and
    pushes the stats JSON plus the flight-delta tail to the
    coordinator's :class:`ClusterObserver`.

    Best-effort by design: a dead observer must never take the graph
    down -- send failures drop the connection and the next tick
    reconnects.  ``_last_seq`` only advances after a successful send,
    so a reconnect re-ships the unacknowledged flight tail and the
    observer's ``(worker, pid, seq)`` dedup absorbs the overlap."""

    def __init__(self, graph, host: str, port: int,
                 interval_s: float = 0.5):
        super().__init__(name="windflow-stats-pusher", daemon=True)
        self.graph = graph
        self.host = host
        self.port = int(port)
        self.interval_s = max(0.05, float(interval_s))
        self._stop_evt = threading.Event()
        self._sock = None
        self._last_seq = 0
        self._final = False
        self.pushes = 0
        self.errors = 0

    def _frame(self) -> Tuple[bytes, int]:
        import os
        g = self.graph
        try:
            g.refresh_gauges()
        except Exception:  # gauge reads race teardown; push what we can
            pass
        diag = getattr(g, "diagnosis", None)
        if diag is not None:
            # the final frame reports the SETTLED state: force the
            # tick past its rate limit so the last published blocks
            # (Slo, History, Diagnosis) are end-of-run fresh -- a
            # short run could otherwise end inside the rate window
            # with the blocks never published at all
            diag.maybe_tick(force=self._final)
        events = [ev for ev in g.flight.snapshot()
                  if (ev.get("seq") or 0) > self._last_seq]
        events = events[:PUSH_FLIGHT_MAX]
        top = max((ev.get("seq") or 0 for ev in events),
                  default=self._last_seq)
        dls = getattr(g, "dead_letters", None)
        stats_json = g.stats.to_json(
            g.get_num_dropped_tuples(),
            dls.count() if dls is not None else 0,
            flight_events=events)
        # wrap without re-parsing the (already serialized) stats JSON;
        # the final frame (sent from stop(), after the wire flushed)
        # marks this worker's books settled -- once every worker is
        # final the observer's fold applies the strict wire identity
        doc = '{"pid":%d,"final":%s,"stats":%s}' % (
            os.getpid(), "true" if self._final else "false", stats_json)
        payload = doc.encode("utf-8")
        return _PUSH_HEADER.pack(len(payload)) + payload, top

    def _push_once(self) -> None:
        import socket
        frame, top = self._frame()
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=2.0)
        self._sock.sendall(frame)
        self._last_seq = top
        self.pushes += 1

    def _close(self) -> None:
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self._push_once()
            except OSError:
                self.errors += 1
                self._close()
        self._final = True
        try:
            self._push_once()  # final (settled-books) state at stop
        except OSError:
            self.errors += 1
        self._close()

    def stop(self) -> None:
        self._stop_evt.set()
        self.join(timeout=5.0)


def attach_pusher(graph, host: str, port: int,
                  interval_s: float = 0.5) -> StatsPusher:
    """Start a :class:`StatsPusher` for ``graph`` (distributed wiring
    calls this when the spec names an observe endpoint; single-process
    graphs can attach one by hand -- e.g. bench ``13_slo_overhead``)."""
    p = StatsPusher(graph, host, port, interval_s)
    p.start()
    return p
