"""Cross-worker observability: merge per-worker stats JSON dumps into
one graph view (docs/DISTRIBUTED.md "One graph view").

Each worker of a distributed run reports exactly like a single-process
graph -- same stats JSON, same Conservation/Diagnosis/Wire blocks,
plus a ``Worker`` id -- and this module folds N such dumps into the
ONE report the operator actually wants:

* **operators** concatenate (every operator lives on exactly one
  worker; its rows carry the worker id);
* **topology** edges union, including the ``wire`` edges each
  producer-side worker recorded, so the bottleneck walk crosses the
  process boundary and can name an operator on a REMOTE worker;
* the **cross-process conservation identity**: every wire edge's
  producer-side book (tuples/frames sent) must equal the consumer-side
  book (delivered) -- with per-worker ledgers already balanced
  per-edge, the composition proves end-to-end transport conservation;
  any shortfall is reported with the exact edge and tuple count;
* trace records concatenate, so the merged attribution charges the
  ``wire`` hop class alongside service/queueing/device.

``build_report`` (diagnosis/report.py) accepts the merged dict as-is:
the per-worker ``Diagnosis`` blocks are folded into their recompute
inputs (sustained-depth union), so the bottleneck/attribution are
re-derived over the whole graph rather than per partition.
"""
from __future__ import annotations

from typing import Dict, List, Optional

MAX_TRACES = 128
MAX_FLIGHT = 256
MAX_EDGE_ROWS = 128


def wire_table(stats_list: List[dict]) -> List[dict]:
    """Per-wire-edge cross-process delivery books: producer-side sums
    vs consumer-side sums."""
    sent: Dict[str, dict] = {}
    got: Dict[str, dict] = {}
    for stats in stats_list:
        wire = (stats or {}).get("Wire") or {}
        w = wire.get("Worker")
        for row in wire.get("out") or ():
            agg = sent.setdefault(row["edge"], {
                "tuples": 0, "frames": 0, "barriers": 0,
                "dropped_frames": 0, "from": []})
            agg["tuples"] += int(row.get("tuples", 0) or 0)
            agg["frames"] += int(row.get("frames", 0) or 0)
            agg["barriers"] += int(row.get("barriers", 0) or 0)
            agg["dropped_frames"] += int(row.get("dropped_frames", 0)
                                         or 0)
            agg["from"].append(w)
        for row in wire.get("in") or ():
            agg = got.setdefault(row["edge"], {
                "tuples": 0, "frames": 0, "barriers": 0, "gaps": 0,
                "on": w})
            agg["tuples"] += int(row.get("tuples", 0) or 0)
            agg["frames"] += int(row.get("frames", 0) or 0)
            agg["barriers"] += int(row.get("barriers", 0) or 0)
            agg["gaps"] += int(row.get("gaps", 0) or 0)
    rows = []
    for edge in sorted(set(sent) | set(got)):
        s = sent.get(edge) or {}
        g = got.get(edge) or {}
        st, gt = int(s.get("tuples", 0)), int(g.get("tuples", 0))
        rows.append({
            "edge": edge,
            "from_workers": sorted(x for x in s.get("from", [])
                                   if x is not None),
            "on_worker": g.get("on"),
            "tuples_sent": st, "tuples_delivered": gt,
            "frames_sent": int(s.get("frames", 0)),
            "frames_delivered": int(g.get("frames", 0)),
            "barriers_sent": int(s.get("barriers", 0)),
            "barriers_delivered": int(g.get("barriers", 0)),
            "dropped_frames": int(s.get("dropped_frames", 0)),
            "gaps": int(g.get("gaps", 0)),
            "missing_tuples": max(0, st - gt),
            "balanced": st == gt,
        })
    return rows


def merge_stats(stats_list: List[dict]) -> dict:
    """Fold per-worker stats dicts into one graph view (see module
    docstring).  Tolerant: blocks are optional per worker, like every
    stats-JSON reader in the repo."""
    stats_list = [s for s in stats_list if isinstance(s, dict)]
    if not stats_list:
        return {}
    first = stats_list[0]
    operators: List[dict] = []
    edges_seen = set()
    topology: List[List[str]] = []
    traces: List[dict] = []
    flight: List[dict] = []
    cons_rows: List[dict] = []
    violations: List[dict] = []
    sustained: Dict[str, float] = {}
    qcap: Optional[int] = None
    sums = {"Dropped_tuples": 0, "Svc_failures": 0,
            "Dead_letter_tuples": 0, "Shed_tuples": 0}
    edges_balanced = True
    final_check = True
    committed: Optional[int] = None
    workers: List[dict] = []
    for stats in stats_list:
        w = stats.get("Worker")
        workers.append({"Worker": w,
                        "PipeGraph_name": stats.get("PipeGraph_name")})
        for op in stats.get("Operators") or ():
            row = dict(op)
            row["Worker"] = w
            operators.append(row)
        topo = (stats.get("Topology") or {}).get("Edges") or []
        for e in topo:
            key = tuple(e[:2])
            if key not in edges_seen:
                edges_seen.add(key)
                topology.append(list(e))
        for rec in stats.get("Trace_records") or ():
            traces.append(rec)
        for ev in stats.get("Flight") or ():
            ev = dict(ev)
            ev.setdefault("worker", w)
            flight.append(ev)
        for k in sums:
            sums[k] += int(stats.get(k, 0) or 0)
        cons = stats.get("Conservation")
        if cons:
            edges_balanced = edges_balanced \
                and bool(cons.get("Edges_balanced"))
            final_check = final_check and bool(cons.get("Final_check"))
            cons_rows.extend(cons.get("Edges") or ())
            for v in cons.get("Violations") or ():
                v = dict(v)
                v.setdefault("worker", w)
                violations.append(v)
        diag = stats.get("Diagnosis") or {}
        for k, v in (diag.get("Sustained_depth") or {}).items():
            sustained[k] = max(sustained.get(k, 0.0), float(v or 0.0))
        if diag.get("Queue_capacity"):
            qcap = max(qcap or 0, int(diag["Queue_capacity"]))
        dur = stats.get("Durability")
        if dur is not None:
            c = int(dur.get("Committed_epoch", 0) or 0)
            committed = c if committed is None else min(committed, c)
    wire_rows = wire_table(stats_list)
    for row in wire_rows:
        if not row["balanced"]:
            edges_balanced = False
            # the consumer worker usually flagged this loss online
            # already (transport STATS-trailer check); synthesize a
            # violation only when no per-worker book carried it, so
            # one loss never counts twice in the merged report
            if not any(v.get("kind") == "lost_wire_delivery"
                       and v.get("edge") == row["edge"]
                       for v in violations):
                violations.append({
                    "kind": "lost_wire_delivery", "edge": row["edge"],
                    "count": row["missing_tuples"],
                    "frames": (row["frames_sent"]
                               - row["frames_delivered"]),
                })
    flight.sort(key=lambda e: e.get("t", 0))
    merged = {
        "PipeGraph_name": first.get("PipeGraph_name", "?"),
        "Schema_version": first.get("Schema_version"),
        "Merged_workers": workers,
        "Operators": operators,
        "Operator_number": len(operators),
        "Topology": {"Edges": topology} if topology else None,
        "Trace_records": traces[-MAX_TRACES:],
        "Flight": flight[-MAX_FLIGHT:],
        "Conservation": {
            "Edges_balanced": edges_balanced,
            "Final_check": final_check,
            "Violations_total": len(violations),
            "Violations": violations,
            "Edges": cons_rows[:MAX_EDGE_ROWS],
            # wire edges already appear as the sender-side
            # "wire:<consumer>" ledger rows; only count ones the
            # per-worker books somehow missed
            "Edges_total": len(cons_rows) + sum(
                1 for r in wire_rows
                if f"wire:{r['edge']}"
                not in {c.get("edge") for c in cons_rows}),
        },
        "Wire": {
            "Edges": wire_rows,
            "Balanced": all(r["balanced"] for r in wire_rows),
        },
        # recompute inputs only: bottleneck/attribution re-derive over
        # the merged operator set (diagnosis/report.py offline path)
        "Diagnosis": {
            "Sustained_depth": sustained,
            "Queue_capacity": qcap,
        } if (sustained or qcap) else None,
        "Durability": ({"Committed_epoch": committed}
                       if committed is not None else None),
    }
    merged.update(sums)
    return merged


def check_wire_conservation(stats_list: List[dict]) -> List[dict]:
    """The cross-process final check: every wire edge balanced to the
    tuple.  Returns violations ([] == the identity holds)."""
    return [{"kind": "lost_wire_delivery", "edge": r["edge"],
             "count": r["missing_tuples"]}
            for r in wire_table(stats_list) if not r["balanced"]]
