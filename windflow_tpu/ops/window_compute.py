"""Batched window computation on device: the XLA replacement for the
reference's per-window CUDA kernels.

The reference assembles a batch of fired windows in pinned host memory
and launches ``ComputeBatch_Kernel`` -- one CUDA thread per window
running the user functor (win_seq_gpu.hpp:61-84, :552-610).  A TPU is
not a scalar-thread machine, so the design is different:

* windows over each key live in one contiguous **flat buffer** (ragged
  concatenation of per-key series); window extents are [start, end)
  index pairs into it.  Windows never span keys, so segment math works
  on the flat buffer directly.
* **invertible combines** (sum/count/mean) use one prefix scan over the
  flat buffer + two gathers per window: O(T + B) work, no [B, W]
  materialization, pure VPU-friendly code XLA fuses well.
* **semigroup combines** (max/min) use a sparse table (log-sweep of
  strided combines) + two gathers per window -- the classic O(1) range
  query, a TPU-shaped replacement for FlatFAT's per-window tree walk.
* **custom window functions** gather padded [B, W_pad] tiles and vmap
  the user's JAX function over the batch (the analogue of the
  reference's arbitrary ``__host__ __device__`` functor path).

All shapes are bucketed to powers of two so XLA compiles a small, cached
set of programs (the reference instead reallocates pinned buffers
adaptively, win_seq_gpu.hpp:574-592).  Dispatch is async: results come
back as handles whose ``.block()`` materializes on host -- the
double-buffering protocol of ``waitAndFlush`` (win_seq_gpu.hpp:267-297)
falls out of JAX's asynchronous dispatch.
"""
from __future__ import annotations

import functools
import os
import threading
from typing import Any, Callable, Dict

import numpy as np

BUILTIN_KINDS = ("sum", "count", "mean", "max", "min")

# pane-partial pair kinds: cols carry a second buffer alongside "value"
# (the native engine's MEAN staging ships per-pane sums + counts)
PAIR_KINDS = ("mean_panes",)

# opt-in escape hatch for transports that cannot take concurrent
# transfers (WINDFLOW_GLOBAL_DISPATCH_LOCK=1)
_GLOBAL_DISPATCH_LOCK = threading.Lock()


def _transfer_guard():
    """Serialization context for device transfers: the global lock when
    the escape hatch is on (D2H in block() must serialize against every
    engine's H2D, not just its own), else a no-op."""
    import contextlib
    import os
    if os.environ.get("WINDFLOW_GLOBAL_DISPATCH_LOCK") == "1":
        return _GLOBAL_DISPATCH_LOCK
    return contextlib.nullcontext()


def next_pow2(n: int) -> int:
    p = 1
    while p < max(1, n):
        p <<= 1
    return p


@functools.lru_cache(maxsize=None)
def _jax():
    import jax
    import jax.numpy as jnp
    return jax, jnp


# ---------------------------------------------------------------------------
# jitted programs (cached per bucketed shape)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _scan_program(kind: str):
    """``se`` packs [starts; ends] as one int32 [2, B] array: a single
    host->device transfer instead of three (padding rows are (0, 0), so
    their range sums are 0 and the host slice drops them anyway)."""
    jax, jnp = _jax()

    @jax.jit
    def run(values, se):
        starts, ends = se[0], se[1]
        c = jnp.concatenate([jnp.zeros((1,), values.dtype),
                             jnp.cumsum(values)])
        s = c[ends] - c[starts]
        n = (ends - starts).astype(values.dtype)
        if kind == "sum":
            out = s
        elif kind == "count":
            out = n
        else:  # mean
            out = s / jnp.maximum(n, 1)
        return out

    return run


@functools.lru_cache(maxsize=None)
def _tile_sum_program(w_pad: int):
    """Window sums via a masked [B, w_pad] gather-tile reduction.
    Used instead of the prefix scan when every window spans few panes:
    the scan's c[end]-c[start] differencing carries the f32 rounding of
    the WHOLE buffer's magnitude into each window (catastrophic for
    small windows late in the buffer), while the tile sums only the
    window's own panes -- exact to within-window rounding, and for
    w_pad this small the gather is cheaper than the scan anyway."""
    jax, jnp = _jax()

    @jax.jit
    def run(values, se):
        starts, ends = se[0], se[1]
        T = values.shape[0]
        idx = starts[:, None] + jnp.arange(w_pad)[None, :]
        mask = idx < ends[:, None]
        idx = jnp.clip(idx, 0, T - 1)
        return jnp.where(mask, values[idx], 0).sum(axis=1)

    return run


@functools.lru_cache(maxsize=None)
def _tile_mean_program(w_pad: int):
    jax, jnp = _jax()

    @jax.jit
    def run(values, counts, se):
        starts, ends = se[0], se[1]
        T = values.shape[0]
        idx = starts[:, None] + jnp.arange(w_pad)[None, :]
        mask = idx < ends[:, None]
        idx = jnp.clip(idx, 0, T - 1)
        s = jnp.where(mask, values[idx], 0).sum(axis=1)
        n = jnp.where(mask, counts[idx], 0).sum(axis=1)
        return s / jnp.maximum(n, 1)

    return run


# max pane extent (already padded to a power of two) served by the
# gather-tile programs; wider windows take the prefix scan
_TILE_MAX_W = 32


@functools.lru_cache(maxsize=None)
def _scan_pair_program():
    """Mean over pane partials: per-window sum of pane sums divided by
    sum of pane counts (the native engine's MEAN staging ships both
    buffers; a windowed mean is NOT the mean of pane means)."""
    jax, jnp = _jax()

    @jax.jit
    def run(values, counts, se):
        starts, ends = se[0], se[1]
        cv = jnp.concatenate([jnp.zeros((1,), values.dtype),
                              jnp.cumsum(values)])
        cc = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                              jnp.cumsum(counts)])
        s = cv[ends] - cv[starts]
        n = cc[ends] - cc[starts]
        return s / jnp.maximum(n, 1)

    return run


@functools.lru_cache(maxsize=None)
def _sparse_table_program(kind: str, n_levels: int):
    """Range-min/max via log-sweep sparse table: level j holds the
    combine over [i, i + 2^j).  Result = combine(table[j][start],
    table[j][end - 2^j]) with j = floor(log2(len)) per window."""
    jax, jnp = _jax()
    neutral = -np.inf if kind == "max" else np.inf
    comb = jnp.maximum if kind == "max" else jnp.minimum

    @jax.jit
    def run(values, se):
        starts, ends = se[0], se[1]
        T = values.shape[0]
        levels = [values]
        v = values
        for j in range(1, n_levels):
            shift = 1 << (j - 1)
            shifted = jnp.concatenate(
                [v[shift:], jnp.full((shift,), neutral, v.dtype)])
            v = comb(v, shifted)
            levels.append(v)
        table = jnp.stack(levels)  # [L, T]
        length = jnp.maximum(ends - starts, 1)
        j = jnp.floor(jnp.log2(length.astype(jnp.float32))).astype(jnp.int32)
        j = jnp.clip(j, 0, n_levels - 1)
        hi = jnp.clip(ends - (1 << j), 0, T - 1)
        lo = jnp.clip(starts, 0, T - 1)
        out = comb(table[j, lo], table[j, hi])
        # padding rows ((0,0) extents) may hold +-inf; zero them so the
        # host-side result buffer stays finite
        return jnp.where(se[1] > se[0], out, 0)

    return run


@functools.lru_cache(maxsize=None)
def _custom_program(fn: Callable, w_pad: int, col_names: tuple):
    jax, jnp = _jax()

    @jax.jit
    def run(gwids, starts, ends, valid, *cols):
        T = cols[0].shape[0]
        idx = starts[:, None] + jnp.arange(w_pad)[None, :]
        mask = idx < ends[:, None]
        idx = jnp.clip(idx, 0, T - 1)
        win_cols = {name: c[idx] for name, c in zip(col_names, cols)}
        out = jax.vmap(fn)(gwids, win_cols, mask)
        return jnp.where(valid, out, 0)

    return run


@functools.lru_cache(maxsize=None)
def _ffat_program(combine: Callable, neutral: float, t_pad: int):
    """FlatFAT path: build the device aggregator tree over the flat
    buffer, then answer every window with a vectorized range query --
    the Win_SeqFFAT_GPU pipeline (flatfat_gpu.hpp kernels) in one jitted
    chain."""
    from .flatfat_jax import _programs
    jax, jnp = _jax()
    build, _update, query = _programs(combine, neutral, t_pad)

    @jax.jit
    def run(values, se):
        starts, ends = se[0], se[1]
        valid = ends > starts
        tree = build(values)
        out = query(tree, starts, ends, valid)
        return jnp.where(valid, out, 0)

    return run


# flat-buffer sizes whose tree fits comfortably in VMEM alongside the
# batch (2 * t_pad f32 <= 4 MiB); larger buffers take the XLA query
_PALLAS_FFAT_MAX_T = 1 << 19


def _use_pallas_ffat(t_pad: int) -> bool:
    """Pallas FFAT query gate: env opt-in only.  The A/B on the real
    chip (docs/PARITY.md "Pallas vs XLA") measured the bit-walk kernel
    at parity with the XLA query for short extents and up to 5.5x
    BEHIND at the extents the engine actually produces for custom
    combines (extent ~ win_len: no pane pre-reduction there), so the
    default is the XLA path on every backend."""
    import os
    flag = os.environ.get("WINDFLOW_PALLAS_FFAT", "auto")
    if flag in ("1", "on"):
        # honored on every backend (interpret mode off-TPU keeps the
        # kernel testable on CPU CI); the VMEM cap still applies, but
        # vetoing an explicit opt-in is said out loud -- execution-time
        # failures of an oversized tree would surface asynchronously,
        # outside the per-shape fallback's reach
        if t_pad > _PALLAS_FFAT_MAX_T:
            import warnings
            warnings.warn(
                f"WINDFLOW_PALLAS_FFAT=1 ignored for t_pad={t_pad} "
                f"(> {_PALLAS_FFAT_MAX_T}: tree would exceed VMEM); "
                f"using the XLA query", RuntimeWarning, stacklevel=3)
            return False
        return True
    return False


# (t_pad, b_pad) shapes whose pallas lowering failed; those shapes fall
# back to the XLA query permanently (first failure logged)
_PALLAS_FFAT_BROKEN: set = set()
_PALLAS_WINSUM_BROKEN: set = set()


@functools.lru_cache(maxsize=None)
def _ffat_pallas_program(combine: Callable, neutral: float, t_pad: int,
                         b_pad: int):
    """XLA tree build + Pallas bit-walk range query (the hand-scheduled
    ComputeResults_Kernel twin, ops/pallas/flatfat_query.py)."""
    from .flatfat_jax import _programs
    from .pallas.flatfat_query import _build as _pallas_build
    jax, jnp = _jax()
    build, _update, _query = _programs(combine, neutral, t_pad)
    # interpret off TPU so forcing the gate on (tests) still runs
    pq = _pallas_build(t_pad, b_pad, combine, float(neutral),
                       jax.default_backend() != "tpu")

    @jax.jit
    def run(values, se):
        starts, ends = se[0], se[1]
        valid = ends > starts
        tree = build(values)
        from .pallas.flatfat_query import pad_tree_rows
        out = pq(starts, ends, pad_tree_rows(tree, neutral))[:b_pad, 0]
        return jnp.where(valid, out, 0)

    return run


class DeviceBatchHandle:
    """Async result of one batched window computation (the PJRT-future
    analogue of the reference's in-flight CUDA kernel).

    The device-to-host copy is started asynchronously at construction
    (``copy_to_host_async``): over a high-latency PJRT transport the
    transfer rides under subsequent host batching, so ``block()`` is
    near-free by the time the double-buffer protocol flushes this
    batch -- the cudaMemcpyAsync-D2H analogue (win_seq_gpu.hpp:610)."""

    __slots__ = ("_dev", "_n")

    def __init__(self, dev_array, n_valid: int):
        self._dev = dev_array
        self._n = n_valid
        try:
            dev_array.copy_to_host_async()
        except Exception:
            pass  # backends without async host copy: block() still works

    def ready(self) -> bool:
        """True when the device computation has finished (block() will
        not stall).  False when the backend can't tell."""
        try:
            return bool(self._dev.is_ready())
        except Exception:
            return False

    def block(self) -> np.ndarray:
        with _transfer_guard():
            return np.asarray(self._dev)[: self._n]


class _ResidentPaneHandle:
    """Async result of one fused resident-pane launch: the device
    array holds 2B ring-wrap query pieces; ``block()`` combines them
    host-side in time order (same protocol as DeviceBatchHandle)."""

    __slots__ = ("_dev", "_wraps", "_B", "_comb")

    def __init__(self, dev, wraps, B, np_comb):
        self._dev = dev
        self._wraps = wraps
        self._B = B
        self._comb = np_comb
        try:
            dev.copy_to_host_async()
        except Exception:
            pass

    def ready(self) -> bool:
        try:
            return bool(self._dev.is_ready())
        except Exception:
            return False

    def block(self) -> np.ndarray:
        with _transfer_guard():
            out = np.asarray(self._dev)
        head, tail = out[: self._B], out[self._B: 2 * self._B]
        if self._wraps.any():
            head = np.where(self._wraps, self._comb(head, tail), head)
        return head


class _ResidentPaneLaunch:
    """One launch's engine view: pins the forest the staging was
    computed against, so a concurrent capacity grow (which swaps the
    carry's forest and re-ships everything dirty) can never retarget
    an already-staged launch."""

    __slots__ = ("carry", "forest")

    def __init__(self, carry: "ResidentPaneCarry", forest):
        self.carry = carry
        self.forest = forest

    def compute(self, cols, starts, ends, gwids) -> _ResidentPaneHandle:
        with self.carry._lock:
            dev, wraps, B = self.forest.update_runs_query_launch(
                cols["run_rows"], cols["run_starts"], cols["run_lens"],
                np.asarray(cols["value"], np.float32),
                cols["q_rows"], starts, ends)
        return _ResidentPaneHandle(dev, wraps, B, self.carry.np_comb)


class ResidentPaneCarry:
    """Device-resident pane-partial state for the WinSeqTPULogic
    resident lane (docs/PLANNER.md "Resident state").

    Where the rebuild lane re-ships the whole staged pane buffer
    (window carry included) on every launch, this keeps one per-key
    ring of pane partials resident in device memory as a
    :class:`~windflow_tpu.ops.flatfat_jax.BatchedFlatFAT` forest
    (donated, double-buffered jit carry) and ships only NEW/changed
    partials per launch; windows are answered as pane-range queries in
    the same fused scatter+query program.  Keyed by pane index: ring
    position = absolute pane id mod capacity, alias-safe because the
    engine's fired frontier proves panes below the oldest unfired
    window dead before their slots are reused."""

    KINDS = ("sum", "count", "max", "min")

    def __init__(self, kind: str, panes_per_window: int,
                 initial_keys: int = 16, headroom: int = 1024):
        import jax.numpy as jnp
        if kind not in self.KINDS:
            raise ValueError(f"resident pane carry needs a builtin "
                             f"monoid kind, not {kind!r}")
        self.kind = kind
        comb = {"sum": jnp.add, "count": jnp.add,
                "max": jnp.maximum, "min": jnp.minimum}[kind]
        self.np_comb = {"sum": np.add, "count": np.add,
                        "max": np.maximum, "min": np.minimum}[kind]
        self.neutral = (0.0 if kind in ("sum", "count")
                        else (-np.inf if kind == "max" else np.inf))
        self.combine = comb
        self.panes_per_window = panes_per_window
        self.capacity = next_pow2(panes_per_window + headroom)
        self._initial_keys = max(2, initial_keys)
        from .flatfat_jax import BatchedFlatFAT
        self.forest = BatchedFlatFAT(comb, self.neutral,
                                     self._initial_keys, self.capacity)
        self.rows: Dict[Any, int] = {}
        # serializes forest launches against snapshot reads (the tree
        # swap in update_query_launch is not atomic with the query)
        self._lock = threading.Lock()

    @property
    def state_bytes(self) -> int:
        return self.forest.state_bytes

    def row_of(self, key) -> int:
        """Assign/look up the key's forest row.  Returns the row; when
        it does not fit the current forest the caller must call
        :meth:`grow` (which swaps in a bigger EMPTY forest) and mark
        every key dirty -- the forest is never migrated by copying,
        because launches already queued on the dispatcher still
        scatter into the OLD forest object and a snapshot copy would
        silently lose them."""
        row = self.rows.get(key)
        if row is None:
            row = self.rows[key] = len(self.rows)
        return row

    def needs_grow(self, span: int) -> bool:
        return span > self.capacity or len(self.rows) > self.forest.n_keys

    def grow(self, min_capacity: int) -> None:
        """Key-count or pane-span overflow: swap in a bigger EMPTY
        forest -- the caller must mark every key fully dirty so the
        next launch re-ships live partials (they are recomputable
        from the host retained series, which the engine's eviction
        keeps exactly down to the oldest unfired window).  Launches
        already in flight keep their pinned (old, complete) forest,
        so their queries stay correct."""
        from .flatfat_jax import BatchedFlatFAT
        n = self.capacity
        while n < min_capacity:
            n <<= 1
        k = self._initial_keys
        while k < max(1, len(self.rows)):
            k <<= 1
        with self._lock:
            self.capacity = n
            self.forest = BatchedFlatFAT(self.combine, self.neutral,
                                         k, n)

    def reset(self) -> None:
        """Drop all resident state (lane flip / state restore): the
        next launch recomputes live partials from the host store."""
        from .flatfat_jax import BatchedFlatFAT
        with self._lock:
            self.rows.clear()
            self.forest = BatchedFlatFAT(self.combine, self.neutral,
                                         self._initial_keys,
                                         self.capacity)

    def launch_engine(self) -> _ResidentPaneLaunch:
        return _ResidentPaneLaunch(self, self.forest)


class WindowComputeEngine:
    """Executes batches of window extents against a flat value buffer.

    ``kind`` is a builtin combine name or a JAX callable
    ``fn(gwid, cols: dict[str, f32[W]], mask: bool[W]) -> f32``
    (the TPU twin of the GPU functor signature, API:104/118).
    """

    def __init__(self, kind: Any = "sum", value_col: str = "value",
                 dtype=np.float32):
        # kind may also be ("ffat", combine_fn, neutral): device FlatFAT
        # tree over the flat buffer (Win_SeqFFAT_GPU analogue)
        is_ffat = isinstance(kind, tuple) and len(kind) == 3 \
            and kind[0] == "ffat"
        if not (callable(kind) or kind in BUILTIN_KINDS
                or kind in PAIR_KINDS or is_ffat):
            raise ValueError(f"unknown window combine kind: {kind!r}")
        self.kind = kind
        self.is_ffat = is_ffat
        self.value_col = value_col
        self.dtype = dtype
        # one in-flight dispatch per ENGINE (scoped from the old
        # process-global lock so farm replicas overlap launches --
        # measured safe on the axon relay: 8 concurrent device_puts
        # complete without error and overlap to ~4x throughput).  For a
        # transport that cannot take concurrent transfers, the env var
        # restores process-global serialization.
        import os
        if os.environ.get("WINDFLOW_GLOBAL_DISPATCH_LOCK") == "1":
            self._lock = _GLOBAL_DISPATCH_LOCK
        else:
            self._lock = threading.Lock()

    def compute(self, cols: Dict[str, np.ndarray], starts: np.ndarray,
                ends: np.ndarray, gwids: np.ndarray) -> DeviceBatchHandle:
        """Launch one batch; returns an async handle."""
        with self._lock:
            return self._compute(cols, starts, ends, gwids)

    def _compute(self, cols: Dict[str, np.ndarray], starts: np.ndarray,
                 ends: np.ndarray, gwids: np.ndarray) -> DeviceBatchHandle:
        import jax.numpy as jnp
        B = len(starts)
        T = len(next(iter(cols.values())))
        # floor the shape buckets: padding a small launch to 2048 costs
        # ~16-32 KB of transfer (noise next to the transport RTT) and
        # collapses the set of distinct compiled programs to a handful,
        # so steady-state launches never hit a mid-stream XLA compile
        T_pad = next_pow2(max(T, 2048))
        B_pad = next_pow2(max(B, 2048))
        # starts/ends ride in ONE packed int32 array: over a high-latency
        # PJRT transport every device_put is a round trip, so the builtin
        # paths ship exactly two buffers (values + extents) per launch
        se = np.zeros((2, B_pad), dtype=np.int32)
        se[0, :B] = starts
        se[1, :B] = ends

        def pad_col(v, fill=0):
            out = np.full(T_pad, fill, dtype=self.dtype)
            out[:T] = v
            return out

        if self.is_ffat:
            _, comb, neutral = self.kind
            vals_dev = jnp.asarray(pad_col(cols[self.value_col], neutral))
            se_dev = jnp.asarray(se)
            dev = None
            if (_use_pallas_ffat(T_pad)
                    and (T_pad, B_pad) not in _PALLAS_FFAT_BROKEN):
                try:
                    dev = _ffat_pallas_program(comb, neutral, T_pad,
                                               B_pad)(vals_dev, se_dev)
                except Exception as e:
                    # this shape falls back to the XLA query permanently
                    _PALLAS_FFAT_BROKEN.add((T_pad, B_pad))
                    import warnings
                    warnings.warn(
                        f"pallas FFAT query lowering failed for shape "
                        f"(T={T_pad}, B={B_pad}); using XLA query: {e!r}")
            if dev is None:
                dev = _ffat_program(comb, neutral, T_pad)(vals_dev, se_dev)
        elif callable(self.kind):
            valid = np.zeros(B_pad, dtype=bool)
            valid[:B] = True
            gwids_p = np.zeros(B_pad, dtype=np.int64)
            gwids_p[:B] = gwids
            w_pad = next_pow2(int((ends - starts).max()) if B else 1)
            names = tuple(sorted(c for c in cols))
            padded = [pad_col(cols[c]) for c in names]
            prog = _custom_program(self.kind, w_pad, names)
            dev = prog(jnp.asarray(gwids_p), jnp.asarray(se[0]),
                       jnp.asarray(se[1]), jnp.asarray(valid), *padded)
        elif self.kind == "mean_panes":
            wp = next_pow2(max(int((ends - starts).max()) if B else 1, 2))
            prog = (_tile_mean_program(wp) if wp <= _TILE_MAX_W
                    else _scan_pair_program())
            dev = prog(jnp.asarray(pad_col(cols[self.value_col])),
                       jnp.asarray(pad_col(cols["count"])),
                       jnp.asarray(se))
        elif self.kind in ("max", "min"):
            fill = -np.inf if self.kind == "max" else np.inf
            n_levels = max(1, int(np.log2(T_pad)) + 1)
            prog = _sparse_table_program(self.kind, n_levels)
            dev = prog(jnp.asarray(pad_col(cols[self.value_col], fill)),
                       jnp.asarray(se))
        elif (self.kind == "sum"
              and os.environ.get("WINDFLOW_PALLAS_WINSUM") == "1"
              and T_pad <= _PALLAS_FFAT_MAX_T and B_pad <= (1 << 15)
              and (T_pad, B_pad) not in _PALLAS_WINSUM_BROKEN):
            # hand-scheduled Pallas alternative to the XLA sum paths
            # (the ComputeBatch_Kernel twin): grid program per window,
            # scalar-prefetched extents.  T_pad/B_pad are powers of two
            # >= 2048, so the lane/row alignment holds by construction;
            # the size gate keeps the unblocked VMEM mapping in budget
            # and a lowering failure falls back to the XLA path for
            # that shape permanently (like the FFAT kernel).
            from .pallas.window_sum import window_sums_device
            try:
                dev = window_sums_device(
                    jnp.asarray(pad_col(cols[self.value_col])),
                    jnp.asarray(se[0]), jnp.asarray(se[1]))[:, 0]
            except Exception as e:
                _PALLAS_WINSUM_BROKEN.add((T_pad, B_pad))
                import warnings
                warnings.warn(
                    f"pallas window-sum lowering failed for shape "
                    f"(T={T_pad}, B={B_pad}); using XLA path: {e!r}")
                dev = _scan_program("sum")(
                    jnp.asarray(pad_col(cols[self.value_col])),
                    jnp.asarray(se))
        else:
            wp = next_pow2(max(int((ends - starts).max()) if B else 1, 2))
            prog = (_tile_sum_program(wp)
                    if self.kind == "sum" and wp <= _TILE_MAX_W
                    else _scan_program(self.kind))
            dev = prog(jnp.asarray(pad_col(cols[self.value_col])),
                       jnp.asarray(se))
        return DeviceBatchHandle(dev, B)
