"""Pallas TPU kernel: batched FlatFAT range queries.

The TPU twin of the reference's ``ComputeResults_Kernel``
(flatfat_gpu.hpp:92-135): there, one CUDA thread per window walks the
device-resident aggregator tree with the bit-trick range decomposition;
here, one grid program per window performs the same O(log n) walk with
the whole heap-layout tree resident in VMEM (it is at most 2 x t_pad
floats -- far under VMEM capacity for every bucketed batch shape the
window engine produces).

The walk keeps separate left/right partial accumulators so the combine
order is preserved oldest->newest, which makes the kernel correct for
non-commutative combines -- same contract as the XLA query in
ops/flatfat_jax.py, against which the tests diff this kernel.

Tree layout: flat [2n] heap (root at 1, leaves at [n, 2n)), reshaped to
(2n / 128, 128) lane-rows.  Scalar tree loads become a dynamic-sublane
row load plus a one-hot lane extract -- the TPU-shaped substitute for
the scalar ``fat[i]`` indexing of the CUDA kernel.

Build/update stay XLA level sweeps (flatfat_jax.py): they are
bandwidth-bound strided combines XLA already fuses optimally; only the
per-window query has the irregular access pattern worth hand-scheduling.
"""
from __future__ import annotations

import functools
from typing import Callable

import numpy as np

LANES = 128


@functools.lru_cache(maxsize=None)
def _build(n_leaves: int, n_windows: int, combine: Callable,
           neutral: float, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    levels = int(np.log2(n_leaves))
    assert 1 << levels == n_leaves, "FlatFAT capacity must be a power of two"

    def kernel(starts_ref, ends_ref, tree_ref, out_ref):
        b = pl.program_id(0)
        lane = jax.lax.broadcasted_iota(jnp.int32, (LANES,), 0)

        def tload(idx):
            """tree[idx] via dynamic row load + one-hot lane extract."""
            row = idx // LANES
            col = idx % LANES
            rowvec = tree_ref[row, :]
            return jnp.sum(jnp.where(lane == col, rowvec, 0.0))

        def body(_, carry):
            lo, hi, left, right = carry
            take_l = (lo < hi) & ((lo & 1) == 1)
            lval = tload(lo)
            left = jnp.where(take_l, combine(left, lval), left)
            lo = jnp.where(take_l, lo + 1, lo)
            take_r = (lo < hi) & ((hi & 1) == 1)
            rval = tload(jax.lax.max(hi - 1, 0))
            right = jnp.where(take_r, combine(rval, right), right)
            hi = jnp.where(take_r, hi - 1, hi)
            return lo >> 1, hi >> 1, left, right

        lo = starts_ref[b] + n_leaves
        hi = ends_ref[b] + n_leaves
        valid = hi > lo
        lo, hi, left, right = jax.lax.fori_loop(
            0, levels + 1, body,
            (lo, hi, jnp.float32(neutral), jnp.float32(neutral)))
        out = combine(left, right)
        # one lane-row per window (1x1 output blocks are not lowerable;
        # the host/caller reads column 0)
        out_ref[b, :] = jnp.full((LANES,), jnp.where(valid, out, neutral),
                                 jnp.float32)

    n_out_rows = ((n_windows + 7) // 8) * 8
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_windows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )

    @jax.jit
    def run(starts, ends, tree2d):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_out_rows, LANES), jnp.float32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(starts, ends, tree2d)

    return run


def pad_tree_rows(tree, neutral: float):
    """Pad a [2n] heap tree to a LANES multiple and reshape to the
    (rows, LANES) layout the kernel expects.  jnp-traceable."""
    import jax.numpy as jnp
    tree = jnp.asarray(tree, jnp.float32)
    two_n = tree.shape[0]
    if two_n % LANES:
        tree = jnp.concatenate(
            [tree, jnp.full((LANES - two_n % LANES,), neutral,
                            jnp.float32)])
    return tree.reshape(-1, LANES)


def flatfat_query_ranges(tree, starts, ends, combine: Callable,
                         neutral: float, interpret: bool = None):
    """out[b] = fold(combine, tree leaves [starts[b], ends[b]))  using
    the heap tree (shape [2n], root at 1) built by flatfat_jax.

    ``combine`` must be a jax-traceable binary fn forming a monoid with
    ``neutral``; starts/ends index the leaf axis.  Returns float32 [B].
    """
    import jax
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    tree = jnp.asarray(tree, jnp.float32)
    n_leaves = tree.shape[0] // 2
    B = len(starts)
    run = _build(n_leaves, B, combine, float(neutral), bool(interpret))
    out = run(jnp.asarray(starts, jnp.int32), jnp.asarray(ends, jnp.int32),
              pad_tree_rows(tree, neutral))
    return np.asarray(out)[:B, 0]
