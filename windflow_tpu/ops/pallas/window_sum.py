"""Pallas TPU kernel: batched window reduction over a flat buffer.

The direct TPU analogue of the reference's grid-stride
``ComputeBatch_Kernel`` (win_seq_gpu.hpp:61-84): one grid program per
fired window instead of one CUDA thread per window.  Window extents
arrive via scalar prefetch (SMEM) so each program DMAs only the tiles
its window touches; lanes outside the extent are masked.

This is the hand-scheduled alternative to the XLA cumsum path in
ops/window_compute.py -- profitable when windows are short relative to
the buffer (e.g. after pane pre-reduction) because it avoids
materializing the prefix scan, and when results feed further device
work without a host round trip.  `window_sums` picks interpret mode off
TPU so tests exercise the same kernel on CPU.
"""
from __future__ import annotations

import functools

import numpy as np

LANES = 128


@functools.lru_cache(maxsize=None)
def _build(n_rows: int, n_windows: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(starts_ref, ends_ref, values_ref, out_ref):
        b = pl.program_id(0)
        start = starts_ref[b]
        end = ends_ref[b]
        first_row = start // LANES
        last_row = jax.lax.max(end - 1, 0) // LANES

        def body(row, acc):
            vals = values_ref[row, :]
            lane = row * LANES + jax.lax.broadcasted_iota(
                jnp.int32, (LANES,), 0)
            mask = (lane >= start) & (lane < end)
            return acc + jnp.sum(jnp.where(mask, vals, 0.0))

        total = jax.lax.fori_loop(first_row, last_row + 1, body, 0.0)
        total = jnp.where(end > start, total, 0.0)
        # one lane-row per window (1x1 output blocks are not lowerable;
        # the host reads column 0)
        out_ref[b, :] = jnp.full((LANES,), total, jnp.float32)

    n_out_rows = ((n_windows + 7) // 8) * 8  # tile-aligned row count
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_windows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),  # unblocked
    )

    @jax.jit
    def run(starts, ends, values2d):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_out_rows, LANES), jnp.float32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(starts, ends, values2d)

    return run


def window_sums(values: np.ndarray, starts: np.ndarray,
                ends: np.ndarray, interpret: bool = None):
    """out[b] = sum(values[starts[b]:ends[b]]) via the Pallas kernel.

    values is padded to a multiple of 128 lanes; starts/ends are int32.
    """
    import jax.numpy as jnp

    T = len(values)
    n_rows = max(1, (T + LANES - 1) // LANES)
    padded = np.zeros(n_rows * LANES, np.float32)
    padded[:T] = values
    B = len(starts)
    out = window_sums_device(jnp.asarray(padded),
                             jnp.asarray(starts, jnp.int32),
                             jnp.asarray(ends, jnp.int32), interpret)
    return np.asarray(out)[:B, 0]


def window_sums_device(values, starts, ends, interpret: bool = None):
    """Async variant for the engine's dispatch path: returns the
    on-device [B_pad, LANES] output (column 0 holds the sums) without
    a host round trip.  ``values`` must already be padded to a multiple
    of LANES rows; starts/ends int32 device-or-host arrays."""
    import jax

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    n_rows = values.shape[0] // LANES
    run = _build(n_rows, len(starts), bool(interpret))
    return run(starts, ends, values.reshape(n_rows, LANES))
