"""Host lane of the placement planner: numpy window computation.

The cost-based placement planner (graph/planner.py) may decide that a
window operator's batches are too small, or the transport round trip
too long, for the device lane to pay off -- every launch would cost
the RTT floor to compute microseconds of work.  For those operators it
swaps :class:`~windflow_tpu.ops.window_compute.WindowComputeEngine`
for this engine: the same ``compute(cols, starts, ends, gwids) ->
handle`` surface, evaluated synchronously in numpy on the dispatching
thread.

The programs mirror the XLA ones program-for-program
(ops/window_compute.py):

* sum/count/mean  -- prefix scan + two gathers (cumsum differencing);
* max/min         -- sparse table (log-sweep of strided combines), the
                     identical O(1) range query;
* mean_panes      -- pane-sum / pane-count pair differencing.

Accumulation runs in float64 (numpy's default), so host-placed results
can differ from the device lane's float32 staging in the last ulps --
the planner trades placement for throughput, never bit-identical
routing (docs/PLANNER.md).  Custom (callable / FFAT) kinds have no
host program; the planner pins those operators to the device lane.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

HOST_KINDS = ("sum", "count", "mean", "max", "min", "mean_panes")


class HostBatchHandle:
    """Synchronous twin of ops.window_compute.DeviceBatchHandle: the
    result already materialized when ``compute`` returned."""

    __slots__ = ("_arr",)

    def __init__(self, arr: np.ndarray):
        self._arr = arr

    def ready(self) -> bool:
        return True

    def block(self) -> np.ndarray:
        return self._arr


def _scan_ranges(values: np.ndarray, starts: np.ndarray,
                 ends: np.ndarray) -> np.ndarray:
    c = np.concatenate([[0.0], np.cumsum(values, dtype=np.float64)])
    return c[ends] - c[starts]


def _sparse_table_ranges(values: np.ndarray, starts: np.ndarray,
                         ends: np.ndarray, kind: str) -> np.ndarray:
    """Range max/min over arbitrary (possibly overlapping) [start, end)
    extents: the numpy transcription of _sparse_table_program."""
    comb = np.maximum if kind == "max" else np.minimum
    neutral = -np.inf if kind == "max" else np.inf
    T = len(values)
    if T == 0:
        return np.zeros(len(starts))
    v = values.astype(np.float64)
    levels = [v]
    n_levels = max(1, int(T).bit_length())
    for j in range(1, n_levels):
        shift = 1 << (j - 1)
        shifted = np.concatenate([v[shift:], np.full(shift, neutral)])
        v = comb(v, shifted)
        levels.append(v)
    table = np.stack(levels)
    length = np.maximum(ends - starts, 1)
    j = np.clip(np.floor(np.log2(length)).astype(np.int64), 0,
                n_levels - 1)
    hi = np.clip(ends - (1 << j), 0, T - 1)
    lo = np.clip(starts, 0, T - 1)
    out = comb(table[j, lo], table[j, hi])
    return np.where(ends > starts, out, 0.0)


class HostComputeEngine:
    """Drop-in host replacement for WindowComputeEngine (builtin kinds
    only).  ``compute`` evaluates immediately and returns an
    always-ready handle, so the dispatcher's waitAndFlush protocol
    degenerates to direct emission -- exactly what a host lane wants:
    no pipelining, no transfer, no launch floor."""

    def __init__(self, kind: str, value_col: str = "value"):
        if not (isinstance(kind, str) and kind in HOST_KINDS):
            raise ValueError(
                f"host window lane supports {HOST_KINDS}, not {kind!r} "
                "(custom combines stay on the device lane)")
        self.kind = kind
        self.value_col = value_col

    def compute(self, cols: Dict[str, np.ndarray], starts: np.ndarray,
                ends: np.ndarray, gwids: np.ndarray) -> HostBatchHandle:
        starts = np.asarray(starts, np.int64)
        ends = np.asarray(ends, np.int64)
        if self.kind != "count":  # count never reads the value column
            values = np.asarray(cols[self.value_col], np.float64)
        if self.kind == "sum":
            out = _scan_ranges(values, starts, ends)
        elif self.kind == "count":
            out = (ends - starts).astype(np.float64)
        elif self.kind == "mean":
            s = _scan_ranges(values, starts, ends)
            n = np.maximum(ends - starts, 1)
            out = s / n
        elif self.kind == "mean_panes":
            s = _scan_ranges(values, starts, ends)
            n = _scan_ranges(np.asarray(cols["count"], np.float64),
                             starts, ends)
            out = s / np.maximum(n, 1)
        else:  # max / min
            out = _sparse_table_ranges(values, starts, ends, self.kind)
        return HostBatchHandle(np.asarray(out, np.float64))
