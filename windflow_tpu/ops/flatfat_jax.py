"""Device-resident FlatFAT: the XLA twin of the GPU aggregator tree.

Re-design of reference ``wf/flatfat_gpu.hpp`` (461 LoC, CUDA): the tree
lives in device memory (HBM here); its three kernels map to three jitted
programs:

* ``InitTreeLevel_Kernel``/host ``build`` (:53-64, :275-333)  -> `build`
  (level-wise strided combine, lax-unrolled over log2(n) levels);
* ``UpdateTreeLevel_Kernel`` (:68-82) -> `update` (scatter new leaves,
  recompute each level vectorized);
* ``ComputeResults_Kernel`` (:92-135, per-window bit-trick range
  decomposition) -> `query_ranges` (vectorized segment-tree fold over
  all windows at once, preserving left-to-right combine order for
  non-commutative functions).

The tree is a flat [2n] array in heap layout (root at 1, leaves at
[n, 2n)), functional-in/functional-out as XLA wants; the host engine
keeps the current tree array between batches (the device-resident state
of the reference).
"""
from __future__ import annotations

import functools
from typing import Callable, Tuple

import numpy as np


@functools.lru_cache(maxsize=None)
def _programs(combine: Callable, neutral: float, n: int):
    import jax
    import jax.numpy as jnp

    levels = int(np.log2(n))
    assert 1 << levels == n, "FlatFAT capacity must be a power of two"

    @jax.jit
    def build(leaves):  # leaves: [n]
        tree = jnp.full((2 * n,), neutral, leaves.dtype)
        tree = tree.at[n:].set(leaves)
        for j in range(levels - 1, -1, -1):  # level j holds 2^j nodes
            lo, hi = 1 << j, 1 << (j + 1)
            children = tree[2 * lo: 2 * hi]
            combined = combine(children[0::2], children[1::2])
            tree = jax.lax.dynamic_update_slice(tree, combined, (lo,))
        return tree

    @jax.jit
    def update(tree, positions, values, valid):
        """Scatter new leaf values then recompute every level (the
        reference updates only touched subtrees per level; recomputing
        whole levels is the vectorized TPU-shaped equivalent)."""
        safe_pos = jnp.where(valid, positions + n, 0)
        tree = tree.at[safe_pos].set(
            jnp.where(valid, values, tree[safe_pos]))
        for j in range(levels - 1, -1, -1):
            lo, hi = 1 << j, 1 << (j + 1)
            children = tree[2 * lo: 2 * hi]
            combined = combine(children[0::2], children[1::2])
            tree = jax.lax.dynamic_update_slice(tree, combined, (lo,))
        return tree

    @jax.jit
    def query_ranges(tree, starts, ends, valid):
        """Fold leaves [start, end) per window, O(log n) steps for all
        windows at once; left/right partial accumulators keep the
        combine order oldest->newest."""
        lo = starts + n
        hi = ends + n
        left = jnp.full(starts.shape, neutral, tree.dtype)
        right = jnp.full(starts.shape, neutral, tree.dtype)
        for _ in range(levels + 1):
            take_l = (lo < hi) & (lo & 1).astype(bool)
            left = jnp.where(take_l, combine(left, tree[lo]), left)
            lo = jnp.where(take_l, lo + 1, lo)
            take_r = (lo < hi) & (hi & 1).astype(bool)
            hi_idx = jnp.where(take_r, hi - 1, hi)
            right = jnp.where(take_r, combine(tree[hi_idx], right), right)
            hi = hi_idx
            lo = lo >> 1
            hi = hi >> 1
        out = combine(left, right)
        return jnp.where(valid, out, neutral)

    return build, update, query_ranges


class FlatFATJax:
    """Stateful host wrapper owning the device tree array.

    ``combine`` must form a monoid with identity ``neutral`` (the
    query seeds its left/right accumulators with ``neutral``); it need
    not be commutative -- fold order is preserved oldest->newest."""

    def __init__(self, combine: Callable, neutral: float, n_leaves: int,
                 dtype=np.float32):
        n = 1
        while n < max(2, n_leaves):
            n <<= 1
        self.n = n
        self.neutral = neutral
        self.dtype = dtype
        self._build, self._update, self._query = _programs(
            combine, neutral, n)
        import jax.numpy as jnp
        self.tree = self._build(jnp.full((n,), neutral, dtype))

    def build(self, leaves: np.ndarray) -> None:
        import jax.numpy as jnp
        padded = np.full(self.n, self.neutral, self.dtype)
        padded[: len(leaves)] = leaves
        self.tree = self._build(jnp.asarray(padded))

    def update(self, positions: np.ndarray, values: np.ndarray) -> None:
        import jax.numpy as jnp
        b = next_pow2 = 1
        while next_pow2 < max(1, len(positions)):
            next_pow2 <<= 1
        pos = np.zeros(next_pow2, np.int32)
        val = np.full(next_pow2, self.neutral, self.dtype)
        ok = np.zeros(next_pow2, bool)
        pos[: len(positions)] = positions
        val[: len(values)] = values
        ok[: len(positions)] = True
        self.tree = self._update(self.tree, jnp.asarray(pos),
                                 jnp.asarray(val), jnp.asarray(ok))

    def query_ranges(self, starts: np.ndarray,
                     ends: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        b = 1
        while b < max(1, len(starts)):
            b <<= 1
        s = np.zeros(b, np.int32)
        e = np.zeros(b, np.int32)
        ok = np.zeros(b, bool)
        s[: len(starts)] = starts
        e[: len(ends)] = ends
        ok[: len(starts)] = True
        out = self._query(self.tree, jnp.asarray(s), jnp.asarray(e),
                          jnp.asarray(ok))
        return np.asarray(out)[: len(starts)]
