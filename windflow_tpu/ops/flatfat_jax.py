"""Device-resident FlatFAT: the XLA twin of the GPU aggregator tree.

Re-design of reference ``wf/flatfat_gpu.hpp`` (461 LoC, CUDA): the tree
lives in device memory (HBM here); its three kernels map to three jitted
programs:

* ``InitTreeLevel_Kernel``/host ``build`` (:53-64, :275-333)  -> `build`
  (level-wise strided combine, lax-unrolled over log2(n) levels);
* ``UpdateTreeLevel_Kernel`` (:68-82) -> `update` (scatter new leaves,
  recompute each level vectorized);
* ``ComputeResults_Kernel`` (:92-135, per-window bit-trick range
  decomposition) -> `query_ranges` (vectorized segment-tree fold over
  all windows at once, preserving left-to-right combine order for
  non-commutative functions).

The tree is a flat [2n] array in heap layout (root at 1, leaves at
[n, 2n)), functional-in/functional-out as XLA wants; the host engine
keeps the current tree array between batches (the device-resident state
of the reference).
"""
from __future__ import annotations

import functools
from typing import Callable

import numpy as np


@functools.lru_cache(maxsize=None)
def _programs(combine: Callable, neutral: float, n: int):
    import jax
    import jax.numpy as jnp

    levels = int(np.log2(n))
    assert 1 << levels == n, "FlatFAT capacity must be a power of two"

    @jax.jit
    def build(leaves):  # leaves: [n]
        tree = jnp.full((2 * n,), neutral, leaves.dtype)
        tree = tree.at[n:].set(leaves)
        for j in range(levels - 1, -1, -1):  # level j holds 2^j nodes
            lo, hi = 1 << j, 1 << (j + 1)
            children = tree[2 * lo: 2 * hi]
            combined = combine(children[0::2], children[1::2])
            tree = jax.lax.dynamic_update_slice(tree, combined, (lo,))
        return tree

    @jax.jit
    def update(tree, positions, values, valid):
        """Scatter new leaf values then recompute every level (the
        reference updates only touched subtrees per level; recomputing
        whole levels is the vectorized TPU-shaped equivalent)."""
        safe_pos = jnp.where(valid, positions + n, 0)
        tree = tree.at[safe_pos].set(
            jnp.where(valid, values, tree[safe_pos]))
        for j in range(levels - 1, -1, -1):
            lo, hi = 1 << j, 1 << (j + 1)
            children = tree[2 * lo: 2 * hi]
            combined = combine(children[0::2], children[1::2])
            tree = jax.lax.dynamic_update_slice(tree, combined, (lo,))
        return tree

    @jax.jit
    def query_ranges(tree, starts, ends, valid):
        """Fold leaves [start, end) per window, O(log n) steps for all
        windows at once; left/right partial accumulators keep the
        combine order oldest->newest."""
        lo = starts + n
        hi = ends + n
        left = jnp.full(starts.shape, neutral, tree.dtype)
        right = jnp.full(starts.shape, neutral, tree.dtype)
        for _ in range(levels + 1):
            take_l = (lo < hi) & (lo & 1).astype(bool)
            left = jnp.where(take_l, combine(left, tree[lo]), left)
            lo = jnp.where(take_l, lo + 1, lo)
            take_r = (lo < hi) & (hi & 1).astype(bool)
            hi_idx = jnp.where(take_r, hi - 1, hi)
            right = jnp.where(take_r, combine(tree[hi_idx], right), right)
            hi = hi_idx
            lo = lo >> 1
            hi = hi >> 1
        out = combine(left, right)
        return jnp.where(valid, out, neutral)

    return build, update, query_ranges


@functools.lru_cache(maxsize=None)
def _batched_programs(combine: Callable, neutral: float, n: int):
    """Key-batched device-resident trees [K, 2n]: the incremental
    (rebuild=false) mode of the reference, where the aggregator tree
    stays on the device between batches and only touched paths are
    recomputed (UpdateTreeLevel_Kernel, flatfat_gpu.hpp:68-82) --
    vectorized here as log n scatter rounds over the update batch."""
    import jax
    import jax.numpy as jnp

    levels = int(np.log2(n))
    assert 1 << levels == n, "FlatFAT capacity must be a power of two"

    # The resident tree is DONATED (donate_argnums): the forest lives
    # in HBM across the stream's lifetime, every update returns its
    # successor, and donation lets XLA reuse the buffer in place --
    # the double-buffered carry of the reference's rebuild=false mode
    # (win_seqffat_gpu.hpp:150) without a second tree's footprint.
    # CPU (the test backend) does not implement donation, so the gate
    # keeps it off there; WINDFLOW_DONATE_FOREST=0 opts a device
    # backend out (e.g. a transport not yet exercised with donation).
    import os
    donate = ((0,) if jax.default_backend() != "cpu"
              and os.environ.get("WINDFLOW_DONATE_FOREST", "1") != "0"
              else ())

    # the level sweeps are lax.fori_loop, not Python-unrolled: every
    # iteration carries fixed shapes, and unrolling 2 x levels rounds
    # of gather/scatter made the fused program's XLA compile scale
    # with log(capacity) (tens of seconds on the CPU test backend for
    # a 2^13-leaf forest); the rolled loop compiles in O(1)

    def _update_body(tree, keys, positions, values, valid):
        """Scatter new leaves at (key, pos) then recompute ONLY the
        touched root paths: O(B log n) work independent of K and n.
        Duplicate parents scatter identical recomputed values, so
        in-batch collisions are benign."""
        safe_k = jnp.where(valid, keys, 0)
        # invalid lanes write heap slot 0 -- never read (root lives at
        # 1) and never a valid target, so duplicate-index scatters
        # cannot clobber a real update with a stale value
        idx = jnp.where(valid, positions + n, 0)
        tree = tree.at[safe_k, idx].set(
            jnp.where(valid, values, tree[safe_k, idx]))

        def level(_j, carry):
            tree, idx = carry
            parent = idx >> 1
            left = tree[safe_k, 2 * parent]
            right = tree[safe_k, 2 * parent + 1]
            tree = tree.at[safe_k, parent].set(
                jnp.where(valid, combine(left, right),
                          tree[safe_k, parent]))
            return tree, parent

        tree, _ = jax.lax.fori_loop(0, levels, level, (tree, idx))
        return tree

    update_sparse = functools.partial(jax.jit, donate_argnums=donate)(
        _update_body)

    def _query_body(tree, keys, starts, ends, valid):
        """Per-window fold over leaf ring positions [start, end) of each
        window's key tree; same bit-walk as the single-tree query."""
        safe_k = jnp.where(valid, keys, 0)
        neutral_col = jnp.full(starts.shape, neutral, tree.dtype)

        def step(_j, carry):
            lo, hi, left, right = carry
            take_l = (lo < hi) & (lo & 1).astype(bool)
            left = jnp.where(take_l, combine(left, tree[safe_k, lo]),
                             left)
            lo = jnp.where(take_l, lo + 1, lo)
            take_r = (lo < hi) & (hi & 1).astype(bool)
            hi_idx = jnp.where(take_r, hi - 1, hi)
            right = jnp.where(take_r,
                              combine(tree[safe_k, hi_idx], right),
                              right)
            return lo >> 1, hi_idx >> 1, left, right

        _lo, _hi, left, right = jax.lax.fori_loop(
            0, levels + 1, step,
            (starts + n, ends + n, neutral_col, neutral_col))
        out = combine(left, right)
        return jnp.where(valid, out, neutral)

    query_ranges = jax.jit(_query_body)

    @functools.partial(jax.jit, donate_argnums=donate)
    def update_and_query(tree, keys, positions, values, valid,
                         q_keys, q_starts, q_ends, q_valid):
        """The fused per-launch program of the resident lane: scatter
        the chunk's new leaves, recompute their root paths, then answer
        every due window against the POST-update tree -- decode ->
        fold -> trigger in ONE launch, so a launch ships only new
        values in and fired results out, never the resident state."""
        tree = _update_body(tree, keys, positions, values, valid)
        out = _query_body(tree, q_keys, q_starts, q_ends, q_valid)
        return tree, out

    @functools.partial(jax.jit, donate_argnums=donate)
    def update_runs_and_query(tree, run_rows, run_starts, run_lens,
                              values, q_keys, q_starts, q_ends,
                              q_valid):
        """Run-descriptor form of the fused program: new leaves always
        land at CONSECUTIVE ring positions per key (arrival order /
        pane order), so a launch ships only the values plus
        (row, start, len) triples -- positions are expanded ON DEVICE
        (12 bytes per run instead of 8 per leaf)."""
        cum = jnp.cumsum(run_lens)
        v = jnp.arange(values.shape[0], dtype=jnp.int32)
        r = jnp.minimum(jnp.searchsorted(cum, v, side="right"),
                        run_lens.shape[0] - 1)
        base = cum[r] - run_lens[r]
        pos = (run_starts[r] + (v - base)) % n
        keys = run_rows[r]
        valid = v < cum[-1]
        tree = _update_body(tree, keys, pos, values, valid)
        out = _query_body(tree, q_keys, q_starts, q_ends, q_valid)
        return tree, out

    return (update_sparse, query_ranges, update_and_query,
            update_runs_and_query)


class BatchedFlatFAT:
    """Device-resident per-key FlatFAT forest (the ``rebuild=false``
    incremental mode of Win_SeqFFAT_GPU).

    One [K, 2n] array holds every key's aggregator tree in HBM across
    batches; leaves form a circular buffer over each key's series
    (leaf position = id % n, the reference's circular level update),
    so capacity ``n_leaves`` must cover the window span.  Updates touch
    only the modified root paths; range queries that wrap the ring are
    answered in two ordered pieces to preserve non-commutative combine
    order (oldest -> newest)."""

    def __init__(self, combine: Callable, neutral: float, n_keys: int,
                 n_leaves: int, dtype=np.float32):
        n = 1
        while n < max(2, n_leaves):
            n <<= 1
        self.n = n
        self.n_keys = n_keys
        self.neutral = neutral
        self.combine = combine
        (self._update, self._query, self._update_query,
         self._update_runs_query) = _batched_programs(combine, neutral,
                                                      n)
        import jax.numpy as jnp
        self.tree = jnp.full((n_keys, 2 * n), neutral, dtype)
        # leaves [n, 2n) start as neutral; internal nodes of a
        # neutral-filled tree are neutral (monoid identity), so no
        # build pass is needed

    @property
    def state_bytes(self) -> int:
        """Resident footprint of the forest in device memory (the
        ``Device_state_bytes_resident`` gauge)."""
        try:
            return int(self.tree.nbytes)
        except Exception:
            return 0

    def update(self, keys, ids, values) -> None:
        """Insert values at ring positions ids % n for their keys."""
        import jax.numpy as jnp
        keys = np.asarray(keys)
        b = 1
        while b < max(512, len(keys)):  # floored bucket (see above)
            b <<= 1
        k = np.zeros(b, np.int32)
        p = np.zeros(b, np.int32)
        v = np.full(b, self.neutral, np.float32)
        ok = np.zeros(b, bool)
        k[: len(keys)] = keys
        p[: len(keys)] = np.asarray(ids) % self.n
        v[: len(keys)] = values
        ok[: len(keys)] = True
        self.tree = self._update(self.tree, jnp.asarray(k), jnp.asarray(p),
                                 jnp.asarray(v), jnp.asarray(ok))

    def _pack_queries(self, keys, starts, ends):
        """Pad query extents to a pow2 bucket with ring-wrap handling:
        a wrapping range [s, e) is answered as two ordered pieces
        ([s, n) then [0, e mod n)) so non-commutative combines keep
        oldest -> newest order.  Returns (k2, s2, e2, ok, wraps, B)."""
        keys = np.asarray(keys, np.int64)
        starts = np.asarray(starts, np.int64)
        ends = np.asarray(ends, np.int64)
        if np.any(ends - starts > self.n):
            raise ValueError("window extent exceeds tree capacity")
        s = starts % self.n
        e_raw = ends % self.n
        wraps = (ends > starts) & (e_raw <= s)
        B = len(keys)
        b = 1
        while b < max(256, 2 * B):  # floored bucket: few compiles
            b <<= 1
        k2 = np.zeros(b, np.int32)
        s2 = np.zeros(b, np.int32)
        e2 = np.zeros(b, np.int32)
        ok = np.zeros(b, bool)
        # piece 1: [s, wrap ? n : e_raw)
        k2[:B] = keys
        s2[:B] = s
        e2[:B] = np.where(wraps, self.n, e_raw)
        ok[:B] = ends > starts
        # piece 2 (wrapping only): [0, e_raw)
        k2[B:2 * B] = keys
        s2[B:2 * B] = 0
        e2[B:2 * B] = np.where(wraps, e_raw, 0)
        ok[B:2 * B] = wraps
        return k2, s2, e2, ok, wraps, B

    def _combine_pieces(self, out: np.ndarray, wraps: np.ndarray,
                        B: int) -> np.ndarray:
        import jax.numpy as jnp
        head, tail = out[:B], out[B:2 * B]
        if not wraps.any():
            return head
        combined = np.asarray(self.combine(jnp.asarray(head),
                                           jnp.asarray(tail)))
        return np.where(wraps, combined, head)

    def update_query_launch(self, keys, ids, values, q_keys, q_starts,
                            q_ends):
        """Fused scatter + root-path recompute + range query in ONE
        jitted launch against the donated resident tree (the
        decode -> fold -> trigger program of the resident lane).
        Returns ``(dev_out, wraps, B)``: the un-blocked device result
        (2B wrap pieces) for async dispatch plus what
        :meth:`finish_query` needs to resolve it on host."""
        import jax.numpy as jnp
        keys = np.asarray(keys)
        # floor the update bucket: padding is cheap device work, and
        # collapsing the distinct pad shapes to a handful means
        # steady-state launches never hit a mid-stream XLA compile
        b = 1
        while b < max(512, len(keys)):
            b <<= 1
        k = np.zeros(b, np.int32)
        p = np.zeros(b, np.int32)
        v = np.full(b, self.neutral, np.float32)
        ok = np.zeros(b, bool)
        k[: len(keys)] = keys
        p[: len(keys)] = np.asarray(ids) % self.n
        v[: len(keys)] = values
        ok[: len(keys)] = True
        k2, s2, e2, qok, wraps, B = self._pack_queries(q_keys, q_starts,
                                                       q_ends)
        self.tree, out = self._update_query(
            self.tree, jnp.asarray(k), jnp.asarray(p), jnp.asarray(v),
            jnp.asarray(ok), jnp.asarray(k2), jnp.asarray(s2),
            jnp.asarray(e2), jnp.asarray(qok))
        return out, wraps, B

    def update_runs_query_launch(self, rows, starts, lens, values,
                                 q_keys, q_starts, q_ends):
        """Run-descriptor form of :meth:`update_query_launch`: each
        (rows[i], starts[i], lens[i]) names a CONSECUTIVE run of new
        leaves for one key; positions expand on device, so the launch
        ships values + 12 bytes per run instead of 8 bytes per leaf.
        ``starts`` may be absolute ids (pre-reduced mod n on host, so
        int32 device arithmetic can never overflow)."""
        import jax.numpy as jnp
        rows = np.asarray(rows, np.int64)
        lens = np.asarray(lens, np.int64)
        total = int(lens.sum())
        R = len(rows)
        rb = 1
        while rb < max(8, R):  # floored run bucket
            rb <<= 1
        rr = np.zeros(rb, np.int32)
        rs = np.zeros(rb, np.int32)
        rl = np.zeros(rb, np.int32)
        rr[:R] = rows
        rs[:R] = np.asarray(starts, np.int64) % self.n
        rl[:R] = lens
        vb = 1
        while vb < max(512, total):  # floored value bucket
            vb <<= 1
        v = np.full(vb, self.neutral, np.float32)
        v[:total] = values
        k2, s2, e2, qok, wraps, B = self._pack_queries(q_keys, q_starts,
                                                       q_ends)
        self.tree, out = self._update_runs_query(
            self.tree, jnp.asarray(rr), jnp.asarray(rs),
            jnp.asarray(rl), jnp.asarray(v), jnp.asarray(k2),
            jnp.asarray(s2), jnp.asarray(e2), jnp.asarray(qok))
        return out, wraps, B

    def update_runs_query(self, rows, starts, lens, values, q_keys,
                          q_starts, q_ends) -> np.ndarray:
        """Blocking form of :meth:`update_runs_query_launch`."""
        dev, wraps, B = self.update_runs_query_launch(
            rows, starts, lens, values, q_keys, q_starts, q_ends)
        return self.finish_query(dev, wraps, B)

    def finish_query(self, dev_out, wraps, B) -> np.ndarray:
        """Materialize one launch's query results on host (ring-wrap
        pieces combined in time order)."""
        return self._combine_pieces(np.asarray(dev_out), wraps, B)

    def update_query(self, keys, ids, values, q_keys, q_starts,
                     q_ends) -> np.ndarray:
        """Blocking form of :meth:`update_query_launch`."""
        dev, wraps, B = self.update_query_launch(keys, ids, values,
                                                 q_keys, q_starts, q_ends)
        return self.finish_query(dev, wraps, B)

    def query(self, keys, starts, ends) -> np.ndarray:
        """Window results for extents [starts, ends) in id space (end -
        start <= n); wrapping ranges are combined as (tail, head) to
        keep time order."""
        import jax.numpy as jnp
        k2, s2, e2, ok, wraps, B = self._pack_queries(keys, starts, ends)
        out = np.asarray(self._query(self.tree, jnp.asarray(k2),
                                     jnp.asarray(s2), jnp.asarray(e2),
                                     jnp.asarray(ok)))
        return self._combine_pieces(out, wraps, B)


class FlatFATJax:
    """Stateful host wrapper owning the device tree array.

    ``combine`` must form a monoid with identity ``neutral`` (the
    query seeds its left/right accumulators with ``neutral``); it need
    not be commutative -- fold order is preserved oldest->newest."""

    def __init__(self, combine: Callable, neutral: float, n_leaves: int,
                 dtype=np.float32):
        n = 1
        while n < max(2, n_leaves):
            n <<= 1
        self.n = n
        self.neutral = neutral
        self.dtype = dtype
        self._build, self._update, self._query = _programs(
            combine, neutral, n)
        import jax.numpy as jnp
        self.tree = self._build(jnp.full((n,), neutral, dtype))

    def build(self, leaves: np.ndarray) -> None:
        import jax.numpy as jnp
        padded = np.full(self.n, self.neutral, self.dtype)
        padded[: len(leaves)] = leaves
        self.tree = self._build(jnp.asarray(padded))

    def update(self, positions: np.ndarray, values: np.ndarray) -> None:
        import jax.numpy as jnp
        b = next_pow2 = 1
        while next_pow2 < max(1, len(positions)):
            next_pow2 <<= 1
        pos = np.zeros(next_pow2, np.int32)
        val = np.full(next_pow2, self.neutral, self.dtype)
        ok = np.zeros(next_pow2, bool)
        pos[: len(positions)] = positions
        val[: len(values)] = values
        ok[: len(positions)] = True
        self.tree = self._update(self.tree, jnp.asarray(pos),
                                 jnp.asarray(val), jnp.asarray(ok))

    def query_ranges(self, starts: np.ndarray,
                     ends: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        b = 1
        while b < max(1, len(starts)):
            b <<= 1
        s = np.zeros(b, np.int32)
        e = np.zeros(b, np.int32)
        ok = np.zeros(b, bool)
        s[: len(starts)] = starts
        e[: len(ends)] = ends
        ok[: len(starts)] = True
        out = self._query(self.tree, jnp.asarray(s), jnp.asarray(e),
                          jnp.asarray(ok))
        return np.asarray(out)[: len(starts)]
