"""Emitter family: the routing plane between operators.

Re-design of reference L2 (SURVEY.md §2.2): an emitter decides, per
item, which downstream replicas receive it.  Interface (the analogue of
basic_emitter.hpp:40-58): ``emit(item, send_to)``, ``eos(send_to)`` for
trailing markers, ``set_n_destinations``, ``clone``.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, List, Optional

import numpy as np

from ..core.meta import default_hash
from ..core.tuples import TupleBatch
from .node import EOSMarker
from .queues import Watermark

SendTo = Callable[[int, Any], None]


class Emitter:
    n_dest: int = 1
    # per-graph ColumnPool for partition sub-batches (attached by the
    # graph compile pass at start; None = allocate fresh columns)
    pool = None

    def set_n_destinations(self, n: int) -> None:
        self.n_dest = n

    def emit(self, item: Any, send_to: SendTo) -> None:
        raise NotImplementedError

    def eos(self, send_to: SendTo) -> None:
        pass

    def clone(self) -> "Emitter":
        return copy.deepcopy(self)


class StandardEmitter(Emitter):
    """FORWARD round-robin or KEYBY hash routing
    (standard_emitter.hpp:42-136).

    Audit plane (audit/census.py): when the graph auditor is enabled a
    space-saving hot-key sketch is attached to every KEYBY instance
    (``key_sketch``); the batch path offers a sampled per-batch key
    histogram, the record path a sampled scalar -- the raw input of
    the Skew table and the elastic controller's skew signal."""

    # attached by audit.GraphAuditor on KEYBY instances; None = off
    key_sketch = None

    def __init__(self, keyed: bool = False,
                 key_of: Callable[[Any], Any] = None):
        self.keyed = keyed
        self.key_of = key_of or (lambda t: t.get_control_fields()[0])
        self._rr = 0

    def emit(self, item, send_to):
        if self.n_dest == 1:
            if self.keyed and self.key_sketch is not None:
                self._observe_keys(item)
            send_to(0, item)
        elif isinstance(item, Watermark):
            # event-time control item: every destination must observe
            # the low-watermark (eventtime/; docs/EVENTTIME.md)
            for d in range(self.n_dest):
                send_to(d, item)
        elif isinstance(item, TupleBatch):
            if not self.keyed:
                send_to(self._rr, item)  # whole-batch round robin
                self._rr = (self._rr + 1) % self.n_dest
            else:
                sk = self.key_sketch
                if sk is not None:
                    sk.offer_batch(item.key)
                # vectorized KEYBY: partition the batch by key hash
                dests = np.abs(item.key) % self.n_dest
                for d, sub in partition_batch(item, dests, self.pool):
                    send_to(d, sub)
        elif self.keyed:
            rec = item.record if isinstance(item, EOSMarker) else item
            sk = self.key_sketch
            if sk is not None:
                sk.offer(self.key_of(rec))
            send_to(default_hash(self.key_of(rec)) % self.n_dest, item)
        else:
            send_to(self._rr, item)
            self._rr = (self._rr + 1) % self.n_dest

    def _observe_keys(self, item) -> None:
        """Single-destination KEYBY: routing is trivial but the skew
        census still wants the key distribution."""
        sk = self.key_sketch
        if isinstance(item, TupleBatch):
            sk.offer_batch(item.key)
        else:
            rec = item.record if isinstance(item, EOSMarker) else item
            try:
                sk.offer(self.key_of(rec))
            except (AttributeError, IndexError, TypeError):
                pass  # keyless control item

    def emit_many(self, items, send_to: SendTo, send_many_to) -> None:
        """Batched-emission plane (Outlet.send_many): route a whole
        buffer, accumulating same-destination items -- including the
        sub-batches of a partitioned TupleBatch -- into one bulk
        transfer per destination.  Per-destination arrival order is
        identical to per-item emit."""
        n = self.n_dest
        if n == 1:
            if self.keyed and self.key_sketch is not None:
                for item in items:
                    self._observe_keys(item)
            send_many_to(0, items)
            return
        buckets: dict = {}
        pool = self.pool
        sk = self.key_sketch if self.keyed else None
        for item in items:
            if isinstance(item, Watermark):
                # broadcast within the buffered run: appending to every
                # bucket preserves each destination's arrival order
                # relative to the surrounding data items
                for d in range(n):
                    buckets.setdefault(d, []).append(item)
            elif isinstance(item, TupleBatch):
                if not self.keyed:
                    d = self._rr
                    self._rr = (self._rr + 1) % n
                    buckets.setdefault(d, []).append(item)
                else:
                    if sk is not None:
                        sk.offer_batch(item.key)
                    dests = np.abs(item.key) % n
                    for d, sub in partition_batch(item, dests, pool):
                        buckets.setdefault(int(d), []).append(sub)
            elif self.keyed:
                rec = item.record if isinstance(item, EOSMarker) else item
                if sk is not None:
                    sk.offer(self.key_of(rec))
                d = default_hash(self.key_of(rec)) % n
                buckets.setdefault(d, []).append(item)
            else:
                d = self._rr
                self._rr = (self._rr + 1) % n
                buckets.setdefault(d, []).append(item)
        for d, run in buckets.items():
            send_many_to(d, run)


def partition_batch(batch, dests, pool=None):
    """Destination partition of a TupleBatch (shared by the KEYBY
    emitters).  A batch whose rows all route to one destination ships
    as-is (zero copies -- the common case for few-key streams); the
    multi-destination path uses one boolean-mask gather per
    destination, which measures faster than a sort-based single pass
    (the argsort dominates).  Mask selection preserves arrival order
    within each destination; contiguous runs ship as views and, with
    ``pool``, gathered sub-batches reuse arena buffers (core/tuples).
    Yields (dest, sub_batch)."""
    if len(dests) == 0:
        return
    lo_d, hi_d = int(dests.min()), int(dests.max())
    if lo_d == hi_d:  # single destination: ship the batch as-is
        yield lo_d, batch
        return
    for d in np.unique(dests):
        yield int(d), batch.take(dests == d, pool)


class BroadcastEmitter(Emitter):
    """Replicates every item to all destinations
    (broadcast_emitter.hpp:42-; refcounted in the reference, shared
    object here -- downstream treats inputs as immutable)."""

    def emit(self, item, send_to):
        for d in range(self.n_dest):
            send_to(d, item)


class SplittingEmitter(Emitter):
    """Runs the user splitting function returning one index or an
    iterable of indices (splitting_emitter.hpp:41-152; signatures
    API:165-172)."""

    def __init__(self, split_fn: Callable[[Any], Any], n_branches: int):
        self.split_fn = split_fn
        self.n_branches = n_branches

    def emit(self, item, send_to):
        if isinstance(item, (EOSMarker, Watermark)):
            for d in range(self.n_dest):
                send_to(d, item)
            return
        out = self.split_fn(item)
        if isinstance(out, int):
            out = (out,)
        for d in out:
            if d < 0 or d >= self.n_branches:
                raise ValueError(
                    f"splitting function returned branch {d} outside "
                    f"[0, {self.n_branches})")
            send_to(d, item)


class TreeEmitter(Emitter):
    """Two-level emitter composition: a root emitter routes to child
    emitters whose channels are flattened to global destination indices
    (tree_emitter.hpp:42-229; built by opt-level-2 fusion)."""

    def __init__(self, root: Emitter, children: List[Emitter]):
        self.root = root
        self.children = [c.clone() for c in children]
        self.root.set_n_destinations(len(self.children))
        # children widths are set at wiring via set_child_widths
        self._offsets: Optional[List[int]] = None

    def set_child_widths(self, widths: List[int]) -> None:
        assert len(widths) == len(self.children)
        self._offsets = []
        off = 0
        for c, w in zip(self.children, widths):
            c.set_n_destinations(w)
            self._offsets.append(off)
            off += w
        self.n_dest = off

    def emit(self, item, send_to):
        assert self._offsets is not None, "TreeEmitter not wired"

        def to_child(child_idx: int):
            off = self._offsets[child_idx]

            def send_child(d: int, it: Any):
                send_to(off + d, it)
            return send_child

        self.root.emit(item, lambda ci, it: self.children[ci].emit(
            it, to_child(ci)))

    def eos(self, send_to):
        def to_child(child_idx: int):
            off = self._offsets[child_idx]

            def send_child(d: int, it: Any):
                send_to(off + d, it)
            return send_child

        # root trailing items (e.g. WF per-key EOS markers) route through
        # the child emitters exactly like regular traffic
        self.root.eos(lambda ci, it: self.children[ci].emit(
            it, to_child(ci)))
        for ci, c in enumerate(self.children):
            c.eos(to_child(ci))
