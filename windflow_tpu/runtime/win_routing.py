"""Window-operator routing plane: WF / KF / WinMap emitters + collectors.

Re-designs of reference ``wf/wf_nodes.hpp`` (emitter :45-249, collector
:253-316), ``wf/kf_nodes.hpp`` (:43-180) and ``wf/wm_nodes.hpp``
(:45-326).  These implement the reference's parallelism strategies at
the routing level: window multicast (Win_Farm), key partitioning
(Key_Farm), and intra-window striping (Win_MapReduce MAP stage).
"""
from __future__ import annotations

import heapq
from typing import Any, Callable, Dict, List

from ..core.basic import Role, WinType
from ..core.meta import default_hash
from ..core.win_assign import wf_destinations, window_range_of
from .emitters import Emitter, partition_batch
from .node import EOSMarker, NodeLogic


class _LastTupleTracker:
    """Per-key most-recent tuple, used to forge EOS markers
    (wf_nodes.hpp:126-138)."""

    __slots__ = ("win_type", "last")

    def __init__(self, win_type: WinType):
        self.win_type = win_type
        self.last: Dict[Any, Any] = {}

    def observe(self, rec) -> None:
        key, tid, ts = rec.get_control_fields()
        field = tid if self.win_type == WinType.CB else ts
        prev = self.last.get(key)
        if prev is None or field > prev[0]:
            self.last[key] = (field, rec)

    def markers(self):
        return [rec for _, rec in self.last.values()]


class WFEmitter(Emitter):
    """Win_Farm emitter: multicasts each tuple to the workers owning the
    windows that contain it; worker of window w of a key is
    ``(hash % pardegree + w) % pardegree`` (wf_nodes.hpp:144-202).  At
    EOS, each key's last tuple goes to all workers as an EOS marker
    (wf_nodes.hpp:207-227)."""

    def __init__(self, win_len: int, slide_len: int, pardegree: int,
                 win_type: WinType, role: Role = Role.SEQ,
                 id_outer: int = 0, n_outer: int = 1, slide_outer: int = 0):
        self.win_len = win_len
        self.slide_len = slide_len
        self.pardegree = pardegree
        self.win_type = win_type
        self.role = role
        self.id_outer = id_outer
        self.n_outer = n_outer
        self.slide_outer = slide_outer
        self.tracker = _LastTupleTracker(win_type)

    def _emit_batch(self, batch, send_to):
        """Columnar multicast: per destination d, select the rows whose
        window range [first_w, last_w] includes a window owned by d
        (vectorized form of wf_destinations)."""
        import numpy as np
        from ..core.tuples import BasicRecord
        keys = batch.key
        ids = batch.id if self.win_type == WinType.CB else batch.ts
        h = np.abs(keys)
        first_gwid = (self.id_outer - (h % self.n_outer)
                      + self.n_outer) % self.n_outer
        initial = first_gwid * self.slide_outer
        if self.role in (Role.WLQ, Role.REDUCE):
            initial = np.zeros_like(initial)
        rel = ids - initial
        ok = rel >= 0
        win, slide, P = self.win_len, self.slide_len, self.pardegree
        if win >= slide:
            first_w = np.maximum(0, -(-(rel + 1 - win) // slide))
            last_w = -(-(rel + 1) // slide) - 1
        else:  # hopping
            n = rel // slide
            inside = (rel >= n * slide) & (rel < n * slide + win)
            ok &= inside
            first_w = last_w = n
        span = last_w - first_w + 1
        start_dst = h % P
        # track per-key last tuples for the EOS markers (vectorized:
        # lexsort groups keys with ascending field; the last row of each
        # group is that key's maximum)
        if ok.any():
            ks, fs = keys[ok], ids[ok]
            bi, bt = batch.id[ok], batch.ts[ok]
            order = np.lexsort((fs, ks))
            ks_s = ks[order]
            last_of_group = np.nonzero(
                np.append(np.diff(ks_s) != 0, True))[0]
            for j in last_of_group:
                row = order[j]
                key = ks_s[j].item()
                field = int(fs[row])
                prev = self.tracker.last.get(key)
                if prev is None or field > prev[0]:
                    self.tracker.last[key] = (field, BasicRecord(
                        key, int(bi[row]), int(bt[row])))
        for d in range(P):
            k = (d - start_dst) % P
            mask = ok & ((span >= P) | (((k - first_w) % P) <= (last_w
                                                               - first_w)))
            if mask.any():
                send_to(d, batch.take(mask))

    def emit(self, item, send_to):
        from ..core.tuples import TupleBatch
        if isinstance(item, TupleBatch):
            self._emit_batch(item, send_to)
            return
        if isinstance(item, EOSMarker):
            for d in range(self.pardegree):
                send_to(d, item)
            return
        rec = item
        key, tid, ts = rec.get_control_fields()
        hashcode = default_hash(key)
        id_ = tid if self.win_type == WinType.CB else ts
        self.tracker.observe(rec)
        # offset for this Win_Farm when nested inside an outer farm
        first_gwid_key = (self.id_outer - (hashcode % self.n_outer)
                          + self.n_outer) % self.n_outer
        initial_id = first_gwid_key * self.slide_outer
        if self.role in (Role.WLQ, Role.REDUCE):
            initial_id = 0
        if id_ < initial_id:
            return  # predates every window of this farm (wf_nodes.hpp:152)
        first_w, last_w = window_range_of(id_, initial_id, self.win_len,
                                          self.slide_len)
        if first_w < 0:
            return  # hopping-window gap
        for d in wf_destinations(hashcode, first_w, last_w, self.pardegree):
            send_to(d, rec)

    def eos(self, send_to):
        for rec in self.tracker.markers():
            m = EOSMarker(rec)
            for d in range(self.pardegree):
                send_to(d, m)


class KFEmitter(Emitter):
    """Key_Farm emitter: each key's whole substream goes to one worker by
    hash (kf_nodes.hpp:43-112)."""

    def __init__(self, pardegree: int,
                 routing: Callable[[int, int], int] = None):
        self.pardegree = pardegree
        self._default_routing = routing is None
        self.routing = routing or (lambda h, n: h % n)

    def emit(self, item, send_to):
        if self.pardegree == 1:
            send_to(0, item)  # all keys to the one worker: skip hashing
            return
        from ..core.tuples import TupleBatch
        if isinstance(item, TupleBatch):
            import numpy as np
            if self._default_routing:
                dests = np.abs(item.key) % self.pardegree
            else:
                # custom routing fn: the record path and the batch path
                # MUST agree per key or a key's substream splits across
                # workers (int64 batch keys hash to themselves)
                dests = np.fromiter(
                    (self.routing(int(k) if k >= 0 else -int(k),
                                  self.pardegree) for k in item.key),
                    np.int64, len(item.key))
            for d, sub in partition_batch(item, dests, self.pool):
                send_to(d, sub)
            return
        rec = item.record if isinstance(item, EOSMarker) else item
        key = rec.get_control_fields()[0]
        send_to(self.routing(default_hash(key), self.pardegree), item)


class WinMapEmitter(Emitter):
    """Win_MapReduce MAP-stage emitter: tuples of each key are striped
    round-robin across the MAP workers so each window is split into
    ``map_degree`` partitions (wm_nodes.hpp:45-255).  At EOS, per-key
    last tuples are broadcast as markers so every partition closes."""

    def __init__(self, map_degree: int, win_type: WinType):
        self.map_degree = map_degree
        self.win_type = win_type
        self.next_dst: Dict[Any, int] = {}
        self.tracker = _LastTupleTracker(win_type)

    def emit(self, item, send_to):
        if isinstance(item, EOSMarker):
            for d in range(self.map_degree):
                send_to(d, item)
            return
        rec = item
        key = rec.get_control_fields()[0]
        self.tracker.observe(rec)
        d = self.next_dst.get(key, 0)
        send_to(d, rec)
        self.next_dst[key] = (d + 1) % self.map_degree

    def eos(self, send_to):
        for rec in self.tracker.markers():
            m = EOSMarker(rec)
            for d in range(self.map_degree):
                send_to(d, m)


class WidOrderCollector(NodeLogic):
    """Reorders window results of each key by window id before
    forwarding -- the WF/KF ordered-collector and the WinMap collector
    (wf_nodes.hpp:253-316, kf_nodes.hpp:116-180, wm_nodes.hpp:259-326).

    Ordering is a per-(key, channel) watermark-by-min merge: each
    producer emits its own windows of a key in wid order, so a result
    is safe to forward once every producer channel has delivered a wid
    at or beyond it.  Unlike a dense from-0 counter, this is correct
    for ANCHORED streams (window ids starting at an epoch-scale anchor)
    and needs no heuristics; a key whose window count is below the
    producer count keeps its (few) results buffered until EOS."""

    def __init__(self, n_channels: int = 1):
        self.n_channels = n_channels
        self.maxs: Dict[Any, List[int]] = {}   # key -> per-channel max wid
        self.pending: Dict[Any, List] = {}

    def set_n_channels(self, n: int) -> None:
        """Called at graph wiring with the upstream producer count."""
        self.n_channels = max(1, n)

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        rec = item
        key, wid, _ = rec.get_control_fields()
        maxs = self.maxs.get(key)
        if maxs is None:
            maxs = self.maxs[key] = [-1] * self.n_channels
        if wid > maxs[channel_id]:
            maxs[channel_id] = wid
        heap = self.pending.setdefault(key, [])
        heapq.heappush(heap, (wid, id(rec), rec))
        watermark = min(maxs)
        while heap and heap[0][0] <= watermark:
            _, _, r = heapq.heappop(heap)
            emit(r)

    def eos_flush(self, emit):
        for key, heap in self.pending.items():
            while heap:
                _, _, r = heapq.heappop(heap)
                emit(r)

    # live-checkpoint snapshots (deep copies: the resumed run keeps
    # popping the live heaps)
    def state_dict(self):
        import copy
        return {"maxs": {k: list(v) for k, v in self.maxs.items()},
                "pending": copy.deepcopy(self.pending)}

    def load_state(self, state):
        import copy
        self.maxs = {k: list(v) for k, v in state["maxs"].items()}
        self.pending = copy.deepcopy(state["pending"])
