"""L0/L2 host runtime plane: channels, threaded nodes, emitters,
ordering collectors (the FastFlow substitute, SURVEY.md §5 last bullet)."""
from .queues import Channel
from .node import EOSMarker, NodeLogic, Outlet, RtNode, SourceLoopLogic
from .emitters import (Emitter, StandardEmitter, BroadcastEmitter,
                       SplittingEmitter, TreeEmitter)
from .ordering import OrderingLogic, KSlackLogic
from .win_routing import (WFEmitter, KFEmitter, WinMapEmitter,
                          WidOrderCollector)

__all__ = [
    "Channel", "EOSMarker", "NodeLogic", "Outlet", "RtNode",
    "SourceLoopLogic", "Emitter", "StandardEmitter", "BroadcastEmitter",
    "SplittingEmitter", "TreeEmitter", "OrderingLogic", "KSlackLogic",
    "WFEmitter", "KFEmitter", "WinMapEmitter", "WidOrderCollector",
]
