"""Bounded channels of the host runtime plane.

The reference rides FastFlow's lock-free SPSC queues with raw pointers
(SURVEY.md §5 "Distributed communication backend"); windflow_tpu's host
plane uses bounded MPSC channels with per-producer EOS accounting.  A
consumer node owns exactly one channel; each upstream replica is a
registered producer.  Backpressure = blocking bounded put (the analogue
of FF_BOUNDED_BUFFER).  When the native C++ runtime is built
(native/windflow_native.cpp), channels transparently use its ring
buffers.

Failure containment (resilience/): every channel supports ``poison()``
-- the graph-wide shutdown sentinel.  A poisoned channel wakes every
blocked ``put``/``get`` and makes them raise
:class:`~windflow_tpu.resilience.GraphCancelled`, so a dead replica
can never strand its upstream producers on a full bounded buffer.
"""
from __future__ import annotations

import threading
import time as _time
import warnings
from collections import deque
from typing import Any, Optional

from ..core.basic import DEFAULT_QUEUE_CAPACITY
from ..resilience.cancel import GraphCancelled

_EOS_SENTINEL = object()


class EpochBarrier:
    """Aligned-epoch barrier marker (durability/; docs/RESILIENCE.md
    "Exactly-once epochs") -- the channel-plane control item of the
    Chandy-Lamport-style snapshot protocol (Carbone et al., Flink's
    aligned barriers).  Injected at source replicas by the epoch
    coordinator, broadcast to every outlet destination, and consumed by
    the per-node aligners (durability/barrier.py) -- it never reaches
    operator ``svc``.  Travels through both channel planes as an
    ordinary item, so per-edge delivery books stay balanced by
    construction.  ``final=True`` is the end-of-stream variant a node
    broadcasts before closing its outlets: it tells downstream aligners
    this producer will inject no further epochs."""

    __slots__ = ("epoch", "final")

    def __init__(self, epoch: int, final: bool = False):
        self.epoch = epoch
        self.final = final

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return ("EpochBarrier(final)" if self.final
                else f"EpochBarrier({self.epoch})")

class Watermark:
    """Event-time low-watermark control item (eventtime/;
    docs/EVENTTIME.md) -- the in-band trigger signal of the event-time
    relational plane (Akidau et al., the Dataflow model).  A
    ``Watermark(ts)`` is a promise from its producer that every FUTURE
    item on this stream has event-time ``>= ts``.  Emitted by
    watermarked sources (eventtime/watermarks.py), broadcast by every
    emitter to all destinations, merged per consumer as the min over
    its producers (runtime/node.py), and consumed by event-time logics
    (``on_watermark``) to fire windows, close sessions and evict join
    state.  Like :class:`EpochBarrier` it travels through both channel
    planes as an ordinary item, so per-edge delivery books stay
    balanced by construction; the graph-wide conservation identity
    subtracts the per-node ``watermarks_in/out`` counters
    (audit/ledger.py)."""

    __slots__ = ("ts",)

    def __init__(self, ts: float):
        self.ts = ts

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Watermark({self.ts})"


# returned by get(timeout=...) when the wait elapses: distinct from
# None (which means every producer closed)
CHANNEL_TIMEOUT = object()

# bounded spin before an empty get() blocks on the condition variable:
# each iteration yields the GIL, so a producer mid-put gets a chance to
# publish without this consumer paying a full cv sleep/wake round trip
GET_SPIN = 24

# default batch a bulk consumer pops per lock round trip
GET_MANY_MAX = 128


class Channel:
    """Bounded multi-producer single-consumer channel.

    Items are ``(producer_id, payload)``.  ``close(producer_id)`` enqueues
    an EOS token for that producer; ``get()`` returns ``None`` once every
    registered producer has closed (the FastFlow EOS-propagation analogue).
    ``poison()`` cancels the channel: blocked and future put/get raise
    GraphCancelled (close becomes a no-op -- the consumer is gone).
    """

    __slots__ = ("_items", "_lock", "_not_empty", "_not_full",
                 "n_producers", "_eos_seen", "capacity", "poisoned",
                 "puts", "gets", "high_watermark", "_all_closed")

    def __init__(self, capacity: int = DEFAULT_QUEUE_CAPACITY):
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self.n_producers = 0
        self._eos_seen = 0
        # 0 (or negative) = unbounded, matching queue.Queue(maxsize=0)
        # which this class replaced
        self.capacity = capacity if capacity > 0 else None
        self.poisoned = False
        # raw queue counters (TRACE_FASTFLOW analogue).  Since the
        # audit plane (audit/ledger.py) these are LOAD-BEARING: the
        # flow-conservation ledger compares ``puts`` against the
        # Outlet-layer delivery books and ``gets + depth`` against
        # ``puts`` at the wait_end closure check.  All three are
        # updated inside the channel's critical section, so they are
        # exact (not merely tracing-grade) on this plane; EOS tokens
        # are counted by neither.  ``high_watermark`` is exported as
        # the Queue_high_watermark gauge (PipeGraph.refresh_gauges).
        self.puts = 0
        self.gets = 0
        self.high_watermark = 0
        self._all_closed = False  # sticky once every producer closed

    def register_producer(self) -> int:
        with self._lock:
            pid = self.n_producers
            self.n_producers += 1
            return pid

    def put(self, producer_id: int, item: Any) -> None:
        with self._not_full:
            while self.capacity is not None \
                    and len(self._items) >= self.capacity \
                    and not self.poisoned:
                self._not_full.wait()
            if self.poisoned:
                raise GraphCancelled(f"channel poisoned (producer "
                                     f"{producer_id})")
            self._items.append((producer_id, item))
            self.puts += 1
            d = len(self._items)
            if d > self.high_watermark:
                self.high_watermark = d
            self._not_empty.notify()

    def put_many(self, producer_id: int, items) -> None:
        """Bulk put: one lock round trip per capacity window instead of
        one per item.  Equivalent to ``for it in items: put(pid, it)``
        including backpressure (never overfills the bound) and poison
        semantics (raises as soon as the channel is cancelled; items
        already appended stay appended, exactly like the loop)."""
        n = len(items)
        if n == 0:
            return
        i = 0
        with self._not_full:
            while i < n:
                while self.capacity is not None \
                        and len(self._items) >= self.capacity \
                        and not self.poisoned:
                    self._not_full.wait()
                if self.poisoned:
                    raise GraphCancelled(f"channel poisoned (producer "
                                         f"{producer_id})")
                room = (n - i if self.capacity is None
                        else self.capacity - len(self._items))
                take = min(room, n - i)
                append = self._items.append
                for j in range(i, i + take):
                    append((producer_id, items[j]))
                i += take
                self.puts += take
                d = len(self._items)
                if d > self.high_watermark:
                    self.high_watermark = d
                self._not_empty.notify()

    def close(self, producer_id: int) -> None:
        # EOS bypasses the capacity bound (like the native channel): a
        # producer must always be able to announce its end of stream
        with self._lock:
            if self.poisoned:
                return
            self._items.append((producer_id, _EOS_SENTINEL))
            self._not_empty.notify()

    def _spin(self) -> None:
        """Bounded spin before blocking: each sleep(0) yields the GIL so
        a producer mid-put can publish, saving the cv round trip on
        busy channels.  Purely an optimization -- falling through to
        the condition wait is always correct."""
        for _ in range(GET_SPIN):
            if self._items or self.poisoned:
                return
            _time.sleep(0)

    def get(self, timeout: Optional[float] = None):
        """Next (channel_id, item); None when all producers closed;
        CHANNEL_TIMEOUT when ``timeout`` seconds pass with nothing to
        deliver (idle-tick consumers).  Raises GraphCancelled once the
        channel is poisoned."""
        if timeout is None and not self._items and not self._all_closed:
            # spin only for indefinite gets: timed gets are idle-tick
            # pollers where the cv wait IS the intended pacing
            self._spin()
        with self._not_empty:
            deadline = (None if timeout is None
                        else _time.monotonic() + timeout)
            while True:
                while not self._items:
                    if self.poisoned:
                        raise GraphCancelled("channel poisoned")
                    if self._all_closed:
                        return None
                    if deadline is None:
                        self._not_empty.wait()
                    else:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            return CHANNEL_TIMEOUT
                        self._not_empty.wait(remaining)
                if self.poisoned:
                    raise GraphCancelled("channel poisoned")
                pid, item = self._items.popleft()
                self._not_full.notify()
                if item is _EOS_SENTINEL:
                    self._eos_seen += 1
                    if self._eos_seen >= self.n_producers:
                        self._all_closed = True
                        return None
                    continue
                self.gets += 1
                return pid, item

    def get_many(self, max_n: int = GET_MANY_MAX,
                 timeout: Optional[float] = None):
        """Pop up to ``max_n`` items under one lock round trip.

        Returns a non-empty list of ``(channel_id, item)`` pairs in
        arrival order, ``None`` once every producer has closed (sticky),
        or ``CHANNEL_TIMEOUT``.  Blocks until at least one item is
        available, like ``get``."""
        out = []
        if timeout is None and not self._items and not self._all_closed:
            self._spin()
        with self._not_empty:
            deadline = (None if timeout is None
                        else _time.monotonic() + timeout)
            while True:
                while not self._items:
                    if self.poisoned:
                        raise GraphCancelled("channel poisoned")
                    if self._all_closed:
                        return None
                    if deadline is None:
                        self._not_empty.wait()
                    else:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            return CHANNEL_TIMEOUT
                        self._not_empty.wait(remaining)
                if self.poisoned:
                    raise GraphCancelled("channel poisoned")
                popleft = self._items.popleft
                while self._items and len(out) < max_n:
                    pid, item = popleft()
                    if item is _EOS_SENTINEL:
                        self._eos_seen += 1
                        if self._eos_seen >= self.n_producers:
                            self._all_closed = True
                            break
                        continue
                    out.append((pid, item))
                self._not_full.notify_all()
                if out:
                    self.gets += len(out)
                    return out
                if self._all_closed:
                    return None
                # only partial EOS tokens were drained: wait for data

    def poison(self) -> None:
        """Graph-cancellation sentinel: wake and fail all blocked ends."""
        with self._lock:
            self.poisoned = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def qsize(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def depth(self) -> int:
        """Lock-free depth gauge: ``len`` of a deque is GIL-atomic, so
        monitoring/elastic samplers can read it without contending on
        the channel lock.  Gauge-grade (may lag a concurrent put/get by
        one item), like the puts/gets counters."""
        return len(self._items)


_native_warned = False


def _warn_native_unavailable(detail: str) -> None:
    """One warning per process: a broken native toolchain should be
    visible, not silently degrade every channel to pure Python."""
    global _native_warned
    if _native_warned:
        return
    _native_warned = True
    warnings.warn(
        f"windflow_tpu native runtime unavailable ({detail}); falling "
        "back to pure-Python channels (set use_native_runtime=False or "
        "WINDFLOW_NATIVE=0 to silence)", RuntimeWarning, stacklevel=3)


def make_channel(config=None) -> "Channel":
    """Channel factory: prefers the native C++ channel when the runtime
    config allows it and the toolchain built it (runtime/native.py)."""
    cap = config.queue_capacity if config is not None else DEFAULT_QUEUE_CAPACITY
    if config is None or config.use_native_runtime:
        try:
            from .native import NativeChannel, native_available
            if native_available():
                return NativeChannel(cap)
            import os
            if os.environ.get("WINDFLOW_NATIVE", "1") != "0":
                # deliberate WINDFLOW_NATIVE=0 runs fall through
                # silently; only a genuinely broken toolchain warns
                _warn_native_unavailable("toolchain probe/build failed")
        except (OSError, RuntimeError) as e:
            # only environment errors are expected here; anything else
            # (a real bug in the binding layer) must propagate
            _warn_native_unavailable(repr(e))
    return Channel(cap)
