"""Bounded channels of the host runtime plane.

The reference rides FastFlow's lock-free SPSC queues with raw pointers
(SURVEY.md §5 "Distributed communication backend"); windflow_tpu's host
plane uses bounded MPSC channels with per-producer EOS accounting.  A
consumer node owns exactly one channel; each upstream replica is a
registered producer.  Backpressure = blocking bounded put (the analogue
of FF_BOUNDED_BUFFER).  When the native C++ runtime is built
(native/spsc.cpp), channels transparently use its ring buffers.
"""
from __future__ import annotations

import queue as _queue
import threading
from typing import Any, List, Optional, Tuple

from ..core.basic import DEFAULT_QUEUE_CAPACITY

_EOS_SENTINEL = object()

# returned by get(timeout=...) when the wait elapses: distinct from
# None (which means every producer closed)
CHANNEL_TIMEOUT = object()


class Channel:
    """Bounded multi-producer single-consumer channel.

    Items are ``(producer_id, payload)``.  ``close(producer_id)`` enqueues
    an EOS token for that producer; ``get()`` returns ``None`` once every
    registered producer has closed (the FastFlow EOS-propagation analogue).
    """

    __slots__ = ("q", "n_producers", "_eos_seen", "_lock", "capacity",
                 "puts", "gets", "high_watermark")

    def __init__(self, capacity: int = DEFAULT_QUEUE_CAPACITY):
        self.q: _queue.Queue = _queue.Queue(maxsize=capacity)
        self.n_producers = 0
        self._eos_seen = 0
        self._lock = threading.Lock()
        self.capacity = capacity
        # raw queue counters (TRACE_FASTFLOW analogue); puts/hwm written
        # under the producer's put, gets by the single consumer
        self.puts = 0
        self.gets = 0
        self.high_watermark = 0

    def register_producer(self) -> int:
        with self._lock:
            pid = self.n_producers
            self.n_producers += 1
            return pid

    def put(self, producer_id: int, item: Any) -> None:
        self.q.put((producer_id, item))
        self.puts += 1
        d = self.q.qsize()
        if d > self.high_watermark:
            self.high_watermark = d

    def close(self, producer_id: int) -> None:
        self.q.put((producer_id, _EOS_SENTINEL))

    def get(self, timeout: Optional[float] = None):
        """Next (channel_id, item); None when all producers closed;
        CHANNEL_TIMEOUT when ``timeout`` seconds pass with nothing to
        deliver (idle-tick consumers)."""
        while True:
            try:
                pid, item = (self.q.get(timeout=timeout)
                             if timeout is not None else self.q.get())
            except _queue.Empty:
                return CHANNEL_TIMEOUT
            if item is _EOS_SENTINEL:
                self._eos_seen += 1
                if self._eos_seen >= self.n_producers:
                    return None
                continue
            self.gets += 1
            return pid, item

    def qsize(self) -> int:
        return self.q.qsize()


def make_channel(config=None) -> "Channel":
    """Channel factory: prefers the native C++ channel when the runtime
    config allows it and the toolchain built it (runtime/native.py)."""
    cap = config.queue_capacity if config is not None else DEFAULT_QUEUE_CAPACITY
    if config is None or config.use_native_runtime:
        try:
            from .native import NativeChannel, native_available
            if native_available():
                return NativeChannel(cap)
        except Exception:
            pass
    return Channel(cap)
