"""ctypes bindings to the native C++ host runtime (native/windflow_native.cpp).

Builds the shared library on first use with g++ (no pip/pybind11
dependency), caches it next to the sources, and degrades gracefully to
the pure-Python plane when a toolchain is unavailable
(RuntimeConfig.use_native_runtime gates usage).

Object hand-off across the native channel: the producer increfs the
Python object and passes its address; the consumer rebuilds the object
reference and decrefs.  Blocking waits happen in C++ with the GIL
released (ctypes drops it around foreign calls).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any, Optional

from .queues import CHANNEL_TIMEOUT

_lib = None
_lib_lock = threading.Lock()
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SRCS = [os.path.join(_NATIVE_DIR, f)
         for f in ("windflow_native.cpp", "window_engine.cpp",
                   "record_pipeline.cpp")]
_SO = os.path.join(_NATIVE_DIR, "libwindflow_native.so")


# -ffp-contract=off: the declared Python/numpy plane rounds mul and
# add separately; FMA contraction in the lowered planes would differ
# by 1 ULP at exact filter thresholds (lowering must never change
# results)
_CMD = ["g++", "-O3", "-march=native", "-ffp-contract=off",
        "-std=c++17", "-shared", "-fPIC", "-pthread", *_SRCS,
        "-o", _SO]
_STAMP = _SO + ".cmd"


def _build() -> Optional[str]:
    # fault-injection hook (resilience/faults.py): tests force the
    # toolchain probe to fail to exercise the pure-Python fallback
    from ..resilience.faults import native_build_forced_to_fail
    if native_build_forced_to_fail():
        return None
    if os.environ.get("WINDFLOW_NATIVE", "1") == "0":
        return None  # CI pure-Python job: skip the toolchain entirely
    cmd_str = " ".join(_CMD)
    fresh = os.path.exists(_SO) and all(
        os.path.getmtime(_SO) >= os.path.getmtime(src) for src in _SRCS)
    try:
        with open(_STAMP) as f:
            same_cmd = f.read() == cmd_str
    except OSError:
        same_cmd = False
    if fresh and same_cmd:
        return _SO
    try:
        subprocess.run(_CMD, check=True, capture_output=True, timeout=180)
        with open(_STAMP, "w") as f:
            f.write(cmd_str)
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None


def get_lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        so = _build()
        if so is None:
            _lib = False
            return None
        lib = ctypes.CDLL(so)
        lib.wfn_channel_new.restype = ctypes.c_void_p
        lib.wfn_channel_new.argtypes = [ctypes.c_size_t]
        lib.wfn_channel_free.argtypes = [ctypes.c_void_p]
        lib.wfn_channel_register_producer.restype = ctypes.c_int
        lib.wfn_channel_register_producer.argtypes = [ctypes.c_void_p]
        lib.wfn_channel_put.restype = ctypes.c_int
        lib.wfn_channel_put.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_size_t]
        lib.wfn_channel_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.wfn_channel_poison.argtypes = [ctypes.c_void_p]
        lib.wfn_channel_drain.restype = ctypes.c_int
        lib.wfn_channel_drain.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t)]
        lib.wfn_channel_get_timed.restype = ctypes.c_int
        lib.wfn_channel_get_timed.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_int), ctypes.c_longlong]
        lib.wfn_channel_get.restype = ctypes.c_int
        lib.wfn_channel_get.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_int)]
        lib.wfn_channel_size.restype = ctypes.c_size_t
        lib.wfn_channel_size.argtypes = [ctypes.c_void_p]
        lib.wfn_pane_sum.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_double)]
        for name in ("wfn_pane_max", "wfn_pane_min"):
            fn = getattr(lib, name)
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
                ctypes.c_double, ctypes.POINTER(ctypes.c_double)]
        lib.wfn_partition_mod.argtypes = [
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_longlong)]
        _PLL = ctypes.POINTER(ctypes.c_longlong)
        lib.wfn_pane_prereduce.restype = ctypes.c_longlong
        lib.wfn_pane_prereduce.argtypes = [
            _PLL, _PLL, ctypes.POINTER(ctypes.c_double),
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            _PLL, _PLL, ctypes.POINTER(ctypes.c_double)]
        lib.wfn_pane_prereduce_f32.restype = ctypes.c_longlong
        lib.wfn_pane_prereduce_f32.argtypes = [
            _PLL, _PLL, ctypes.POINTER(ctypes.c_float),
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            _PLL, _PLL, ctypes.POINTER(ctypes.c_double)]
        LL = ctypes.c_longlong
        PLL = ctypes.POINTER(LL)
        PD = ctypes.POINTER(ctypes.c_double)
        lib.wfn_engine_new.restype = ctypes.c_void_p
        lib.wfn_engine_new.argtypes = [LL, LL, ctypes.c_int, LL,
                                       ctypes.c_int, ctypes.c_int]
        lib.wfn_engine_free.argtypes = [ctypes.c_void_p]
        lib.wfn_engine_ingest.restype = LL
        lib.wfn_engine_ingest.argtypes = [ctypes.c_void_p, PLL, PLL, PLL,
                                          PD, LL]
        lib.wfn_engine_ingest_f32.restype = LL
        lib.wfn_engine_ingest_f32.argtypes = [
            ctypes.c_void_p, PLL, PLL, PLL,
            ctypes.POINTER(ctypes.c_float), LL]
        lib.wfn_engine_synth_ingest.restype = LL
        lib.wfn_engine_synth_ingest.argtypes = [
            ctypes.c_void_p, LL, LL, LL, LL,
            ctypes.c_double, ctypes.c_double]
        lib.wfn_engine_synth_ingest_masked.restype = LL
        lib.wfn_engine_synth_ingest_masked.argtypes = [
            ctypes.c_void_p, LL, LL, LL, LL,
            ctypes.c_double, ctypes.c_double,
            ctypes.POINTER(ctypes.c_ubyte), PD]
        lib.wfn_engine_ready.restype = LL
        lib.wfn_engine_ready.argtypes = [ctypes.c_void_p]
        lib.wfn_engine_ignored.restype = LL
        lib.wfn_engine_ignored.argtypes = [ctypes.c_void_p]
        lib.wfn_engine_eos.argtypes = [ctypes.c_void_p]
        lib.wfn_engine_flush.restype = LL
        lib.wfn_engine_flush.argtypes = [
            ctypes.c_void_p, LL, ctypes.POINTER(PD), PLL,
            ctypes.POINTER(PD), PLL,
            ctypes.POINTER(PLL), ctypes.POINTER(PLL), ctypes.POINTER(PLL),
            ctypes.POINTER(PLL), ctypes.POINTER(PLL)]
        lib.wfn_engine_serialize.restype = LL
        lib.wfn_engine_serialize.argtypes = [ctypes.c_void_p,
                                             ctypes.c_char_p, LL]
        lib.wfn_engine_deserialize.restype = ctypes.c_int
        lib.wfn_engine_deserialize.argtypes = [ctypes.c_void_p,
                                               ctypes.c_char_p, LL]
        lib.wfn_rp_new.restype = ctypes.c_void_p
        lib.wfn_rp_new.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.wfn_rp_free.argtypes = [ctypes.c_void_p]
        lib.wfn_rp_add_stage.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            LL, LL, LL, LL, ctypes.c_double, ctypes.c_double]
        lib.wfn_rp_set_synth.argtypes = [ctypes.c_void_p, LL, LL, LL,
                                         ctypes.c_double, ctypes.c_double]
        lib.wfn_rp_set_feed.argtypes = [ctypes.c_void_p]
        lib.wfn_rp_start.argtypes = [ctypes.c_void_p]
        lib.wfn_rp_feed.argtypes = [ctypes.c_void_p, PLL, PLL, PLL, PD, LL]
        lib.wfn_rp_feed_eos.argtypes = [ctypes.c_void_p]
        lib.wfn_rp_poll.restype = LL
        lib.wfn_rp_poll.argtypes = [ctypes.c_void_p, LL, PLL, PLL, PLL, PD,
                                    ctypes.POINTER(ctypes.c_int)]
        lib.wfn_rp_wait.argtypes = [ctypes.c_void_p, PLL, PD, PLL]
        _lib = lib
        return lib


def native_available() -> bool:
    return get_lib() is not None


class NativeChannel:
    """Drop-in for runtime.queues.Channel backed by the C++ channel."""

    __slots__ = ("lib", "ptr", "n_producers", "capacity", "poisoned",
                 "puts", "gets", "high_watermark", "_all_closed")

    def __init__(self, capacity: int = 2048):
        self.lib = get_lib()
        if self.lib is None:
            raise RuntimeError("native runtime unavailable")
        self.ptr = self.lib.wfn_channel_new(capacity)
        self.n_producers = 0
        self.capacity = capacity
        self.poisoned = False
        # raw queue counters (TRACE_FASTFLOW analogue), consumed by
        # the audit plane's conservation ledger (audit/ledger.py) and
        # the Queue_high_watermark gauge.  Unlike the pure-Python
        # channel they are incremented OUTSIDE the C++ ring's lock
        # (one GIL-held += per successful call): exact under the
        # single-consumer contract and at quiescent points (the
        # wait_end closure check), gauge-grade between concurrent
        # producers mid-stream -- which is why the online dup rule in
        # the ledger only fires on an inflight-clean snapshot.
        self.puts = 0
        self.gets = 0
        self.high_watermark = 0
        self._all_closed = False  # sticky once every producer closed

    def register_producer(self) -> int:
        self.n_producers += 1
        return self.lib.wfn_channel_register_producer(self.ptr)

    def put(self, producer_id: int, item: Any) -> None:
        ctypes.pythonapi.Py_IncRef(ctypes.py_object(item))
        rc = self.lib.wfn_channel_put(self.ptr, producer_id, id(item))
        if rc < 0:  # poisoned: the channel did not take ownership
            ctypes.pythonapi.Py_DecRef(ctypes.py_object(item))
            from ..resilience.cancel import GraphCancelled
            raise GraphCancelled(f"native channel poisoned (producer "
                                 f"{producer_id})")
        self.puts += 1
        d = self.lib.wfn_channel_size(self.ptr)
        if d > self.high_watermark:
            self.high_watermark = d

    def put_many(self, producer_id: int, items) -> None:
        """Bulk put.  The C++ ring blocks with the GIL released per
        item already; the win here is one Python-level call per batch
        from the outlet plane (and API parity with the pure-Python
        channel)."""
        for item in items:
            self.put(producer_id, item)

    def close(self, producer_id: int) -> None:
        self.lib.wfn_channel_close(self.ptr, producer_id)

    def get_many(self, max_n: int = 128, timeout: Optional[float] = None):
        """Bulk get: one blocking get, then opportunistic non-blocking
        pops while the ring is non-empty.  Same return contract as
        ``Channel.get_many`` (list / sticky None / CHANNEL_TIMEOUT)."""
        if self._all_closed:
            return None
        got = self.get(timeout)
        if got is CHANNEL_TIMEOUT:
            return CHANNEL_TIMEOUT
        if got is None:
            self._all_closed = True
            return None
        out = [got]
        while len(out) < max_n and self.qsize() > 0:
            nxt = self.get(timeout=0.001)
            if nxt is CHANNEL_TIMEOUT:
                break  # the visible entry was an unresolved EOS token
            if nxt is None:
                self._all_closed = True
                break
            out.append(nxt)
        return out

    def get(self, timeout: Optional[float] = None):
        handle = ctypes.c_size_t()
        cid = ctypes.c_int()
        if timeout is None:
            rc = self.lib.wfn_channel_get(self.ptr, ctypes.byref(handle),
                                          ctypes.byref(cid))
        else:
            rc = self.lib.wfn_channel_get_timed(
                self.ptr, ctypes.byref(handle), ctypes.byref(cid),
                max(1, int(timeout * 1000)))
        if rc < 0:
            from ..resilience.cancel import GraphCancelled
            raise GraphCancelled("native channel poisoned")
        if rc == 2:
            return CHANNEL_TIMEOUT
        if not rc:
            return None
        obj = ctypes.cast(handle.value, ctypes.py_object).value
        ctypes.pythonapi.Py_DecRef(ctypes.py_object(obj))
        self.gets += 1
        return cid.value, obj

    def poison(self) -> None:
        """Graph-cancellation sentinel: wake and fail all blocked ends."""
        self.poisoned = True
        self.lib.wfn_channel_poison(self.ptr)

    def qsize(self) -> int:
        return self.lib.wfn_channel_size(self.ptr)

    @property
    def depth(self) -> int:
        """Depth gauge (monitoring/elastic samplers): the C++ size read
        is already lock-cheap, so this just mirrors the pure-Python
        channel's surface."""
        return self.lib.wfn_channel_size(self.ptr)

    def __del__(self):
        try:
            lib, ptr = getattr(self, "lib", None), getattr(self, "ptr", None)
            if lib is not None and ptr:
                # drain remaining handles to avoid leaking references
                # (drain works on poisoned channels too, unlike get)
                handle = ctypes.c_size_t()
                while lib.wfn_channel_drain(ptr, ctypes.byref(handle)):
                    obj = ctypes.cast(handle.value, ctypes.py_object).value
                    ctypes.pythonapi.Py_DecRef(ctypes.py_object(obj))
                lib.wfn_channel_free(ptr)
        except (TypeError, AttributeError):
            pass  # interpreter shutdown: ctypes globals already torn down


def pane_prereduce(keys, tss, values, pane: int):
    """Fused ingest-plane pane pre-reduction (ingest/coalesce.py):
    collapse a columnar chunk to per-(key, pane) sum partials in one
    native pass.  Returns (keys, pane_starts, sums) arrays or None when
    the library is unavailable / the domain is too sparse for the
    dense-grid kernel (callers fall back to numpy or pass-through)."""
    import numpy as np
    lib = get_lib()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, np.int64)
    tss = np.ascontiguousarray(tss, np.int64)
    if values.dtype == np.float32:
        values = np.ascontiguousarray(values)
        fn = lib.wfn_pane_prereduce_f32
        vp = values.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    else:
        values = np.ascontiguousarray(values, np.float64)
        fn = lib.wfn_pane_prereduce
        vp = values.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    n = len(keys)
    cap = min(n, 1 << 16)
    while True:
        out_k = np.empty(cap, np.int64)
        out_p = np.empty(cap, np.int64)
        out_s = np.empty(cap, np.float64)
        m = fn(keys.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
               tss.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
               vp, n, pane, cap,
               out_k.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
               out_p.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
               out_s.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        if m == -1:
            return None  # sparse domain: dense grid refused
        if m == -2:
            cap = n      # partials cannot outnumber tuples
            continue
        return out_k[:m], out_p[:m], out_s[:m]


def pane_reduce(values, pos, kind: str):
    """Native pane partial reduction; returns None if lib unavailable."""
    import numpy as np
    lib = get_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, np.float64)
    pos = np.ascontiguousarray(pos, np.int64)
    n = len(pos) - 1
    out = np.empty(n, np.float64)
    vp = values.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    pp = pos.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
    op = out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    if kind == "sum":
        lib.wfn_pane_sum(vp, pp, n, op)
    elif kind == "max":
        lib.wfn_pane_max(vp, pp, n, float("-inf"), op)
    elif kind == "min":
        lib.wfn_pane_min(vp, pp, n, float("inf"), op)
    else:
        return None
    return out


class NativeRecordPipeline:
    """ctypes wrapper over the native record-at-a-time pipeline engine
    (native/record_pipeline.cpp).

    ``mode="threaded"`` is the reference-architecture baseline (one
    thread per operator stage over SPSC rings -- the FastFlow design,
    SURVEY.md L0); ``mode="fused"`` is the chain-fused fast host path
    (multipipe.hpp:345-390 applied end-to-end) with ``shards``
    key-sharded workers.

    Stages are added in pipeline order with the expression-descriptor
    helpers; the source is either native-synthetic (``set_synth``) or
    Python-fed columnar batches (``set_feed`` + ``feed``/``feed_eos``).
    """

    __slots__ = ("lib", "ptr", "_started", "_waited", "_store")

    FIELDS = {"key": 0, "id": 1, "ts": 2, "value": 3}
    WKINDS = {"sum": 0, "count": 1, "max": 2, "min": 3, "mean": 4}
    _FILTER_OPS = {"mod_eq": 0, "lt": 1, "gt": 2, "le": 3, "ge": 4, "eq": 5}

    def __init__(self, mode: str = "fused", shards: int = 1,
                 store_results: bool = False):
        self.lib = get_lib()
        if self.lib is None:
            raise RuntimeError("native runtime unavailable")
        self.ptr = self.lib.wfn_rp_new(
            {"threaded": 0, "fused": 1}[mode], shards,
            1 if store_results else 0)
        self._started = False
        self._waited = False
        self._store = store_results

    # -- stage construction -------------------------------------------
    def add_filter(self, field: str, op: str, *, m: int = 0, r: int = 0,
                   const: float = 0.0) -> "NativeRecordPipeline":
        """op in mod_eq (keep when field % m == r) | lt|gt|le|ge|eq
        (compare field against const)."""
        self.lib.wfn_rp_add_stage(self.ptr, 1, self.FIELDS[field],
                                  self._FILTER_OPS[op], m, r, 0, 0,
                                  const, 0.0)
        return self

    def add_map_affine(self, scale: float, offset: float = 0.0,
                       square: bool = False) -> "NativeRecordPipeline":
        """value = value*scale + offset (or value^2*scale + offset)."""
        self.lib.wfn_rp_add_stage(self.ptr, 2, 3, 2 if square else 0,
                                  0, 0, 0, 0, scale, offset)
        return self

    def add_map_load(self, field: str, scale: float = 1.0,
                     offset: float = 0.0) -> "NativeRecordPipeline":
        """value = field*scale + offset."""
        self.lib.wfn_rp_add_stage(self.ptr, 2, self.FIELDS[field], 1,
                                  0, 0, 0, 0, scale, offset)
        return self

    def add_accumulator(self) -> "NativeRecordPipeline":
        """Keyed rolling sum (the reference Accumulator)."""
        self.lib.wfn_rp_add_stage(self.ptr, 3, 3, 0, 0, 0, 0, 0, 0.0, 0.0)
        return self

    def add_window(self, win_len: int, slide_len: int, is_tb: bool,
                   kind: str = "sum",
                   renumber: bool = False) -> "NativeRecordPipeline":
        self.lib.wfn_rp_add_stage(self.ptr, 4, 3, 1 if renumber else 0,
                                  win_len, slide_len,
                                  1 if is_tb else 0, self.WKINDS[kind],
                                  0.0, 0.0)
        return self

    # -- source -------------------------------------------------------
    def set_synth(self, n_events: int, n_keys: int, vmod: int = 97,
                  vscale: float = 1.0, voff: float = 0.0) -> None:
        """Native synthetic source: key=i%K, id=ts=i//K,
        value=(i%vmod)*vscale+voff (the bench/test fixture shape)."""
        self.lib.wfn_rp_set_synth(self.ptr, n_events, n_keys, vmod,
                                  vscale, voff)

    def set_feed(self) -> None:
        self.lib.wfn_rp_set_feed(self.ptr)

    def feed(self, keys, ids, ts, vals) -> None:
        import numpy as np
        LL = ctypes.c_longlong
        keys = np.ascontiguousarray(keys, np.int64)
        ids = np.ascontiguousarray(ids, np.int64)
        ts = np.ascontiguousarray(ts, np.int64)
        vals = np.ascontiguousarray(vals, np.float64)
        self.lib.wfn_rp_feed(
            self.ptr, keys.ctypes.data_as(ctypes.POINTER(LL)),
            ids.ctypes.data_as(ctypes.POINTER(LL)),
            ts.ctypes.data_as(ctypes.POINTER(LL)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(keys))

    def feed_eos(self) -> None:
        self.lib.wfn_rp_feed_eos(self.ptr)

    # -- execution ----------------------------------------------------
    def start(self) -> None:
        self._started = True
        self.lib.wfn_rp_start(self.ptr)

    def poll(self, max_n: int = 65536):
        """Blocking poll of stored results; returns (keys, wids, ts,
        vals, done). Requires store_results=True."""
        import numpy as np
        LL = ctypes.c_longlong
        keys = np.empty(max_n, np.int64)
        wids = np.empty(max_n, np.int64)
        ts = np.empty(max_n, np.int64)
        vals = np.empty(max_n, np.float64)
        done = ctypes.c_int()
        n = self.lib.wfn_rp_poll(
            self.ptr, max_n, keys.ctypes.data_as(ctypes.POINTER(LL)),
            wids.ctypes.data_as(ctypes.POINTER(LL)),
            ts.ctypes.data_as(ctypes.POINTER(LL)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.byref(done))
        return (keys[:n], wids[:n], ts[:n], vals[:n], bool(done.value))

    def wait(self):
        """Join all pipeline threads; returns (n_results, result_sum,
        dropped)."""
        LL = ctypes.c_longlong
        count, dropped = LL(), LL()
        total = ctypes.c_double()
        self.lib.wfn_rp_wait(self.ptr, ctypes.byref(count),
                             ctypes.byref(total), ctypes.byref(dropped))
        self._waited = True
        return count.value, total.value, dropped.value

    def __del__(self):
        lib, ptr = getattr(self, "lib", None), getattr(self, "ptr", None)
        if lib is not None and ptr:
            if self._started and not self._waited:
                # joining requires the feed to be closed; best effort
                try:
                    self.feed_eos()
                except Exception:
                    pass
            lib.wfn_rp_free(ptr)


class NativeWindowEngine:
    """ctypes wrapper over the C++ columnar window engine
    (native/window_engine.cpp)."""

    __slots__ = ("lib", "ptr")

    KINDS = {"sum": 0, "count": 1, "max": 2, "min": 3, "mean": 4}

    def __init__(self, win_len: int, slide_len: int, is_tb: bool,
                 delay: int = 0, renumber: bool = False, kind: str = "sum"):
        self.lib = get_lib()
        if self.lib is None:
            raise RuntimeError("native runtime unavailable")
        self.ptr = self.lib.wfn_engine_new(win_len, slide_len,
                                           1 if is_tb else 0, delay,
                                           1 if renumber else 0,
                                           self.KINDS[kind])

    def ingest(self, keys, ids, ts, vals) -> int:
        import numpy as np
        keys = np.ascontiguousarray(keys, np.int64)
        ids = np.ascontiguousarray(ids, np.int64)
        ts = np.ascontiguousarray(ts, np.int64)
        LL = ctypes.c_longlong
        vals = np.asarray(vals)
        if vals.dtype == np.float32 and vals.flags.c_contiguous:
            # f32 lane: no widening copy; the engine widens per element
            return self.lib.wfn_engine_ingest_f32(
                self.ptr,
                keys.ctypes.data_as(ctypes.POINTER(LL)),
                ids.ctypes.data_as(ctypes.POINTER(LL)),
                ts.ctypes.data_as(ctypes.POINTER(LL)),
                vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                len(keys))
        vals = np.ascontiguousarray(vals, np.float64)
        return self.lib.wfn_engine_ingest(
            self.ptr,
            keys.ctypes.data_as(ctypes.POINTER(LL)),
            ids.ctypes.data_as(ctypes.POINTER(LL)),
            ts.ctypes.data_as(ctypes.POINTER(LL)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(keys))

    def synth_ingest(self, start: int, n: int, n_keys: int,
                     vmod: int = 97, vscale: float = 1.0,
                     voff: float = 0.0, mask=None, vtab=None) -> int:
        """Fused generate+fold of the declared synthetic law
        (operators/synth.py): events [start, start+n) never materialize
        as host arrays.  ``mask`` (uint8[vmod], optional) drops events
        whose mask[e % vmod] entry is 0 -- the folded form of a
        declared value-predicate Filter; a dropped event neither folds
        nor advances triggering.  ``vtab`` (float64[vmod], optional)
        overrides the affine law with a per-residue value table (the
        sequentially-applied declared map chain).  Returns the
        ready-window count."""
        if mask is None and vtab is None:
            return self.lib.wfn_engine_synth_ingest(
                self.ptr, start, n, n_keys, vmod, vscale, voff)
        import numpy as np
        PD = ctypes.POINTER(ctypes.c_double)
        mp = None
        if mask is not None:
            mask = np.ascontiguousarray(mask, np.uint8)
            assert len(mask) == vmod
            mp = mask.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte))
        vp = None
        if vtab is not None:
            vtab = np.ascontiguousarray(vtab, np.float64)
            assert len(vtab) == vmod
            vp = vtab.ctypes.data_as(PD)
        return self.lib.wfn_engine_synth_ingest_masked(
            self.ptr, start, n, n_keys, vmod, vscale, voff, mp, vp)

    def ready(self) -> int:
        return self.lib.wfn_engine_ready(self.ptr)

    def ignored(self) -> int:
        """Tuples dropped behind the fired frontier (the acceptance
        rule of win_seq.hpp:417-428)."""
        return self.lib.wfn_engine_ignored(self.ptr)

    def eos(self) -> None:
        self.lib.wfn_engine_eos(self.ptr)

    def flush(self, max_windows: int):
        """Returns (vals[f64], starts, ends, keys, gwids, rts[, cnts])
        numpy copies, or None when nothing is ready.  ``cnts`` (per-pane
        tuple counts, same layout as vals) is appended only for the
        'mean' kind."""
        import numpy as np
        LL = ctypes.c_longlong
        PD = ctypes.POINTER(ctypes.c_double)
        PLL = ctypes.POINTER(LL)
        vals_p, n_vals = PD(), LL()
        cnts_p, n_cnts = PD(), LL()
        sp, ep, kp, gp, rp = PLL(), PLL(), PLL(), PLL(), PLL()
        b = self.lib.wfn_engine_flush(
            self.ptr, max_windows, ctypes.byref(vals_p),
            ctypes.byref(n_vals), ctypes.byref(cnts_p),
            ctypes.byref(n_cnts), ctypes.byref(sp), ctypes.byref(ep),
            ctypes.byref(kp), ctypes.byref(gp), ctypes.byref(rp))
        if b == 0:
            return None
        nv = n_vals.value

        def arr(p, n, dt):
            return np.ctypeslib.as_array(p, shape=(n,)).astype(dt, copy=True)

        out = (arr(vals_p, nv, np.float64), arr(sp, b, np.int64),
               arr(ep, b, np.int64), arr(kp, b, np.int64),
               arr(gp, b, np.int64), arr(rp, b, np.int64))
        if n_cnts.value:
            out = out + (arr(cnts_p, n_cnts.value, np.float64),)
        return out

    def serialize(self) -> bytes:
        """Versioned binary snapshot of all mutable engine state."""
        n = self.lib.wfn_engine_serialize(self.ptr, None, 0)
        buf = ctypes.create_string_buffer(n)
        got = self.lib.wfn_engine_serialize(self.ptr, buf, n)
        if got != n:
            raise RuntimeError("engine snapshot size changed mid-call")
        return buf.raw[:n]

    def deserialize(self, blob: bytes) -> None:
        """Restore a snapshot into an identically-configured engine."""
        ok = self.lib.wfn_engine_deserialize(self.ptr, blob, len(blob))
        if not ok:
            raise ValueError("malformed or mismatched engine snapshot")

    def __del__(self):
        lib, ptr = getattr(self, "lib", None), getattr(self, "ptr", None)
        if lib is not None and ptr:
            lib.wfn_engine_free(ptr)
