"""Runtime nodes: the threaded executors of the host plane.

The reference makes every operator replica an ``ff_node`` with a
``svc()`` called per queue item (SURVEY.md §3.2).  windflow_tpu splits
that into a passive **NodeLogic** (the operator semantics: svc /
eos_flush / svc_end) and an active **RtNode** thread owning the input
channel and an **Outlet** (emitter + destination channels).  This keeps
operator logic runtime-agnostic: the same logic objects are driven by
Python threads here and by the native C++ executor when built.
"""
from __future__ import annotations

import threading
import time as _time
import traceback
from typing import Any, Callable, Optional, Sequence

from ..core.tuples import SynthChunk
from ..resilience.cancel import GraphCancelled
from ..resilience.policies import POLICY_DEAD_LETTER, POLICY_FAIL
from ..telemetry.trace import attach_if_absent
from .queues import Channel, CHANNEL_TIMEOUT, GET_MANY_MAX, Watermark


class EOSMarker:
    """A tuple travelling as an EOS marker (reference wraps the per-key
    last tuple with an eos flag, meta.hpp:770-783 + wf_nodes.hpp:207-227):
    it updates window triggering state downstream but carries no data."""

    __slots__ = ("record",)

    def __init__(self, record: Any):
        self.record = record


class NodeLogic:
    """Base class for operator replica logic."""

    stats = None  # replica StatsRecord, attached by RtNode under tracing
    # telemetry plane (telemetry/): the graph FlightRecorder (always
    # bound at PipeGraph.start; record() is a no-op when disabled) and,
    # for logics that stamp trace hops themselves (FusedLogic, the
    # device window engines), the graph TelemetryHub
    flight = None
    telemetry = None

    # True (the default) promises every ``emit`` happens before the
    # ``svc``/``eos_flush`` call that received the callback returns.
    # Logics that stash ``emit`` and call it later from another thread
    # (the window engines' async dispatcher) set False, which disables
    # the runtime's batched-emission fast path for their node.
    sync_emit = True

    def svc_init(self) -> None:
        pass

    def svc(self, item: Any, channel_id: int, emit: Callable[[Any], None]) -> None:
        raise NotImplementedError

    def eos_flush(self, emit: Callable[[Any], None]) -> None:
        """Called once when all input producers reached EOS (the
        ``eosnotify`` cascade, e.g. win_seq.hpp:514-579)."""

    def svc_end(self) -> None:
        pass

    # -- checkpoint hooks (utils/checkpoint.py; absent in the reference,
    # SURVEY.md §5 "Checkpoint / resume") ---------------------------------
    def state_dict(self):
        """Picklable snapshot of this replica's state; None = stateless."""
        return None

    def load_state(self, state) -> None:
        raise NotImplementedError(f"{type(self).__name__} is stateless")

    # -- keyed-state hooks (elastic/rescale.py): a logic whose state is
    # a per-key mapping exposes it so a runtime rescale can repartition
    # keys over a new replica count -------------------------------------
    def keyed_state_dict(self):
        """``{key: state}`` snapshot for key repartitioning; None =
        stateless (nothing to migrate at rescale)."""
        return None

    def load_keyed_state(self, kv) -> None:
        """Replace this replica's per-key state with ``kv`` (the keys
        this replica owns under the new routing); clears keys it no
        longer owns."""
        raise NotImplementedError(
            f"{type(self).__name__} has no keyed state")

    # -- audit-plane hooks (audit/; docs/OBSERVABILITY.md).  Both are
    # read from the auditor thread against a LIVE replica, so
    # implementations must be lock-free gauge-grade reads (len() of a
    # dict, a monotone counter) -- never a full-state iteration --------
    def keyed_state_census(self):
        """``(key_count, bytes_estimate)`` for the keyed-state census,
        or None when this logic holds no keyed state."""
        return None

    def progress_frontier(self):
        """Monotone source position (replay offset / synth index /
        socket chunk seq) for progress tracking; None defers to the
        generic emitted-items frontier.  Only meaningful on source
        logics."""
        return None

    # -- event-time hook (eventtime/; docs/EVENTTIME.md).  A logic that
    # DEFINES ``on_watermark(wm, emit)`` receives every advanced
    # min-merged watermark before the runtime forwards it downstream
    # (fire windows / close sessions / evict join state -- emissions
    # precede the watermark in every destination channel).  Logics
    # without the hook never see watermarks: the RtNode intercepts and
    # forwards them generically.  Deliberately NOT defined on the base
    # class so ``getattr(logic, "on_watermark", None)`` stays a cheap
    # one-time probe.


class ChainedLogic(NodeLogic):
    """Thread fusion of two logics: b consumes a's emissions inline
    (the reference's combine_with_laststage, multipipe.hpp:381, and the
    ff_comb PLQ/WLQ fusion of optimize_PaneFarm, pane_farm.hpp:222-250)."""

    def __init__(self, a: NodeLogic, b: NodeLogic):
        self.a = a
        self.b = b
        # the chain accepts synth-chunk descriptors iff its first half
        # does (the runtime materializes them otherwise)
        self.accepts_synth_chunks = getattr(a, "accepts_synth_chunks",
                                            False)
        # a chain emits synchronously only if BOTH halves do: an async
        # half (device engine dispatcher) calls the wrapped emit after
        # svc returns, so the runtime must not hand the chain a
        # buffered emit
        self.sync_emit = (getattr(a, "sync_emit", True)
                          and getattr(b, "sync_emit", True))
        # delegate idle ticks only when a half defines them: RtNode
        # probes hasattr, and unconditional definition would put every
        # fused map chain on timed gets for nothing
        if hasattr(a, "idle_tick") or hasattr(b, "idle_tick"):
            self.idle_tick = self._idle_tick

    def _idle_tick(self, emit):
        ta = getattr(self.a, "idle_tick", None)
        if ta is not None:
            ta(lambda x: self.b.svc(x, 0, emit))
        tb = getattr(self.b, "idle_tick", None)
        if tb is not None:
            tb(emit)

    def svc_init(self):
        # the RtNode attaches the replica StatsRecord to the OUTER
        # logic only; forward it so fused stages report device metrics
        self.a.stats = self.stats
        self.b.stats = self.stats
        self.a.svc_init()
        self.b.svc_init()

    def _feed_b(self, x, emit):
        # watermarks emitted inside the chain (a watermarked source
        # half) must not reach b.svc: offer b's event-time hook, then
        # pass the watermark through (eventtime/; docs/EVENTTIME.md)
        if isinstance(x, Watermark):
            hook = getattr(self.b, "on_watermark", None)
            if hook is not None:
                hook(x, emit)
            emit(x)
            return
        self.b.svc(x, 0, emit)

    def svc(self, item, channel_id, emit):
        self.a.svc(item, channel_id,
                   lambda x: self._feed_b(x, emit))

    def on_watermark(self, wm, emit):
        """Channel watermark: both halves observe it in chain order."""
        ha = getattr(self.a, "on_watermark", None)
        if ha is not None:
            ha(wm, lambda x: self._feed_b(x, emit))
        hb = getattr(self.b, "on_watermark", None)
        if hb is not None:
            hb(wm, emit)

    def eos_flush(self, emit):
        self.a.eos_flush(lambda x: self._feed_b(x, emit))
        self.b.eos_flush(emit)

    def svc_end(self):
        self.a.svc_end()
        self.b.svc_end()

    def quiesce(self, emit) -> bool:
        """Live-barrier hook: drain both halves' in-flight device work
        (a's emissions feed b inline, exactly like svc)."""
        emitted = False
        qa = getattr(self.a, "quiesce", None)
        if qa is not None:
            emitted = bool(qa(lambda x: self.b.svc(x, 0, emit)))
        qb = getattr(self.b, "quiesce", None)
        if qb is not None:
            emitted = bool(qb(emit)) or emitted
        return emitted

    # -- checkpoint: delegate to both halves ---------------------------
    def state_dict(self):
        sa, sb = self.a.state_dict(), self.b.state_dict()
        if sa is None and sb is None:
            return None
        return {"a": sa, "b": sb}

    def load_state(self, state):
        if state.get("a") is not None:
            self.a.load_state(state["a"])
        if state.get("b") is not None:
            self.b.load_state(state["b"])


class _FusedDownstreamError(BaseException):
    """Carrier for an exception crossing a fused-segment boundary
    upstream.  Deliberately a BaseException: an upstream segment's
    ``except Exception`` policy guard must never swallow a DOWNSTREAM
    segment's failure (at LEVEL0 it happens in another thread, out of
    the upstream policy's scope).  FusedLogic unwraps it at the top."""

    def __init__(self, error: BaseException):
        self.error = error
        super().__init__(str(error))


class FusedSegment:
    """One operator replica inside a :class:`FusedLogic`: the logic plus
    the runtime identity it had (or would have had) as its own RtNode --
    name, error policy, stats record, fault state, dead-letter store.
    The fusion pass (graph/fuse.py) builds these; PipeGraph.start binds
    faults per segment so a FaultPlan targeting a fused-away operator
    still fires."""

    __slots__ = ("logic", "name", "policy", "stats", "faults",
                 "dead_letters", "taken", "accepts_chunks")

    def __init__(self, logic: NodeLogic, name: str,
                 policy: str = POLICY_FAIL):
        self.logic = logic
        self.name = name
        self.policy = policy
        self.stats = None
        self.faults = None
        self.dead_letters = None
        self.taken = 0  # items entering this segment (1-based fault clock)
        self.accepts_chunks = getattr(logic, "accepts_synth_chunks", False)


class FusedLogic(NodeLogic):
    """N-ary stage fusion: the segments run inline in one replica thread,
    each emission feeding the next segment's ``svc`` directly (the
    graph-wide generalization of :class:`ChainedLogic`, realizing
    ``OptLevel.LEVEL2`` -- reference ``ff_comb``, multipipe.hpp:345-390
    and pane_farm.hpp:222-250).

    Unlike ``ChainedLogic`` (whose halves share the node's single error
    policy, which is why ``chain()`` refuses policied operators), every
    segment keeps its own error policy, stats record, fault-injection
    state and checkpoint identity: a skip/dead_letter segment
    quarantines its own tuples without swallowing its neighbours'
    errors, and snapshots restore across fusion-level changes because
    state stays keyed by the original node names
    (utils/checkpoint.graph_state flattens segments)."""

    def __init__(self, segments):
        self.segments: list = []
        for seg in segments:
            if isinstance(seg.logic, FusedLogic):  # flatten nested fusion
                self.segments.extend(seg.logic.segments)
            else:
                self.segments.append(seg)
        first = self.segments[0]
        self.accepts_synth_chunks = first.accepts_chunks
        self.sync_emit = all(getattr(s.logic, "sync_emit", True)
                             for s in self.segments)
        self.pool = None            # graph ColumnPool (boundary
        #                             materialization), set at fuse time
        self._emit_out = None       # the node's outward emit, set per call
        self._obs_left = 1          # sampled whole-chain service timing
        # trace context inside the chain -- THREAD-LOCAL: in a chain
        # with an async-emitting segment the dispatcher thread runs
        # the downstream entries/exits concurrently with the consume
        # thread, and a shared slot would attach (and double-close)
        # one thread's in-flight context onto the other's emissions
        self._live = threading.local()
        # set by RtNode.run on terminal (outlet-less) nodes: the LAST
        # segment's entry closes traces, so an async engine segment's
        # results still measure the device leg before closure
        self.closes_traces = False
        # set by PipeGraph.start on fused SOURCE heads: the first
        # segment's emissions never traverse RtNode._emit, so the
        # 1-in-N trace sampler runs in the first segment's exit instead
        self.trace_sampler = None
        self._entry0 = None
        self._exits = None
        self._build_chain()
        # idle ticks delegate only when some segment defines them (the
        # RtNode probes hasattr, exactly like ChainedLogic)
        if any(hasattr(s.logic, "idle_tick") for s in self.segments):
            self.idle_tick = self._idle_tick

    # -- inline chain construction (closures built once) ----------------
    def _build_chain(self):
        segs = self.segments
        n = len(segs)
        exits = [None] * n
        entry_next = None
        for k in range(n - 1, -1, -1):
            seg = segs[k]
            exits[k] = self._make_exit(seg, entry_next, first=(k == 0))
            entry_next = self._make_entry(seg, exits[k], first=(k == 0),
                                          last=(k == n - 1))
        self._exits = exits
        self._entry0 = entry_next

    def _make_exit(self, seg: FusedSegment, entry_next,
                   first: bool = False):
        if entry_next is None:      # last segment: leave the fused node
            def exit_(item):
                if seg.faults is not None:
                    seg.faults.before_put()
                if seg.stats is not None:
                    seg.stats.outputs_sent += 1
                lc = getattr(self._live, "ctx", None)
                if lc is not None:
                    attach_if_absent(item, lc)
                self._emit_out(item)
        else:
            def exit_(item):
                if first:
                    # fused SOURCE head: its emissions never reach
                    # RtNode._emit, so the 1-in-N sampler runs here
                    s = self.trace_sampler
                    if s is not None:
                        s.maybe_attach(item)
                if seg.faults is not None:
                    seg.faults.before_put()
                if seg.stats is not None:
                    seg.stats.outputs_sent += 1
                lc = getattr(self._live, "ctx", None)
                if lc is not None:
                    attach_if_absent(item, lc)
                try:
                    entry_next(item, 0)
                except Exception as e:
                    # escaping the downstream guard means its policy is
                    # 'fail': carry it past the UPSTREAM guards (whose
                    # policies must not apply to a downstream failure)
                    raise _FusedDownstreamError(e) from e
        return exit_

    def _make_entry(self, seg: FusedSegment, exit_, first: bool = False,
                    last: bool = False):
        svc = seg.logic.svc
        # live-context inheritance is SAME-THREAD state: an async-
        # emitting segment (sync_emit=False, the device dispatcher)
        # runs exits from its own thread, which must not read the
        # consume thread's in-flight context (the engine carries its
        # context across the dispatcher itself -- win_seq_tpu.py)
        inherit = getattr(seg.logic, "sync_emit", True)

        def entry(item, cid):
            if isinstance(item, Watermark):
                # event-time control item generated INSIDE the chain (a
                # fused watermarked source head): offer this segment's
                # hook, then pass it through -- it must never reach a
                # plain segment's svc (docs/EVENTTIME.md)
                hook = getattr(seg.logic, "on_watermark", None)
                if hook is not None:
                    hook(item, exit_)
                exit_(item)
                return
            if isinstance(item, SynthChunk) and not seg.accepts_chunks:
                item = item.materialize(self.pool)  # plane boundary
            seg.taken += 1
            if seg.faults is not None:
                # outside the policy guard: an injected crash is a
                # replica death, never a skippable tuple failure
                seg.faults.on_tuple(seg.taken)
            st = seg.stats
            if st is not None:
                st.inputs_received += 1
            # per-segment trace attribution (telemetry/): residency is
            # a channel property so only the first segment records it;
            # every segment stamps its own hop.  An inner segment's
            # hop interval includes its downstream segments' inline
            # work (documented in docs/OBSERVABILITY.md)
            ctx = None if self.telemetry is None \
                else getattr(item, "trace", None)
            if ctx is not None:
                t_in = _time.perf_counter()
                if first and st is not None \
                        and st.residency_hist is not None:
                    st.residency_hist.observe((t_in - ctx.last) * 1e6)
                if inherit:
                    live = self._live
                    prev = getattr(live, "ctx", None)
                    live.ctx = ctx
            try:
                svc(item, cid, exit_)
            except Exception as e:
                if seg.policy == POLICY_FAIL:
                    raise
                if st is not None:
                    st.svc_failures += 1
                if self.flight is not None:
                    self.flight.record("svc_failure", node=seg.name,
                                       error=repr(e))
                if seg.policy == POLICY_DEAD_LETTER \
                        and seg.dead_letters is not None:
                    seg.dead_letters.add(seg.name, item, e)
            finally:
                if ctx is not None:
                    if inherit:
                        live.ctx = prev
                    t_done = _time.perf_counter()
                    ctx.hop(seg.name, t_in, t_done)
                    if last and self.closes_traces:
                        # terminal fused node: the trace ends when the
                        # item (or an engine result carrying its
                        # context) reaches the final segment
                        self.telemetry.close(ctx, st, t_done)
        return entry

    # -- NodeLogic surface ----------------------------------------------
    def svc_init(self):
        for seg in self.segments:
            # device logics write launch metrics into their own record
            seg.logic.stats = seg.stats
            seg.logic.svc_init()

    def svc(self, item, channel_id, emit):
        self._emit_out = emit
        try:
            st0 = self.segments[0].stats
            if st0 is not None:
                self._obs_left -= 1
                if self._obs_left <= 0:
                    t0 = _time.perf_counter()
                    self._entry0(item, channel_id)
                    st0.observe((_time.perf_counter() - t0) * 1e6)
                    self._obs_left = 1 if st0.samples < 64 else 16
                    return
            self._entry0(item, channel_id)
        except _FusedDownstreamError as w:
            raise w.error

    def on_watermark(self, wm, emit):
        """Channel watermark against a fused node: every segment with
        the event-time hook observes it in chain order, emissions
        feeding the downstream segments inline (the runtime forwards
        the watermark itself afterwards, like any other logic)."""
        self._emit_out = emit
        try:
            for k, seg in enumerate(self.segments):
                hook = getattr(seg.logic, "on_watermark", None)
                if hook is not None:
                    hook(wm, self._exits[k])
        except _FusedDownstreamError as w:
            raise w.error

    def eos_flush(self, emit):
        self._emit_out = emit
        try:
            for k, seg in enumerate(self.segments):
                seg.logic.eos_flush(self._exits[k])
        except _FusedDownstreamError as w:
            raise w.error

    def svc_end(self):
        first_err = None
        for seg in self.segments:
            try:
                seg.logic.svc_end()
            except BaseException as e:  # run every teardown hook
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    def set_segments_terminated(self):
        """Clean-EOS hook (RtNode.run): mark every segment's record."""
        for seg in self.segments:
            if seg.stats is not None:
                seg.stats.set_terminated()

    def _idle_tick(self, emit):
        self._emit_out = emit
        try:
            for k, seg in enumerate(self.segments):
                tick = getattr(seg.logic, "idle_tick", None)
                if tick is not None:
                    tick(self._exits[k])
        except _FusedDownstreamError as w:
            raise w.error

    def quiesce(self, emit) -> bool:
        """Live-barrier hook: drain every segment's in-flight device
        work; emissions feed the downstream segments inline."""
        self._emit_out = emit
        emitted = False
        try:
            for k, seg in enumerate(self.segments):
                q = getattr(seg.logic, "quiesce", None)
                if q is not None:
                    emitted = bool(q(self._exits[k])) or emitted
        except _FusedDownstreamError as w:
            raise w.error
        return emitted

    # -- checkpoint: per-segment, keyed by original node name ----------
    def state_dict(self):
        states = {}
        for seg in self.segments:
            getter = getattr(seg.logic, "state_dict", None)
            st = getter() if getter is not None else None
            if st is not None:
                states[seg.name] = st
        return {"fused": states} if states else None

    def load_state(self, state):
        states = state.get("fused", state)
        for seg in self.segments:
            if seg.name in states:
                seg.logic.load_state(states[seg.name])


def source_loop_of(logic) -> Optional["SourceLoopLogic"]:
    """The SourceLoopLogic driving a channel-less node, seen through
    fusion/chaining wrappers (PipeGraph.start attaches the pause gate
    to it)."""
    if isinstance(logic, SourceLoopLogic):
        return logic
    if isinstance(logic, FusedLogic):
        return source_loop_of(logic.segments[0].logic)
    if isinstance(logic, ChainedLogic):
        return source_loop_of(logic.a)
    return None


class Outlet:
    """Output side of a node: an emitter routing items to destination
    channels.  ``dests`` is a list of (channel, producer_id).

    Audit plane (audit/ledger.py): when the graph auditor is enabled,
    ``audit_cells`` holds one :class:`~windflow_tpu.audit.EdgeCell` per
    destination -- the producer-side delivery books (``sent`` counted
    before the put = intent, ``delivered`` after it returns,
    ``inflight`` True in between).  Books are written only by the
    node's single emitting thread (the runtime's emission contract),
    so plain int adds suffice.  ``faults`` carries the node's
    put-level fault state (FaultPlan drop_put/dup_put): an injected
    drop/duplication lands exactly between the two books, which is the
    divergence the flow-conservation ledger must detect."""

    __slots__ = ("emitter", "dests", "audit_cells", "faults")

    def __init__(self, emitter, dests: Sequence):
        self.emitter = emitter
        self.dests = list(dests)
        self.audit_cells = None
        self.faults = None

    @property
    def n_destinations(self) -> int:
        return len(self.dests)

    def send_to(self, dest_idx: int, item: Any) -> None:
        ch, pid = self.dests[dest_idx]
        cells = self.audit_cells
        if cells is None:
            f = self.faults
            if f is not None:
                act = f.put_action()
                if act is not None:
                    if act == "drop":
                        return
                    ch.put(pid, item)  # dup: deliver twice
            ch.put(pid, item)
            return
        cell = cells[dest_idx]
        cell.inflight = True
        cell.sent += 1
        f = self.faults
        if f is not None:
            act = f.put_action()
            if act is not None:
                if act == "drop":
                    # lost on the wire: intent counted, never delivered
                    cell.inflight = False
                    return
                ch.put(pid, item)  # dup: one intent, two deliveries
        ch.put(pid, item)
        cell.delivered += 1
        cell.inflight = False

    def send_many_to(self, dest_idx: int, items) -> None:
        """Ship a same-destination run of items as one bulk transfer
        (one channel lock round trip instead of one per item).  Put
        faults never reach this path: RtNode._flush_emits falls back to
        per-item sends whenever put-level faults are bound."""
        ch, pid = self.dests[dest_idx]
        cells = self.audit_cells
        cell = None
        if cells is not None:
            cell = cells[dest_idx]
            cell.inflight = True
            cell.sent += len(items)
        pm = getattr(ch, "put_many", None)
        if pm is not None:
            pm(pid, items)
        else:
            for item in items:
                ch.put(pid, item)
        if cell is not None:
            cell.delivered += len(items)
            cell.inflight = False

    def send(self, item: Any) -> None:
        if len(self.dests) > 1 and isinstance(item, SynthChunk):
            # routing emitters read key/id columns: materialize the
            # descriptor before fan-out (single-destination outlets
            # pass it through; the consuming node decides there)
            item = item.materialize(self.emitter.pool)
        self.emitter.emit(item, self.send_to)

    def send_many(self, items) -> None:
        """Batched send: route a whole emission buffer, accumulating
        same-destination items into single transfers.  Emitters that
        implement ``emit_many`` (StandardEmitter) group; others fall
        back to per-item ``send``."""
        emit_many = getattr(self.emitter, "emit_many", None)
        if emit_many is None:
            for item in items:
                self.send(item)
            return
        if len(self.dests) > 1:
            pool = self.emitter.pool
            items = [it.materialize(pool) if isinstance(it, SynthChunk)
                     else it for it in items]
        emit_many(items, self.send_to, self.send_many_to)

    def flush_eos(self) -> None:
        """Let the emitter publish trailing items (e.g. WF per-key EOS
        markers), then close every destination once."""
        self.emitter.eos(self.send_to)
        for ch, pid in self.dests:
            ch.close(pid)


class SourcePauseControl:
    """Cooperative source pause: the live-checkpoint barrier's first
    phase.  Sources call ``gate()`` between generation steps; while a
    pause is requested they ack and block until ``resume()``."""

    def __init__(self):
        self._cond = threading.Condition()
        self.pausing = False
        self.paused_count = 0

    def gate(self) -> None:
        with self._cond:
            if not self.pausing:
                return
            self.paused_count += 1
            self._cond.notify_all()
            while self.pausing:
                self._cond.wait()
            self.paused_count -= 1
            self._cond.notify_all()

    def request_pause(self) -> None:
        with self._cond:
            self.pausing = True

    def resume(self) -> None:
        with self._cond:
            self.pausing = False
            self._cond.notify_all()


class RtNode(threading.Thread):
    """One operator replica = one host thread (FastFlow analogue; thread
    count report mirrors pipegraph.hpp:610-612)."""

    def __init__(self, name: str, logic: NodeLogic, channel: Optional[Channel],
                 outlets: Sequence[Outlet]):
        super().__init__(name=name, daemon=True)
        self.logic = logic
        self.channel = channel
        self.outlets = list(outlets)
        self.error: Optional[BaseException] = None
        self.cancelled = False  # unwound by graph cancellation, no error
        self.stats = None  # StatsRecord when tracing is enabled
        self.group = None  # complex-nesting group id (multipipe grouping)
        # wiring marks collector nodes (ordering/K-slack/farm merge)
        # structurally; the fusion pass must never fuse across them
        self.is_collector = False
        # distributed runtime (distributed/partition.py): the builder's
        # .with_worker(i) pin, copied from the operator at wiring; the
        # partition planner and the fusion pass's partition barrier
        # read it.  None = placed automatically.
        self.worker_pin = None
        # elastic-operator membership (elastic/rescale.py): the handle
        # key when this replica belongs to a runtime-rescalable stage.
        # The compile pass must not fuse such nodes (rescale rebuilds
        # replica threads and rewires their channels at runtime), and
        # chain() falls back to add() for them.
        self.elastic_group = None
        # drain detection for the live-checkpoint barrier: an item is
        # in flight while taken != done
        self.taken = 0
        self.done = 0
        # the graph's SourcePauseControl (attached at start): idle
        # ticks must not fire while a live-checkpoint barrier is
        # pausing -- any launch they start strictly precedes a barrier
        # drain pass only if no NEW ticks begin after the pause request
        self.pause_ctl = None
        # failure containment (attached by PipeGraph.start): the graph
        # CancelToken, this operator's error policy, the graph
        # dead-letter store, and any bound fault-injection state
        self.cancel_token = None
        self.error_policy = POLICY_FAIL
        self.dead_letters = None
        self.faults = None
        # per-graph ColumnPool (attached at start; None = allocate fresh)
        self.pool = None
        # global-scheduler plane (scheduler/leases.py): the tenant's
        # fair-share lease, bound by PipeGraph.start from
        # RuntimeConfig.sched_lease.  None (the default) = ungated.
        self.sched_lease = None
        # sampled service-time observation: stride 1 for the first 64
        # samples, then 1/16 -- tracing must not cost a perf_counter
        # pair per tuple on the hot path
        self._obs_left = 1
        # telemetry plane (telemetry/; docs/OBSERVABILITY.md): the
        # graph TelemetryHub (None = tracing off -> zero per-item
        # stamping), a TraceSampler on source nodes, the builder's
        # per-source sample-period override, the graph FlightRecorder,
        # and the context of the traced item currently inside svc (so
        # emissions it produces inherit the trace)
        self.telemetry = None
        self.trace_sampler = None
        self.trace_sample = None
        self.flight = None
        self._live_trace = None
        self._terminal = False    # no outlets: traces close here
        self._fused = False       # FusedLogic: segments stamp their hops
        self._hop_rec = None      # record taking residency observations
        self._e2e_rec = None      # record taking e2e closures
        # outlet-level put faults (drop_put/dup_put): resolved once per
        # thread in run(); forces the per-item emission fallback
        self._outlet_put_faults = False
        # durability plane (durability/; docs/RESILIENCE.md): the graph
        # EpochCoordinator (None = epochs off -> zero per-item cost),
        # the per-consumer barrier aligner, and the barrier counters
        # the ledger's graph-wide roll-up subtracts (per-edge books
        # count barriers symmetrically; the sources/sinks totals must
        # not)
        self.epoch_coord = None
        self.epochs = None
        self.epoch_barriers_in = 0
        self.epoch_barriers_out = 0
        # event-time plane (eventtime/; docs/EVENTTIME.md): per-producer
        # watermark maxima, the min-merged watermark last forwarded, the
        # logic's resolved on_watermark hook, and the control-item
        # counters the ledger's graph-wide roll-up subtracts (exactly
        # like the epoch-barrier pair above).  The per-producer map is
        # deliberately NOT checkpointed: watermarks regenerate from the
        # replayed data and the merge is monotone from -inf.
        self._wm_chan: dict = {}
        self._wm_out_ts = float("-inf")
        self._wm_hook = None
        # supervised replica self-healing (durability/supervision.py):
        # the graph ReplicaSupervisor and this replica's group key,
        # bound at start for .with_restartable() stages under
        # RuntimeConfig.supervision.  An accepted crash exits WITHOUT
        # the svc_end/flush_eos teardown -- the rebuilt replica reuses
        # this node's outlets, so their producer slots must stay open
        self.supervisor = None
        self.supervised_group = None
        self._supervised_handoff = False
        self.watermarks_in = 0
        self.watermarks_out = 0
        self._accepts_chunks = False  # resolved per thread (durable path)
        self._sync_emit = True

    def bind_outlet_faults(self) -> None:
        """Propagate put-level fault state (FaultPlan drop_put /
        dup_put) to the Outlet layer, where channel deliveries happen.
        Fused nodes bind the LAST segment's faults -- the operator
        whose emissions actually cross the channel.  Called by
        PipeGraph.start and the elastic rescale after per-node fault
        binding; independent of the audit plane, so an injected
        transport fault fires with or without the ledger books."""
        f = self.faults
        if isinstance(self.logic, FusedLogic):
            f = self.logic.segments[-1].faults
        if f is not None and f.put_rules:
            for o in self.outlets:
                o.faults = f

    def _emit(self, item: Any) -> None:
        if isinstance(item, Watermark):
            # event-time control item leaving this node: emitters
            # broadcast it to every destination, so count one per
            # destination cell -- the same shape as the per-edge
            # delivery books the ledger subtracts it from
            self.watermarks_out += sum(o.n_destinations
                                       for o in self.outlets)
        s = self.trace_sampler
        if s is not None:         # source replica: 1-in-N trace starts
            s.maybe_attach(item)
        else:
            lt = self._live_trace
            if lt is not None:
                # a traced input's emissions inherit its context even
                # when the logic built a fresh item (window results)
                attach_if_absent(item, lt)
        if self.stats is not None:
            self.stats.outputs_sent += 1
        if self.faults is not None:
            self.faults.before_put()
        for o in self.outlets:
            o.send(item)

    def _svc_guarded(self, item: Any, cid: int) -> None:
        """One svc call under this node's error policy: 'fail' lets the
        exception kill the replica (and cancel the graph); 'skip' and
        'dead_letter' quarantine the offending tuple and keep going.
        GraphCancelled and non-Exception BaseExceptions always
        propagate -- a shutdown signal is not a tuple failure."""
        stats = self.stats
        try:
            if stats is not None:
                stats.inputs_received += 1
                self._obs_left -= 1
                if self._obs_left <= 0:
                    t0 = _time.perf_counter()
                    self.logic.svc(item, cid, self._emit)
                    stats.observe((_time.perf_counter() - t0) * 1e6)
                    self._obs_left = 1 if stats.samples < 64 else 16
                else:
                    self.logic.svc(item, cid, self._emit)
            else:
                self.logic.svc(item, cid, self._emit)
        except Exception as e:
            if self.error_policy == POLICY_FAIL:
                raise
            if stats is not None:
                stats.svc_failures += 1
            if self.flight is not None:
                self.flight.record("svc_failure", node=self.name,
                                   error=repr(e))
            if self.error_policy == POLICY_DEAD_LETTER \
                    and self.dead_letters is not None:
                self.dead_letters.add(self.name, item, e)

    def _flush_emits(self, buf) -> None:
        """Deliver a buffered emission run as grouped bulk channel
        transfers.  Under a bound FaultPlan, fall back to the per-item
        path: a put-targeted fault must interleave its clock with the
        actual deliveries (crash at tick k delivers exactly the k-1
        item prefix, as at LEVEL0) -- batching the ticks ahead of the
        sends would lose the whole batch instead.  Outlet-level put
        faults (drop_put/dup_put, bound per outlet even when the node
        itself carries none -- fused nodes) force the same fallback so
        the per-delivery fault clock stays exact."""
        if self.faults is not None or self._outlet_put_faults:
            for item in buf:
                self._emit(item)
            return
        if self.stats is not None:
            self.stats.outputs_sent += len(buf)
        for o in self.outlets:
            o.send_many(buf)

    def _svc_batch(self, got, accepts_chunks: bool, faults, pool) -> None:
        """Process one get_many batch with buffered emissions: outputs
        accumulate in a list and leave in grouped bulk puts afterwards
        (only for logics whose ``sync_emit`` contract holds).  Error
        policies, fault clocks and drain accounting match the per-item
        loop; ``done`` advances only after the flush so the quiesce
        barrier never sees buffered emissions as drained."""
        buf: list = []
        append = buf.append
        stats = self.stats
        svc = self.logic.svc
        tele = self.telemetry
        processed = 0
        t0 = _time.perf_counter() if stats is not None else 0.0
        try:
            for cid, item in got:
                if isinstance(item, Watermark):
                    # buffered path: hook emissions and the forwarded
                    # watermark ride the SAME buffer, so per-destination
                    # order relative to surrounding data is preserved
                    self._handle_watermark(cid, item, append)
                    continue
                if not accepts_chunks and isinstance(item, SynthChunk):
                    item = item.materialize(pool)  # plane boundary
                self.taken += 1
                processed += 1
                if faults is not None:
                    faults.on_tuple(self.taken)  # may raise
                if stats is not None:
                    stats.inputs_received += 1
                ctx = None if tele is None else getattr(item, "trace",
                                                        None)
                if ctx is None:
                    out_cb = append
                else:
                    t_in = _time.perf_counter()
                    rec = self._hop_rec
                    if rec is not None and rec.residency_hist is not None:
                        rec.residency_hist.observe(
                            (t_in - ctx.last) * 1e6)

                    def out_cb(x, _c=ctx):   # emissions inherit ctx
                        attach_if_absent(x, _c)
                        append(x)
                try:
                    svc(item, cid, out_cb)
                except Exception as e:
                    if self.error_policy == POLICY_FAIL:
                        raise
                    if stats is not None:
                        stats.svc_failures += 1
                    if self.flight is not None:
                        self.flight.record("svc_failure", node=self.name,
                                           error=repr(e))
                    if self.error_policy == POLICY_DEAD_LETTER \
                            and self.dead_letters is not None:
                        self.dead_letters.add(self.name, item, e)
                if ctx is not None:
                    t_done = _time.perf_counter()
                    if not self._fused:
                        # fused nodes stamp per-SEGMENT hops inline and
                        # close traces in their last segment's entry
                        ctx.hop(self.name, t_in, t_done)
                        if self._terminal:
                            tele.close(ctx, self._e2e_rec, t_done)
        finally:
            try:
                if buf:
                    self._flush_emits(buf)
            finally:
                self.done += processed
        if stats is not None and processed:
            # one amortized observation per batch, not per tuple
            stats.observe((_time.perf_counter() - t0) * 1e6 / processed)

    def _handle_watermark(self, cid: int, wm: Watermark, emit) -> None:
        """Min-merge a watermark arriving on producer ``cid`` and, when
        the merged low-watermark advances, offer it to the logic's
        event-time hook and forward it downstream (eventtime/;
        docs/EVENTTIME.md).  Emissions the hook produces go out BEFORE
        the watermark -- per-channel FIFO then guarantees downstream
        consumers see fired results before the trigger that fired them.
        Watermarks advance no fault clock and neither ``taken`` nor
        ``done``: they are control items, invisible to the quiesce
        barrier's in-flight arithmetic (per-edge delivery books still
        count them symmetrically; the ledger's graph-wide identity
        subtracts ``watermarks_in/out`` at the sinks/sources)."""
        self.watermarks_in += 1
        m = self._wm_chan
        prev = m.get(cid)
        if prev is None or wm.ts > prev:
            m[cid] = wm.ts
        # the merged watermark is defined only once EVERY producer has
        # reported one (min over a partial view would overshoot)
        n_prod = getattr(self.channel, "n_producers", 1) or 1
        if len(m) < n_prod:
            return
        cur = min(m.values())
        if cur <= self._wm_out_ts:
            return
        self._wm_out_ts = cur
        out = wm if wm.ts == cur else Watermark(cur)
        hook = self._wm_hook
        if hook is not None:
            hook(out, emit)
        if self.outlets:
            emit(out)

    def _process_one(self, cid: int, item: Any) -> None:
        """One guarded svc call: the per-item consume body, factored
        out for the durability plane's dispatch path (barrier-aware
        routing + the aligner's held-item replay).  Must stay
        semantically identical to the inline loop below -- the inline
        copy exists so the epochs-off hot path pays no extra call."""
        if isinstance(item, Watermark):
            self._handle_watermark(cid, item, self._emit)
            return
        if not self._accepts_chunks and isinstance(item, SynthChunk):
            item = item.materialize(self.pool)  # plane boundary
        self.taken += 1
        if self.faults is not None:
            self.faults.on_tuple(self.taken)  # may raise InjectedFailure
        tele = self.telemetry
        ctx = None if tele is None else getattr(item, "trace", None)
        if ctx is not None:
            t_in = _time.perf_counter()
            rec = self._hop_rec
            if rec is not None and rec.residency_hist is not None:
                rec.residency_hist.observe((t_in - ctx.last) * 1e6)
            if self._sync_emit:
                self._live_trace = ctx
        try:
            self._svc_guarded(item, cid)
        finally:
            self.done += 1
            if ctx is not None:
                self._live_trace = None
                t_done = _time.perf_counter()
                if not self._fused:
                    ctx.hop(self.name, t_in, t_done)
                    if self._terminal:
                        tele.close(ctx, self._e2e_rec, t_done)

    def _consume_loop(self) -> None:
        # logics with an idle_tick hook (time-bounded device launches on
        # stalled streams) take timed gets so the tick fires without input
        tick = getattr(self.logic, "idle_tick", None)
        accepts_chunks = getattr(self.logic, "accepts_synth_chunks", False)
        faults = self.faults
        channel = self.channel
        pool = self.pool
        get_many = getattr(channel, "get_many", None)
        # buffered emissions require the logic's emits to happen inside
        # the svc call (sync_emit); the async window engines opt out.
        # The durability plane opts out too: the epoch cut must emit
        # (fence results, forward the barrier) in stream order, which
        # buffered emission runs would reorder around the barrier.
        sync_emit = getattr(self.logic, "sync_emit", True)
        aligner = self.epochs
        buffered = get_many is not None and sync_emit and aligner is None
        tele = self.telemetry
        # event-time hook resolved once per thread (None on logics
        # without it -- watermarks then just merge-and-forward)
        self._wm_hook = getattr(self.logic, "on_watermark", None)
        self._accepts_chunks = accepts_chunks
        self._sync_emit = sync_emit
        # fair-share gate resolved once per thread: a lease-less graph
        # (the default) pays a single None check per batch
        lease = self.sched_lease
        stats = self.stats
        timeout = 0.025 if tick else None
        while True:
            if get_many is not None:
                got = get_many(GET_MANY_MAX, timeout)
            else:  # duck-typed channel without the bulk surface
                got = channel.get(timeout) if tick else channel.get()
                if isinstance(got, tuple):
                    got = [got]
            if got is CHANNEL_TIMEOUT:
                if not (self.pause_ctl is not None
                        and self.pause_ctl.pausing):
                    tick(self._emit)
                continue
            if got is None:
                break
            if lease is not None:
                # weighted fair share across co-resident tenants:
                # charge the batch, block while over-share (solo
                # tenants never wait -- scheduler/leases.py)
                waited = lease.acquire(len(got))
                if waited and stats is not None:
                    stats.sched_wait_s += waited
            if buffered and len(got) > 1:
                self._svc_batch(got, accepts_chunks, faults, pool)
                continue
            if aligner is not None:
                # durable dispatch: barriers route to the aligner
                # (alignment, epoch cut, holdback replay); everything
                # else takes the factored per-item body
                process = self._process_one
                for cid, item in got:
                    if not aligner.offer(cid, item, process):
                        process(cid, item)
                continue
            for cid, item in got:
                if isinstance(item, Watermark):
                    self._handle_watermark(cid, item, self._emit)
                    continue
                if not accepts_chunks and isinstance(item, SynthChunk):
                    item = item.materialize(pool)  # plane boundary
                self.taken += 1
                if faults is not None:
                    faults.on_tuple(self.taken)  # may raise InjectedFailure
                ctx = None if tele is None else getattr(item, "trace",
                                                        None)
                if ctx is not None:
                    t_in = _time.perf_counter()
                    rec = self._hop_rec
                    if rec is not None and rec.residency_hist is not None:
                        rec.residency_hist.observe(
                            (t_in - ctx.last) * 1e6)
                    if sync_emit:
                        # same-thread inheritance only: an async-
                        # emitting logic's dispatcher thread calls
                        # _emit concurrently and must not pick up the
                        # consume thread's in-flight context (the
                        # engine carries its own across the dispatcher)
                        self._live_trace = ctx
                try:
                    self._svc_guarded(item, cid)
                finally:
                    # count failed tuples as done too: the quiesce
                    # barrier's in-flight detection must not see a
                    # skipped tuple as forever in flight
                    self.done += 1
                    if ctx is not None:
                        self._live_trace = None
                        t_done = _time.perf_counter()
                        if not self._fused:
                            # fused nodes stamp per-SEGMENT hops inline
                            # and close traces in their last segment
                            ctx.hop(self.name, t_in, t_done)
                            if self._terminal:
                                tele.close(ctx, self._e2e_rec, t_done)

    def run(self) -> None:
        try:
            # logics that track device metrics (launches, staged bytes)
            # write them into the replica's record directly
            self.logic.stats = self.stats
            # telemetry wiring resolved once per thread, not per item:
            # fused nodes attribute residency to their first segment and
            # e2e closures to their last (per-segment records)
            self._fused = isinstance(self.logic, FusedLogic)
            if self._fused:
                # segments observe residency and close traces in their
                # own entries -- the consume loops must NOT observe too
                # (it would double-count every traced arrival)
                self._hop_rec = self._e2e_rec = None
            else:
                self._hop_rec = self._e2e_rec = self.stats
            self._terminal = self.telemetry is not None \
                and not self.outlets
            self._outlet_put_faults = any(o.faults is not None
                                          for o in self.outlets)
            if self._fused:
                self.logic.closes_traces = self._terminal
            self.logic.svc_init()
            if self.channel is not None:
                self._consume_loop()
            self.logic.eos_flush(self._emit)
            if self.epoch_coord is not None:
                # durability plane: hand the coordinator this replica's
                # final state (it backfills epochs this node will never
                # cut for) and tell downstream aligners no further
                # barriers come from here -- BEFORE flush_eos closes
                # the producer slots
                from ..durability.barrier import (broadcast_final,
                                                  capture_states)
                self.epoch_coord.node_finished(self.name,
                                               capture_states(self))
                broadcast_final(self)
            if self.stats is not None:
                self.stats.set_terminated()
            term = getattr(self.logic, "set_segments_terminated", None)
            if term is not None:  # fused node: per-segment records
                term()
        except GraphCancelled:
            self.cancelled = True  # clean unwind, not a failure
        except BaseException as e:  # surfaced by PipeGraph.wait_end
            if self.supervisor is not None and isinstance(e, Exception) \
                    and self.supervisor.report_failure(self, e):
                # supervised replica (durability/supervision.py): the
                # supervisor rebuilds this replica in place from the
                # last committed epoch -- no error, no graph cancel,
                # and no teardown (the flag below skips the finally
                # block: the rebuilt node reuses these outlets, so
                # svc_end/flush_eos must not close their producer
                # slots downstream)
                self._supervised_handoff = True
            else:
                self.error = e
                traceback.print_exc()
                # poison every channel of the graph so blocked peers
                # unwind instead of deadlocking on this dead replica's
                # channel
                if self.cancel_token is not None:
                    self.cancel_token.cancel(e, origin=self.name)
        finally:
            if not self._supervised_handoff:
                # svc_end BEFORE closing outlets: teardown hooks (e.g.
                # the device dispatcher abort) must stop emitting before
                # the EOS sentinel is enqueued downstream
                try:
                    self.logic.svc_end()
                except GraphCancelled:
                    self.cancelled = True
                except BaseException as e:
                    if self.error is None:
                        self.error = e
                        if self.cancel_token is not None:
                            self.cancel_token.cancel(e, origin=self.name)
                    traceback.print_exc()
                try:
                    for o in self.outlets:
                        o.flush_eos()
                except GraphCancelled:
                    # downstream already poisoned: nobody is listening
                    self.cancelled = True


class SourceLoopLogic(NodeLogic):
    """Drives a generation function with no input channel: the function
    is called until it returns False (reference source.hpp:175-252).

    ``pause_control`` (a SourcePauseControl, attached by
    PipeGraph.start) gates every generation step so a live checkpoint
    can halt production at a step boundary.  ``epoch_injector``
    (durability/barrier.py, attached by the EpochCoordinator) injects
    aligned epoch barriers at the same boundaries -- BEFORE the pause
    gate, so an epoch held open can never deadlock against a parked
    source (PipeGraph.quiesce drains epochs before pausing).
    ``cancel_token`` (attached by PipeGraph.start) is checked at the
    same boundary: an unfused source learns of cancellation from its
    poisoned outlet channel, but a FULLY fused source->...->sink chain
    owns no channel at all, so without this check its replica thread
    would spin forever after cancel() -- the exact leak the serving
    plane's lifecycle census caught (repeated submit/evict of an
    endless fused tenant stranded one thread per cycle)."""

    pause_control = None
    epoch_injector = None
    cancel_token = None

    def __init__(self, step: Callable[[Callable[[Any], None]], bool]):
        self.step = step

    def svc(self, item, channel_id, emit):  # pragma: no cover
        raise RuntimeError("source has no inputs")

    def eos_flush(self, emit):
        while True:
            tok = self.cancel_token
            if tok is not None and tok.cancelled:
                raise GraphCancelled("source cancelled")
            inj = self.epoch_injector
            if inj is not None:
                inj.maybe_inject()
            ctl = self.pause_control
            if ctl is not None:
                ctl.gate()
            if not self.step(emit):
                break
