"""Runtime nodes: the threaded executors of the host plane.

The reference makes every operator replica an ``ff_node`` with a
``svc()`` called per queue item (SURVEY.md §3.2).  windflow_tpu splits
that into a passive **NodeLogic** (the operator semantics: svc /
eos_flush / svc_end) and an active **RtNode** thread owning the input
channel and an **Outlet** (emitter + destination channels).  This keeps
operator logic runtime-agnostic: the same logic objects are driven by
Python threads here and by the native C++ executor when built.
"""
from __future__ import annotations

import threading
import time as _time
import traceback
from typing import Any, Callable, Optional, Sequence

from ..core.tuples import SynthChunk
from ..resilience.cancel import GraphCancelled
from ..resilience.policies import POLICY_DEAD_LETTER, POLICY_FAIL
from .queues import Channel, CHANNEL_TIMEOUT


class EOSMarker:
    """A tuple travelling as an EOS marker (reference wraps the per-key
    last tuple with an eos flag, meta.hpp:770-783 + wf_nodes.hpp:207-227):
    it updates window triggering state downstream but carries no data."""

    __slots__ = ("record",)

    def __init__(self, record: Any):
        self.record = record


class NodeLogic:
    """Base class for operator replica logic."""

    stats = None  # replica StatsRecord, attached by RtNode under tracing

    def svc_init(self) -> None:
        pass

    def svc(self, item: Any, channel_id: int, emit: Callable[[Any], None]) -> None:
        raise NotImplementedError

    def eos_flush(self, emit: Callable[[Any], None]) -> None:
        """Called once when all input producers reached EOS (the
        ``eosnotify`` cascade, e.g. win_seq.hpp:514-579)."""

    def svc_end(self) -> None:
        pass

    # -- checkpoint hooks (utils/checkpoint.py; absent in the reference,
    # SURVEY.md §5 "Checkpoint / resume") ---------------------------------
    def state_dict(self):
        """Picklable snapshot of this replica's state; None = stateless."""
        return None

    def load_state(self, state) -> None:
        raise NotImplementedError(f"{type(self).__name__} is stateless")


class ChainedLogic(NodeLogic):
    """Thread fusion of two logics: b consumes a's emissions inline
    (the reference's combine_with_laststage, multipipe.hpp:381, and the
    ff_comb PLQ/WLQ fusion of optimize_PaneFarm, pane_farm.hpp:222-250)."""

    def __init__(self, a: NodeLogic, b: NodeLogic):
        self.a = a
        self.b = b
        # the chain accepts synth-chunk descriptors iff its first half
        # does (the runtime materializes them otherwise)
        self.accepts_synth_chunks = getattr(a, "accepts_synth_chunks",
                                            False)
        # delegate idle ticks only when a half defines them: RtNode
        # probes hasattr, and unconditional definition would put every
        # fused map chain on timed gets for nothing
        if hasattr(a, "idle_tick") or hasattr(b, "idle_tick"):
            self.idle_tick = self._idle_tick

    def _idle_tick(self, emit):
        ta = getattr(self.a, "idle_tick", None)
        if ta is not None:
            ta(lambda x: self.b.svc(x, 0, emit))
        tb = getattr(self.b, "idle_tick", None)
        if tb is not None:
            tb(emit)

    def svc_init(self):
        # the RtNode attaches the replica StatsRecord to the OUTER
        # logic only; forward it so fused stages report device metrics
        self.a.stats = self.stats
        self.b.stats = self.stats
        self.a.svc_init()
        self.b.svc_init()

    def svc(self, item, channel_id, emit):
        self.a.svc(item, channel_id,
                   lambda x: self.b.svc(x, 0, emit))

    def eos_flush(self, emit):
        self.a.eos_flush(lambda x: self.b.svc(x, 0, emit))
        self.b.eos_flush(emit)

    def svc_end(self):
        self.a.svc_end()
        self.b.svc_end()

    def quiesce(self, emit) -> bool:
        """Live-barrier hook: drain both halves' in-flight device work
        (a's emissions feed b inline, exactly like svc)."""
        emitted = False
        qa = getattr(self.a, "quiesce", None)
        if qa is not None:
            emitted = bool(qa(lambda x: self.b.svc(x, 0, emit)))
        qb = getattr(self.b, "quiesce", None)
        if qb is not None:
            emitted = bool(qb(emit)) or emitted
        return emitted

    # -- checkpoint: delegate to both halves ---------------------------
    def state_dict(self):
        sa, sb = self.a.state_dict(), self.b.state_dict()
        if sa is None and sb is None:
            return None
        return {"a": sa, "b": sb}

    def load_state(self, state):
        if state.get("a") is not None:
            self.a.load_state(state["a"])
        if state.get("b") is not None:
            self.b.load_state(state["b"])


class Outlet:
    """Output side of a node: an emitter routing items to destination
    channels.  ``dests`` is a list of (channel, producer_id)."""

    __slots__ = ("emitter", "dests")

    def __init__(self, emitter, dests: Sequence):
        self.emitter = emitter
        self.dests = list(dests)

    @property
    def n_destinations(self) -> int:
        return len(self.dests)

    def send_to(self, dest_idx: int, item: Any) -> None:
        ch, pid = self.dests[dest_idx]
        ch.put(pid, item)

    def send(self, item: Any) -> None:
        if len(self.dests) > 1 and isinstance(item, SynthChunk):
            # routing emitters read key/id columns: materialize the
            # descriptor before fan-out (single-destination outlets
            # pass it through; the consuming node decides there)
            item = item.materialize()
        self.emitter.emit(item, self.send_to)

    def flush_eos(self) -> None:
        """Let the emitter publish trailing items (e.g. WF per-key EOS
        markers), then close every destination once."""
        self.emitter.eos(self.send_to)
        for ch, pid in self.dests:
            ch.close(pid)


class SourcePauseControl:
    """Cooperative source pause: the live-checkpoint barrier's first
    phase.  Sources call ``gate()`` between generation steps; while a
    pause is requested they ack and block until ``resume()``."""

    def __init__(self):
        self._cond = threading.Condition()
        self.pausing = False
        self.paused_count = 0

    def gate(self) -> None:
        with self._cond:
            if not self.pausing:
                return
            self.paused_count += 1
            self._cond.notify_all()
            while self.pausing:
                self._cond.wait()
            self.paused_count -= 1
            self._cond.notify_all()

    def request_pause(self) -> None:
        with self._cond:
            self.pausing = True

    def resume(self) -> None:
        with self._cond:
            self.pausing = False
            self._cond.notify_all()


class RtNode(threading.Thread):
    """One operator replica = one host thread (FastFlow analogue; thread
    count report mirrors pipegraph.hpp:610-612)."""

    def __init__(self, name: str, logic: NodeLogic, channel: Optional[Channel],
                 outlets: Sequence[Outlet]):
        super().__init__(name=name, daemon=True)
        self.logic = logic
        self.channel = channel
        self.outlets = list(outlets)
        self.error: Optional[BaseException] = None
        self.cancelled = False  # unwound by graph cancellation, no error
        self.stats = None  # StatsRecord when tracing is enabled
        self.group = None  # complex-nesting group id (multipipe grouping)
        # drain detection for the live-checkpoint barrier: an item is
        # in flight while taken != done
        self.taken = 0
        self.done = 0
        # the graph's SourcePauseControl (attached at start): idle
        # ticks must not fire while a live-checkpoint barrier is
        # pausing -- any launch they start strictly precedes a barrier
        # drain pass only if no NEW ticks begin after the pause request
        self.pause_ctl = None
        # failure containment (attached by PipeGraph.start): the graph
        # CancelToken, this operator's error policy, the graph
        # dead-letter store, and any bound fault-injection state
        self.cancel_token = None
        self.error_policy = POLICY_FAIL
        self.dead_letters = None
        self.faults = None

    def _emit(self, item: Any) -> None:
        if self.stats is not None:
            self.stats.outputs_sent += 1
        if self.faults is not None:
            self.faults.before_put()
        for o in self.outlets:
            o.send(item)

    def _svc_guarded(self, item: Any, cid: int) -> None:
        """One svc call under this node's error policy: 'fail' lets the
        exception kill the replica (and cancel the graph); 'skip' and
        'dead_letter' quarantine the offending tuple and keep going.
        GraphCancelled and non-Exception BaseExceptions always
        propagate -- a shutdown signal is not a tuple failure."""
        stats = self.stats
        try:
            if stats is not None:
                stats.inputs_received += 1
                t0 = _time.perf_counter()
                self.logic.svc(item, cid, self._emit)
                stats.observe((_time.perf_counter() - t0) * 1e6)
            else:
                self.logic.svc(item, cid, self._emit)
        except Exception as e:
            if self.error_policy == POLICY_FAIL:
                raise
            if stats is not None:
                stats.svc_failures += 1
            if self.error_policy == POLICY_DEAD_LETTER \
                    and self.dead_letters is not None:
                self.dead_letters.add(self.name, item, e)

    def _consume_loop(self) -> None:
        # logics with an idle_tick hook (time-bounded device launches on
        # stalled streams) take timed gets so the tick fires without input
        tick = getattr(self.logic, "idle_tick", None)
        accepts_chunks = getattr(self.logic, "accepts_synth_chunks", False)
        faults = self.faults
        channel = self.channel
        while True:
            got = (channel.get(timeout=0.025) if tick else channel.get())
            if got is CHANNEL_TIMEOUT:
                if not (self.pause_ctl is not None
                        and self.pause_ctl.pausing):
                    tick(self._emit)
                continue
            if got is None:
                break
            cid, item = got
            if not accepts_chunks and isinstance(item, SynthChunk):
                item = item.materialize()  # plane boundary
            self.taken += 1
            if faults is not None:
                faults.on_tuple(self.taken)  # may raise InjectedFailure
            try:
                self._svc_guarded(item, cid)
            finally:
                # count failed tuples as done too: the quiesce barrier's
                # in-flight detection must not see a skipped tuple as
                # forever in flight
                self.done += 1

    def run(self) -> None:
        try:
            # logics that track device metrics (launches, staged bytes)
            # write them into the replica's record directly
            self.logic.stats = self.stats
            self.logic.svc_init()
            if self.channel is not None:
                self._consume_loop()
            self.logic.eos_flush(self._emit)
            if self.stats is not None:
                self.stats.set_terminated()
        except GraphCancelled:
            self.cancelled = True  # clean unwind, not a failure
        except BaseException as e:  # surfaced by PipeGraph.wait_end
            self.error = e
            traceback.print_exc()
            # poison every channel of the graph so blocked peers unwind
            # instead of deadlocking on this dead replica's channel
            if self.cancel_token is not None:
                self.cancel_token.cancel(e, origin=self.name)
        finally:
            # svc_end BEFORE closing outlets: teardown hooks (e.g. the
            # device dispatcher abort) must stop emitting before the EOS
            # sentinel is enqueued downstream
            try:
                self.logic.svc_end()
            except GraphCancelled:
                self.cancelled = True
            except BaseException as e:
                if self.error is None:
                    self.error = e
                    if self.cancel_token is not None:
                        self.cancel_token.cancel(e, origin=self.name)
                traceback.print_exc()
            try:
                for o in self.outlets:
                    o.flush_eos()
            except GraphCancelled:
                # downstream already poisoned: nobody is listening
                self.cancelled = True


class SourceLoopLogic(NodeLogic):
    """Drives a generation function with no input channel: the function
    is called until it returns False (reference source.hpp:175-252).

    ``pause_control`` (a SourcePauseControl, attached by
    PipeGraph.start) gates every generation step so a live checkpoint
    can halt production at a step boundary."""

    pause_control = None

    def __init__(self, step: Callable[[Callable[[Any], None]], bool]):
        self.step = step

    def svc(self, item, channel_id, emit):  # pragma: no cover
        raise RuntimeError("source has no inputs")

    def eos_flush(self, emit):
        while True:
            ctl = self.pause_control
            if ctl is not None:
                ctl.gate()
            if not self.step(emit):
                break
