"""Ordering + K-slack collectors (the DETERMINISTIC / PROBABILISTIC plane).

Re-designs of reference ``wf/ordering_node.hpp`` (watermark-by-min
priority queues, :121-193; EOS flush :196-281) and ``wf/kslack_node.hpp``
(adaptive K-slack buffering :93-139, late drops :193-200).

Both collectors speak BOTH planes: records ride per-item priority
queues like the reference; ``TupleBatch`` items ride a columnar lane
(per-channel row buffers, one vectorized sort-merge per emission) so
the batch plane runs under DETERMINISTIC/PROBABILISTIC modes too --
something the record-at-a-time reference has no analogue for.
"""
from __future__ import annotations

import bisect
import heapq
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.basic import OrderingMode
from ..core.tuples import TupleBatch
from .node import EOSMarker, NodeLogic


class _ColumnarMerge:
    """Per-channel columnar buffers merged by a watermark-by-min over
    the order field: rows at or below the smallest per-channel maximum
    are safe to emit in sorted order (ordering_node.hpp:121-193 at
    batch granularity)."""

    __slots__ = ("field", "n_channels", "parts", "maxs")

    def __init__(self, field: str, n_channels: int):
        self.field = field
        self.n_channels = n_channels
        self.parts: List[List[TupleBatch]] = [[] for _ in range(n_channels)]
        self.maxs = [-1] * n_channels

    def push(self, batch: TupleBatch, channel_id: int):
        f = batch[self.field] if self.field == "ts" else batch.id
        if len(f) > 1 and not np.all(f[:-1] <= f[1:]):
            batch = batch.take(np.argsort(f, kind="stable"))
        self.parts[channel_id].append(batch)
        if len(f):
            self.maxs[channel_id] = max(self.maxs[channel_id],
                                        int(f.max()))

    def _field_of(self, b: TupleBatch):
        return b[self.field] if self.field == "ts" else b.id

    def drain(self, watermark: Optional[int] = None):
        """Merged rows with field <= watermark (None = everything),
        sorted by the order field; remainder stays buffered."""
        ready = []
        for ch in range(self.n_channels):
            kept = []
            for b in self.parts[ch]:
                f = self._field_of(b)
                if watermark is None:
                    ready.append(b)
                    continue
                cut = int(np.searchsorted(f, watermark, "right"))
                if cut:
                    ready.append(b.take(slice(0, cut)))
                if cut < len(f):
                    kept.append(b.take(slice(cut, len(f))))
            self.parts[ch] = kept
        if not ready:
            return None
        if len(ready) > 1:
            merged = TupleBatch({k: np.concatenate([b.cols[k]
                                                    for b in ready])
                                 for k in ready[0].cols})
        else:
            merged = ready[0]
        f = self._field_of(merged)
        if len(f) > 1 and not np.all(f[:-1] <= f[1:]):
            merged = merged.take(np.argsort(f, kind="stable"))
        return merged

    def watermark(self) -> int:
        return min(self.maxs)


def _check_plane(logic, plane: str) -> None:
    """The record queues and the columnar buffers are independent
    orderings; interleaving them would silently break the global order,
    so a collector serves exactly one plane per stream."""
    cur = getattr(logic, "_plane", None)
    if cur is None:
        logic._plane = plane
    elif cur != plane:
        raise RuntimeError(
            "mixed record/batch streams through one ordering collector "
            "are unsupported; materialize one plane before the "
            "DETERMINISTIC/PROBABILISTIC stage")


def _renumber_columnar(batch: TupleBatch, get_counter, bump_counter):
    """Per-key dense ids in emitted order (columnar twin of the
    TS_RENUMBERING record path, shared by both collectors)."""
    keys = batch.key
    new_ids = np.empty(len(keys), np.int64)
    order = np.argsort(keys, kind="stable")  # keeps ts order per key
    keys_s = keys[order]
    edges = np.nonzero(np.diff(keys_s))[0] + 1
    bounds = np.concatenate([[0], edges, [len(keys_s)]])
    for j in range(len(bounds) - 1):
        lo, hi = int(bounds[j]), int(bounds[j + 1])
        key = keys_s[lo].item()
        c = get_counter(key)
        new_ids[order[lo:hi]] = np.arange(c, c + (hi - lo))
        bump_counter(key, c + (hi - lo))
    return batch.with_cols(id=new_ids)


class _KeyState:
    __slots__ = ("maxs", "heap", "eos_marker", "emit_counter")

    def __init__(self, n_channels: int):
        self.maxs = [0] * n_channels
        self.heap: List = []
        self.eos_marker: Optional[EOSMarker] = None
        self.emit_counter = 0


class OrderingLogic(NodeLogic):
    """DETERMINISTIC-mode collector: buffers items in priority queues and
    releases them once their id/ts is covered by the watermark = min of
    per-channel maxima (ordering_node.hpp:121-193).

    mode ID             -- per-key queues ordered by tuple id.
    mode TS             -- one global queue ordered by timestamp.
    mode TS_RENUMBERING -- TS ordering + per-key dense re-assignment of
                           ids on emission (used for CB windows inside
                           complex nestings, multipipe.hpp:1039-1051).
    """

    def __init__(self, mode: OrderingMode, n_channels: int):
        self.mode = mode
        self.n_channels = n_channels
        self.keys: Dict[Any, _KeyState] = {}
        self.global_heap: List = []
        self.global_maxs = [0] * n_channels
        self._cmerge: Optional[_ColumnarMerge] = None  # batch lane
        # unique tiebreaker (ptr compare in ref); a plain int, not
        # itertools.count, so collector state pickles for the live
        # checkpoint barrier
        self._seq = 0

    # -- columnar lane -----------------------------------------------------
    def _svc_batch(self, batch: TupleBatch, channel_id: int, emit):
        if self.mode == OrderingMode.ID:
            # ID ordering is per-key dense-id arithmetic; the columnar
            # lane is timestamp-based, so degrade this batch to the
            # record plane (slow but correct -- CB batch streams in
            # DETERMINISTIC mode are an edge, not the hot path; bypasses
            # the plane guard, which tracks the USER-facing item type)
            for rec in batch.records():
                self._svc_record(rec, channel_id, emit)
            return
        if self._cmerge is None:
            self._cmerge = _ColumnarMerge("ts", self.n_channels)
        self._cmerge.push(batch, channel_id)
        wm = self._cmerge.watermark()
        if wm >= 0:
            out = self._cmerge.drain(wm)
            if out is not None and len(out):
                emit(self._renumber_batch(out))

    def _renumber_batch(self, batch: TupleBatch) -> TupleBatch:
        """TS_RENUMBERING: per-key dense ids in emitted (ts) order --
        the columnar twin of _emit_rec's per-record renumbering."""
        if self.mode != OrderingMode.TS_RENUMBERING:
            return batch

        def get(key):
            return self._key_state(key).emit_counter

        def bump(key, c):
            self._key_state(key).emit_counter = c

        return _renumber_columnar(batch, get, bump)

    def _key_state(self, key) -> _KeyState:
        st = self.keys.get(key)
        if st is None:
            st = self.keys[key] = _KeyState(self.n_channels)
        return st

    def _order_field(self, rec) -> int:
        k, tid, ts = rec.get_control_fields()
        return tid if self.mode == OrderingMode.ID else ts

    def _emit_rec(self, rec, emit, is_marker=False):
        if self.mode == OrderingMode.TS_RENUMBERING:
            # renumber a COPY: under the CB broadcast plane every
            # replica's collector receives the SAME record object
            # (BroadcastEmitter's immutability contract), and each
            # assigns its own dense id
            import copy
            rec = copy.copy(rec)
            key = rec.get_control_fields()[0]
            st = self._key_state(key)
            rec.set_control_fields(key, st.emit_counter,
                                   rec.get_control_fields()[2])
            st.emit_counter += 1
        emit(EOSMarker(rec) if is_marker else rec)

    def svc(self, item, channel_id, emit):
        if isinstance(item, TupleBatch):
            _check_plane(self, "batch")
            self._svc_batch(item, channel_id, emit)
            return
        if not isinstance(item, EOSMarker):
            # EOS markers are plane-neutral: batch streams still carry
            # per-key record markers (WFEmitter._emit_batch)
            _check_plane(self, "record")
        self._svc_record(item, channel_id, emit)

    def _svc_record(self, item, channel_id, emit):
        rec = item.record if isinstance(item, EOSMarker) else item
        key = rec.get_control_fields()[0]
        wid = self._order_field(rec)
        st = self._key_state(key)
        if isinstance(item, EOSMarker):
            # keep only the most recent EOS marker per key (:136-150)
            if st.eos_marker is None or wid > self._order_field(st.eos_marker.record):
                st.eos_marker = item
            return
        if self.mode == OrderingMode.ID:
            st.maxs[channel_id] = wid
            min_id = min(st.maxs)
            heap = st.heap
        else:
            self.global_maxs[channel_id] = wid
            min_id = min(self.global_maxs)
            heap = self.global_heap
        self._seq += 1
        heapq.heappush(heap, (wid, self._seq, rec))
        while heap and heap[0][0] <= min_id:
            _, _, nxt = heapq.heappop(heap)
            self._emit_rec(nxt, emit)

    # live-checkpoint snapshots: buffered records are part of the
    # in-flight stream and must survive a restore.  Deep copies on both
    # sides: the resumed run keeps heappop-ing the live heaps, and an
    # aliased snapshot would decay with it.
    def state_dict(self):
        import copy
        st = {"keys": copy.deepcopy(self.keys),
              "global_heap": copy.deepcopy(self.global_heap),
              "global_maxs": list(self.global_maxs), "seq": self._seq}
        if self._cmerge is not None:
            st["cmerge"] = (self._cmerge.field,
                            copy.deepcopy(self._cmerge.parts),
                            list(self._cmerge.maxs))
        return st

    def load_state(self, state):
        import copy
        self.keys = copy.deepcopy(state["keys"])
        self.global_heap = copy.deepcopy(state["global_heap"])
        self.global_maxs = list(state["global_maxs"])
        self._seq = state["seq"]
        if "cmerge" in state:
            field, parts, maxs = state["cmerge"]
            self._cmerge = _ColumnarMerge(field, len(maxs))
            self._cmerge.parts = copy.deepcopy(parts)
            self._cmerge.maxs = list(maxs)

    def eos_flush(self, emit):
        """Drain every queue in order, then re-publish the retained EOS
        markers (ordering_node.hpp:196-281)."""
        if self._cmerge is not None:
            out = self._cmerge.drain(None)
            if out is not None and len(out):
                emit(self._renumber_batch(out))
        if self.mode == OrderingMode.ID:
            for key, st in self.keys.items():
                while st.heap:
                    _, _, nxt = heapq.heappop(st.heap)
                    self._emit_rec(nxt, emit)
                if st.eos_marker is not None:
                    self._emit_rec(st.eos_marker.record, emit, is_marker=True)
        else:
            while self.global_heap:
                _, _, nxt = heapq.heappop(self.global_heap)
                self._emit_rec(nxt, emit)
            for key, st in self.keys.items():
                if st.eos_marker is not None:
                    self._emit_rec(st.eos_marker.record, emit, is_marker=True)


class LateTupleDropped(Exception):
    """Quarantine reason attached to event-time-dropped tuples: the
    tuple's timestamp fell behind the already-emitted watermark (K-slack
    late drop, kslack_node.hpp:193-200; eventtime/ allowed-lateness
    misses reuse it)."""


class KSlackLogic(NodeLogic):
    """PROBABILISTIC-mode collector: K-slack buffering with K adapted to
    the maximum observed delay; tuples older than the emitted watermark
    are dropped and counted (kslack_node.hpp:93-200).

    Drop accounting (docs/EVENTTIME.md "Late data"): beyond the exact
    ``dropped`` counter and the capped ``dropped_records`` identities,
    every drop is quarantined in the graph dead-letter store with a
    :class:`LateTupleDropped` reason and announced as a ``late_data``
    flight event -- event-time loss is loud, never a silent counter.
    ``dead_letters``/``node_name`` are bound by PipeGraph.start through
    the ``uses_dead_letters`` marker (None outside a started graph).
    """

    uses_dead_letters = True
    dead_letters = None
    node_name = "kslack"

    def __init__(self, mode: OrderingMode = OrderingMode.TS,
                 on_drop: Callable[[int], None] = None):
        assert mode != OrderingMode.ID
        self.mode = mode
        self.K = 0
        self.tcurr = 0
        self.buffer_ts: List[int] = []   # sorted timestamps
        self.buffer: List[Any] = []      # records, parallel to buffer_ts
        self.ts_sample: List[int] = []   # delays sampled since last advance
        self.last_timestamp = 0
        self.dropped = 0
        # control fields of dropped records, for exact accounting
        # oracles (each source tuple is either emitted in-order exactly
        # once or appears here).  The reference only counts
        # (kslack_node.hpp dropped_inputs); identities are retained up
        # to a cap so a long-running lossy stream cannot leak -- the
        # `dropped` counter stays exact past it
        self.dropped_records: List = []
        self.dropped_records_cap = 1 << 16
        self.on_drop = on_drop or (lambda n: None)
        self.key_counters: Dict[Any, int] = {}
        self._cbuf: Optional[_ColumnarMerge] = None  # batch lane
        self._cmin = 2**63 - 1  # min ts sampled since the last advance

    # -- columnar lane -----------------------------------------------------
    def _svc_batch(self, batch: TupleBatch, emit):
        if self._cbuf is None:
            self._cbuf = _ColumnarMerge("ts", 1)
        ts = batch.ts
        if len(ts) == 0:
            return
        self._cbuf.push(batch, 0)
        # sample EVERY batch's minimum into the delay window -- a late
        # batch (max <= tcurr) must still grow K on the next advance,
        # exactly like the record lane's ts_sample of late tuples,
        # otherwise cross-channel disorder is dropped forever
        self._cmin = min(self._cmin, int(ts.min()))
        new_max = int(ts.max())
        if new_max <= self.tcurr:
            return
        self.tcurr = new_max
        max_d = self.tcurr - self._cmin
        self._cmin = self.tcurr
        if max_d > self.K:
            self.K = max_d
        # strict `< tcurr - K` like the record lane's bisect_left cut
        out = self._cbuf.drain(self.tcurr - self.K - 1)
        if out is None or not len(out):
            return
        self._emit_batch_in_order(out, emit)

    def _emit_batch_in_order(self, out: TupleBatch, emit):
        ots = out.ts
        keep = ots >= self.last_timestamp
        n_drop = int((~keep).sum())
        if n_drop:
            self.dropped += n_drop
            d = out.take(~keep)
            room = self.dropped_records_cap - len(self.dropped_records)
            if room > 0:
                self.dropped_records.extend(
                    zip(d.key[:room].tolist(), d.id[:room].tolist(),
                        d.ts[:room].tolist()))
            self.on_drop(n_drop)
            self._quarantine(d, n_drop)
            out = out.take(keep)
        if not len(out):
            return
        self.last_timestamp = int(out.ts[-1])
        if self.mode == OrderingMode.TS_RENUMBERING:
            out = _renumber_columnar(
                out, lambda k: self.key_counters.get(k, 0),
                self.key_counters.__setitem__)
        emit(out)

    def _quarantine(self, item, n: int) -> None:
        """Loud accounting for ``n`` event-time drops: one dead-letter
        entry per call (the columnar lane passes the whole dropped
        sub-batch as the sample, like ingest shedding) plus a
        ``late_data`` flight event naming the emitted watermark the
        tuples fell behind."""
        dl = self.dead_letters
        if dl is not None:
            dl.add(self.node_name, item,
                   LateTupleDropped(
                       f"event-time ts behind emitted watermark "
                       f"{self.last_timestamp}"), count=n)
        fl = self.flight
        if fl is not None:
            fl.record("late_data", node=self.node_name, n=n,
                      watermark=self.last_timestamp)

    def _emit_in_order(self, recs, emit):
        for rec in recs:
            ts = rec.get_control_fields()[2]
            if ts < self.last_timestamp:
                self.dropped += 1
                if len(self.dropped_records) < self.dropped_records_cap:
                    self.dropped_records.append(rec.get_control_fields())
                self.on_drop(1)
                self._quarantine(rec, 1)
                continue
            self.last_timestamp = ts
            if self.mode == OrderingMode.TS_RENUMBERING:
                import copy
                rec = copy.copy(rec)  # shared under the broadcast plane
                key = rec.get_control_fields()[0]
                c = self.key_counters.get(key, 0)
                self.key_counters[key] = c + 1
                rec.set_control_fields(key, c, ts)
            emit(rec)

    def svc(self, item, channel_id, emit):
        if isinstance(item, TupleBatch):
            _check_plane(self, "batch")
            self._svc_batch(item, emit)
            return
        if isinstance(item, EOSMarker):
            return  # plane-neutral; flush happens at EOS
        _check_plane(self, "record")
        rec = item
        ts = rec.get_control_fields()[2]
        self.ts_sample.append(ts)
        i = bisect.bisect_left(self.buffer_ts, ts)
        self.buffer_ts.insert(i, ts)
        self.buffer.insert(i, rec)
        if ts <= self.tcurr:
            return
        self.tcurr = ts
        max_d = max(self.tcurr - t for t in self.ts_sample)
        if max_d > self.K:
            self.K = max_d
        self.ts_sample.clear()
        cut = bisect.bisect_left(self.buffer_ts, self.tcurr - self.K)
        out, self.buffer = self.buffer[:cut], self.buffer[cut:]
        del self.buffer_ts[:cut]
        self._emit_in_order(out, emit)

    def state_dict(self):
        import copy
        st = {"K": self.K, "tcurr": self.tcurr,
              "buffer_ts": list(self.buffer_ts),
              "buffer": copy.deepcopy(self.buffer),
              "ts_sample": list(self.ts_sample),
              "last_timestamp": self.last_timestamp,
              "dropped": self.dropped,
              "dropped_records": list(self.dropped_records),
              "key_counters": dict(self.key_counters),
              "cmin": self._cmin}
        if self._cbuf is not None:
            st["cbuf"] = copy.deepcopy(self._cbuf.parts)
        return st

    def load_state(self, state):
        import copy
        self.K = state["K"]
        self.tcurr = state["tcurr"]
        self.buffer_ts = list(state["buffer_ts"])
        self.buffer = copy.deepcopy(state["buffer"])
        self.ts_sample = list(state["ts_sample"])
        self.last_timestamp = state["last_timestamp"]
        self.dropped = state["dropped"]
        self.dropped_records = list(state.get("dropped_records", []))
        self.key_counters = dict(state["key_counters"])
        self._cmin = state.get("cmin", 2**63 - 1)
        if "cbuf" in state:
            self._cbuf = _ColumnarMerge("ts", 1)
            self._cbuf.parts = copy.deepcopy(state["cbuf"])

    def eos_flush(self, emit):
        if self._cbuf is not None:
            out = self._cbuf.drain(None)
            if out is not None and len(out):
                self._emit_batch_in_order(out, emit)
        out, self.buffer = self.buffer, []
        self.buffer_ts.clear()
        self._emit_in_order(out, emit)
