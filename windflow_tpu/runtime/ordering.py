"""Ordering + K-slack collectors (the DETERMINISTIC / PROBABILISTIC plane).

Re-designs of reference ``wf/ordering_node.hpp`` (watermark-by-min
priority queues, :121-193; EOS flush :196-281) and ``wf/kslack_node.hpp``
(adaptive K-slack buffering :93-139, late drops :193-200).
"""
from __future__ import annotations

import bisect
import heapq
from typing import Any, Callable, Dict, List, Optional

from ..core.basic import OrderingMode
from .node import EOSMarker, NodeLogic


class _KeyState:
    __slots__ = ("maxs", "heap", "eos_marker", "emit_counter")

    def __init__(self, n_channels: int):
        self.maxs = [0] * n_channels
        self.heap: List = []
        self.eos_marker: Optional[EOSMarker] = None
        self.emit_counter = 0


class OrderingLogic(NodeLogic):
    """DETERMINISTIC-mode collector: buffers items in priority queues and
    releases them once their id/ts is covered by the watermark = min of
    per-channel maxima (ordering_node.hpp:121-193).

    mode ID             -- per-key queues ordered by tuple id.
    mode TS             -- one global queue ordered by timestamp.
    mode TS_RENUMBERING -- TS ordering + per-key dense re-assignment of
                           ids on emission (used for CB windows inside
                           complex nestings, multipipe.hpp:1039-1051).
    """

    def __init__(self, mode: OrderingMode, n_channels: int):
        self.mode = mode
        self.n_channels = n_channels
        self.keys: Dict[Any, _KeyState] = {}
        self.global_heap: List = []
        self.global_maxs = [0] * n_channels
        # unique tiebreaker (ptr compare in ref); a plain int, not
        # itertools.count, so collector state pickles for the live
        # checkpoint barrier
        self._seq = 0

    def _key_state(self, key) -> _KeyState:
        st = self.keys.get(key)
        if st is None:
            st = self.keys[key] = _KeyState(self.n_channels)
        return st

    def _order_field(self, rec) -> int:
        k, tid, ts = rec.get_control_fields()
        return tid if self.mode == OrderingMode.ID else ts

    def _emit_rec(self, rec, emit, is_marker=False):
        if self.mode == OrderingMode.TS_RENUMBERING:
            # renumber a COPY: under the CB broadcast plane every
            # replica's collector receives the SAME record object
            # (BroadcastEmitter's immutability contract), and each
            # assigns its own dense id
            import copy
            rec = copy.copy(rec)
            key = rec.get_control_fields()[0]
            st = self._key_state(key)
            rec.set_control_fields(key, st.emit_counter,
                                   rec.get_control_fields()[2])
            st.emit_counter += 1
        emit(EOSMarker(rec) if is_marker else rec)

    def svc(self, item, channel_id, emit):
        rec = item.record if isinstance(item, EOSMarker) else item
        key = rec.get_control_fields()[0]
        wid = self._order_field(rec)
        st = self._key_state(key)
        if isinstance(item, EOSMarker):
            # keep only the most recent EOS marker per key (:136-150)
            if st.eos_marker is None or wid > self._order_field(st.eos_marker.record):
                st.eos_marker = item
            return
        if self.mode == OrderingMode.ID:
            st.maxs[channel_id] = wid
            min_id = min(st.maxs)
            heap = st.heap
        else:
            self.global_maxs[channel_id] = wid
            min_id = min(self.global_maxs)
            heap = self.global_heap
        self._seq += 1
        heapq.heappush(heap, (wid, self._seq, rec))
        while heap and heap[0][0] <= min_id:
            _, _, nxt = heapq.heappop(heap)
            self._emit_rec(nxt, emit)

    # live-checkpoint snapshots: buffered records are part of the
    # in-flight stream and must survive a restore.  Deep copies on both
    # sides: the resumed run keeps heappop-ing the live heaps, and an
    # aliased snapshot would decay with it.
    def state_dict(self):
        import copy
        return {"keys": copy.deepcopy(self.keys),
                "global_heap": copy.deepcopy(self.global_heap),
                "global_maxs": list(self.global_maxs), "seq": self._seq}

    def load_state(self, state):
        import copy
        self.keys = copy.deepcopy(state["keys"])
        self.global_heap = copy.deepcopy(state["global_heap"])
        self.global_maxs = list(state["global_maxs"])
        self._seq = state["seq"]

    def eos_flush(self, emit):
        """Drain every queue in order, then re-publish the retained EOS
        markers (ordering_node.hpp:196-281)."""
        if self.mode == OrderingMode.ID:
            for key, st in self.keys.items():
                while st.heap:
                    _, _, nxt = heapq.heappop(st.heap)
                    self._emit_rec(nxt, emit)
                if st.eos_marker is not None:
                    self._emit_rec(st.eos_marker.record, emit, is_marker=True)
        else:
            while self.global_heap:
                _, _, nxt = heapq.heappop(self.global_heap)
                self._emit_rec(nxt, emit)
            for key, st in self.keys.items():
                if st.eos_marker is not None:
                    self._emit_rec(st.eos_marker.record, emit, is_marker=True)


class KSlackLogic(NodeLogic):
    """PROBABILISTIC-mode collector: K-slack buffering with K adapted to
    the maximum observed delay; tuples older than the emitted watermark
    are dropped and counted (kslack_node.hpp:93-200).
    """

    def __init__(self, mode: OrderingMode = OrderingMode.TS,
                 on_drop: Callable[[int], None] = None):
        assert mode != OrderingMode.ID
        self.mode = mode
        self.K = 0
        self.tcurr = 0
        self.buffer_ts: List[int] = []   # sorted timestamps
        self.buffer: List[Any] = []      # records, parallel to buffer_ts
        self.ts_sample: List[int] = []   # delays sampled since last advance
        self.last_timestamp = 0
        self.dropped = 0
        # control fields of dropped records, for exact accounting
        # oracles (each source tuple is either emitted in-order exactly
        # once or appears here).  The reference only counts
        # (kslack_node.hpp dropped_inputs); identities are retained up
        # to a cap so a long-running lossy stream cannot leak -- the
        # `dropped` counter stays exact past it
        self.dropped_records: List = []
        self.dropped_records_cap = 1 << 16
        self.on_drop = on_drop or (lambda n: None)
        self.key_counters: Dict[Any, int] = {}

    def _emit_in_order(self, recs, emit):
        for rec in recs:
            ts = rec.get_control_fields()[2]
            if ts < self.last_timestamp:
                self.dropped += 1
                if len(self.dropped_records) < self.dropped_records_cap:
                    self.dropped_records.append(rec.get_control_fields())
                self.on_drop(1)
                continue
            self.last_timestamp = ts
            if self.mode == OrderingMode.TS_RENUMBERING:
                import copy
                rec = copy.copy(rec)  # shared under the broadcast plane
                key = rec.get_control_fields()[0]
                c = self.key_counters.get(key, 0)
                self.key_counters[key] = c + 1
                rec.set_control_fields(key, c, ts)
            emit(rec)

    def svc(self, item, channel_id, emit):
        rec = item.record if isinstance(item, EOSMarker) else item
        ts = rec.get_control_fields()[2]
        if isinstance(item, EOSMarker):
            return  # markers carry no data; flush happens at EOS
        self.ts_sample.append(ts)
        i = bisect.bisect_left(self.buffer_ts, ts)
        self.buffer_ts.insert(i, ts)
        self.buffer.insert(i, rec)
        if ts <= self.tcurr:
            return
        self.tcurr = ts
        max_d = max(self.tcurr - t for t in self.ts_sample)
        if max_d > self.K:
            self.K = max_d
        self.ts_sample.clear()
        cut = bisect.bisect_left(self.buffer_ts, self.tcurr - self.K)
        out, self.buffer = self.buffer[:cut], self.buffer[cut:]
        del self.buffer_ts[:cut]
        self._emit_in_order(out, emit)

    def state_dict(self):
        import copy
        return {"K": self.K, "tcurr": self.tcurr,
                "buffer_ts": list(self.buffer_ts),
                "buffer": copy.deepcopy(self.buffer),
                "ts_sample": list(self.ts_sample),
                "last_timestamp": self.last_timestamp,
                "dropped": self.dropped,
                "dropped_records": list(self.dropped_records),
                "key_counters": dict(self.key_counters)}

    def load_state(self, state):
        import copy
        self.K = state["K"]
        self.tcurr = state["tcurr"]
        self.buffer_ts = list(state["buffer_ts"])
        self.buffer = copy.deepcopy(state["buffer"])
        self.ts_sample = list(state["ts_sample"])
        self.last_timestamp = state["last_timestamp"]
        self.dropped = state["dropped"]
        self.dropped_records = list(state.get("dropped_records", []))
        self.key_counters = dict(state["key_counters"])

    def eos_flush(self, emit):
        out, self.buffer = self.buffer, []
        self.buffer_ts.clear()
        self._emit_in_order(out, emit)
