"""Win_MapReduce: intra-window data parallelism.

Re-design of reference ``wf/win_mapreduce.hpp`` (1096 LoC): each
window's tuples are striped round-robin across MAP workers (WinMap
emitter, wm_nodes.hpp:62); every MAP worker runs a Win_Seq(role MAP)
over its stripe with the *same* win/slide (win_mapreduce.hpp:186-191)
and emits partials with dense striped ids (emit_counter start i, step
map_parallelism); a collector reorders partials per key; the REDUCE
stage consumes CB tumbling windows of exactly ``map_parallelism``
partials (win_mapreduce.hpp:208-221).  The ML analogue is
tensor/sequence-parallel reduction within one window (psum over the
stripe partials, SURVEY.md §2.4).
"""
from __future__ import annotations

from typing import Callable

from ..core.basic import (OptLevel, OrderingMode, Pattern, Role, RoutingMode,
                          WinOperatorConfig, WinType)
from ..core.tuples import BasicRecord
from ..runtime.emitters import StandardEmitter
from ..runtime.win_routing import WidOrderCollector, WinMapEmitter
from .base import Operator, StageSpec
from .win_farm import WinFarm
from .win_seq import WinSeqLogic


class WinMapReduce(Operator):
    def __init__(self, map_func: Callable, reduce_func: Callable,
                 win_len: int, slide_len: int, win_type: WinType,
                 map_parallelism: int = 2, reduce_parallelism: int = 1,
                 triggering_delay: int = 0, map_incremental: bool = False,
                 reduce_incremental: bool = False, name: str = "win_mr",
                 result_factory=BasicRecord, closing_func=None,
                 ordered: bool = True,
                 opt_level: OptLevel = OptLevel.LEVEL0,
                 config: WinOperatorConfig = None):
        super().__init__(name, map_parallelism + reduce_parallelism,
                         RoutingMode.COMPLEX, Pattern.WIN_MAPREDUCE)
        if win_len == 0 or slide_len == 0:
            raise ValueError("window length and slide cannot be zero")
        if map_parallelism < 1:
            raise ValueError("MAP parallelism must be >= 1")
        self.map_func = map_func
        self.reduce_func = reduce_func
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.map_parallelism = map_parallelism
        self.reduce_parallelism = reduce_parallelism
        self.triggering_delay = triggering_delay
        self.map_incremental = map_incremental
        self.reduce_incremental = reduce_incremental
        self.result_factory = result_factory
        self.closing_func = closing_func
        self.ordered = ordered
        self.opt_level = opt_level
        self.config = config or WinOperatorConfig(0, 1, slide_len,
                                                  0, 1, slide_len)

    def stages(self):
        cfg = self.config
        mp = self.map_parallelism
        stages = []
        # ---- MAP stage (win_mapreduce.hpp:180-206) ----
        map_cfg = WinOperatorConfig(cfg.id_inner, cfg.n_inner,
                                    cfg.slide_inner, 0, 1, self.slide_len)
        replicas = [WinSeqLogic(
            self.map_func, self.win_len, self.slide_len, self.win_type,
            triggering_delay=self.triggering_delay,
            incremental=self.map_incremental,
            result_factory=self.result_factory,
            closing_func=self.closing_func, config=map_cfg, role=Role.MAP,
            map_indexes=(i, mp), parallelism=mp, replica_index=i)
            for i in range(mp)]
        stages.append(StageSpec(
            f"{self.name}_map", replicas, WinMapEmitter(mp, self.win_type),
            RoutingMode.COMPLEX,
            ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                           else OrderingMode.TS),
            collector=WidOrderCollector()))
        # ---- REDUCE stage: CB tumbling windows of mp partials
        # (win_mapreduce.hpp:208-224) ----
        if self.reduce_parallelism > 1:
            red = WinFarm(self.reduce_func, mp, mp, WinType.CB,
                          self.reduce_parallelism, 0,
                          self.reduce_incremental, f"{self.name}_reduce",
                          self.result_factory, self.closing_func,
                          ordered=self.ordered, opt_level=self.opt_level,
                          config=WinOperatorConfig(
                              cfg.id_outer, cfg.n_outer, cfg.slide_outer,
                              cfg.id_inner, cfg.n_inner, cfg.slide_inner),
                          role=Role.REDUCE)
            stages.extend(red.stages())
        else:
            logic = WinSeqLogic(
                self.reduce_func, mp, mp, WinType.CB,
                incremental=self.reduce_incremental,
                result_factory=self.result_factory,
                closing_func=self.closing_func,
                config=WinOperatorConfig(cfg.id_inner, cfg.n_inner,
                                         cfg.slide_inner, 0, 1, mp),
                role=Role.REDUCE)
            stages.append(StageSpec(
                f"{self.name}_reduce", [logic], StandardEmitter(keyed=True),
                RoutingMode.KEYBY, ordering_mode=OrderingMode.ID))
        return stages
