"""Basic streaming operators: Source, Filter, Map, FlatMap, Accumulator, Sink.

Re-designs of reference ``wf/source.hpp`` (439 LoC), ``filter.hpp``
(574), ``map.hpp`` (471), ``flatmap.hpp`` (427), ``accumulator.hpp``
(402), ``sink.hpp`` (498).  All follow the reference template
(SURVEY.md §2.3): a farm of N replica logics, Standard emitter, plain +
rich callable variants, closing function called at svc_end.

Python signature conventions (replacing the C++ overload sets, API:11-43):
* Source:      fn(shipper[, ctx]) -> bool     (loop/shipper style) or an
               iterable/generator factory via SourceBuilder.
* Filter:      fn(t[, ctx]) -> bool | None | record   (False/None drops;
               a record transforms -- the optional<result_t> variant).
* Map:         fn(t[, ctx]) -> None (in-place) | record (transform).
* FlatMap:     fn(t, shipper[, ctx]) -> None.
* Accumulator: fn(t, acc[, ctx]) -> None|acc  (keyed rolling fold,
               acc seeded from init_value; result emitted per input).
* Sink:        fn(t_or_None[, ctx]) -> None   (None signals stream end).
"""
from __future__ import annotations

import copy

from ..core.basic import Pattern, RoutingMode, OrderingMode
from ..core.context import RuntimeContext
from ..core.expr import Expr
from ..core.meta import with_context
from ..core.shipper import Shipper
from ..runtime.emitters import StandardEmitter
from ..runtime.node import EOSMarker, NodeLogic, SourceLoopLogic
from .base import Operator, StageSpec


def _noop_closing(ctx):
    return None


class _ReplicaLogic(NodeLogic):
    """Common skeleton: context binding + closing function."""

    def __init__(self, fn, base_arity, parallelism, replica_index,
                 closing_func):
        self.context = RuntimeContext(parallelism, replica_index)
        self.fn = with_context(fn, base_arity, self.context)
        self.closing_func = closing_func or _noop_closing

    def svc_end(self):
        self.closing_func(self.context)


class SourceLogic(SourceLoopLogic):
    """Shipper-style source: user fn pushes 0..N records, returns False
    at end of stream (source.hpp:228-249)."""

    def __init__(self, fn, parallelism, replica_index, closing_func):
        self.context = RuntimeContext(parallelism, replica_index)
        self.user_fn = with_context(fn, 1, self.context)
        self.closing_func = closing_func or _noop_closing

        def step(emit):
            return self.user_fn(Shipper(emit))
        super().__init__(step)

    def svc_end(self):
        self.closing_func(self.context)


class FilterLogic(_ReplicaLogic):
    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            emit(item)
            return
        out = self.fn(item)
        if out is None or out is False:
            return  # dropped (empty optional, filter.hpp:260-296)
        emit(item if out is True else out)


class MapLogic(_ReplicaLogic):
    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            emit(item)
            return
        out = self.fn(item)
        emit(item if out is None else out)


class FlatMapLogic(_ReplicaLogic):
    def __init__(self, fn, base_arity, parallelism, replica_index,
                 closing_func):
        super().__init__(fn, base_arity, parallelism, replica_index,
                         closing_func)

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            emit(item)
            return
        self.fn(item, Shipper(emit))


class AccumulatorLogic(_ReplicaLogic):
    """Keyed rolling fold (accumulator.hpp:98-177): per-key accumulator
    seeded from ``init_value``; emits a snapshot after every input with
    the input's control fields carried over."""

    def __init__(self, fn, parallelism, replica_index, closing_func,
                 init_value):
        super().__init__(fn, 2, parallelism, replica_index, closing_func)
        self.init_value = init_value
        self.state = {}

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        key, tid, ts = item.get_control_fields()
        acc = self.state.get(key)
        if acc is None:
            acc = copy.deepcopy(self.init_value)
            acc.set_control_fields(key, 0, 0)
            self.state[key] = acc
        ret = self.fn(item, acc)
        if ret is not None:
            acc = self.state[key] = ret
        out = copy.copy(acc)
        out.set_control_fields(key, tid, ts)
        emit(out)

    def state_dict(self):
        st = self.state
        if hasattr(st, "materialize"):     # tiered store: inline copy
            st = st.materialize()
        return {"state": st}

    def load_state(self, st):
        if hasattr(self.state, "replace_all"):
            self.state.replace_all(st["state"])
        else:
            self.state = st["state"]

    # -- tiered keyed state (state/; docs/RESILIENCE.md "Tiered state
    # & memory pressure"): under RuntimeConfig.state_budget_bytes the
    # plain dict is swapped for a TieredKeyedStore -- svc() is
    # untouched (the store is dict-like and self-maintains its budget
    # on this thread), every contract below routes through it ---------
    def enable_tiered_state(self, store):
        store.replace_all(self.state)
        self.state = store

    def bind_hot_sketch(self, hot_keys_fn):
        """Audit plane handoff: pin the sketch's current top keys hot."""
        if hasattr(self.state, "bind_hot_sketch"):
            self.state.bind_hot_sketch(hot_keys_fn)

    def state_tier_of(self, key):
        """Tier name of ``key`` for census/doctor, or None."""
        if hasattr(self.state, "tier_of"):
            return self.state.tier_of(key)
        return "hot" if key in self.state else None

    def keyed_state_pickled(self):
        """Delta-capture fast path: warm/cold keys serve their stored
        pickled bytes (durability/delta.KeyedCapture)."""
        if hasattr(self.state, "keyed_state_pickled"):
            return self.state.keyed_state_pickled()
        return None

    # -- keyed-state hooks (elastic/rescale.py): the per-key fold store
    # repartitions over a new replica count at runtime rescale --------
    def keyed_state_dict(self):
        st = self.state
        if hasattr(st, "materialize"):
            return st.materialize()
        return dict(st)

    def load_keyed_state(self, kv):
        if hasattr(self.state, "replace_all"):
            self.state.replace_all(kv)
        else:
            self.state = dict(kv)

    # -- audit-plane census (audit/census.py): gauge-grade read from
    # the auditor thread against the LIVE store -- len() is GIL-atomic,
    # the byte estimate samples one entry (guarded against a racing
    # resize) ---------------------------------------------------------
    def keyed_state_census(self):
        state = self.state
        if hasattr(state, "census"):       # tiered: per-tier gauges
            return state.census()
        n = len(state)
        if n == 0:
            return (0, 0)
        import sys
        try:
            per = sys.getsizeof(next(iter(state.values()))) + 64
        except (RuntimeError, StopIteration):
            per = 64  # resized under us: count-only estimate
        return (n, n * per)


class SinkLogic(_ReplicaLogic):
    def __init__(self, fn, parallelism, replica_index, closing_func):
        super().__init__(fn, 1, parallelism, replica_index, closing_func)

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        self.fn(item)

    def eos_flush(self, emit):
        self.fn(None)  # empty optional = end of stream (sink.hpp:73-77)


# ---------------------------------------------------------------------------
# Operator descriptors
# ---------------------------------------------------------------------------

class Source(Operator):
    def __init__(self, fn, parallelism=1, name="source", closing_func=None):
        super().__init__(name, parallelism, RoutingMode.NONE, Pattern.SOURCE)
        self.fn = fn
        self.closing_func = closing_func

    def stages(self):
        reps = [SourceLogic(self.fn, self.parallelism, i, self.closing_func)
                for i in range(self.parallelism)]
        return [StageSpec(self.name, reps, StandardEmitter(), self.routing)]


class _BasicOp(Operator):
    logic_cls: type = None
    base_arity: int = 1

    def __init__(self, fn, parallelism, name, closing_func=None,
                 keyed=False, pattern=None):
        super().__init__(name, parallelism,
                         RoutingMode.KEYBY if keyed else RoutingMode.FORWARD,
                         pattern)
        self.fn = fn
        self.closing_func = closing_func
        self.keyed = keyed

    def _make_logic(self, i, n=None):
        return self.logic_cls(self.fn, self.base_arity,
                              n or self.parallelism, i, self.closing_func)

    def stages(self):
        reps = [self._make_logic(i) for i in range(self.parallelism)]
        return [StageSpec(self.name, reps,
                          StandardEmitter(keyed=self.keyed), self.routing,
                          ordering_mode=OrderingMode.TS)]

    def chain_logics(self):
        if self.keyed:
            return None  # KEYBY ops cannot be thread-fused (multipipe chain)
        return [self._make_logic(i) for i in range(self.parallelism)]

    def elastic_logic_factory(self):
        """Fresh replica logics for runtime rescaling (elastic/): the
        basic ops are stateless per replica (their emissions depend only
        on the tuple), so any replica count is semantically equivalent;
        keyed variants repartition by ``hash % n`` like the emitter."""
        return self._make_logic


class Filter(_BasicOp):
    """Predicate may be a Python callable or a declarative ``Expr``
    (e.g. ``Filter(F.value % 4 == 0)``) -- expressions additionally let
    the whole chain lower onto the native C++ record pipeline
    (graph/native_lowering.py)."""

    logic_cls = FilterLogic
    base_arity = 1

    def __init__(self, fn, parallelism=1, name="filter", closing_func=None,
                 keyed=False):
        self.expr = fn if isinstance(fn, Expr) else None
        if self.expr is not None:
            # plane-agnostic: records evaluate scalar, TupleBatch
            # evaluates vectorized over columns
            import numpy as np

            from ..core.tuples import TupleBatch
            pred = self.expr.eval_record
            pred_cols = self.expr.eval_columns

            def fn(t):
                if isinstance(t, TupleBatch):
                    out = t.take(np.asarray(pred_cols(t), bool))
                    return out if len(out) else None
                return bool(pred(t))
        super().__init__(fn, parallelism, name, closing_func, keyed,
                         Pattern.FILTER)


class Map(_BasicOp):
    """Transform may be a Python callable or a value ``Expr``
    (``Map(F.value * 2 + 1)`` assigns the expression to ``value``)."""

    logic_cls = MapLogic
    base_arity = 1

    def __init__(self, fn, parallelism=1, name="map", closing_func=None,
                 keyed=False):
        self.expr = fn if isinstance(fn, Expr) else None
        if self.expr is not None:
            from ..core.tuples import TupleBatch
            ev = self.expr.eval_record
            ev_cols = self.expr.eval_columns

            def fn(t):
                if isinstance(t, TupleBatch):
                    return t.with_cols(value=ev_cols(t))
                t.value = ev(t)  # in-place value assignment
        super().__init__(fn, parallelism, name, closing_func, keyed,
                         Pattern.MAP)


class FlatMap(_BasicOp):
    logic_cls = FlatMapLogic
    base_arity = 2

    def __init__(self, fn, parallelism=1, name="flatmap", closing_func=None,
                 keyed=False):
        super().__init__(fn, parallelism, name, closing_func, keyed,
                         Pattern.FLATMAP)


class Accumulator(Operator):
    """Always KEYBY (multipipe.hpp:967-973)."""

    def __init__(self, fn, init_value, parallelism=1, name="accumulator",
                 closing_func=None):
        super().__init__(name, parallelism, RoutingMode.KEYBY,
                         Pattern.ACCUMULATOR)
        self.fn = fn
        self.init_value = init_value
        self.closing_func = closing_func

    def stages(self):
        reps = [AccumulatorLogic(self.fn, self.parallelism, i,
                                 self.closing_func, self.init_value)
                for i in range(self.parallelism)]
        return [StageSpec(self.name, reps, StandardEmitter(keyed=True),
                          self.routing, ordering_mode=OrderingMode.TS)]

    def elastic_logic_factory(self):
        """Rescalable: per-key fold state migrates through the
        keyed-state hooks (elastic/rescale.py)."""
        return lambda i, n: AccumulatorLogic(
            self.fn, n, i, self.closing_func, self.init_value)


class Sink(_BasicOp):
    logic_cls = SinkLogic
    base_arity = 1

    def __init__(self, fn, parallelism=1, name="sink", closing_func=None,
                 keyed=False, exactly_once=None):
        super().__init__(fn, parallelism, name, closing_func, keyed,
                         Pattern.SINK)
        # exactly-once sink contract (durability/transaction.py;
        # docs/RESILIENCE.md): 'transactional' buffers effects per
        # epoch and releases on durable commit; 'idempotent' applies
        # immediately through an epoch-keyed writer
        if exactly_once not in (None, "transactional", "idempotent"):
            raise ValueError(
                "exactly_once must be None, 'transactional' or "
                f"'idempotent', not {exactly_once!r}")
        self.exactly_once = exactly_once

    def _make_logic(self, i, n=None):
        if self.exactly_once == "transactional":
            from ..durability.transaction import TransactionalSinkLogic
            return TransactionalSinkLogic(self.fn, n or self.parallelism,
                                          i, self.closing_func)
        if self.exactly_once == "idempotent":
            from ..durability.transaction import IdempotentSinkLogic
            return IdempotentSinkLogic(self.fn, n or self.parallelism,
                                       i, self.closing_func)
        return SinkLogic(self.fn, n or self.parallelism, i,
                         self.closing_func)

    def elastic_logic_factory(self):
        # a sink's eos_flush IS the end-of-stream signal (fn(None),
        # sink.hpp:73-77); retiring a replica mid-stream would fire it
        # early, so sinks keep their build-time parallelism
        return None
